"""Public eager collective API: named asynchronous tensor operations.

Mirrors the reference's op surface (``horovod/torch/mpi_ops.py``: sync/async
pairs, auto-generated names, Average/Sum/Adasum ops, prescale/postscale,
``synchronize``/``poll``, ``join``), executed through the controller +
XLA data plane instead of MPI/NCCL.
"""

import threading

from horovod_tpu.common import basics
from horovod_tpu.common.handles import Handle
from horovod_tpu.common.ops_enum import Adasum, Average, ReduceOp, RequestType, Sum
from horovod_tpu.ops.python_controller import EagerRequest

_tls = threading.local()


def _auto_name(kind: str) -> str:
    """Per-rank sequence-numbered names, matching across ranks when call
    order matches (reference: handle-derived names in mpi_ops.py)."""
    counters = getattr(_tls, "counters", None)
    if counters is None:
        counters = _tls.counters = {}
    n = counters.get(kind, 0)
    counters[kind] = n + 1
    return f"{kind}.noname.{n}"


def _resolve_op(op, average):
    """Reference semantics (torch/mpi_ops.py:94-129): exactly one of op /
    average may be set; default is Average."""
    if op is not None and average is not None:
        raise ValueError("cannot specify both op and average")
    if op is None:
        op = Average if average in (None, True) else Sum
    return ReduceOp(op)


def _require_rank_context(state, name):
    """Device-rank mode runs every logical rank inside this process; an
    eager collective from the plain main thread would wait forever for the
    other ranks' submissions.  Fail fast with directions instead
    (reference analog: hanging negotiation is what the StallInspector
    exists to flag)."""
    if (state.config.controller != "tcp" and state.topology.local_size > 1
            and getattr(basics._tls, "local_rank", None) is None):
        raise RuntimeError(
            f"eager collective '{name}' called from the main thread in "
            f"device-rank mode (local_size="
            f"{state.topology.local_size}): each logical rank needs its "
            f"own context. Use horovod_tpu.common.basics.run_parallel(fn), "
            f"launch one process per rank with hvdrun, or use the SPMD "
            f"API (DistributedOptimizer inside shard_map)")


def _submit(req_type, tensor, name, *, op=Sum, root_rank=-1,
            prescale_factor=1.0, postscale_factor=1.0, splits=None,
            compression=None, group=None) -> Handle:
    state = basics._get_state()
    _require_rank_context(state, name)
    from horovod_tpu import groups as groups_mod
    from horovod_tpu.common.compression import resolve_compression

    # group scoping (docs/groups.md): resolve the handle to its CURRENT
    # incarnation — unsatisfiable groups fail typed here, before
    # anything reaches a controller — and require membership (a
    # collective from a non-member can never complete)
    gid, granks = groups_mod.resolve(group)
    if gid:
        me = basics.rank()
        if me not in granks:
            raise ValueError(
                f"collective '{name}': rank {me} is not a member of "
                f"process group {group.name!r} (ranks {list(granks)})")

    # None -> the configured default (HVD_TPU_COMPRESSION / autotune);
    # accepts a canonical name or a Compression class.  Adasum combines
    # full-precision vectors by construction, so it never compresses.
    compression = resolve_compression(
        compression, default=getattr(state.config, "compression", "none"))
    if req_type == RequestType.ADASUM:
        compression = "none"
    # rank indexes the executor's device list (global in gmesh mode, local
    # otherwise).  The tcp plane keeps tensors as numpy: a device commit
    # there would let jax narrow 64-bit dtypes before the exact numpy
    # transport ever sees them.
    if tensor is None:
        committed = None
    elif state.config.controller == "tcp":
        import numpy as _np

        # copy, not a view: capture-at-call semantics — the caller may
        # legally reuse its buffer before the coordinator cycle runs,
        # and different ranks racing that mutation would reduce
        # inconsistent snapshots.  NOTE the device path's contract is
        # weaker for MUTABLE framework tensors: jax.Array inputs are
        # immutable (capture-at-call for free), but a torch tensor
        # staged zero-copy via DLPack is aliased until the cycle reads
        # it — do not mutate between an async submit and synchronize
        # (the reference's adapters have the same rule,
        # torch/adapter_v2.h:42).
        committed = _np.array(tensor, copy=True)
    elif gid:
        # group-local commit: the entry executes on the group's
        # sub-executor, whose device list is indexed by group rank
        committed = state.executor.subset(granks).commit(
            tensor, granks.index(basics.rank()))
    else:
        committed = state.executor.commit(tensor, basics.rank())
    handle = Handle(name)
    state.controller.enqueue(EagerRequest(
        rank=basics.rank(), req_type=req_type, name=name, tensor=committed,
        handle=handle, op=op, root_rank=root_rank,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        splits=splits, compression=compression,
        schedule=getattr(state.config, "schedule", "auto"),
        group=gid, group_ranks=granks))
    return handle


# ------------------------------------------------------------- allreduce ----
def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    compression=None, group=None) -> Handle:
    """``compression``: ``None`` (use the configured default), a name
    ("none" / "bf16" / "fp16" / "int8") or a
    :class:`horovod_tpu.Compression` member — selects the on-the-wire
    representation of this allreduce (reference: the ``compression``
    argument of ``hvd.DistributedOptimizer``, fp16 in the paper)."""
    op = _resolve_op(op, average)
    req_type = RequestType.ADASUM if op == Adasum else RequestType.ALLREDUCE
    return _submit(req_type, tensor, name or _auto_name("allreduce"),
                   op=op, prescale_factor=prescale_factor,
                   postscale_factor=postscale_factor,
                   compression=compression, group=group)


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0, compression=None,
              group=None):
    return synchronize(allreduce_async(
        tensor, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        compression=compression, group=group))


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      compression=None, group=None):
    """Allreduce a list of tensors as one negotiation group; fusion batches
    them into single XLA programs."""
    base = name or _auto_name("grouped_allreduce")
    handles = [
        allreduce_async(t, average=average, name=f"{base}.{i}", op=op,
                        compression=compression, group=group)
        for i, t in enumerate(tensors)
    ]
    return [synchronize(h) for h in handles]


# -------------------------------------------------------- reduce_scatter ----
def reduce_scatter_async(tensor, op=None, average=None, name=None,
                         prescale_factor=1.0, postscale_factor=1.0,
                         compression=None, group=None) -> Handle:
    """Reduce across ranks, then scatter row blocks of the first
    dimension: rank ``r`` receives rows ``split_sizes[r]`` of the reduced
    tensor (np.array_split partition — the first ``dim0 % size`` ranks
    get one extra row).  The ZeRO decomposition's first half (PAPERS.md
    arXiv:2004.13336); paired with :func:`allgather` it replaces an
    allreduce with the optimizer update in between."""
    op = _resolve_op(op, average)
    if op == Adasum:
        raise ValueError("reduce_scatter does not support the Adasum op")
    return _submit(RequestType.REDUCE_SCATTER, tensor,
                   name or _auto_name("reduce_scatter"), op=op,
                   prescale_factor=prescale_factor,
                   postscale_factor=postscale_factor,
                   compression=compression, group=group)


def reduce_scatter(tensor, op=None, average=None, name=None,
                   prescale_factor=1.0, postscale_factor=1.0,
                   compression=None, group=None):
    return synchronize(reduce_scatter_async(
        tensor, op=op, average=average, name=name,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        compression=compression, group=group))


# ------------------------------------------------------------- allgather ----
def allgather_async(tensor, name=None, group=None) -> Handle:
    return _submit(RequestType.ALLGATHER, tensor,
                   name or _auto_name("allgather"), group=group)


def allgather(tensor, name=None, group=None):
    return synchronize(allgather_async(tensor, name=name, group=group))


def grouped_allgather(tensors, name=None, group=None):
    """Allgather a list of tensors as one negotiation group, mirroring
    :func:`grouped_allreduce`'s naming contract (``base.{i}``)."""
    base = name or _auto_name("grouped_allgather")
    handles = [allgather_async(t, name=f"{base}.{i}", group=group)
               for i, t in enumerate(tensors)]
    return [synchronize(h) for h in handles]


# ------------------------------------------------------------- broadcast ----
def broadcast_async(tensor, root_rank, name=None, group=None) -> Handle:
    """``root_rank`` is always a GLOBAL rank, with or without a group
    (the group path translates it internally)."""
    return _submit(RequestType.BROADCAST, tensor,
                   name or _auto_name("broadcast"), root_rank=root_rank,
                   group=group)


def broadcast(tensor, root_rank, name=None, group=None):
    return synchronize(broadcast_async(tensor, root_rank, name=name,
                                       group=group))


# -------------------------------------------------------------- alltoall ----
def alltoall_async(tensor, splits=None, name=None, group=None) -> Handle:
    if splits is None:
        if group is not None:
            from horovod_tpu import groups as groups_mod
            n = len(groups_mod.resolve(group)[1])
        else:
            n = basics.size()
        dim0 = int(tensor.shape[0])
        if dim0 % n != 0:
            raise ValueError(
                f"alltoall without explicit splits requires the first "
                f"dimension ({dim0}) to be divisible by size ({n})")
        splits = [dim0 // n] * n
    return _submit(RequestType.ALLTOALL, tensor,
                   name or _auto_name("alltoall"), splits=list(splits),
                   group=group)


def alltoall(tensor, splits=None, name=None, group=None):
    result, _ = synchronize(alltoall_async(tensor, splits=splits, name=name,
                                           group=group))
    return result


# -------------------------------------------------------------- barrier ----
def barrier(group=None, name=None):
    """Block until every rank of ``group`` (default: the world) has
    entered the barrier.  Implemented as a 1-element allreduce under a
    reserved auto-name: it rides the ordinary negotiation machinery, so
    it composes with groups, aborts and elastic epochs for free."""
    import numpy as _np

    allreduce(_np.zeros(1, dtype=_np.int32), op=Sum,
              name=name or _auto_name("barrier"), group=group)
    return None


# ------------------------------------------------------------ completion ----
def synchronize(handle: Handle, timeout=None):
    """Block until the async op completes and return its result
    (reference: mpi_ops.synchronize / HandleManager.WaitForCompletion)."""
    return handle.wait(timeout)


def poll(handle: Handle) -> bool:
    return handle.poll()


def join() -> int:
    """Signal that this rank has no more data; outstanding allreduces from
    other ranks proceed with zero stand-ins from this rank.  Blocks until
    every rank has joined and returns the last rank to join (reference:
    torch/mpi_ops_v2.cc:240 DoJoin, controller.cc joined handling)."""
    state = basics._get_state()
    _require_rank_context(state, "join")
    handle = Handle("join")
    state.controller.join(basics.rank(), handle)
    return handle.wait()
