"""Pure-Python coordination loop (fallback / reference controller).

Implements the reference's coordinator protocol (``horovod/common/
controller.cc:62`` ComputeResponseList) for the single-process device-rank
mode: per-rank threads enqueue named requests; a background coordination
thread counts readiness across ranks, validates agreement, fuses compatible
allreduces into buckets and dispatches them to the XLA executor.  The
negotiation that costs the reference 1-2 network round-trips per cycle
(MPI_Gatherv + MPI_Bcast) is process-local here; in multi-process mode the
native TCP controller plays that role.

Also hosts the reference's auxiliary semantics:

- **Join** (``controller.cc:219-221,263-273``): joined ranks stop
  contributing; allreduces proceed with zero stand-ins; the join handle
  completes when every rank has joined.
- **StallInspector** (``stall_inspector.cc``): warn when some ranks submitted
  a tensor and others didn't for longer than the stall window; optionally
  shut down.
- **ResponseCache** (``response_cache.cc``): steady-state tensors whose
  signature (type/dtype/shape/op/root/scales) is unchanged since the last
  cycle skip cross-rank validation entirely; stalled names are evicted
  (reference: ``stall_inspector.cc`` InvalidateStalledCachedTensors).
- **Timeline** phases NEGOTIATE_* / op activities.
"""

import dataclasses
import threading
import time

import numpy as np

from horovod_tpu.common.fusion import plan_buckets
from horovod_tpu.common.handles import HvdAbortedError
from horovod_tpu.common.ops_enum import ReduceOp, RequestType
from horovod_tpu.common.response_cache import SignatureCache
from horovod_tpu.utils.logging import get_logger


@dataclasses.dataclass
class EagerRequest:
    rank: int
    req_type: RequestType
    name: str
    tensor: object  # committed jax.Array (None for join)
    handle: object
    op: ReduceOp = ReduceOp.SUM
    root_rank: int = -1
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    splits: list | None = None
    compression: str = "none"
    schedule: str = "auto"
    # process-group scoping (docs/groups.md): "" is the world; a group
    # id makes the group part of the negotiation identity — entries,
    # signatures and fusion buckets are all group-qualified, so
    # cross-group requests can never meet, fuse, or cache-collide
    group: str = ""
    group_ranks: tuple | None = None

    def signature(self):
        """Everything validation checks, flattened into a hashable key
        (reference: ``response_cache.h:45`` — cache key is tensor name +
        params)."""
        # sig-exempt: ring — the ring flag is tcp-transport-local wire
        # negotiation; the in-process plane executes through XLA and
        # has no ring path to disagree about

        tensor = self.tensor
        shape = tuple(tensor.shape) if tensor is not None else None
        dtype = np.dtype(tensor.dtype).name if tensor is not None else None
        return (self.req_type, dtype, shape, self.op, self.root_rank,
                self.prescale_factor, self.postscale_factor,
                tuple(self.splits) if self.splits is not None else None,
                self.compression, self.schedule, self.group,
                self.group_ranks)


class _NameEntry:
    __slots__ = ("first_ts", "req_type", "requests", "stall_warned",
                 "group", "group_ranks")

    def __init__(self, req_type, group="", group_ranks=None):
        self.first_ts = time.monotonic()
        self.req_type = req_type
        self.requests = {}
        self.stall_warned = False
        self.group = group
        self.group_ranks = group_ranks


class GroupEntry:
    """One named tensor inside a fused response — the executor's unit of
    work (reference: TensorTableEntry, common.h:233-250)."""

    __slots__ = ("name", "shape", "dtype", "tensors", "handles", "root_rank",
                 "splits", "op", "prescale_factor", "postscale_factor",
                 "all_dims0", "compression", "schedule", "group",
                 "group_ranks")

    def __init__(self, name, shape, dtype, tensors, handles, root_rank=-1,
                 splits=None, op=ReduceOp.SUM, prescale_factor=1.0,
                 postscale_factor=1.0, all_dims0=None, compression="none",
                 schedule="auto", group="", group_ranks=None):
        self.name = name
        self.shape = shape
        self.dtype = dtype
        self.tensors = tensors
        self.handles = handles
        self.root_rank = root_rank
        self.splits = splits
        self.op = op
        self.prescale_factor = prescale_factor
        self.postscale_factor = postscale_factor
        self.all_dims0 = all_dims0
        self.compression = compression
        self.schedule = schedule
        self.group = group
        self.group_ranks = group_ranks


class PythonController:
    def __init__(self, topology, executor, timeline, config):
        self._topo = topology
        self._executor = executor
        self._timeline = timeline
        self._config = config
        self._size = topology.size
        self._lock = threading.Lock()
        self._wakeup = threading.Event()
        self._queue = []
        self._table = {}  # name -> _NameEntry, insertion-ordered
        self._joined = set()
        self._joined_view = set()  # per-cycle snapshot, coordinator-only
        self._join_handles = {}
        self._running = False
        self._shutdown_error = None
        self._abort_request = None  # (origin_rank, reason), loop-applied
        self._thread = None
        self._log = get_logger()
        self._sig_cache = SignatureCache(
            getattr(config, "cache_capacity", 1024))
        self._autotune = None
        self._tuned = None   # last applied tuned-parameter dict

    @property
    def cache_hits(self):
        return self._sig_cache.hits

    # ----------------------------------------------------------- producer API
    def start(self):
        if self._owns_autotune():
            from horovod_tpu.ops.autotune import AutotuneManager
            self._autotune = AutotuneManager.create(self._config,
                                                    self._log)
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-coordinator")
        self._thread.start()

    def _owns_autotune(self):
        """The in-process cycle loop both tunes and applies; the gmesh
        subclass tunes at its metadata coordinator instead."""
        return True

    def tuned_params(self):
        """Current (possibly autotuned) runtime knob values — same
        surface as the native controller (reference: ParameterManager
        values after SynchronizeParameters)."""
        if self._autotune is not None:
            return self._autotune.params()
        if self._tuned is not None:
            return dict(self._tuned)
        from horovod_tpu.ops.autotune import default_params
        return default_params(self._config)

    def _apply_tuned(self, params):
        """Apply a tuned-parameter set to this process's knobs (the
        reference applies SynchronizeParameters results the same way:
        config values swap at a cycle boundary) — including the
        categorical choices, which the tuner is actively scoring: the
        executor must really run hierarchically when the candidate says
        so, or every hierarchical sample would measure the flat path."""
        self._tuned = dict(params)
        self._config.fusion_threshold_bytes = \
            params["fusion_threshold_bytes"]
        self._config.cycle_time_ms = params["cycle_time_ms"]
        self._executor.hierarchical_allreduce = \
            params["hierarchical_allreduce"]
        self._executor.hierarchical_allgather = \
            params["hierarchical_allgather"]
        self._sig_cache.enabled = params["cache_enabled"]
        if "compression" in params:
            # the DEFAULT wire compression for allreduces that didn't
            # pass one explicitly; requests already in flight keep the
            # compression they were submitted with
            self._config.compression = params["compression"]
        # ring transfer-engine knobs: inert on the in-process planes,
        # but kept in config so tuned_params() reports one consistent
        # surface across controllers
        if "ring_segment_bytes" in params:
            self._config.ring_segment_bytes = \
                int(params["ring_segment_bytes"])
        if "ring_stripes" in params:
            self._config.ring_stripes = int(params["ring_stripes"])
        if "schedule" in params:
            # the DEFAULT collective schedule stamped on subsequent
            # requests (tcp plane: ring-vs-star choice + coordinator
            # negotiation input); in-flight requests keep theirs
            self._config.schedule = str(params["schedule"])

    def enqueue(self, request: EagerRequest):
        with self._lock:
            if not self._running:
                request.handle.set_error("horovod_tpu has been shut down")
                return
            if self._shutdown_error is not None:
                request.handle.set_error(self._shutdown_error)
                return
            self._queue.append(request)
        self._wakeup.set()

    # req-exempt: JOIN — joins never travel through the collective
    # dispatch; they arrive via this dedicated entry point and fold
    # into negotiation as the joined-rank set (docs/elastic.md)
    def join(self, rank, handle):
        with self._lock:
            self._joined.add(rank)
            self._join_handles[rank] = handle
        self._wakeup.set()

    def shutdown(self):
        with self._lock:
            self._running = False
        self._wakeup.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._autotune is not None:
            self._autotune.close()
            self._autotune = None
        with self._lock:
            for request in self._queue:
                request.handle.set_error("horovod_tpu has been shut down")
            self._queue.clear()
            for entry in self._table.values():
                for request in entry.requests.values():
                    request.handle.set_error(
                        "horovod_tpu has been shut down")
            self._table.clear()

    def request_drain(self) -> bool:
        """Graceful-drain announcement (docs/checkpoint.md): the
        in-process controllers coordinate device ranks inside ONE
        process, so there is no coordinator to notify and no survivor
        set to re-form — a preemption notice here simply ends the
        process.  Always False (drain impossible)."""
        return False

    # ----------------------------------------------------------------- abort
    def abort(self, origin_rank, reason):
        """Coordinated abort (``hvd.abort()`` / a rank detecting an
        unrecoverable failure): every in-flight and future collective
        fails with one typed ``HvdAbortedError``.  The table is owned by
        the coordination thread, so the abort is recorded here and
        applied at the next cycle boundary — bounded by cycle_time."""
        with self._lock:
            if self._abort_request is None:
                self._abort_request = (origin_rank, reason)
        self._wakeup.set()

    def _apply_abort(self, exc):
        """Fail everything in flight with the typed error and poison the
        controller so later enqueues fail fast (coordination-thread
        context — the only legal place to touch the table)."""
        self._log.error(str(exc))
        with self._lock:
            self._shutdown_error = exc
            queued, self._queue = self._queue, []
            join_handles = dict(self._join_handles)
            self._join_handles.clear()
            self._joined.clear()
            # a signature validated before the abort must not satisfy a
            # post-abort (or post-reconfiguration) round of the same name
            self._sig_cache.clear()
        for request in queued:
            request.handle.set_error(exc)
        for handle in join_handles.values():
            handle.set_error(exc)
        self._fail_all(exc)

    # ------------------------------------------------------- coordinator loop
    def _loop(self):
        while True:
            # re-read each cycle: autotune retunes cycle_time_ms live
            cycle_s = self._config.cycle_time_ms / 1000.0
            self._wakeup.wait(timeout=cycle_s)
            self._wakeup.clear()
            with self._lock:
                if not self._running:
                    return
                pending, self._queue = self._queue, []
                abort_req, self._abort_request = self._abort_request, None
            if abort_req is not None:
                for request in pending:
                    request.handle.set_error(HvdAbortedError(*abort_req))
                self._apply_abort(HvdAbortedError(*abort_req))
                continue
            self._timeline.mark_cycle()
            try:
                self._run_cycle(pending)
            except Exception as exc:  # noqa: BLE001 — never kill the loop
                self._log.error("coordinator cycle failed: %s", exc)
                self._fail_all(str(exc))

    def _fail_all(self, message):
        for entry in self._table.values():
            for request in entry.requests.values():
                request.handle.set_error(message)
        self._table.clear()

    def _absorb(self, pending):
        """Absorb new requests into the message table (reference:
        TensorQueue pop + table insert).  The table key is
        (group, name): same-named tensors from different groups are
        DIFFERENT negotiations and must never meet in one entry."""
        for request in pending:
            key = (getattr(request, "group", ""), request.name)
            entry = self._table.get(key)
            if entry is None:
                entry = _NameEntry(request.req_type,
                                   group=key[0],
                                   group_ranks=getattr(
                                       request, "group_ranks", None))
                self._table[key] = entry
                self._timeline.begin(
                    request.name, f"NEGOTIATE_{request.req_type.name}")
            if request.rank in entry.requests:
                request.handle.set_error(
                    f"duplicate request for tensor '{request.name}' from "
                    f"rank {request.rank} before previous one completed")
                continue
            entry.requests[request.rank] = request
            self._timeline.instant(request.name, f"{request.rank}")

    def _run_cycle(self, pending):
        # snapshot joined state once per cycle (rank threads mutate it under
        # the lock; iterating the live set would race)
        with self._lock:
            self._joined_view = set(self._joined)

        # 1. absorb new requests into the message table
        self._absorb(pending)

        # 2. stall inspection
        if not self._config.stall_check_disable:
            self._check_stalls()

        # 2b. cross-group concurrency gauge (docs/groups.md): distinct
        # groups with entries open right now — read by the acceptance
        # tests to assert concurrency rather than assume it
        if self._table:
            from horovod_tpu import groups as groups_mod
            groups_mod.note_inflight(g for (g, _) in self._table)

        # 3. collect ready responses in deterministic (arrival) order.
        # Readiness is per entry: a group entry needs exactly its
        # member ranks (no join stand-ins — joins are a world-level
        # protocol), the world needs every non-joined rank.
        ready_keys = []
        world_needed = set(range(self._size)) - self._joined_view
        for key, entry in self._table.items():
            needed = (set(entry.group_ranks) if entry.group
                      else world_needed)
            if needed.issubset(entry.requests.keys()):
                ready_keys.append(key)

        responses = []
        for key in ready_keys:
            entry = self._table.pop(key)
            _, name = key
            self._timeline.end(name)
            if self._cache_check(key, entry):
                group = self._build_group(name, entry)
            else:
                group = self._construct_response(name, entry)
                if group is not None:
                    self._cache_store(key, entry)
            if group is not None:
                responses.append((entry.req_type, group))

        # 4. fuse + dispatch
        self._dispatch(responses)

        # 4b. feed the tuner (rank-0-analog: this process IS the
        # coordinator) and apply any retuned knobs at this cycle
        # boundary
        if self._autotune is not None:
            for _, group in responses:
                self._autotune.record(
                    np.dtype(group.dtype).itemsize
                    * int(np.prod(group.shape or (1,))))
            upd = self._autotune.maybe_update()
            if upd is not None:
                _, params = upd
                self._apply_tuned(params)

        # 5. join barrier: everyone joined -> complete join handles with the
        # last rank to join (dict preserves join-call order)
        with self._lock:
            if self._joined and len(self._joined) == self._size \
                    and not self._table and not self._queue:
                last = next(reversed(self._join_handles))
                for handle in self._join_handles.values():
                    handle.set_result(last)
                self._join_handles.clear()
                self._joined.clear()

    # ---------------------------------------------------------- response cache
    @staticmethod
    def _cache_key(key):
        """Group-qualified response-cache name: a group's validated
        signature must never satisfy the world's (or another group's)
        entry of the same tensor name."""
        group, name = key
        return f"g:{group}:{name}" if group else name

    def _cache_check(self, key, entry) -> bool:
        """Fast path (reference: ``response_cache.cc`` HIT): every rank's
        request carries the same signature as the last validated cycle for
        this name — skip validation.  Never taken while ranks have joined
        (zero stand-ins change response construction)."""
        if self._joined_view:
            return False
        return self._sig_cache.check(
            self._cache_key(key),
            (r.signature() for r in entry.requests.values()))

    def _cache_store(self, key, entry):
        self._sig_cache.store(
            self._cache_key(key),
            (r.signature() for r in entry.requests.values()))

    @staticmethod
    def resolve_group_compression(compressions):
        """Cross-rank compression resolution: unanimous choice wins,
        disagreement resolves to "none" (exact) rather than erroring —
        an autotune publication applying at slightly different times on
        different ranks must not kill in-flight collectives (same spirit
        as the tcp coordinator resolving ring-vs-payload)."""
        comps = set(compressions)
        return comps.pop() if len(comps) == 1 else "none"

    @staticmethod
    def resolve_group_schedule(schedules):
        """Cross-rank collective-schedule resolution, same contract as
        the compression resolver: unanimous choice wins, disagreement —
        e.g. a tuned schedule applying at slightly different times on
        different ranks — resolves to "auto" (the coordinator then
        picks) rather than erroring."""
        scheds = set(schedules)
        return scheds.pop() if len(scheds) == 1 else "auto"

    def _build_group(self, name, entry):
        """Build the executor GroupEntry from an already-validated (or
        cache-hit) table entry."""
        requests = entry.requests
        any_req = next(iter(requests.values()))
        gid = getattr(entry, "group", "")
        granks = getattr(entry, "group_ranks", None)
        if gid:
            # group entries are re-keyed to GROUP-LOCAL ranks: the
            # executor that runs them is the group's sub-executor
            # (devices[granks]), whose world is 0..len(granks)-1
            order = list(granks)
            tensors = {order.index(rank): r.tensor
                       for rank, r in requests.items()}
            handles = {order.index(rank): r.handle
                       for rank, r in requests.items()}
            root = (order.index(any_req.root_rank)
                    if any_req.root_rank in order else any_req.root_rank)
            splits = {order.index(rank): r.splits
                      for rank, r in requests.items()}
        else:
            tensors = {rank: r.tensor for rank, r in requests.items()}
            for joined_rank in self._joined_view:
                tensors.setdefault(joined_rank, None)
            handles = {rank: r.handle for rank, r in requests.items()}
            root = any_req.root_rank
            splits = {rank: r.splits for rank, r in requests.items()}
        return GroupEntry(
            name=name, shape=tuple(any_req.tensor.shape),
            dtype=any_req.tensor.dtype, tensors=tensors,
            handles=handles,
            root_rank=root,
            splits=splits,
            op=any_req.op, prescale_factor=any_req.prescale_factor,
            postscale_factor=any_req.postscale_factor,
            compression=self.resolve_group_compression(
                r.compression for r in requests.values()),
            schedule=self.resolve_group_schedule(
                getattr(r, "schedule", "auto")
                for r in requests.values()),
            group=gid, group_ranks=granks)

    # ------------------------------------------------------------- validation
    @staticmethod
    def validate_requests(name, requests, *, size, joined):
        """Cross-rank agreement rules (reference: controller.cc:378
        ConstructResponse), shared by the in-process controllers and the
        gmesh controller's local (intra-process) pre-check.  Returns an
        error string or None."""
        types = {r.req_type for r in requests.values()}
        if len(types) > 1:
            return (f"mismatched collective types for tensor '{name}': "
                    f"{sorted(t.name for t in types)}")
        req_type = next(iter(types))

        if joined and req_type in (RequestType.ALLGATHER,
                                   RequestType.BROADCAST,
                                   RequestType.ALLTOALL,
                                   RequestType.REDUCE_SCATTER):
            return (f"{req_type.name} is not supported while ranks have "
                    f"joined")

        dtypes = {np.dtype(r.tensor.dtype).name for r in requests.values()
                  if r.tensor is not None}
        if len(dtypes) > 1:
            return f"mismatched dtypes for tensor '{name}': {sorted(dtypes)}"

        if req_type in (RequestType.ALLREDUCE, RequestType.ADASUM):
            ops = {r.op for r in requests.values()}
            if len(ops) > 1:
                return f"mismatched reduce ops for tensor '{name}'"
            pre = {r.prescale_factor for r in requests.values()}
            post = {r.postscale_factor for r in requests.values()}
            if len(pre) > 1 or len(post) > 1:
                return f"mismatched scale factors for tensor '{name}'"
            shapes = {tuple(r.tensor.shape) for r in requests.values()}
            if len(shapes) > 1:
                return (f"mismatched shapes for allreduce '{name}': "
                        f"{sorted(shapes)}")
        elif req_type == RequestType.ALLGATHER:
            ndims = {r.tensor.ndim for r in requests.values()}
            if len(ndims) > 1:
                return f"mismatched tensor ranks for allgather '{name}'"
            if 0 in ndims:
                return (f"allgather '{name}': 0-d tensors are not "
                        f"supported; reshape to (1,) first")
            trailing = {tuple(r.tensor.shape[1:])
                        for r in requests.values()}
            if len(trailing) > 1:
                return (f"mismatched trailing dimensions for allgather "
                        f"'{name}'")
        elif req_type == RequestType.BROADCAST:
            roots = {r.root_rank for r in requests.values()}
            if len(roots) > 1:
                return f"mismatched root ranks for broadcast '{name}'"
            shapes = {tuple(r.tensor.shape) for r in requests.values()}
            if len(shapes) > 1:
                return f"mismatched shapes for broadcast '{name}'"
        elif req_type == RequestType.REDUCE_SCATTER:
            ops = {r.op for r in requests.values()}
            if len(ops) > 1:
                return f"mismatched reduce ops for tensor '{name}'"
            pre = {r.prescale_factor for r in requests.values()}
            post = {r.postscale_factor for r in requests.values()}
            if len(pre) > 1 or len(post) > 1:
                return f"mismatched scale factors for tensor '{name}'"
            ndims = {r.tensor.ndim for r in requests.values()}
            if 0 in ndims:
                return (f"reduce_scatter '{name}': 0-d tensors are not "
                        f"supported; reshape to (1,) first")
            shapes = {tuple(r.tensor.shape) for r in requests.values()}
            if len(shapes) > 1:
                return (f"mismatched shapes for reduce_scatter '{name}': "
                        f"{sorted(shapes)}")
        elif req_type == RequestType.ALLTOALL:
            for r in requests.values():
                if len(r.splits) != size:
                    return (f"alltoall '{name}': splits must have one "
                            f"entry per rank ({size}), got "
                            f"{len(r.splits)}")
                if sum(r.splits) != r.tensor.shape[0]:
                    return (f"alltoall '{name}': splits sum "
                            f"{sum(r.splits)} != first dimension "
                            f"{r.tensor.shape[0]}")
        return None

    def _construct_response(self, name, entry):
        """Validate cross-rank agreement and build a GroupEntry, or
        error every handle."""
        requests = entry.requests
        granks = getattr(entry, "group_ranks", None)
        message = self.validate_requests(
            name, requests,
            size=(len(granks) if getattr(entry, "group", "") else
                  self._size),
            joined=bool(self._joined_view)
            and not getattr(entry, "group", ""))
        if message is not None:
            for request in requests.values():
                request.handle.set_error(message)
            return None
        return self._build_group(name, entry)

    # ----------------------------------------------------------------- fusion
    @staticmethod
    def allreduce_bucket_key(dtype, op, prescale, postscale,
                             compression="none", schedule="auto",
                             group=""):
        """Bucket-compatibility key shared with the gmesh coordinator
        (reference: FuseResponses fuses dtype/op/scale-homogeneous runs).
        Compression is part of the key: a compressed and an uncompressed
        request must never fuse into one program — they have different
        wire formats and different numerics.  The collective schedule
        likewise: requests negotiated for different schedules must never
        fuse into one bucket (a hierarchical and a flat-ring tensor take
        different data paths with different round structures).  The
        process-group id completes the never-fuse rules: requests from
        different groups reduce over different rank sets and must never
        share a program (docs/groups.md)."""
        return (np.dtype(dtype).name, int(op), prescale, postscale,
                compression, schedule, group)

    def _dispatch(self, responses):
        """Fuse compatible allreduces into <= fusion_threshold buckets
        (reference: controller.cc:640 FuseResponses) and execute."""
        def safe(execute, groups):
            try:
                execute()
            except Exception as exc:  # noqa: BLE001 — surface on handles
                self._log.error("collective execution failed: %s", exc)
                for g in groups:
                    for handle in g.handles.values():
                        handle.set_error(f"collective execution failed: {exc}")

        def key(item):
            req_type, group = item
            if req_type != RequestType.ALLREDUCE:
                return ("single", id(group))  # never fuses
            return self.allreduce_bucket_key(
                group.dtype, group.op, group.prescale_factor,
                group.postscale_factor, group.compression,
                getattr(group, "schedule", "auto"),
                getattr(group, "group", ""))

        def nbytes(item):
            _, group = item
            return (np.dtype(group.dtype).itemsize
                    * int(np.prod(group.shape or (1,))))

        for bucket in plan_buckets(
                responses, key_fn=key, nbytes_fn=nbytes,
                threshold=self._config.fusion_threshold_bytes):
            req_type = bucket[0][0]
            groups = [g for _, g in bucket]
            if req_type == RequestType.ALLREDUCE:
                safe(lambda groups=groups:
                     self._execute_allreduce_bucket(groups), groups)
            else:
                safe(lambda req_type=req_type, g=groups[0]:
                     self._execute_single(req_type, g), groups)

    def _exec_for(self, group_entry):
        """Executor for one response: the shared world executor, or —
        for a process-group entry — the memoized sub-executor over the
        group's device subset (XLA plane: per-(group, signature)
        program caches come for free from the sub-executor's own
        per-signature caches, docs/groups.md)."""
        granks = getattr(group_entry, "group_ranks", None)
        if getattr(group_entry, "group", "") and granks:
            return self._executor.subset(tuple(granks))
        return self._executor

    def _execute_allreduce_bucket(self, groups):
        first = groups[0]
        self._timeline_begin_groups(groups, "ALLREDUCE")
        self._exec_for(first).allreduce_fused(
            groups, op=first.op,
            prescale_factor=first.prescale_factor,
            postscale_factor=first.postscale_factor,
            compression=first.compression)
        self._timeline_end_groups(groups)

    def _execute_single(self, req_type, group):
        self._timeline_begin_groups([group], req_type.name)
        executor = self._exec_for(group)
        if req_type == RequestType.ALLGATHER:
            executor.allgather(group)
        elif req_type == RequestType.BROADCAST:
            executor.broadcast(group)
        elif req_type == RequestType.ALLTOALL:
            executor.alltoall(group)
        elif req_type == RequestType.ADASUM:
            executor.adasum(group)
        elif req_type == RequestType.REDUCE_SCATTER:
            executor.reduce_scatter(group)
        self._timeline_end_groups([group])

    def _timeline_begin_groups(self, groups, phase):
        for g in groups:
            self._timeline.begin(g.name, phase)

    def _timeline_end_groups(self, groups):
        for g in groups:
            self._timeline.end(g.name)

    # ------------------------------------------------------------------ stall
    def _check_stalls(self):
        now = time.monotonic()
        warn_after = self._config.stall_warning_seconds
        shutdown_after = self._config.stall_shutdown_seconds
        for key, entry in list(self._table.items()):
            _, name = key
            expected = (set(entry.group_ranks) if entry.group
                        else set(range(self._size)))
            age = now - entry.first_ts
            if age > warn_after and not entry.stall_warned:
                ready = sorted(entry.requests.keys())
                missing = sorted(expected - set(ready)
                                 - self._joined_view)
                self._log.warning(
                    "One or more tensors were submitted to be reduced, "
                    "gathered or broadcasted by subset of ranks and are "
                    "waiting for remainder of ranks for more than %ds. "
                    "Stalled tensor: %s ready ranks: %s, waiting on: %s",
                    int(warn_after), name, ready, missing)
                entry.stall_warned = True
                # reference: stall_inspector.cc InvalidateStalledCachedTensors
                self._sig_cache.evict(self._cache_key(key))
            if shutdown_after > 0 and age > shutdown_after:
                # promoted from a log line into a coordinated abort: one
                # typed error on every rank, naming the first lagging
                # rank as the origin — group-scoped entries stamp the
                # lagging GROUP member, and the abort still fails the
                # whole job (docs/groups.md: no half-dead jobs)
                missing = sorted(expected
                                 - set(entry.requests.keys())
                                 - self._joined_view)
                origin = missing[0] if missing else -1
                self._apply_abort(HvdAbortedError(
                    origin,
                    f"stalled tensor '{name}' exceeded shutdown "
                    f"threshold of {shutdown_after}s (waiting on ranks "
                    f"{missing})"))
                return
