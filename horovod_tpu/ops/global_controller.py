"""Global-mesh controller: the TPU-pod data plane.

Multi-process (``hvdrun --tpu`` / ``--global-mesh``) coordination where
the wire carries **metadata only** and every byte of tensor data moves as
compiled XLA collectives over the global ``jax.distributed`` device mesh
(ICI within a slice, DCN across hosts).  This is the reference's
negotiate-then-execute split (``controller.cc:62`` ComputeResponseList →
backend op) rebuilt for multi-controller JAX:

- Each process runs a local coordination loop for the device ranks it
  hosts (same table as :class:`PythonController`).
- When all local ranks have submitted a name, the process reports the
  name's *metadata* (shape/dtype/op/... — never the payload) to the
  rank-0 coordinator service (HMAC TCP, reference: gloo controller's
  gather to rank 0).
- The coordinator validates cross-process agreement, fuses compatible
  allreduces (``controller.cc:640`` FuseResponses), assigns each fused
  response a **global sequence number**, and long-polls it back to every
  process (reference: response-list broadcast).
- Every process executes the response log in sequence order, so all
  processes issue identical XLA programs in identical order — the
  multi-controller SPMD contract.  The per-signature compiled-program
  cache in :class:`XlaExecutor` plays the reference's ResponseCache role.

This replaces round 1's TCP data plane (rank-0 star shipping numpy
payloads) for pod jobs: the coordinator round-trip is O(names), not
O(bytes).
"""

import base64
import os
import threading
import time

import numpy as np

from horovod_tpu.common.handles import HvdAbortedError
from horovod_tpu.common.ops_enum import ReduceOp, RequestType
from horovod_tpu.common.fusion import plan_buckets
from horovod_tpu.ops.python_controller import GroupEntry, PythonController
from horovod_tpu.run.service import network
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

# consecutive coordinator send failures tolerated before the job is
# failed (the launcher kills on nonzero exit; this is the in-process
# analog for a dead rank-0)
_SEND_FAIL_LIMIT_S = 60.0

GMESH_SCOPE = "gmesh"
GMESH_KEY = "addr"
POLL_WAIT_S = 0.2


# ------------------------------------------------------------------ messages
class MetaReq:
    """One name's metadata from one process (payload-free)."""

    __slots__ = ("name", "req_type", "op", "dtype", "shape", "dims0",
                 "splits", "root_rank", "prescale", "postscale", "ranks",
                 "error", "compression", "schedule", "group",
                 "group_ranks")

    def __init__(self, name, req_type, op, dtype, shape, dims0, splits,
                 root_rank, prescale, postscale, ranks, error=None,
                 compression="none", schedule="auto", group="",
                 group_ranks=None):
        self.error = error  # intra-process validation failure, if any
        self.name = name
        self.req_type = int(req_type)
        self.op = int(op)
        self.dtype = dtype            # numpy dtype string
        self.shape = tuple(shape)
        self.dims0 = dims0            # {rank: dim0} for allgather
        self.splits = splits          # {rank: [..]} for alltoall
        self.root_rank = root_rank
        self.prescale = prescale
        self.postscale = postscale
        self.ranks = tuple(ranks)     # local ranks that submitted
        self.compression = compression  # process-resolved wire compression
        self.schedule = schedule      # process-resolved collective schedule
        # process-group scoping (docs/groups.md): "" is the world; a
        # group id keeps negotiations from different groups apart at the
        # coordinator exactly as in the in-process table
        self.group = group
        self.group_ranks = (tuple(group_ranks) if group_ranks is not None
                            else None)


class CycleMsg:
    __slots__ = ("pid", "reqs", "joined", "last_seq", "join_epoch")

    def __init__(self, pid, reqs, joined, last_seq, join_epoch=0):
        self.pid = pid
        self.reqs = reqs
        self.joined = tuple(joined)
        self.last_seq = last_seq
        # the client's count of join_done rounds observed; a stale epoch
        # marks a replayed joined-report from before the last join_done
        self.join_epoch = join_epoch


class LogEntry:
    """One globally-ordered response (possibly a fused allreduce bucket)."""

    __slots__ = ("seq", "kind", "req_type", "names", "shapes", "dtype",
                 "op", "prescale", "postscale", "root_rank", "all_dims0",
                 "splits_matrix", "error", "last_rank", "joined", "params",
                 "compression", "schedule", "origin", "group",
                 "group_ranks")

    def __init__(self, seq, kind, req_type=None, names=(), shapes=(),
                 dtype=None, op=0, prescale=1.0, postscale=1.0,
                 root_rank=-1, all_dims0=None, splits_matrix=None,
                 error=None, last_rank=-1, joined=(), params=None,
                 compression="none", schedule="auto", origin=-1,
                 group="", group_ranks=None):
        self.seq = seq
        self.kind = kind  # "group" | "error" | "join_done" | "params"
        #                   | "abort"
        self.req_type = req_type
        self.names = tuple(names)
        self.shapes = tuple(tuple(s) for s in shapes)
        self.dtype = dtype
        self.op = op
        self.prescale = prescale
        self.postscale = postscale
        self.root_rank = root_rank
        self.all_dims0 = all_dims0
        self.splits_matrix = splits_matrix
        self.error = error
        self.last_rank = last_rank
        self.joined = tuple(joined)   # global joined snapshot at emit time
        self.params = params          # tuned knob dict ("params" entries)
        self.compression = compression  # coordinator-resolved wire format
        self.schedule = schedule      # coordinator-resolved schedule
        self.origin = origin          # abort origin rank ("abort" entries)
        # process-group scoping: "" is the world; a group's entries
        # carry the full member list so every process re-keys to
        # group-local ranks identically (docs/groups.md)
        self.group = group
        self.group_ranks = (tuple(group_ranks) if group_ranks is not None
                            else None)


class CycleResp:
    __slots__ = ("entries",)

    def __init__(self, entries):
        self.entries = entries


class _GlobalName:
    __slots__ = ("first_ts", "reqs", "stall_warned", "group",
                 "group_ranks")

    def __init__(self, group="", group_ranks=None):
        self.first_ts = time.monotonic()
        self.reqs = {}   # pid -> MetaReq
        self.stall_warned = False
        self.group = group
        self.group_ranks = group_ranks


# ---------------------------------------------------------------- coordinator
class MetaCoordinatorService(network.MuxService):
    """Rank-0 process's metadata coordinator (reference: rank 0 in
    ComputeResponseList — gathers requests, validates, fuses, broadcasts
    the ordered response list)."""

    NAME = "horovod_tpu gmesh coordinator"

    def __init__(self, num_processes, local_sizes, key, fusion_threshold,
                 stall_warning_sec=60.0, stall_shutdown_sec=0.0,
                 autotune=None, liveness_timeout_sec=0.0):
        self._nproc = num_processes
        self._local_sizes = local_sizes      # ranks per process
        self._rank_pid = {}
        base = 0
        for pid, ls in enumerate(local_sizes):
            for r in range(base, base + ls):
                self._rank_pid[r] = pid
            base += ls
        self._world = base
        self._fusion_threshold = fusion_threshold
        self._autotune = autotune    # rank-0-owned AutotuneManager|None
        self._stall_warning = stall_warning_sec
        self._stall_shutdown = stall_shutdown_sec
        self._cv = threading.Condition()
        # name -> _GlobalName (ordered); guarded by self._cv
        self._table = {}
        self._joined = set()             # global ranks; guarded by self._cv
        # coordinator-serialized arrivals; guarded by self._cv
        self._join_order = []
        self._log_entries = []           # guarded by self._cv
        # pid -> highest seq acknowledged; guarded by self._cv
        self._acked = {}
        self._seq = 0                    # guarded by self._cv
        self._join_epoch = 0  # completed join_done rounds
        self._liveness = liveness_timeout_sec
        # seeded for EVERY pid at construction: a process that dies
        # before its first CycleMsg must still trip the liveness window
        # (safe: the jax.distributed barrier precedes controller start,
        # so all processes exist by now and report within a heartbeat)
        self._last_seen = {p: time.monotonic()
                           for p in range(num_processes)}  # guarded by self._cv
        # (origin_rank, reason), sticky; guarded by self._cv
        self._aborted = None
        self._log = get_logger()
        super().__init__(self.NAME, key)

    # ------------------------------------------------------------- protocol
    def _handle(self, req, client_address):
        if isinstance(req, CycleMsg):
            return self._handle_cycle(req)
        if isinstance(req, network.HeartbeatMsg):
            # dedicated liveness beat (``rank`` carries the pid): keeps
            # last_seen fresh even while the sender's coordination loop
            # is blocked inside a long collective execution or compile
            with self._cv:
                self._last_seen[req.rank] = time.monotonic()
                self._check_liveness()
                return network.HeartbeatReply(abort=self._aborted)
        if isinstance(req, network.AbortMsg):
            with self._cv:
                self._initiate_abort(req.origin_rank, req.reason)
            return network.AckResponse()
        return super()._handle(req, client_address)

    # -------------------------------------------------- abort + liveness
    def _initiate_abort(self, origin_rank, reason):  # holds: self._cv
        """Emit one globally-ordered abort entry (caller holds the lock):
        every process applies it at the same point of the response
        stream and fails all of its ranks with the same typed error."""
        if self._aborted is not None:
            return
        self._aborted = (origin_rank, reason)
        self._table.clear()
        self._log.error("coordinated abort (origin rank %s): %s",
                        origin_rank, reason)
        self._emit(LogEntry(self._next_seq(), "abort", error=reason,
                            origin=origin_rank))

    def _check_liveness(self):  # holds: self._cv
        """A process silent past the liveness window is presumed dead —
        convert the silence into an abort naming its first global rank
        (caller holds the lock).  Fully-joined processes are exempt:
        they legitimately go quiet (and may exit) once no collective
        needs them."""
        if self._liveness <= 0 or self._aborted is not None:
            return
        now = time.monotonic()
        required = self._required_pids()
        dead = sorted(p for p, ts in self._last_seen.items()
                      if now - ts > self._liveness and p in required)
        if dead:
            base = sum(self._local_sizes[:dead[0]])
            self._initiate_abort(
                base,
                f"process {dead[0]} (ranks from {base}) sent no heartbeat "
                f"for more than {self._liveness:g}s (presumed dead)")

    def _required_pids(self):  # holds: self._cv
        """Processes that still host at least one non-joined rank."""
        out = set()
        base = 0
        for pid, ls in enumerate(self._local_sizes):
            if any(r not in self._joined for r in range(base, base + ls)):
                out.add(pid)
            base += ls
        return out

    def _entry_required_pids(self, entry):  # holds: self._cv
        """Processes whose report this entry waits on: a group entry
        needs exactly the processes hosting its member ranks (joins are
        a world-level protocol and never stand in for group members,
        docs/groups.md); a world entry needs every process with a
        non-joined rank."""
        if entry.group:
            return {self._rank_pid[r] for r in entry.group_ranks
                    if r in self._rank_pid}
        return self._required_pids()

    def _handle_cycle(self, msg):
        with self._cv:
            self._last_seen[msg.pid] = time.monotonic()
            self._check_liveness()
            self._acked[msg.pid] = max(self._acked.get(msg.pid, 0),
                                       msg.last_seq)
            self._trim_log()
            # req-exempt: JOIN — joins never travel through the
            # collective dispatch; they ride CycleMsg as the
            # joined-rank report folded in right here (docs/elastic.md)
            if msg.join_epoch == self._join_epoch:
                for r in msg.joined:
                    if r not in self._joined:
                        self._joined.add(r)
                        self._join_order.append(r)
            # else: a replay from before the last join_done (lost
            # response); honoring it would poison the cleared join set
            # names already emitted but not yet acked by this pid: a
            # re-report is the lost-response replay, not a new request
            inflight = {(getattr(e, "group", ""), n)
                        for e in self._log_entries
                        if e.seq > msg.last_seq for n in e.names}
            for req in msg.reqs:
                key = (getattr(req, "group", ""), req.name)
                if key in inflight or self._aborted is not None:
                    # post-abort requests would never complete — the
                    # abort entry below fails them process-side instead
                    continue
                entry = self._table.get(key)
                if entry is None:
                    entry = _GlobalName(
                        group=key[0],
                        group_ranks=getattr(req, "group_ranks", None))
                    self._table[key] = entry
                entry.reqs[msg.pid] = req
            if self._table:
                # cross-group concurrency gauge (docs/groups.md): the
                # coordinator sees every process's open negotiations, so
                # this is the pod-wide in-flight measurement
                from horovod_tpu import groups as groups_mod
                groups_mod.note_inflight(g for (g, _) in self._table)
            self._advance()
            self._check_stalls()
            entries = [e for e in self._log_entries if e.seq > msg.last_seq]
            if entries:
                return CycleResp(entries)
        # long-poll outside the lock-held fast path
        deadline = time.monotonic() + POLL_WAIT_S
        with self._cv:
            while True:
                entries = [e for e in self._log_entries
                           if e.seq > msg.last_seq]
                if entries:
                    return CycleResp(entries)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._check_stalls()
                    return CycleResp([])
                self._cv.wait(timeout=remaining)

    # ------------------------------------------------------- response build
    def _advance(self):  # holds: self._cv
        """Emit log entries for names every required process reported.
        Caller holds the lock."""
        ready = [(key, entry) for key, entry in self._table.items()
                 if self._entry_required_pids(entry)
                 .issubset(entry.reqs.keys())]
        if not ready and not self._join_done_ready():
            return

        # validate first; bucket the valid ones with the SAME planner and
        # compatibility key the in-process controllers use
        validated = []  # (key, meta) | error LogEntries emitted inline
        for key, entry in ready:
            del self._table[key]
            err, meta = self._validate(key, entry)
            if err is not None:
                self._emit(LogEntry(self._next_seq(), "error",
                                    names=[key[1]], error=err,
                                    group=key[0],
                                    group_ranks=entry.group_ranks))
                continue
            validated.append((key, meta))

        def key(item):
            _, meta = item
            rtype = RequestType(meta["req_type"])
            if rtype != RequestType.ALLREDUCE:
                return ("single", item[0])
            return PythonController.allreduce_bucket_key(
                meta["dtype"], meta["op"], meta["prescale"],
                meta["postscale"], meta.get("compression", "none"),
                meta.get("schedule", "auto"), meta.get("group", ""))

        def nbytes(item):
            _, meta = item
            return (np.dtype(meta["dtype"]).itemsize *
                    int(np.prod(meta["shape"] or (1,))))

        if self._autotune is not None:
            for item in validated:
                self._autotune.record(nbytes(item))
            upd = self._autotune.maybe_update()
            if upd is not None:
                _, params = upd
                # the coordinator's own fusion planning retunes here;
                # the "params" entry hands every process the same values
                # at the same point of the ordered response stream
                # (reference: SynchronizeParameters, controller.cc:33)
                self._fusion_threshold = params["fusion_threshold_bytes"]
                self._emit(LogEntry(self._next_seq(), "params",
                                    params=params))

        for bucket in plan_buckets(validated, key_fn=key,
                                   nbytes_fn=nbytes,
                                   threshold=self._fusion_threshold):
            first_meta = bucket[0][1]
            rtype = RequestType(first_meta["req_type"])
            if rtype == RequestType.ALLREDUCE:
                # group joins the bucket key above, so every member of a
                # fused bucket belongs to ONE group (never-fuse rule)
                self._emit(LogEntry(
                    self._next_seq(), "group",
                    req_type=int(RequestType.ALLREDUCE),
                    names=[k[1] for k, _ in bucket],
                    shapes=[m["shape"] for _, m in bucket],
                    dtype=first_meta["dtype"], op=first_meta["op"],
                    prescale=first_meta["prescale"],
                    postscale=first_meta["postscale"],
                    compression=first_meta.get("compression", "none"),
                    schedule=first_meta.get("schedule", "auto"),
                    joined=sorted(self._joined),
                    group=first_meta.get("group", ""),
                    group_ranks=first_meta.get("group_ranks")))
            else:
                (_, name), meta = bucket[0]
                self._emit(LogEntry(
                    self._next_seq(), "group", req_type=int(rtype),
                    names=[name], shapes=[meta["shape"]],
                    dtype=meta["dtype"], op=meta["op"],
                    prescale=meta["prescale"],
                    postscale=meta["postscale"],
                    root_rank=meta["root_rank"],
                    compression=meta.get("compression", "none"),
                    all_dims0=meta.get("all_dims0"),
                    splits_matrix=meta.get("splits_matrix"),
                    joined=sorted(self._joined),
                    group=meta.get("group", ""),
                    group_ranks=meta.get("group_ranks")))
        self._maybe_emit_join_done()

    def _join_done_ready(self):  # holds: self._cv
        return (self._joined and len(self._joined) == self._world
                and not self._table)

    def _maybe_emit_join_done(self):  # holds: self._cv
        if self._join_done_ready():
            # the last rank to join in coordinator-arrival order
            # (reference: join() returns the last joining rank so it can
            # seed a broadcast from the most-advanced worker)
            last = self._join_order[-1]
            self._emit(LogEntry(self._next_seq(), "join_done",
                                last_rank=last))
            self._joined.clear()
            self._join_order.clear()
            self._join_epoch += 1

    def _next_seq(self):  # holds: self._cv
        self._seq += 1
        return self._seq

    def _emit(self, entry):  # holds: self._cv
        self._log_entries.append(entry)
        self._cv.notify_all()

    def _trim_log(self):  # holds: self._cv
        """Drop entries every process has acknowledged (via CycleMsg
        last_seq) — never an entry some process hasn't fetched yet."""
        if len(self._log_entries) < 1024 or len(self._acked) < self._nproc:
            return
        floor = min(self._acked.values())
        self._log_entries = [e for e in self._log_entries if e.seq > floor]

    # ------------------------------------------------------------ validation
    def _validate(self, key, entry):  # holds: self._cv
        """Cross-process agreement (reference: ConstructResponse,
        controller.cc:378).  Returns (error, meta)."""
        # sig-exempt: group, group_ranks — agreement is structural here:
        # the entry table is keyed by (group, tensor), so requests from
        # different groups can never land in the same entry to disagree
        # sig-exempt: ring — the ring flag is tcp-transport-local wire
        # negotiation; the global mesh validates at the meta layer and
        # has no ring path to disagree about
        gid, name = key
        # a group entry's world is its member list in spec order; dims /
        # splits matrices are emitted in THAT order so every process
        # re-keys to group-local ranks identically (docs/groups.md)
        member_ranks = (list(entry.group_ranks) if gid
                        else list(range(self._world)))
        gsize = len(member_ranks)
        reqs = list(entry.reqs.values())
        first = reqs[0]

        for r in reqs:
            # a process that failed intra-process validation reports the
            # error so every other process's ranks fail too, instead of
            # executing a misaligned collective
            if getattr(r, "error", None):
                return (r.error, None)
        if any(r.req_type != first.req_type for r in reqs):
            return (f"mismatched collective types for tensor '{name}'",
                    None)
        if any(r.dtype != first.dtype for r in reqs):
            return (f"mismatched dtypes for tensor '{name}'", None)
        rtype = RequestType(first.req_type)

        if self._joined and rtype in (RequestType.ALLGATHER,
                                      RequestType.BROADCAST,
                                      RequestType.ALLTOALL,
                                      RequestType.REDUCE_SCATTER):
            return (f"{rtype.name} is not supported while ranks have "
                    f"joined", None)

        meta = {"req_type": first.req_type, "dtype": first.dtype,
                "op": first.op, "prescale": first.prescale,
                "postscale": first.postscale, "root_rank": first.root_rank,
                "shape": first.shape,
                # cross-process wire-format resolution, same rule as the
                # in-process controllers: unanimous wins, else exact
                "compression": PythonController.resolve_group_compression(
                    getattr(r, "compression", "none") for r in reqs),
                # cross-process schedule resolution: unanimous wins,
                # else auto — and it joins the bucket key above, so
                # requests negotiated for different schedules can never
                # fuse into one program
                "schedule": PythonController.resolve_group_schedule(
                    getattr(r, "schedule", "auto") for r in reqs),
                "group": gid, "group_ranks": entry.group_ranks}

        if rtype in (RequestType.ALLREDUCE, RequestType.ADASUM):
            if any(r.shape != first.shape for r in reqs):
                return (f"mismatched shapes for allreduce '{name}'", None)
            if any(r.op != first.op or r.prescale != first.prescale
                   or r.postscale != first.postscale for r in reqs):
                return (f"mismatched reduce ops or scale factors for "
                        f"tensor '{name}'", None)
        elif rtype == RequestType.REDUCE_SCATTER:
            if any(not r.shape for r in reqs):
                return (f"reduce_scatter '{name}': 0-d tensors are not "
                        f"supported; reshape to (1,) first", None)
            if any(r.shape != first.shape for r in reqs):
                return (f"mismatched shapes for reduce_scatter '{name}'",
                        None)
            if any(r.op != first.op or r.prescale != first.prescale
                   or r.postscale != first.postscale for r in reqs):
                return (f"mismatched reduce ops or scale factors for "
                        f"tensor '{name}'", None)
        elif rtype == RequestType.ALLGATHER:
            trailing = {tuple(r.shape[1:]) for r in reqs}
            if len(trailing) > 1:
                return (f"mismatched trailing dimensions for allgather "
                        f"'{name}'", None)
            if any(not r.shape for r in reqs):
                return (f"allgather '{name}': 0-d tensors are not "
                        f"supported; reshape to (1,) first", None)
            dims = {}
            for r in reqs:
                dims.update(r.dims0 or {})
            missing = [r for r in member_ranks
                       if r not in dims and (gid or r not in self._joined)]
            if missing:
                return (f"allgather '{name}': missing first-dim info for "
                        f"ranks {missing}", None)
            meta["all_dims0"] = [int(dims.get(r, 0))
                                 for r in member_ranks]
        elif rtype == RequestType.BROADCAST:
            if any(r.root_rank != first.root_rank for r in reqs):
                return (f"mismatched root ranks for broadcast '{name}'",
                        None)
            if any(r.shape != first.shape for r in reqs):
                return (f"mismatched shapes for broadcast '{name}'", None)
            if gid and first.root_rank not in member_ranks:
                return (f"broadcast '{name}': root rank "
                        f"{first.root_rank} is not a member of group "
                        f"'{gid}'", None)
            root_pid = self._rank_pid.get(first.root_rank)
            if root_pid is None or root_pid not in entry.reqs \
                    or first.root_rank not in entry.reqs[root_pid].ranks:
                return (f"broadcast '{name}': root rank "
                        f"{first.root_rank} did not participate", None)
        elif rtype == RequestType.ALLTOALL:
            splits = {}
            for r in reqs:
                splits.update(r.splits or {})
            missing = [r for r in member_ranks if r not in splits]
            if missing:
                return (f"alltoall '{name}': missing splits for ranks "
                        f"{missing}", None)
            dims = {}
            for r in reqs:
                dims.update(r.dims0 or {})
            for r, row in splits.items():
                if len(row) != gsize:
                    return (f"alltoall '{name}': splits must have one "
                            f"entry per rank ({gsize})", None)
                if r in dims and sum(row) != dims[r]:
                    return (f"alltoall '{name}': splits sum {sum(row)} "
                            f"!= first dimension {dims[r]} on rank {r}",
                            None)
            meta["splits_matrix"] = [list(splits[r])
                                     for r in member_ranks]
        return (None, meta)

    # ----------------------------------------------------------------- stall
    def _check_stalls(self):  # holds: self._cv
        """Caller holds the lock (reference: StallInspector on rank 0)."""
        now = time.monotonic()
        for key, entry in list(self._table.items()):
            gid, name = key
            label = f"{name} (group '{gid}')" if gid else name
            age = now - entry.first_ts
            if age > self._stall_warning and not entry.stall_warned:
                waiting = sorted(self._entry_required_pids(entry)
                                 - set(entry.reqs.keys()))
                self._log.warning(
                    "Stalled tensor: %s reported by processes %s, waiting "
                    "on processes %s for more than %ds", label,
                    sorted(entry.reqs.keys()), waiting,
                    int(self._stall_warning))
                entry.stall_warned = True
            if self._stall_shutdown > 0 and age > self._stall_shutdown:
                # promoted into a coordinated abort: the first silent
                # REQUIRED process names the origin rank (a fully-joined
                # process legitimately submits nothing and must not take
                # the blame), and EVERY process's ranks fail with the
                # same typed error (not just this name's waiters).
                # Group-scoped entries stamp the lagging GROUP member —
                # and the abort still fails the whole job (docs/groups.md:
                # no half-dead jobs)
                waiting = sorted(self._entry_required_pids(entry)
                                 - set(entry.reqs.keys()))
                if not waiting:
                    origin = -1
                elif gid:
                    origin = min(
                        r for r in entry.group_ranks
                        if self._rank_pid.get(r) == waiting[0])
                else:
                    origin = sum(self._local_sizes[:waiting[0]])
                self._initiate_abort(
                    origin,
                    f"stalled tensor '{label}' exceeded shutdown "
                    f"threshold of {self._stall_shutdown}s (waiting on "
                    f"processes {waiting})")
                return


# ----------------------------------------------------------------- controller
class GlobalMeshController(PythonController):
    """Per-process controller for global-mesh (pod) jobs.

    Local device ranks negotiate in-process exactly like the single-host
    :class:`PythonController`; globally-ready work is discovered through
    the metadata coordinator and executed in coordinator-assigned
    sequence order by every process."""

    def __init__(self, topology, executor, timeline, config):
        super().__init__(topology, executor, timeline, config)
        self._pid = topology.cross_rank
        self._nproc = topology.cross_size
        self._local_size = topology.local_size
        base = self._pid * self._local_size
        self._local_rank_set = set(range(base, base + self._local_size))
        self._reported = set()
        self._joined_reported = set()
        self._join_epoch = 0  # join_done rounds observed
        self._send_fail_since = None
        self._last_seq = 0
        self._last_cycle_sent = time.monotonic()
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._coordinator = None
        self._client_addrs = None
        self._client_obj = None
        self._key = None
        self._coord_autotune = None

    def _owns_autotune(self):
        return False  # tuning happens at the pid-0 metadata coordinator

    # -------------------------------------------------------------- lifecycle
    def start(self):
        key_b64 = env_util.get_str(env_util.HVD_SECRET_KEY)
        if key_b64:
            self._key = base64.b64decode(key_b64)
        else:
            # No shared secret: only acceptable for single-machine runs.
            # A key derived from the (public) rendezvous address would
            # let anyone who can reach the port forge HMACs and drive
            # pickle deserialization — refuse instead of degrading.
            addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
            if addr not in (None, "localhost", "127.0.0.1", "::1"):
                raise RuntimeError(
                    "global-mesh mode on a non-loopback rendezvous "
                    "requires HVD_SECRET_KEY (hvdrun sets it "
                    "automatically); refusing to derive an HMAC key "
                    "from public values")
            import hashlib
            seed = ((addr or "local") +
                    env_util.get_str(env_util.HVD_RENDEZVOUS_PORT, "0"))
            self._key = hashlib.sha256(seed.encode()).digest()

        addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
        port = env_util.get_str(env_util.HVD_RENDEZVOUS_PORT)
        from horovod_tpu.run import http_client
        if self._pid == 0:
            from horovod_tpu.ops.autotune import AutotuneManager
            self._coord_autotune = AutotuneManager.create(self._config,
                                                          self._log)
            # liveness is only meaningful while heartbeats flow: with
            # them off, a quiet-but-healthy process (long compile, gap
            # between steps) would read as dead
            from horovod_tpu.common.config import \
                effective_heartbeat_interval
            liveness = (self._config.liveness_timeout_seconds
                        if effective_heartbeat_interval(self._config) > 0
                        else 0.0)
            self._coordinator = MetaCoordinatorService(
                self._nproc,
                [self._local_size] * self._nproc,
                self._key,
                self._config.fusion_threshold_bytes,
                stall_warning_sec=self._config.stall_warning_seconds,
                stall_shutdown_sec=self._config.stall_shutdown_seconds,
                autotune=self._coord_autotune,
                liveness_timeout_sec=liveness)
            tagged = [(iface, ip, self._coordinator.port)
                      for iface, ip in network.local_interfaces().items()]
            tagged.append(("lo", "127.0.0.1", self._coordinator.port))
            if addr is not None:
                http_client.put(
                    addr, int(port), GMESH_SCOPE, GMESH_KEY,
                    ";".join(f"{i}={ip}:{p}"
                             for i, ip, p in tagged).encode())
            self._client_addrs = self._filter_ifaces(tagged)
        else:
            if addr is None:
                raise RuntimeError(
                    "global-mesh mode requires the rendezvous env "
                    "contract (launch with hvdrun)")
            blob = http_client.get(addr, int(port), GMESH_SCOPE,
                                   GMESH_KEY, timeout=120).decode()
            tagged = []
            for part in blob.split(";"):
                iface, rest = part.split("=", 1)
                ip, p = rest.rsplit(":", 1)
                tagged.append((iface, ip, int(p)))
            self._client_addrs = self._filter_ifaces(tagged)
        super().start()

        # dedicated liveness heartbeat, SEPARATE from the coordination
        # loop: the loop executes collectives synchronously, and a long
        # XLA compile inside one would otherwise read as a dead process
        # at the coordinator.  Same clamp as the tcp controller
        # (heartbeats fully off only when interval AND abort timeout
        # are 0).
        from horovod_tpu.common.config import effective_heartbeat_interval
        interval = effective_heartbeat_interval(self._config)
        if self._nproc > 1 and interval > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(interval,),
                daemon=True, name="hvd-gmesh-heartbeat")
            self._hb_thread.start()

    def _heartbeat_loop(self, interval):
        hb_client = network.MuxClient(self._client_addrs, self._key,
                                      timeout=max(interval, 2.0),
                                      retry_for=0)
        try:
            while not self._hb_stop.wait(timeout=interval):
                try:
                    reply = hb_client.send(
                        network.HeartbeatMsg(self._pid),
                        timeout=max(interval * 2, 5.0))
                except Exception:  # noqa: BLE001 — the coordination
                    # loop's own send/backoff path owns dead-coordinator
                    # handling; a failed beat just means a stale
                    # last_seen entry
                    continue
                ab = getattr(reply, "abort", None)
                if ab is not None:
                    # record for the loop to apply at its next safe
                    # point (the loop owns the table); do NOT re-send an
                    # AbortMsg like the public override would
                    PythonController.abort(self, *ab)
                    return
        finally:
            hb_client.close()

    @staticmethod
    def _filter_ifaces(tagged):
        iface = env_util.get_str(env_util.HVD_IFACE)
        pinned = [(ip, p) for i, ip, p in tagged if i == iface]
        return pinned or [(ip, p) for _, ip, p in tagged]

    def _client(self):
        # one long-lived multiplexed connection: only the
        # coordination-loop thread sends, and the persistent socket skips
        # re-probing the advertised NIC list every cycle
        if self._client_obj is None:
            self._client_obj = network.MuxClient(
                self._client_addrs, self._key, timeout=30)
        return self._client_obj

    def request_drain(self) -> bool:
        """Graceful drain is a tcp-controller capability: the gmesh data
        plane is a single compiled XLA program over a FIXED global mesh —
        jax.distributed cannot shrink the mesh mid-job, so a preempted
        process cannot be drained around (docs/checkpoint.md).  Always
        False; the launcher-side grace window still applies."""
        return False

    def abort(self, origin_rank, reason):
        """Broadcast a coordinated abort: best-effort notify the
        metadata coordinator (which relays the globally-ordered abort
        entry to every process), then fail locally."""
        try:
            self._client().send(network.AbortMsg(origin_rank, reason),
                                timeout=5.0)
        except Exception:  # noqa: BLE001 — local abort still proceeds
            pass
        super().abort(origin_rank, reason)

    def shutdown(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        super().shutdown()
        from horovod_tpu.utils.timeline import publish_and_merge

        publish_and_merge(self._pid, self._nproc,
                          self._config.timeline_path, self._timeline,
                          scope="timeline-gmesh")
        if self._client_obj is not None:
            self._client_obj.close()
            self._client_obj = None
        if self._coordinator is not None:
            self._coordinator.shutdown()
            self._coordinator = None
        if self._coord_autotune is not None:
            self._coord_autotune.close()
            self._coord_autotune = None

    # --------------------------------------------------------- the wire cycle
    def _run_cycle(self, pending):
        with self._lock:
            aborted = self._shutdown_error
        if aborted is not None:
            # post-abort: fail fast instead of polling dead peers
            for request in pending:
                request.handle.set_error(aborted)
            return
        with self._lock:
            self._joined_view = set(self._joined)

        self._absorb(pending)
        if not self._config.stall_check_disable:
            self._check_local_stalls()

        # cross-group concurrency gauge (docs/groups.md), same as the
        # in-process cycle this method overrides
        if self._table:
            from horovod_tpu import groups as groups_mod
            groups_mod.note_inflight(g for (g, _) in self._table)

        # names whose local ranks have all contributed -> report
        # metadata.  A group entry waits on exactly the LOCAL members of
        # its group (joins never stand in for group ranks); the world
        # waits on every non-joined local rank.
        world_needed = self._local_rank_set - self._joined_view
        new_reqs = []
        for key, entry in self._table.items():
            if key in self._reported:
                continue
            needed_local = (self._local_rank_set & set(entry.group_ranks)
                            if entry.group else world_needed)
            if needed_local and not needed_local.issubset(
                    entry.requests.keys()):
                continue
            new_reqs.append(self._meta_for(key, entry))

        newly_joined = sorted(self._joined_view - self._joined_reported)

        with self._lock:
            join_outstanding = bool(self._join_handles)
        # idle processes still report in every heartbeat interval: the
        # coordinator's liveness window needs a steady last-seen signal,
        # and the empty CycleMsg doubles as the abort-state poll
        hb = self._config.heartbeat_interval_seconds
        heartbeat_due = (self._nproc > 1 and hb > 0
                         and time.monotonic() - self._last_cycle_sent >= hb)
        if not (new_reqs or newly_joined or self._reported
                or join_outstanding or heartbeat_due):
            return

        msg = CycleMsg(self._pid, new_reqs, newly_joined, self._last_seq,
                       join_epoch=self._join_epoch)
        try:
            resp = self._client().send(msg)
        except Exception as exc:  # noqa: BLE001 — transient wire failure
            # nothing was marked reported, so every request resends next
            # cycle; nuking local state on the FIRST failure would orphan
            # the coordinator's view of this process — but a dead
            # coordinator must still fail the job, not hang it
            if self._send_fail_since is None:
                self._send_fail_since = time.monotonic()
            self._log.warning(
                "coordinator cycle send failed (will retry): %s", exc)
            if self._client_obj is not None:
                try:
                    self._client_obj.close()
                except Exception:  # noqa: BLE001 — already broken
                    pass
                self._client_obj = None
            outage = time.monotonic() - self._send_fail_since
            if outage > _SEND_FAIL_LIMIT_S:
                # dead coordinator -> typed abort, not a hang: same
                # surface as every other unrecoverable runtime failure
                self._apply_abort(HvdAbortedError(
                    0, f"coordinator unreachable for {int(outage)}s: "
                       f"{exc}"))
                return
            time.sleep(min(0.05 * 2 ** min(
                int(outage), 6), 2.0))  # backoff, then retry
            self._wakeup.set()
            return
        self._send_fail_since = None
        self._last_cycle_sent = time.monotonic()
        # reported only once the coordinator actually received them
        self._reported.update((r.group, r.name) for r in new_reqs)
        self._joined_reported.update(newly_joined)

        for entry in resp.entries:
            self._apply(entry)
            self._last_seq = entry.seq

        # keep polling while work is outstanding
        with self._lock:
            join_outstanding = bool(self._join_handles)
        if self._reported or join_outstanding:
            self._wakeup.set()

    def _meta_for(self, key, entry):
        gid, name = key
        reqs = entry.requests
        # intra-process agreement first (the coordinator only compares
        # ACROSS processes); a local mismatch is reported as an error so
        # every process's ranks fail consistently
        error = PythonController.validate_requests(
            name, reqs,
            size=(len(entry.group_ranks) if gid else self._size),
            joined=bool(self._joined_view) and not gid)
        first = next(iter(reqs.values()))
        shape = tuple(first.tensor.shape) if first.tensor is not None else ()
        dtype = (np.dtype(first.tensor.dtype).name
                 if first.tensor is not None else "float32")
        dims0 = {rank: (r.tensor.shape[0] if r.tensor is not None
                        and r.tensor.ndim else 0)
                 for rank, r in reqs.items()}
        splits = {rank: list(r.splits) for rank, r in reqs.items()
                  if r.splits is not None}
        return MetaReq(
            name=name, req_type=first.req_type, op=first.op, dtype=dtype,
            shape=shape, dims0=dims0, splits=splits,
            root_rank=first.root_rank, prescale=first.prescale_factor,
            postscale=first.postscale_factor, ranks=sorted(reqs.keys()),
            error=error,
            compression=self.resolve_group_compression(
                r.compression for r in reqs.values()),
            schedule=self.resolve_group_schedule(
                getattr(r, "schedule", "auto") for r in reqs.values()),
            group=gid, group_ranks=entry.group_ranks)

    # ------------------------------------------------------------- execution
    def _apply(self, entry):
        if entry.kind == "params":
            self._apply_tuned(entry.params)
            return

        if entry.kind == "abort":
            # coordinated abort: one typed error for every local rank's
            # in-flight handle; the controller stays poisoned so later
            # enqueues fail fast instead of waiting on dead peers
            self._reported.clear()
            self._joined_reported.clear()
            self._apply_abort(HvdAbortedError(
                getattr(entry, "origin", -1), entry.error))
            return

        if entry.kind == "error":
            egid = getattr(entry, "group", "")
            for name in entry.names:
                local = self._table.pop((egid, name), None)
                self._reported.discard((egid, name))
                if local is not None:
                    for request in local.requests.values():
                        request.handle.set_error(entry.error)
            return

        if entry.kind == "join_done":
            with self._lock:
                for handle in self._join_handles.values():
                    handle.set_result(entry.last_rank)
                self._join_handles.clear()
                self._joined.clear()
            self._joined_reported.clear()
            self._joined_view = set()
            self._join_epoch += 1  # stale joined-replays now ignored
            return

        rtype = RequestType(entry.req_type)
        joined_global = set(entry.joined)
        gid = getattr(entry, "group", "")
        granks = (list(entry.group_ranks)
                  if gid and entry.group_ranks else None)
        groups = []
        for name, shape in zip(entry.names, entry.shapes):
            local = self._table.pop((gid, name), None)
            self._reported.discard((gid, name))
            requests = local.requests if local is not None else {}
            if granks is not None:
                # group entries are re-keyed to GROUP-LOCAL ranks (same
                # rule as python_controller._build_group): the executor
                # that runs them is the group's sub-mesh, whose world is
                # 0..len(granks)-1 in member order
                tensors = {granks.index(rank): r.tensor
                           for rank, r in requests.items()}
                handles = {granks.index(rank): r.handle
                           for rank, r in requests.items()}
                root = (granks.index(entry.root_rank)
                        if entry.root_rank in granks else entry.root_rank)
            else:
                tensors = {rank: r.tensor for rank, r in requests.items()}
                for rank in self._local_rank_set:
                    if rank in joined_global or rank not in tensors:
                        tensors.setdefault(rank, None)
                handles = {rank: r.handle for rank, r in requests.items()}
                root = entry.root_rank
            groups.append(GroupEntry(
                name=name, shape=tuple(shape), dtype=np.dtype(entry.dtype),
                tensors=tensors,
                handles=handles,
                root_rank=root,
                splits=(entry.splits_matrix
                        if entry.splits_matrix is not None else None),
                op=ReduceOp(entry.op), prescale_factor=entry.prescale,
                postscale_factor=entry.postscale,
                all_dims0=entry.all_dims0,
                compression=getattr(entry, "compression", "none"),
                schedule=getattr(entry, "schedule", "auto"),
                group=gid,
                group_ranks=(tuple(granks) if granks is not None
                             else None)))
            self._timeline.end(name)

        if granks is not None and not (self._local_rank_set
                                       & set(granks)):
            # no local device belongs to this group: nothing to
            # contribute, and the group's sub-mesh program is not
            # addressable from this process.  The ordered response
            # stream is still consumed in sequence, so SPMD ordering
            # across member processes is untouched.
            return

        # execution + error surfacing shared with the in-process
        # controller (PythonController._execute_allreduce_bucket /
        # _execute_single)
        try:
            if rtype == RequestType.ALLREDUCE:
                self._execute_allreduce_bucket(groups)
            else:
                self._execute_single(rtype, groups[0])
        except Exception as exc:  # noqa: BLE001 — surface on handles
            self._log.error("collective execution failed: %s", exc)
            for g in groups:
                for handle in g.handles.values():
                    handle.set_error(
                        f"collective execution failed: {exc}")

    # ------------------------------------------------------------------ stall
    def _check_local_stalls(self):
        """Warn about names stuck waiting on LOCAL ranks (pre-report);
        once reported, the coordinator owns stall handling."""
        now = time.monotonic()
        warn_after = self._config.stall_warning_seconds
        for key, entry in list(self._table.items()):
            if key in self._reported:
                continue
            gid, name = key
            age = now - entry.first_ts
            if age > warn_after and not entry.stall_warned:
                ready = sorted(entry.requests.keys())
                if entry.group:
                    expected = self._local_rank_set & set(entry.group_ranks)
                    missing = sorted(expected - set(ready))
                    name = f"{name} (group '{gid}')"
                else:
                    missing = sorted(self._local_rank_set - set(ready)
                                     - self._joined_view)
                self._log.warning(
                    "Tensor %s waiting on local ranks %s (ready: %s) for "
                    "more than %ds", name, missing, ready, int(warn_after))
                entry.stall_warned = True
