"""Native controller: ctypes binding over the C++ coordination core.

The background cycle loop, tensor queue, negotiation, response cache, fusion
planning, stall inspection and timeline live in ``csrc/hvd`` (the reference
keeps the same responsibilities in C++: ``horovod/common/operations.cc``,
``controller.cc``).  This module is the thin producer/dispatcher glue:

- rank threads encode metadata requests and hand them to the core
  (``hvd_core_enqueue``); tensors and completion handles stay Python-side,
  keyed by request id;
- one dispatcher thread blocks in ``hvd_core_next_batch`` (GIL released by
  ctypes) and executes each fused ResponseBatch as compiled XLA programs via
  the shared :class:`XlaExecutor`, then reports ``hvd_core_mark_done`` so the
  core can close timeline spans and maintain its cache.
"""

import ctypes
import itertools
import os
import threading

from horovod_tpu.common import wire
from horovod_tpu.common.ops_enum import ReduceOp, ResponseType
from horovod_tpu.ops.python_controller import GroupEntry, PythonController
from horovod_tpu.utils.logging import get_logger

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                         "lib", "libhvdcore.so")


def _build_lib():
    """Build libhvdcore.so in-tree when absent (fresh checkouts don't ship
    binaries; the reference likewise compiles its core at install time,
    reference: setup.py:47-52).

    Multiple ranks on one host may race here on first launch, so the
    existence check and the build run under an exclusive flock; everyone
    re-checks after acquiring it.
    """
    import fcntl
    import subprocess

    csrc = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "csrc")
    if not os.path.isdir(csrc):
        raise OSError(
            f"{_LIB_PATH} is missing and cannot be built automatically "
            f"(no csrc/ tree next to the package); build libhvdcore.so "
            f"with `make -C csrc` from a source checkout")
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    lock_path = os.path.join(os.path.dirname(_LIB_PATH), ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if not _lib_stale():
            return
        try:
            proc = subprocess.run(["make", "-C", csrc],
                                  capture_output=True, text=True)
        except FileNotFoundError:
            raise OSError(
                f"{_LIB_PATH} is missing and `make` is not on PATH; "
                f"build it with `make -C {csrc}`")
        if proc.returncode != 0:
            raise OSError(
                f"building libhvdcore.so failed (make -C {csrc}):\n"
                f"{proc.stdout}\n{proc.stderr}")


def _lib_stale():
    """True when any csrc source is newer than the built library."""
    if not os.path.exists(_LIB_PATH):
        return True
    csrc = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "csrc")
    if not os.path.isdir(csrc):
        return False
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for root, _, files in os.walk(csrc):
        for f in files:
            if f.endswith((".cc", ".h")) or f == "Makefile":
                if os.path.getmtime(os.path.join(root, f)) > lib_mtime:
                    return True
    return False


def _load_lib():
    if _lib_stale():
        _build_lib()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.hvd_core_create.restype = ctypes.c_void_p
    lib.hvd_core_create.argtypes = [ctypes.c_int]
    lib.hvd_core_start.argtypes = [ctypes.c_void_p]
    lib.hvd_core_shutdown.argtypes = [ctypes.c_void_p]
    lib.hvd_core_finalize.argtypes = [ctypes.c_void_p]
    lib.hvd_core_destroy.argtypes = [ctypes.c_void_p]
    lib.hvd_core_enqueue.restype = ctypes.c_int
    lib.hvd_core_enqueue.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.c_size_t]
    lib.hvd_core_join.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_uint64]
    lib.hvd_core_next_batch.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.hvd_core_next_batch.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_size_t)]
    lib.hvd_core_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.hvd_core_mark_done.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_char_p]
    for fn in ("hvd_core_cache_hits", "hvd_core_cache_misses",
               "hvd_core_cache_size"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]

    # Autotuned parameter getters (reference: tuned values synchronized by
    # Controller::SynchronizeParameters; here the dispatcher polls).
    lib.hvd_core_param_fusion_bytes.restype = ctypes.c_int64
    lib.hvd_core_param_fusion_bytes.argtypes = [ctypes.c_void_p]
    lib.hvd_core_param_cycle_ms.restype = ctypes.c_double
    lib.hvd_core_param_cycle_ms.argtypes = [ctypes.c_void_p]
    for fn in ("hvd_core_param_hierarchical_allreduce",
               "hvd_core_param_hierarchical_allgather",
               "hvd_core_param_cache_enabled", "hvd_core_autotune_tuning"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.hvd_core_autotune_best_score.restype = ctypes.c_double
    lib.hvd_core_autotune_best_score.argtypes = [ctypes.c_void_p]

    # Standalone autotune math (GP / BO / ParameterManager), unit-tested
    # against numpy oracles in tests/test_autotune.py.
    dbl_p = ctypes.POINTER(ctypes.c_double)
    lib.hvd_gp_create.restype = ctypes.c_void_p
    lib.hvd_gp_create.argtypes = [ctypes.c_double] * 3
    lib.hvd_gp_destroy.argtypes = [ctypes.c_void_p]
    lib.hvd_gp_fit.restype = ctypes.c_int
    lib.hvd_gp_fit.argtypes = [ctypes.c_void_p, dbl_p, dbl_p, ctypes.c_int,
                               ctypes.c_int]
    lib.hvd_gp_predict.argtypes = [ctypes.c_void_p, dbl_p, ctypes.c_int,
                                   dbl_p, dbl_p]
    lib.hvd_expected_improvement.restype = ctypes.c_double
    lib.hvd_expected_improvement.argtypes = [ctypes.c_double] * 4
    lib.hvd_bo_create.restype = ctypes.c_void_p
    lib.hvd_bo_create.argtypes = [dbl_p, dbl_p, ctypes.c_int, ctypes.c_double,
                                  ctypes.c_int]
    lib.hvd_bo_destroy.argtypes = [ctypes.c_void_p]
    lib.hvd_bo_add_sample.argtypes = [ctypes.c_void_p, dbl_p, ctypes.c_int,
                                      ctypes.c_double]
    lib.hvd_bo_suggest.argtypes = [ctypes.c_void_p, dbl_p, ctypes.c_int]
    lib.hvd_bo_best_y.restype = ctypes.c_double
    lib.hvd_bo_best_y.argtypes = [ctypes.c_void_p]
    lib.hvd_pm_create.restype = ctypes.c_void_p
    lib.hvd_pm_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_double, ctypes.c_char_p,
                                  ctypes.c_int64, ctypes.c_double,
                                  ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int64, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.hvd_pm_destroy.argtypes = [ctypes.c_void_p]
    lib.hvd_pm_record.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.hvd_pm_update.restype = ctypes.c_int
    lib.hvd_pm_update.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.hvd_pm_fusion_bytes.restype = ctypes.c_int64
    lib.hvd_pm_fusion_bytes.argtypes = [ctypes.c_void_p]
    lib.hvd_pm_cycle_ms.restype = ctypes.c_double
    lib.hvd_pm_cycle_ms.argtypes = [ctypes.c_void_p]
    for fn in ("hvd_pm_hierarchical_allreduce",
               "hvd_pm_hierarchical_allgather", "hvd_pm_cache_enabled",
               "hvd_pm_compression_enabled", "hvd_pm_tuning",
               "hvd_pm_ring_stripes", "hvd_pm_schedule"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.hvd_pm_ring_segment_bytes.restype = ctypes.c_int64
    lib.hvd_pm_ring_segment_bytes.argtypes = [ctypes.c_void_p]
    lib.hvd_pm_best_score.restype = ctypes.c_double
    lib.hvd_pm_best_score.argtypes = [ctypes.c_void_p]
    return lib


class NativeController:
    def __init__(self, topology, executor, timeline, config):
        # the core writes the timeline itself; the reference is kept
        # only for the grouped-collective companion controller below
        self._timeline = timeline
        self._topo = topology
        self._executor = executor
        self._config = config
        # Grouped collectives (group= on the eager API) carry fields the
        # embedded C++ core's wire format predates; they are routed to a
        # lazily-created in-process PythonController that shares this
        # controller's executor, so group isolation (sub-executors,
        # (group, name) negotiation keys, never-fuse bucket keys) holds
        # without a binary-format change (docs/groups.md).
        self._companion = None
        self._lib = _load_lib()
        self._core = self._lib.hvd_core_create(topology.size)
        self._pending = {}   # req_id -> (EagerRequest-ish record)
        self._joins = {}     # req_id -> handle
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._thread = None
        self._running = False
        self._log = get_logger()

    # ----------------------------------------------------------- producer API
    def start(self):
        self._running = True
        self._lib.hvd_core_start(self._core)
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        daemon=True, name="hvd-dispatcher")
        self._thread.start()

    def _companion_controller(self):
        with self._lock:
            if not self._running:
                return None
            if self._companion is None:
                timeline = self._timeline
                if timeline is None:
                    # the native path passes timeline=None (the core
                    # writes its own trace); the companion needs a real
                    # (no-op) Timeline object
                    from horovod_tpu.utils.timeline import Timeline
                    timeline = Timeline(None)
                companion = PythonController(self._topo, self._executor,
                                             timeline, self._config)
                companion.start()
                self._companion = companion
            return self._companion

    def enqueue(self, request):
        if getattr(request, "group", ""):
            companion = self._companion_controller()
            if companion is None:
                request.handle.set_error("horovod_tpu has been shut down")
                return
            companion.enqueue(request)
            return
        req_id = next(self._ids)
        tensor = request.tensor
        shape = [] if tensor is None else [int(d) for d in tensor.shape]
        payload = wire.encode_request(
            req_id=req_id, rank=request.rank, req_type=int(request.req_type),
            op=int(request.op),
            dtype=None if tensor is None else tensor.dtype,
            root_rank=request.root_rank, prescale=request.prescale_factor,
            postscale=request.postscale_factor, name=request.name,
            shape=shape, splits=request.splits or [])
        err = ctypes.create_string_buffer(1024)
        with self._lock:
            # the core pointer must not be destroyed (shutdown) between
            # the check and the C call — both sides hold this lock
            if not self._running or self._core is None:
                request.handle.set_error("horovod_tpu has been shut down")
                return
            self._pending[req_id] = request
            rc = self._lib.hvd_core_enqueue(self._core, payload,
                                            len(payload), err, len(err))
        if rc != 0:
            with self._lock:
                self._pending.pop(req_id, None)
            request.handle.set_error(err.value.decode() or "enqueue failed")

    def join(self, rank, handle):
        req_id = next(self._ids)
        with self._lock:
            if not self._running or self._core is None:
                handle.set_error("horovod_tpu has been shut down")
                return
            self._joins[req_id] = handle
            self._lib.hvd_core_join(self._core, rank, req_id)

    def shutdown(self):
        if not self._running:
            return
        self._running = False
        with self._lock:
            companion, self._companion = self._companion, None
        if companion is not None:
            companion.shutdown()
        self._lib.hvd_core_shutdown(self._core)
        drained = True
        if self._thread is not None:
            self._thread.join(timeout=10)
            drained = not self._thread.is_alive()
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
            joins = list(self._joins.values())
            self._joins.clear()
        for request in pending:
            request.handle.set_error("horovod_tpu has been shut down")
        for handle in joins:
            handle.set_error("horovod_tpu has been shut down")
        if drained:
            # close the timeline only after the dispatcher drained its
            # last MarkDone (op End events) — closing inside Shutdown
            # raced it; destroy under the lock so no producer thread is
            # mid-C-call on the pointer
            with self._lock:
                self._lib.hvd_core_finalize(self._core)
                self._lib.hvd_core_destroy(self._core)
                self._core = None
        else:
            # a stuck dispatcher may still touch the core; leak it (the
            # pointer stays VALID — nulling it would turn the stuck
            # dispatcher's next C call into a null-pointer crash)
            self._log.warning(
                "dispatcher did not drain within 10s; leaking the core "
                "and leaving the timeline file unfinalized")

    # ------------------------------------------------------------- statistics
    def _require_core(self):
        if self._core is None:
            raise RuntimeError("horovod_tpu has been shut down")
        return self._core

    def cache_stats(self):
        with self._lock:  # core must not be destroyed mid-call
            core = self._require_core()
            return {
                "hits": int(self._lib.hvd_core_cache_hits(core)),
                "misses": int(self._lib.hvd_core_cache_misses(core)),
                "size": int(self._lib.hvd_core_cache_size(core)),
            }

    def tuned_params(self):
        """Current (possibly autotuned) runtime knob values (reference:
        ParameterManager values after SynchronizeParameters)."""
        lib = self._lib
        with self._lock:  # core must not be destroyed mid-call
            core = self._require_core()
            return {
                "fusion_threshold_bytes": int(
                    lib.hvd_core_param_fusion_bytes(core)),
                "cycle_time_ms": float(lib.hvd_core_param_cycle_ms(core)),
                "hierarchical_allreduce": bool(
                    lib.hvd_core_param_hierarchical_allreduce(core)),
                "hierarchical_allgather": bool(
                    lib.hvd_core_param_hierarchical_allgather(core)),
                "cache_enabled": bool(
                    lib.hvd_core_param_cache_enabled(core)),
                # the embedded core's tuner predates the compression
                # knob; the configured value is reported so the params
                # surface stays uniform across controllers
                "compression": getattr(self._config, "compression",
                                       "none"),
                "tuning": bool(lib.hvd_core_autotune_tuning(core)),
                "best_score_bytes_per_sec": float(
                    lib.hvd_core_autotune_best_score(core)),
            }

    # ------------------------------------------------------------- dispatcher
    def _next_batch(self):
        length = ctypes.c_size_t(0)
        ptr = self._lib.hvd_core_next_batch(self._core, ctypes.byref(length))
        try:
            return bytes(ctypes.cast(
                ptr, ctypes.POINTER(ctypes.c_uint8 * length.value)).contents)
        finally:
            self._lib.hvd_core_free(ptr)

    def _dispatch_loop(self):
        autotune = bool(self._config.autotune)
        while True:
            batch_id, is_shutdown, responses = wire.decode_batch(
                self._next_batch())
            if is_shutdown:
                return
            if autotune:
                # Keep the data plane in step with the tuner's categorical
                # choices (reference: tuned values take effect through
                # SynchronizeParameters).
                params = self.tuned_params()
                self._executor.hierarchical_allreduce = \
                    params["hierarchical_allreduce"]
                self._executor.hierarchical_allgather = \
                    params["hierarchical_allgather"]
                autotune = params["tuning"]  # stop polling once pinned
            error = None
            for resp in responses:
                try:
                    self._execute_response(resp)
                except Exception as exc:  # noqa: BLE001 — surface on handles
                    self._log.error("collective execution failed: %s", exc)
                    error = str(exc)
                    self._fail_response(resp,
                                        f"collective execution failed: {exc}")
            self._lib.hvd_core_mark_done(
                self._core, batch_id,
                error.encode() if error is not None else None)

    def _take(self, req_id):
        with self._lock:
            return self._pending.pop(req_id, None)

    def _fail_response(self, resp, message):
        for _, parts, _, _ in resp["entries"]:
            for _, req_id in parts:
                request = self._take(req_id)
                if request is not None:
                    request.handle.set_error(message)

    def _execute_response(self, resp):
        rtype = ResponseType(resp["type"])

        if rtype == ResponseType.ERROR:
            self._fail_response(resp, resp["error"])
            return

        if rtype == ResponseType.JOIN:
            _, parts, _, last_rank = resp["entries"][0]
            with self._lock:
                handles = [self._joins.pop(req_id, None)
                           for _, req_id in parts]
            for handle in handles:
                if handle is not None:
                    handle.set_result(last_rank)
            return

        groups = []
        for name, parts, joined, root_rank in resp["entries"]:
            requests = {}
            for rank, req_id in parts:
                request = self._take(req_id)
                if request is None:
                    raise RuntimeError(
                        f"lost request {req_id} for tensor '{name}'")
                requests[rank] = request
            any_req = next(iter(requests.values()))
            tensors = {self._local(rank): r.tensor
                       for rank, r in requests.items()}
            for rank in joined:
                tensors[self._local(rank)] = None
            groups.append(GroupEntry(
                name=name, shape=tuple(any_req.tensor.shape),
                dtype=any_req.tensor.dtype, tensors=tensors,
                handles={self._local(rank): r.handle
                         for rank, r in requests.items()},
                root_rank=self._local(root_rank) if root_rank >= 0 else -1,
                splits={self._local(rank): r.splits
                        for rank, r in requests.items()},
                op=ReduceOp(resp["op"]),
                prescale_factor=resp["prescale"],
                postscale_factor=resp["postscale"],
                compression=PythonController.resolve_group_compression(
                    getattr(r, "compression", "none")
                    for r in requests.values())))

        try:
            if rtype in (ResponseType.ALLREDUCE,):
                # The C++ core's fusion key predates the compression
                # knob, so a fused response can mix wire formats —
                # partition here so compressed and uncompressed entries
                # never execute as one program (each partition is still
                # one compiled XLA program).
                by_comp = {}
                for g in groups:
                    by_comp.setdefault(g.compression, []).append(g)
                for comp, subset in by_comp.items():
                    self._executor.allreduce_fused(
                        subset, op=ReduceOp(resp["op"]),
                        prescale_factor=resp["prescale"],
                        postscale_factor=resp["postscale"],
                        compression=comp)
            elif rtype == ResponseType.ADASUM:
                for g in groups:
                    self._executor.adasum(g)
            elif rtype == ResponseType.ALLGATHER:
                for g in groups:
                    self._executor.allgather(g)
            elif rtype == ResponseType.BROADCAST:
                for g in groups:
                    self._executor.broadcast(g)
            elif rtype == ResponseType.ALLTOALL:
                for g in groups:
                    self._executor.alltoall(g)
            elif rtype == ResponseType.REDUCE_SCATTER:
                # never fused by the core (FuseAndPublish only buckets
                # ALLREDUCE), so each group is its own compiled program
                for g in groups:
                    self._executor.reduce_scatter(g)
            else:
                raise RuntimeError(f"unknown response type {rtype}")
        except Exception as exc:
            # the requests were already popped from _pending, so the
            # caller's _fail_response cannot reach these handles — fail
            # them HERE or every waiting rank thread hangs forever
            for g in groups:
                for handle in g.handles.values():
                    handle.set_error(
                        f"collective execution failed: {exc}")
            raise

    def _local(self, global_rank):
        """Global rank -> executor device index (identical in single-process
        device mode; process mode uses the TCP data plane instead)."""
        return global_rank % self._topo.local_size
