"""XLA collective executor — the TPU data plane.

This is the TPU-native replacement for the reference's collective backends
(``horovod/common/ops/{nccl,mpi,gloo}_operations.cc``): fused groups built by
the controller are staged into a stacked, mesh-sharded ``jax.Array`` (the
fusion buffer) and executed by ONE compiled XLA program per steady-state
signature — ``lax.psum`` / ``lax.all_gather`` over the ``hvd`` mesh axis rides
ICI within a slice and DCN across slices.

Design notes (vs the reference):

- The reference caches NCCL communicators and reuses a persistent 64 MB fusion
  buffer (``fusion_buffer_manager.cc``).  Here the analogous steady-state
  object is the **compiled executable**: programs are memoized by fused-group
  signature (op, dtype, shapes, scale factors), so a training loop's recurring
  gradient buckets hit the XLA executable cache after the first step — the
  ResponseCache idea (``response_cache.cc``) mapped onto the compilation model.
- Fusion-buffer "memcpy in/out" (``collective_operations.cc:44``) becomes a
  per-rank jitted concat/split running on that rank's device; XLA fuses the
  reshape/cast/scale into the collective program.
- GPU ready-events + finalizer threads (``gpu_operations.h:92``) are
  unnecessary: JAX's async dispatch returns immediately and consumers block
  only when they touch the result.
"""

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from horovod_tpu.common.compression import (INT8_BLOCK,
                                            quantized_all_gather,
                                            quantized_reduce_scatter,
                                            resolve_compression)
from horovod_tpu.common.ops_enum import ReduceOp, is_float_dtype
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

AXIS = "hvd"

# The hierarchical data plane pads fused buffers so the reduce-scatter
# chunks are equal; the reference rounds its fusion buffer to be divisible
# by local_size * 64 elements the same way (controller.cc:358-376).
FUSION_ALIGN_ELEMS = 64


def _shard_map_gathered(body, mesh, in_specs, out_specs):
    """shard_map whose body returns an all-gathered (hence device-invariant,
    but not statically-inferrable-as-replicated) value."""
    try:
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def _prod(shape):
    return int(math.prod(shape)) if shape else 1


class XlaExecutor:
    """Executes fused collective groups as compiled XLA programs over a 1-D
    device mesh whose axis enumerates logical ranks."""

    def __init__(self, devices, hier_local_size=None):
        self.devices = list(devices)
        self.num_ranks = len(self.devices)
        # The mesh and the rank-enumerating axis name are a subclass hook:
        # MeshExecutor (horovod_tpu/sharding/mesh_executor.py) swaps in a
        # parallel.mesh-vocabulary mesh so model-parallel axes can later
        # share the topology.
        self.mesh, self.axis = self._build_mesh(self.devices)
        self._sharded = NamedSharding(self.mesh, P(self.axis))
        # Multi-process (global-mesh) support: this process only produces
        # and consumes the shards that live on its own devices; the
        # compiled program spans the full mesh (reference analog: each
        # worker contributes its ranks' buffers, NCCL moves the bytes).
        my_pid = jax.process_index()
        self.local_ranks = [
            i for i, d in enumerate(self.devices)
            if getattr(d, "process_index", my_pid) == my_pid]
        self.multiprocess = len(self.local_ranks) != self.num_ranks
        # caches are touched only from the coordinator thread
        self._fuse_in_cache = {}
        self._allreduce_cache = {}
        self._allgather_cache = {}
        self._alltoall_cache = {}
        self._reduce_scatter_cache = {}
        # process-group sub-executors, memoized per rank tuple
        # (docs/groups.md): each carries its own caches, so per-signature
        # programs are effectively keyed (group, signature)
        self._subsets = {}

        # Two-level (cross, local) mesh for hierarchical collectives
        # (reference: NCCLHierarchicalAllreduce intra-node/inter-node split,
        # nccl_operations.cc:162-289).  "local" = ranks sharing fast
        # interconnect (one host's chips / one ICI slice); "cross" rides
        # DCN.  Grouping source: explicit arg > HVD_HIER_LOCAL_SIZE env >
        # device process_index.
        explicit = hier_local_size is not None
        if hier_local_size is None:
            hier_local_size = env_util.get_int(
                env_util.HVD_HIER_LOCAL_SIZE, 0) or None
            explicit = hier_local_size is not None
        if hier_local_size is None:
            per_proc = {}
            for d in self.devices:
                per_proc.setdefault(getattr(d, "process_index", 0),
                                    []).append(d)
            sizes = {len(v) for v in per_proc.values()}
            if len(sizes) == 1:
                hier_local_size = sizes.pop()
        self.hier_mesh = None
        if hier_local_size and 1 < hier_local_size < self.num_ranks:
            try:
                from horovod_tpu.parallel.mesh import hierarchical_mesh
                self.hier_mesh = hierarchical_mesh(hier_local_size,
                                                   self.devices)
            except ValueError as exc:
                if explicit:
                    get_logger().warning(
                        "ignoring HVD_HIER_LOCAL_SIZE=%s: %s — hierarchical "
                        "collectives will run the flat path",
                        hier_local_size, exc)
        elif explicit:
            get_logger().warning(
                "HVD_HIER_LOCAL_SIZE=%s does not define a two-level "
                "hierarchy over %d ranks; hierarchical collectives will "
                "run the flat path", hier_local_size, self.num_ranks)
        # Allreduce/allgather schedules are flipped by config at init and by
        # the autotuner at runtime (pure communication-schedule choices —
        # same numbers either way).  Adasum's hierarchical mode CHANGES THE
        # REDUCTION SEMANTICS (adasum of per-group averages, reference
        # AdasumGpuAllreduceOp), so it is pinned at init and never touched
        # by the tuner.
        self.hierarchical_allreduce = False
        self.hierarchical_allgather = False
        self.adasum_hierarchical = False

    # ------------------------------------------------------------------ utils
    def _build_mesh(self, devices):
        """Return ``(mesh, axis_name)`` — the 1-D rank mesh and the name of
        its rank-enumerating axis.  Subclass hook."""
        return Mesh(np.array(devices), (AXIS,)), AXIS

    def subset(self, ranks):
        """The sub-executor over ``ranks``'s devices (memoized).  Ranks are
        GLOBAL; inside the returned executor they renumber to 0..k-1 in
        the given order, which is how grouped entries are re-keyed before
        execution (python_controller._build_group)."""
        key = tuple(int(r) for r in ranks)
        sub = self._subsets.get(key)
        if sub is None:
            sub = type(self)([self.devices[r] for r in key])
            self._subsets[key] = sub
        return sub

    def commit(self, tensor, rank):
        """Pin a rank's tensor to its device (no-op if already there)."""
        dev = self.devices[rank % self.num_ranks]
        if isinstance(tensor, jax.Array):
            try:
                if tensor.devices() == {dev}:
                    return tensor
            except Exception:  # noqa: BLE001 — fall through to device_put
                pass
        return jax.device_put(tensor, dev)

    def _shard_for(self, replicated, rank):
        """Zero-copy view of a replicated array's shard on rank's device."""
        dev = self.devices[rank]
        for shard in replicated.addressable_shards:
            if shard.device == dev:
                return shard.data
        raise RuntimeError(f"no addressable shard on {dev}")

    def _stack(self, per_rank_bufs, shard_shape, dtype):
        """Assemble the mesh-sharded fusion buffer from this process's
        per-rank shards (``per_rank_bufs``: list in local-rank order).

        Each buffer is pinned to its rank's device first: XLA constant-
        folds programs over empty/trivial shards, and folded outputs land
        on the DEFAULT device regardless of input placement (no-op when
        already resident)."""
        per_rank_bufs = [
            jax.device_put(buf, self.devices[rank])
            for buf, rank in zip(per_rank_bufs, self.local_ranks)]
        global_shape = (self.num_ranks,) + tuple(shard_shape[1:])
        return jax.make_array_from_single_device_arrays(
            global_shape, self._sharded, per_rank_bufs)

    # ------------------------------------------------------- fusion buffer in
    def _fuse_in(self, tensors, sizes, dtype):
        """Concat one rank's tensors into a flat [1, total] buffer on its
        device (reference: MemcpyInFusionBuffer)."""
        key = (tuple(sizes), np.dtype(dtype).name)
        fn = self._fuse_in_cache.get(key)
        if fn is None:
            def fuse(*ts):
                return jnp.concatenate(
                    [t.reshape(-1) for t in ts]).reshape(1, -1)
            fn = jax.jit(fuse)
            self._fuse_in_cache[key] = fn
        return fn(*tensors)

    def _zeros_buf(self, total, dtype, rank):
        """Zero stand-in buffer for a joined rank (reference:
        tensor_queue.cc GetTensorEntriesFromResponse joined path)."""
        return jax.device_put(np.zeros((1, total), dtype=dtype),
                              self.devices[rank])

    # -------------------------------------------------------------- allreduce
    def _effective_compression(self, compression, dtype, total):
        """Resolve the on-the-wire compression for a fused group: exact
        passthrough for non-float dtypes, for tensors too small to pay
        the scale overhead, for single-rank meshes, and for casts that
        would be no-ops (bf16 of bf16, fp16 of fp16).  Deterministic in
        (dtype, total), so every process of a multi-process job resolves
        the coordinator's bucket identically."""
        comp = resolve_compression(compression) if compression else "none"
        if comp == "none":
            return comp
        npdt = np.dtype(dtype)
        if not is_float_dtype(npdt) or self.num_ranks == 1:
            return "none"
        if comp == "bf16" and npdt.name == "bfloat16":
            return "none"
        if comp == "fp16" and npdt == np.float16:
            return "none"
        if comp == "int8" and total < INT8_BLOCK:
            return "none"
        return comp

    def allreduce_fused(self, entries, op, prescale_factor, postscale_factor,
                        compression="none"):
        """Execute a fused allreduce group.

        ``entries`` is a list of group entries with ``.shape``, ``.dtype``,
        ``.tensors`` (rank -> committed array, or None for joined ranks) and
        ``.handles`` (rank -> Handle).  All entries share one dtype (and
        one ``compression`` — the bucket key separates them).
        """
        shapes = tuple(tuple(e.shape) for e in entries)
        sizes = [_prod(s) for s in shapes]
        total = sum(sizes)
        dtype = entries[0].dtype
        comp = self._effective_compression(compression, dtype, total)

        bufs = []
        for rank in self.local_ranks:
            tensors = [e.tensors.get(rank) for e in entries]
            if all(t is None for t in tensors):
                bufs.append(self._zeros_buf(total, dtype, rank))
            elif any(t is None for t in tensors):
                # mixed bucket (the rank joined between two entries'
                # submissions): zero ONLY the absent entries — zeroing
                # the whole buffer would silently drop this rank's real
                # contributions to the present ones
                filled = [t if t is not None
                          else jax.device_put(
                              np.zeros(shapes[i], dtype),
                              self.devices[rank])
                          for i, t in enumerate(tensors)]
                bufs.append(self._fuse_in(filled, sizes, dtype))
            else:
                bufs.append(self._fuse_in(tensors, sizes, dtype))
        garr = self._stack(bufs, (1, total), dtype)

        hierarchical = bool(self.hierarchical_allreduce
                            and self.hier_mesh is not None)
        key = (shapes, np.dtype(dtype).name, int(op),
               float(prescale_factor), float(postscale_factor), hierarchical,
               comp)
        fn = self._allreduce_cache.get(key)
        if fn is None and comp == "int8":
            fn = self._build_int8_allreduce(
                shapes, sizes, total, dtype, op, prescale_factor,
                postscale_factor, hierarchical)
            self._allreduce_cache[key] = fn
        if fn is None:
            num_ranks = self.num_ranks
            axis = self.axis
            # Cast compression (bf16/fp16): the collective itself runs in
            # the narrow dtype — XLA fuses the casts into the program and
            # every leg (ICI and DCN) moves half the bytes (reference:
            # fp16 compression, horovod/torch/compression.py:45).
            wire_dt = {"bf16": jnp.bfloat16,
                       "fp16": jnp.float16}.get(comp)
            # Integer tensors: the reduction stays exact in the integer
            # dtype and ALL scaling (pre x post x 1/n, which commutes
            # with the sum) happens once in float32 with a cast back —
            # casting a fractional factor to an int dtype would truncate
            # it to 0 and silently zero every result, and int/int true
            # division would silently change the output dtype.
            int_dtype = not np.issubdtype(np.dtype(dtype), np.floating)

            def flat_body(shard):  # shard: [1, total] on one rank
                x = shard
                if prescale_factor != 1.0 and not int_dtype:
                    x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
                if wire_dt is not None:
                    x = x.astype(wire_dt)
                return jax.lax.psum(x, axis)

            def hier_body(shard):
                # reduce-scatter on ICI -> cross allreduce on DCN ->
                # allgather on ICI (reference: nccl_operations.cc:162-289:
                # ncclReduceScatter -> MPI allreduce -> ncclAllgather).
                x = shard.reshape(-1)
                if prescale_factor != 1.0 and not int_dtype:
                    x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
                if wire_dt is not None:
                    x = x.astype(wire_dt)
                local = self.hier_mesh.shape["local"]
                align = local * FUSION_ALIGN_ELEMS
                padded = -(-total // align) * align
                if padded != total:
                    x = jnp.pad(x, (0, padded - total))
                chunk = jax.lax.psum_scatter(x, "local", scatter_dimension=0,
                                             tiled=True)
                chunk = jax.lax.psum(chunk, "cross")
                full = jax.lax.all_gather(chunk, "local", tiled=True)
                return full[:total][None]

            def fused(g):
                if hierarchical:
                    red = _shard_map_gathered(
                        hier_body, self.hier_mesh,
                        P(("cross", "local")), P())(g)
                else:
                    red = _shard_map(flat_body, mesh=self.mesh,
                                     in_specs=P(axis), out_specs=P())(g)
                flat = red.reshape(-1)
                if wire_dt is not None:
                    flat = flat.astype(dtype)
                if int_dtype:
                    factor = prescale_factor * postscale_factor
                    if op == ReduceOp.AVERAGE:
                        factor /= num_ranks
                    if factor != 1.0:
                        # float64 when x64 is on; otherwise f32 caps
                        # exactness at 2**24 — large int sums can lose
                        # low bits (the tcp plane scales in f64)
                        sdt = (jnp.float64 if jax.config.jax_enable_x64
                               else jnp.float32)
                        flat = (flat.astype(sdt)
                                * factor).astype(flat.dtype)
                else:
                    if op == ReduceOp.AVERAGE:
                        flat = flat / jnp.asarray(num_ranks,
                                                  dtype=flat.dtype)
                    if postscale_factor != 1.0:
                        flat = flat * jnp.asarray(postscale_factor,
                                                  dtype=flat.dtype)
                outs = []
                offset = 0
                for size, shape in zip(sizes, shapes):
                    outs.append(
                        jax.lax.slice(flat, (offset,),
                                      (offset + size,)).reshape(shape))
                    offset += size
                return tuple(outs)

            fn = jax.jit(fused, donate_argnums=0)
            self._allreduce_cache[key] = fn

        outs = fn(garr)
        for entry, out in zip(entries, outs):
            for rank, handle in entry.handles.items():
                handle.set_result(self._shard_for(out, rank))

    def _build_int8_allreduce(self, shapes, sizes, total, dtype, op,
                              prescale_factor, postscale_factor,
                              hierarchical):
        """Compile the block-scaled int8 fused allreduce (EQuARX,
        arXiv:2506.17615): quantize inside the jitted program, exchange
        int8 + fp32 block scales via ``all_to_all`` (the reduce-scatter
        leg), accumulate in fp32, requantize the reduced chunk before the
        allgather leg, dequantize on unpack.  Each element passes through
        exactly two quantizations regardless of rank count.  On the
        hierarchical mesh the quantized legs run over the fast "local"
        axis and the owned chunk crosses DCN once in fp32 (already
        1/local_size of the payload)."""
        num_ranks = self.num_ranks
        hier = bool(hierarchical and self.hier_mesh is not None)
        mesh = self.hier_mesh if hier else self.mesh
        axis = "local" if hier else self.axis
        n_split = mesh.shape["local"] if hier else num_ranks
        chunk = -(-total // (n_split * INT8_BLOCK)) * INT8_BLOCK
        padded = chunk * n_split
        in_spec = P(("cross", "local")) if hier else P(self.axis)

        def body(shard):  # [1, total] on one rank
            x = shard.reshape(-1).astype(jnp.float32)
            if prescale_factor != 1.0:
                x = x * prescale_factor
            x = jnp.pad(x, (0, padded - total))
            red = quantized_reduce_scatter(x.reshape(n_split, chunk), axis)
            if hier:
                red = jax.lax.psum(red, "cross")
            full = quantized_all_gather(red, axis)
            return full[:total][None]

        def fused(g):
            red = _shard_map_gathered(body, mesh, in_spec, P())(g)
            flat = red.reshape(-1)  # fp32 accumulate
            if op == ReduceOp.AVERAGE:
                flat = flat / num_ranks
            if postscale_factor != 1.0:
                flat = flat * postscale_factor
            flat = flat.astype(dtype)
            outs = []
            offset = 0
            for size, shape in zip(sizes, shapes):
                outs.append(
                    jax.lax.slice(flat, (offset,),
                                  (offset + size,)).reshape(shape))
                offset += size
            return tuple(outs)

        return jax.jit(fused, donate_argnums=0)

    # -------------------------------------------------------------- allgather
    def allgather(self, entry):
        """Allgather with per-rank variable first dimension (reference:
        controller.cc:453-518 computes recvcounts/displacements; here the
        compiled program pads to max(dim0), all-gathers over the mesh and
        concatenates the valid rows)."""
        dtype = entry.dtype
        if getattr(entry, "all_dims0", None) is not None:
            # multi-process: per-rank first dims were negotiated globally
            dims0 = [int(d) for d in entry.all_dims0]
            some_local = entry.tensors[self.local_ranks[0]]
            rest = tuple(some_local.shape[1:])
        else:
            shapes_all = tuple(tuple(entry.tensors[r].shape)
                               for r in range(self.num_ranks))
            dims0 = [s[0] if s else 1 for s in shapes_all]
            rest = shapes_all[0][1:]
        max0 = max(dims0)

        hierarchical = bool(self.hierarchical_allgather
                            and self.hier_mesh is not None)
        key = (tuple(dims0), rest, np.dtype(dtype).name, hierarchical)
        fn = self._allgather_cache.get(key)
        if fn is None:
            axis = self.axis

            def pad(t, n0=max0):
                padded = jnp.zeros((1, n0) + t.shape[1:], dtype=t.dtype)
                return jax.lax.dynamic_update_slice(
                    padded, t[None], (0,) * (t.ndim + 1))

            def body(shard):  # [1, max0, *rest]
                return jax.lax.all_gather(shard[0], axis)  # [N, max0, *rest]

            def hier_body(shard):
                # gather within the fast local group first, then move the
                # assembled block once across the slow axis (reference:
                # MPIHierarchicalAllgather's node-leader + shared-memory
                # two-phase gather, mpi_operations.cc).  Rank order is
                # (cross major, local minor), matching host:slots rank
                # numbering, so the reshape restores flat rank order.
                g_local = jax.lax.all_gather(shard[0], "local")
                g = jax.lax.all_gather(g_local, "cross")  # [C, L, max0, ...]
                return g.reshape((self.num_ranks,) + g.shape[2:])

            def gather(g):
                if hierarchical:
                    full = _shard_map_gathered(
                        hier_body, self.hier_mesh,
                        P(("cross", "local")), P())(g)
                else:
                    full = _shard_map_gathered(body, self.mesh,
                                               P(axis), P())(g)
                parts = [jax.lax.slice_in_dim(full[i], 0, dims0[i], axis=0)
                         for i in range(self.num_ranks)]
                return jnp.concatenate(parts, axis=0)

            fn = (jax.jit(pad), jax.jit(gather, donate_argnums=0))
            self._allgather_cache[key] = fn

        pad_fn, gather_fn = fn
        bufs = [pad_fn(entry.tensors[r]) for r in self.local_ranks]
        garr = self._stack(bufs, (1, max0) + rest, dtype)
        out = gather_fn(garr)
        for rank, handle in entry.handles.items():
            handle.set_result(self._shard_for(out, rank))

    # --------------------------------------------------------- reduce_scatter
    def reduce_scatter(self, entry):
        """Reduce + scatter row blocks of the first dimension: rank ``r``
        receives ``reduce_scatter_split_sizes(dim0, N)[r]`` rows of the
        reduced tensor (np.array_split partition, shared with the TCP
        planes).  The first half of the ZeRO decomposition (PAPERS.md
        arXiv:2004.13336) as an eager collective; int8 compression reuses
        the quantized reduce-scatter wire format from the fused allreduce.
        """
        from horovod_tpu.common.ops_enum import reduce_scatter_split_sizes

        shape = tuple(entry.shape)
        rest = shape[1:]
        total = _prod(shape)
        dtype = entry.dtype
        num_ranks = self.num_ranks
        counts = reduce_scatter_split_sizes(shape[0], num_ranks)
        offsets = [sum(counts[:r]) for r in range(num_ranks)]
        op = entry.op
        prescale_factor = entry.prescale_factor
        postscale_factor = entry.postscale_factor
        comp = self._effective_compression(entry.compression, dtype, total)

        bufs = [self._fuse_in([entry.tensors[r]], [total], dtype)
                for r in self.local_ranks]
        garr = self._stack(bufs, (1, total), dtype)

        key = ("reduce_scatter", shape, np.dtype(dtype).name, int(op),
               float(prescale_factor), float(postscale_factor), comp)
        fn = self._reduce_scatter_cache.get(key)
        if fn is None:
            axis = self.axis
            wire_dt = {"bf16": jnp.bfloat16,
                       "fp16": jnp.float16}.get(comp)
            int_dtype = not np.issubdtype(np.dtype(dtype), np.floating)

            if comp == "int8":
                chunk = -(-total // (num_ranks * INT8_BLOCK)) * INT8_BLOCK
                padded = chunk * num_ranks

                def body(shard):  # [1, total] on one rank
                    x = shard.reshape(-1).astype(jnp.float32)
                    if prescale_factor != 1.0:
                        x = x * prescale_factor
                    x = jnp.pad(x, (0, padded - total))
                    red = quantized_reduce_scatter(
                        x.reshape(num_ranks, chunk), axis)
                    full = quantized_all_gather(red, axis)
                    return full[:total][None]
            else:
                def body(shard):
                    x = shard
                    if prescale_factor != 1.0 and not int_dtype:
                        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
                    if wire_dt is not None:
                        x = x.astype(wire_dt)
                    return jax.lax.psum(x, axis)

            def fused(g):
                if comp == "int8":
                    red = _shard_map_gathered(body, self.mesh,
                                              P(axis), P())(g)
                else:
                    red = _shard_map(body, mesh=self.mesh,
                                     in_specs=P(axis), out_specs=P())(g)
                flat = red.reshape(-1)
                if wire_dt is not None:
                    flat = flat.astype(dtype)
                if comp == "int8":
                    if op == ReduceOp.AVERAGE:
                        flat = flat / num_ranks
                    if postscale_factor != 1.0:
                        flat = flat * postscale_factor
                    flat = flat.astype(dtype)
                elif int_dtype:
                    factor = prescale_factor * postscale_factor
                    if op == ReduceOp.AVERAGE:
                        factor /= num_ranks
                    if factor != 1.0:
                        sdt = (jnp.float64 if jax.config.jax_enable_x64
                               else jnp.float32)
                        flat = (flat.astype(sdt)
                                * factor).astype(flat.dtype)
                else:
                    if op == ReduceOp.AVERAGE:
                        flat = flat / jnp.asarray(num_ranks,
                                                  dtype=flat.dtype)
                    if postscale_factor != 1.0:
                        flat = flat * jnp.asarray(postscale_factor,
                                                  dtype=flat.dtype)
                full = flat.reshape(shape)
                return tuple(
                    jax.lax.slice_in_dim(full, offsets[r],
                                         offsets[r] + counts[r], axis=0)
                    for r in range(num_ranks))

            fn = jax.jit(fused, donate_argnums=0)
            self._reduce_scatter_cache[key] = fn

        outs = fn(garr)
        for rank, handle in entry.handles.items():
            handle.set_result(self._shard_for(outs[rank], rank))

    # -------------------------------------------------------------- broadcast
    def broadcast(self, entry):
        """Replicate the root rank's tensor to every rank's device
        (reference: MPIBroadcast / NCCLBroadcast).

        Single-process: direct XLA replication transfer.  Multi-process:
        one compiled program — non-root ranks contribute zero rows to the
        mesh-stacked buffer and a ``psum`` over the rank axis materializes
        the root's data everywhere (data rides ICI/DCN collectives, never
        the host control plane)."""
        if not self.multiprocess:
            src = entry.tensors[entry.root_rank]
            replicated = jax.device_put(src, NamedSharding(self.mesh, P()))
            for rank, handle in entry.handles.items():
                handle.set_result(self._shard_for(replicated, rank))
            return

        shape = tuple(entry.shape)
        total = _prod(shape)
        dtype = entry.dtype
        bufs = []
        for rank in self.local_ranks:
            if rank == entry.root_rank:
                bufs.append(self._fuse_in([entry.tensors[rank]], [total],
                                          dtype))
            else:
                bufs.append(self._zeros_buf(total, dtype, rank))
        garr = self._stack(bufs, (1, total), dtype)

        key = ("broadcast", shape, np.dtype(dtype).name)
        fn = self._allreduce_cache.get(key)
        if fn is None:
            axis = self.axis

            def fused(g):
                def body(shard):
                    x = shard
                    # pred/int psum: sum of one real row + zeros is exact
                    if x.dtype == jnp.bool_:
                        x = x.astype(jnp.uint8)
                    out = jax.lax.psum(x, axis)
                    return out.astype(shard.dtype)
                red = _shard_map(body, mesh=self.mesh,
                                 in_specs=P(axis), out_specs=P())(g)
                return red.reshape(shape)

            fn = jax.jit(fused, donate_argnums=0)
            self._allreduce_cache[key] = fn

        out = fn(garr)
        for rank, handle in entry.handles.items():
            handle.set_result(self._shard_for(out, rank))

    # ----------------------------------------------------------------- adasum
    def adasum(self, entry):
        """Adasum reduction of one named tensor (reference:
        AdasumMPIAllreduceOp / AdasumGpuAllreduceOp).  Zero stand-ins from
        joined ranks fall out naturally: a zero-norm operand contributes
        plain addition."""
        from horovod_tpu.ops.adasum import (adasum_reduce_hierarchical,
                                            adasum_reduce_stacked)

        shape = tuple(entry.shape)
        total = _prod(shape)
        dtype = entry.dtype
        bufs = []
        for rank in self.local_ranks:
            t = entry.tensors.get(rank)
            if t is None:
                bufs.append(self._zeros_buf(total, dtype, rank))
            else:
                bufs.append(self._fuse_in([t], [total], dtype))
        garr = self._stack(bufs, (1, total), dtype)

        # Hierarchical Adasum (reference: AdasumGpuAllreduceOp — NCCL
        # reduce-scatter intra-node, VHDD across nodes, allgather back)
        # needs a power-of-two cross size for the VHDD pairing tree.  Pinned
        # at init (adasum_hierarchical), NOT autotuned: the two modes
        # combine gradients differently by design.
        hierarchical = bool(
            self.adasum_hierarchical and self.hier_mesh is not None
            and (self.hier_mesh.shape["cross"]
                 & (self.hier_mesh.shape["cross"] - 1)) == 0)
        key = ("adasum", shape, np.dtype(dtype).name, hierarchical)
        fn = self._allreduce_cache.get(key)
        if fn is None:
            if hierarchical:
                def fused(g):
                    def body(shard):
                        return adasum_reduce_hierarchical(
                            shard[0], local_axis="local",
                            cross_axis="cross")[None]
                    return _shard_map_gathered(
                        body, self.hier_mesh,
                        P(("cross", "local")), P())(g).reshape(shape)
            else:
                axis = self.axis

                def fused(g):
                    def body(shard):
                        gathered = jax.lax.all_gather(shard[0], axis)
                        return adasum_reduce_stacked(gathered)
                    return _shard_map_gathered(
                        body, self.mesh, P(axis), P())(g).reshape(shape)

            fn = jax.jit(fused, donate_argnums=0)
            self._allreduce_cache[key] = fn

        out = fn(garr)
        for rank, handle in entry.handles.items():
            handle.set_result(self._shard_for(out, rank))

    # --------------------------------------------------------------- alltoall
    def alltoall(self, entry):
        """Variable-split all-to-all as ONE compiled XLA program (API
        parity with later reference versions; also the Ulysses
        sequence-parallel primitive).

        Each rank pads its per-destination segments to the global max
        split, the compiled program runs ``lax.all_to_all`` over the mesh
        axis, and a second compiled program (keyed by the negotiated
        receive splits) slices out the valid rows — the same pad/slice
        trick the variable-dim allgather uses.  Replaces the round-1
        host-orchestrated per-destination ``device_put`` loop.  Sizing
        logic mirrors ``controller.cc:453-518`` recvcounts/displacements.
        """
        num_ranks = self.num_ranks
        splits_matrix = tuple(tuple(int(s) for s in entry.splits[r])
                              for r in range(num_ranks))
        some_local = entry.tensors[self.local_ranks[0]]
        rest = tuple(some_local.shape[1:])
        dtype = entry.dtype
        max_split = max((max(row) if row else 0)
                        for row in splits_matrix) or 1

        key = (splits_matrix, rest, np.dtype(dtype).name)
        fns = self._alltoall_cache.get(key)
        if fns is None:
            axis = self.axis

            def make_pad(row):
                # [sum(row), *rest] -> [1, N, max_split, *rest]
                def pad(t):
                    out = jnp.zeros((num_ranks, max_split) + rest,
                                    dtype=t.dtype)
                    off = 0
                    for dst, n in enumerate(row):
                        if n:
                            seg = jax.lax.slice_in_dim(t, off, off + n,
                                                       axis=0)
                            out = jax.lax.dynamic_update_slice(
                                out, seg[None],
                                (dst, 0) + (0,) * len(rest))
                        off += n
                    return out[None]
                return jax.jit(pad)

            def exchange(g):  # [N, N, max_split, *rest] sharded on axis 0
                def body(shard):
                    return jax.lax.all_to_all(
                        shard[0], axis, split_axis=0, concat_axis=0)[None]
                return _shard_map(body, mesh=self.mesh,
                                  in_specs=P(axis), out_specs=P(axis))(g)

            def make_unpack(recv_row):
                # [N, max_split, *rest] -> [sum(recv_row), *rest]
                def unpack(x):
                    parts = [jax.lax.slice_in_dim(x[src], 0, n, axis=0)
                             for src, n in enumerate(recv_row) if n]
                    if not parts:
                        return jnp.zeros((0,) + rest, dtype=x.dtype)
                    return jnp.concatenate(parts, axis=0)
                return jax.jit(unpack)

            pad_fns = {r: make_pad(splits_matrix[r])
                       for r in self.local_ranks}
            unpack_fns = {
                r: make_unpack(tuple(splits_matrix[src][r]
                                     for src in range(num_ranks)))
                for r in self.local_ranks}
            fns = (pad_fns, jax.jit(exchange, donate_argnums=0),
                   unpack_fns)
            self._alltoall_cache[key] = fns

        pad_fns, exchange_fn, unpack_fns = fns
        bufs = [pad_fns[r](entry.tensors[r]) for r in self.local_ranks]
        garr = self._stack(bufs, (1, num_ranks, max_split) + rest, dtype)
        out = exchange_fn(garr)
        for rank, handle in entry.handles.items():
            recv_splits = [splits_matrix[src][rank]
                           for src in range(num_ranks)]
            shard = self._shard_for(out, rank)[0]  # [N, max_split, *rest]
            handle.set_result((unpack_fns[rank](shard), recv_splits))
