"""Ring attention: sequence/context parallelism over the ICI ring.

The reference framework has no long-context support (SURVEY §5: no ring
attention / sequence parallelism anywhere in d3v3l0/horovod); this module is
the TPU-native design for it.  Queries stay resident on their shard while
key/value blocks rotate around the mesh axis with ``jax.lax.ppermute`` —
each hop rides one ICI link, so communication overlaps with the local
blockwise attention compute (XLA schedules the collective-permute
asynchronously against the einsums).

Numerical scheme: streaming (online) softmax in float32 — the same
log-sum-exp accumulation flash attention uses — so the result is exact
attention, independent of how many ring steps the K/V visit takes.

Usage: call :func:`ring_attention` *inside* a ``shard_map`` whose mesh has
the sequence axis, or use :func:`ring_self_attention` which wraps the
shard_map for you.

Shapes (per shard): q ``[B, Tq, H, D]``, k/v ``[B, Tkv, H, D]`` with the
global sequence dimension split over ``axis_name``.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel._compat import axis_size
# unchecked: jax's replication checker mis-infers through the
# grad-of-cond in the ring step on some releases (the error text
# itself prescribes check_rep=False as the workaround)
from horovod_tpu.parallel._compat import shard_map_unchecked as shard_map


_NEG_INF = -1e30


def _block_attend(q, k, v, *, scale, mask=None):
    """One blockwise attention step; returns (numerator, denom, running max)
    contributions in float32.

    q: [B, Tq, H, D]; k, v: [B, Tkv, H, D].
    mask: broadcastable to [B, H, Tq, Tkv] (True = attend) or None.
    """
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                      # [B, H, Tq]
    p = jnp.exp(s - m[..., None])
    # Fully-masked rows have m == _NEG_INF; exp(s - m) would be 1 there.
    p = jnp.where(m[..., None] > _NEG_INF / 2, p, 0.0)
    l = jnp.sum(p, axis=-1)                      # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, l, m


def _combine(o1, l1, m1, o2, l2, m2):
    """Merge two streaming-softmax partial results (flash-attention rule)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    a1 = jnp.where(m1 > _NEG_INF / 2, a1, 0.0)
    a2 = jnp.where(m2 > _NEG_INF / 2, a2, 0.0)
    o = o1 * a1.transpose(0, 2, 1)[..., None] + \
        o2 * a2.transpose(0, 2, 1)[..., None]
    l = l1 * a1 + l2 * a2
    return o, l, m


def ring_attention(q, k, v, *, axis_name, causal=False, scale=None,
                   query_chunk_idx=None, use_flash=None):
    """Exact multi-head attention with K/V blocks rotating over ``axis_name``.

    Must run inside ``shard_map``/``pmap`` with ``axis_name`` bound; the
    global sequence dimension of q/k/v is split across that axis.

    causal: positions are global — shard ``i`` holds queries
    ``[i*Tq, (i+1)*Tq)`` and keys ``[i*Tkv, (i+1)*Tkv)``.  Off-diagonal
    blocks fully behind the queries are computed unmasked; blocks fully
    ahead are skipped via ``lax.cond`` (no FLOPs on the MXU for them).

    use_flash: compute each local block with the Pallas flash kernel
    (``ops/pallas/flash_attention.py``) instead of the dense einsum —
    O(block) VMEM instead of the O(Tq*Tkv) score matrix.  Default: on
    when running on TPU.  The kernel's logsumexp output feeds the same
    streaming-softmax combine as the dense path, so results are exact
    either way.
    """
    p_size = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name) if query_chunk_idx is None \
        else query_chunk_idx
    b, tq, h, d = q.shape
    tkv = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"

    q32 = q.astype(jnp.float32)
    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    m0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    # Newer shard_map tracks varying-manual-axes: the accumulators become
    # device-varying inside the loop, so the initial carry must be too.
    if hasattr(lax, "pcast"):
        o0, l0, m0 = (lax.pcast(x, (axis_name,), to="varying")
                      for x in (o0, l0, m0))
    elif hasattr(lax, "pvary"):  # pragma: no cover
        o0, l0, m0 = (lax.pvary(x, (axis_name,)) for x in (o0, l0, m0))

    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    def _flash_block(kc, vc, kv_idx):
        """Local block via the Pallas kernel.  On the diagonal block the
        global causal mask reduces to the local one (tq == tkv and equal
        offsets), behind-blocks are unmasked, ahead-blocks were already
        skipped — so the kernel's static `causal` flag suffices."""
        from horovod_tpu.ops.pallas.flash_attention import flash_attention

        def run(is_causal):
            out, lse = flash_attention(
                q, kc.astype(q.dtype), vc.astype(q.dtype),
                causal=is_causal, scale=scale, return_lse=True)
            # represent as (numerator, denom, max): normalized out with
            # denom=1 in lse units plugs into the same _combine rule
            ones = jnp.ones((b, h, tq), jnp.float32)
            if hasattr(lax, "pcast"):
                ones = lax.pcast(ones, (axis_name,), to="varying")
            elif hasattr(lax, "pvary"):  # pragma: no cover
                ones = lax.pvary(ones, (axis_name,))
            return (out.astype(jnp.float32), ones, lse)

        if causal:
            return lax.cond(kv_idx == my_idx,
                            lambda _: run(True),
                            lambda _: run(False), operand=None)
        return run(False)

    def block(o, l, m, kc, vc, kv_idx):
        def attend(_):
            # the kernel's local causal mask only matches the global one
            # on equal-length shards; fall back to the dense path else
            if use_flash and (not causal or tq == tkv):
                return _flash_block(kc, vc, kv_idx)
            if causal:
                q_pos = my_idx * tq + jnp.arange(tq)
                k_pos = kv_idx * tkv + jnp.arange(tkv)
                msk = q_pos[:, None] >= k_pos[None, :]
                msk = msk[None, None, :, :]
            else:
                msk = None
            return _block_attend(q32, kc, vc, scale=scale, mask=msk)

        def skip(_):
            return (jnp.zeros_like(o), jnp.zeros_like(l),
                    jnp.full_like(m, _NEG_INF))

        if causal:
            # Skip blocks strictly in the future of every query on this shard
            # (assumes tq == tkv sharding of one global sequence).
            need = (kv_idx * tkv) <= (my_idx * tq + tq - 1)
            bo, bl, bm = lax.cond(need, attend, skip, operand=None)
        else:
            bo, bl, bm = attend(None)
        return _combine(o, l, m, bo, bl, bm)

    # Peel the resident (local) K/V block so the scan does exactly
    # p_size - 1 permutes — no discarded final rotation on the ICI.
    # K/V rotate in their ORIGINAL dtype: upcasting first would double
    # the ICI bytes per hop for bf16 activations, and both local paths
    # cast per block anyway (_block_attend to f32, flash to q.dtype).
    o0, l0, m0 = block(o0, l0, m0, k, v, my_idx)

    def step(carry, s):
        o, l, m, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        kv_idx = (my_idx - s) % p_size      # origin shard of current K/V
        o, l, m = block(o, l, m, kc, vc, kv_idx)
        return (o, l, m, kc, vc), None

    (o, l, m, _, _), _ = lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(1, p_size))

    denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_self_attention(q, k, v, mesh, *, axis_name="sp", causal=False,
                        scale=None):
    """Convenience wrapper: shard q/k/v on their sequence dim over
    ``axis_name`` and run :func:`ring_attention` under ``shard_map``.

    q, k, v: global arrays ``[B, T, H, D]`` (T divisible by the axis size).
    """
    spec = P(None, axis_name, None, None)

    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)

    sharding = NamedSharding(mesh, spec)
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal=False, scale=None):
    """Dense single-device reference (for tests and small sequences)."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        msk = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(msk[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
