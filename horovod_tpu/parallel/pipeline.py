"""Pipeline parallelism: GPipe-style microbatch pipeline over ``ppermute``.

Absent from the reference (data-parallel only, SURVEY §2.7); designed
TPU-first: every pipeline stage is one shard of a ``shard_map`` over the
``pp`` mesh axis, stage weights live sharded on that axis (stage i's
weights are shard i of a leading stage dimension), and activations hop to
the next stage with ``lax.ppermute`` — one ICI neighbor-transfer per tick,
which XLA overlaps with the next microbatch's compute.  The schedule is a
single ``lax.scan`` of ``M + S - 1`` ticks (M microbatches, S stages):
static shapes, no data-dependent control flow, fully jittable.

The stage function must be shape-preserving (``[mb, ...] -> [mb, ...]``),
which transformer blocks are.  Embedding / head layers run outside the
pipelined middle.
"""

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel._compat import axis_size, shard_map_unchecked


def pipeline_apply(stage_fn, stage_params, microbatches, *, axis_name="pp"):
    """Run inside ``shard_map``: push M microbatches through S stages.

    stage_fn: ``(params_for_this_stage, x) -> y`` with y.shape == x.shape.
    stage_params: this shard's slice of the stacked stage weights (pytree
        whose arrays have the stage dim already stripped by sharding, i.e.
        leading dim 1) — a leading axis of size 1 is squeezed.
    microbatches: ``[M, mb, ...]`` — replicated across the axis (every
        stage sees the full set; only stage 0 reads from it).

    Returns ``[M, mb, ...]`` outputs, valid on every shard (the last
    stage's results are broadcast back with a masked psum).
    """
    s = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + s - 1

    params = jax.tree_util.tree_map(
        lambda a: a[0] if a.ndim and a.shape[0] == 1 else a, stage_params)

    perm = [(i, (i + 1) % s) for i in range(s)]
    state0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    if hasattr(lax, "pcast"):
        state0 = lax.pcast(state0, (axis_name,), to="varying")
        out0 = lax.pcast(out0, (axis_name,), to="varying")

    def tick(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t (zeros once the feed is exhausted)
        feed = microbatches[jnp.minimum(t, m - 1)]
        state = jnp.where(jnp.logical_and(idx == 0, t < m), feed, state)
        y = stage_fn(params, state)
        # the last stage retires microbatch t - (s-1) at tick t
        done = t - (s - 1)
        is_last = idx == s - 1
        outs = lax.cond(
            jnp.logical_and(is_last, done >= 0),
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(done, 0), axis=0),
            lambda o: o, outs)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
    # replicate results from the last stage to all shards
    mask = jnp.where(idx == s - 1, 1.0, 0.0).astype(outs.dtype)
    return lax.psum(outs * mask, axis_name)


def pipelined(stage_fn, mesh, *, axis_name="pp", stage_param_specs=None,
              data_spec=None):
    """Wrap ``stage_fn`` into a global-array pipeline callable.

    Returns ``fn(stacked_params, microbatches)`` where ``stacked_params``
    arrays have a leading stage dimension of size = axis size, and
    ``microbatches`` is ``[M, mb, ...]``.

    ``mesh`` may be a ``jax`` Mesh or an ``hvd.grid(...)`` Grid
    (docs/groups.md): the grid resolves to the device mesh with the
    same C-order layout, so the ``pp`` stage sequence matches the
    grid's ``pp`` process groups.
    """
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.mesh import as_mesh

    mesh = as_mesh(mesh)

    if stage_param_specs is None:
        stage_param_specs = P(axis_name)
    if data_spec is None:
        data_spec = P()

    def run(stacked_params, microbatches):
        specs_params = jax.tree_util.tree_map(
            lambda _: stage_param_specs, stacked_params)
        fn = shard_map_unchecked(
            lambda p, x: pipeline_apply(stage_fn, p, x,
                                        axis_name=axis_name),
            mesh=mesh,
            in_specs=(specs_params, data_spec),
            out_specs=data_spec,
        )
        return fn(stacked_params, microbatches)

    return run
