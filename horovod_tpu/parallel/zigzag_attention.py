"""Zigzag ring attention: load-BALANCED causal sequence parallelism.

The plain causal ring (`ring_attention.py`) skips future K/V blocks,
but the ring is lockstep — every hop costs the *maximum* compute over
ranks, and rank P-1 attends every block while rank 0 attends one, so
causality saves almost no wall-clock.  The zigzag layout fixes the
balance (the technique behind the public zigzag/striped ring-attention
kernels; no reference-framework analog — SURVEY §5 lists long-context
as design-fresh):

- the global sequence is cut into ``2P`` chunks and rank ``i`` holds
  the PAIR (chunk ``i``, chunk ``2P-1-i``) — one early, one late;
- when rank ``i`` meets K/V from rank ``j != i``, exactly TWO of the
  four chunk interactions are causally live, and both are FULLY
  unmasked:

  * ``q_hi x kv_lo`` — always (chunk ``2P-1-i`` is later than any low
    chunk ``j``);
  * ``q_hi x kv_hi`` if ``j > i``, else ``q_lo x kv_lo`` — one XOR the
    other, same shape, so it lowers to a select over which operands
    feed ONE block attend;

  (``q_lo x kv_hi`` is never live: ``i + j <= 2P - 2 < 2P - 1``.)

Every rank therefore computes exactly 2 unmasked ``C x C`` block
attends per hop (plus a fixed resident step) — perfect balance, no
masking waste on the MXU, and ~2x the causal throughput of the naive
ring at large P.

Each block attend runs through the Pallas flash kernel on TPU (same
``return_lse`` streaming-softmax combine as ``ring_attention``), the
dense einsum elsewhere.  Results are EXACT attention in the original
token order: :func:`zigzag_shard` / :func:`zigzag_unshard` reorder
between the natural layout and the zigzag layout.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel._compat import axis_size, shard_map
from horovod_tpu.parallel.ring_attention import (_NEG_INF, _block_attend,
                                                 _combine)


def zigzag_chunk_order(p_size):
    """Chunk ids in shard order: rank ``i`` gets ``[i, 2P-1-i]``."""
    order = []
    for i in range(p_size):
        order.extend([i, 2 * p_size - 1 - i])
    return order


def zigzag_shard(x, p_size, axis=1):
    """Reorder a global ``[..., T, ...]`` array so a contiguous split
    over ``p_size`` shards hands rank ``i`` chunks ``(i, 2P-1-i)``."""
    t = x.shape[axis]
    if t % (2 * p_size):
        raise ValueError(
            f"sequence length {t} not divisible by 2*{p_size}")
    c = t // (2 * p_size)
    parts = [lax.slice_in_dim(x, k * c, (k + 1) * c, axis=axis)
             for k in zigzag_chunk_order(p_size)]
    return jnp.concatenate(parts, axis=axis)


def zigzag_unshard(x, p_size, axis=1):
    """Inverse of :func:`zigzag_shard`."""
    t = x.shape[axis]
    if t % (2 * p_size):
        raise ValueError(
            f"sequence length {t} not divisible by 2*{p_size}")
    c = t // (2 * p_size)
    order = zigzag_chunk_order(p_size)
    inverse = [0] * len(order)
    for pos, chunk in enumerate(order):
        inverse[chunk] = pos
    parts = [lax.slice_in_dim(x, pos * c, (pos + 1) * c, axis=axis)
             for pos in inverse]
    return jnp.concatenate(parts, axis=axis)


def _attend(q, k, v, *, scale, causal, use_flash, axis_name):
    """One block attend -> (numerator, denom, max) in the streaming-
    softmax representation ``_combine`` merges."""
    b, tq, h, d = q.shape
    if use_flash:
        from horovod_tpu.ops.pallas.flash_attention import flash_attention

        out, lse = flash_attention(q, k.astype(q.dtype),
                                   v.astype(q.dtype), causal=causal,
                                   scale=scale, return_lse=True)
        ones = jnp.ones((b, h, tq), jnp.float32)
        if hasattr(lax, "pcast"):
            ones = lax.pcast(ones, (axis_name,), to="varying")
        elif hasattr(lax, "pvary"):  # pragma: no cover
            ones = lax.pvary(ones, (axis_name,))
        return out.astype(jnp.float32), ones, lse
    if causal:
        msk = (jnp.arange(tq)[:, None]
               >= jnp.arange(k.shape[1])[None, :])[None, None]
    else:
        msk = None
    return _block_attend(q.astype(jnp.float32), k, v, scale=scale,
                         mask=msk)


def zigzag_ring_attention(q, k, v, *, axis_name, scale=None,
                          use_flash=None):
    """Balanced causal ring attention over ``axis_name``.

    Must run inside ``shard_map`` with the ZIGZAG shard layout: this
    rank's ``[B, 2C, H, D]`` slice is chunk ``i`` then chunk
    ``2P-1-i`` of the global sequence (:func:`zigzag_shard`).  Always
    causal — for the non-causal case the plain ring is already
    balanced; use :func:`ring_attention`.
    """
    p_size = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t2, h, d = q.shape
    if t2 % 2:
        raise ValueError(f"zigzag shard holds 2 chunks; got T={t2}")
    c = t2 // 2
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    att = functools.partial(_attend, scale=scale, use_flash=use_flash,
                            axis_name=axis_name)

    q_lo, q_hi = q[:, :c], q[:, c:]
    k_lo, k_hi = k[:, :c], k[:, c:]
    v_lo, v_hi = v[:, :c], v[:, c:]

    def init(tq):
        o = jnp.zeros((b, tq, h, d), jnp.float32)
        l = jnp.zeros((b, h, tq), jnp.float32)
        m = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
        if hasattr(lax, "pcast"):
            o, l, m = (lax.pcast(x, (axis_name,), to="varying")
                       for x in (o, l, m))
        elif hasattr(lax, "pvary"):  # pragma: no cover
            o, l, m = (lax.pvary(x, (axis_name,)) for x in (o, l, m))
        return o, l, m

    # Resident step (kv from this rank): q_lo/q_hi diagonal-causal on
    # their own chunks + q_hi attends kv_lo fully (chunk 2P-1-i is
    # always later than chunk i).
    acc_lo = _combine(*init(c), *att(q_lo, k_lo, v_lo, causal=True))
    acc_hi = _combine(*init(c), *att(q_hi, k_hi, v_hi, causal=True))
    acc_hi = _combine(*acc_hi, *att(q_hi, k_lo, v_lo, causal=False))

    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    def step(carry, s):
        acc_lo, acc_hi, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        j = (my_idx - s) % p_size          # origin rank of current K/V
        kc_lo, kc_hi = kc[:, :c], kc[:, c:]
        vc_lo, vc_hi = vc[:, :c], vc[:, c:]

        # always live: q_hi x kv_lo, fully unmasked
        acc_hi = _combine(*acc_hi, *att(q_hi, kc_lo, vc_lo,
                                        causal=False))

        # exactly one of (q_hi x kv_hi | j > i) / (q_lo x kv_lo | j < i)
        # is live, both unmasked and same-shaped: select the operands,
        # run ONE attend, then merge into the matching accumulator.
        hi_live = j > my_idx
        q_sel = jnp.where(hi_live, q_hi, q_lo)
        k_sel = jnp.where(hi_live, kc_hi, kc_lo)
        v_sel = jnp.where(hi_live, vc_hi, vc_lo)
        bo, bl, bm = att(q_sel, k_sel, v_sel, causal=False)
        lo_new = _combine(*acc_lo, bo, bl, bm)
        hi_new = _combine(*acc_hi, bo, bl, bm)
        acc_lo = tuple(jnp.where(hi_live, a, n)
                       for a, n in zip(acc_lo, lo_new))
        acc_hi = tuple(jnp.where(hi_live, n, a)
                       for a, n in zip(acc_hi, hi_new))
        return (acc_lo, acc_hi, kc, vc), None

    (acc_lo, acc_hi, _, _), _ = lax.scan(
        step, (acc_lo, acc_hi, k, v), jnp.arange(1, p_size))

    def finish(o, l, m):
        denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
        return o / denom

    out = jnp.concatenate([finish(*acc_lo), finish(*acc_hi)], axis=1)
    return out.astype(q.dtype)


@functools.lru_cache(maxsize=32)
def _jitted_zigzag(mesh, axis_name, scale, use_flash):
    spec = P(None, axis_name, None, None)
    return jax.jit(shard_map(
        functools.partial(zigzag_ring_attention, axis_name=axis_name,
                          scale=scale, use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))


def zigzag_ring_self_attention(q, k, v, mesh, *, axis_name="sp",
                               scale=None, use_flash=None):
    """Convenience wrapper: zigzag-reorder global ``[B, T, H, D]``
    arrays, run :func:`zigzag_ring_attention` under ``shard_map``
    (jitted, cached per (mesh, axis, scale, flash)), and restore the
    natural token order."""
    p_size = mesh.shape[axis_name]
    sharding = NamedSharding(mesh, P(None, axis_name, None, None))

    fn = _jitted_zigzag(mesh, axis_name, scale, use_flash)
    args = (jax.device_put(zigzag_shard(x, p_size), sharding)
            for x in (q, k, v))
    return zigzag_unshard(fn(*args), p_size)
