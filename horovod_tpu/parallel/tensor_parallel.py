"""Tensor parallelism: GSPMD sharding rules for transformer weights.

The reference framework is data-parallel only (SURVEY §2.7); this module
provides the TPU-native tensor-parallel layer.  Rather than Megatron-style
hand-written column/row-parallel linear layers with explicit all-reduces,
the TPU idiom is GSPMD: annotate the *weights* with ``PartitionSpec``s and
constrain key *activations*, then let XLA insert the collectives on ICI
("pick a mesh, annotate shardings, let XLA insert collectives").

The canonical 2-way split for a transformer block (both halves need one
psum per block, which XLA fuses into the matmuls):

- attention qkv projection: column-parallel → heads split over ``tp``
- attention out projection: row-parallel
- MLP up projection: column-parallel; MLP down projection: row-parallel
- embedding / lm_head: vocab split over ``tp``

:func:`transformer_sharding_rules` maps parameter-path regexes to specs;
:func:`shard_params` applies them to a pytree.  Works with the flax
transformer in ``horovod_tpu.models.transformer`` and any pytree whose
path names follow the same conventions.
"""

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.mesh import as_mesh


def transformer_sharding_rules(tp_axis="tp", fsdp_axis=None):
    """[(path_regex, PartitionSpec)] for GPT-style parameter trees.

    Matching is ``re.search`` over the ``/``-joined parameter path, first
    match wins.  ``fsdp_axis`` additionally shards the non-tp dimension of
    the big matrices (ZeRO-3 style) when given.
    """
    f = fsdp_axis
    return [
        # attention; fused qkv DenseGeneral kernel is [d, 3, heads, d_head]
        # — split the heads dim
        (r"attn.*qkv.*kernel", P(f, None, tp_axis, None)),
        (r"attn.*(query|key|value).*kernel", P(f, tp_axis)),
        (r"attn.*(out|proj_out|output).*kernel", P(tp_axis, f)),
        # mlp
        (r"mlp.*(up|fc1|wi|gate).*kernel", P(f, tp_axis)),
        (r"mlp.*(down|fc2|wo).*kernel", P(tp_axis, f)),
        # moe experts: [n_experts, d_in, d_out]
        (r"moe.*(wi|up).*kernel", P("ep", f, tp_axis)),
        (r"moe.*(wo|down).*kernel", P("ep", tp_axis, f)),
        (r"moe.*router.*kernel", P(f, None)),
        # embeddings / head: vocab-split; position table replicated
        (r"pos_embed", P()),
        (r"(embed|wte).*embedding", P(tp_axis, f)),
        (r"(lm_head|output_head).*kernel", P(f, tp_axis)),
        # biases & layernorms replicated
        (r".*", P()),
    ]


def _path_str(path):
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def spec_for_path(path, rules):
    for pat, spec in rules:
        if re.search(pat, path):
            return spec
    return P()


def _fit_spec(spec, ndim):
    """Trim/pad a spec to the array rank (drop trailing axes that don't
    exist, e.g. biases matched by a kernel rule)."""
    parts = tuple(spec) + (None,) * max(0, ndim - len(spec))
    return P(*parts[:ndim])


def params_shardings(params, mesh, rules=None):
    """Pytree of NamedShardings matching ``params`` via the rule table.

    ``mesh`` may be a ``jax`` Mesh or an ``hvd.grid(...)`` Grid
    (docs/groups.md) — the grid resolves to the device mesh with the
    same axis names and C-order rank layout, so its ``tp`` group and
    the ``tp`` sharding axis name the same devices."""
    mesh = as_mesh(mesh)
    if rules is None:
        rules = transformer_sharding_rules()
    mesh_axes = set(mesh.axis_names)

    def one(path, x):
        spec = spec_for_path(_path_str(path), rules)
        # ignore axes the mesh doesn't have (e.g. no ep axis configured)
        parts = tuple(a if (a is None or a in mesh_axes) else None
                      for a in _fit_spec(spec, x.ndim))
        return NamedSharding(mesh, P(*parts))

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params, mesh, rules=None):
    """Place a parameter pytree onto the mesh per the sharding rules."""
    return jax.device_put(params, params_shardings(params, mesh, rules))


def constrain(x, mesh, *spec):
    """Activation sharding constraint (a true no-op if the mesh lacks
    every requested axis — mapping absent axes to None would impose a
    full-replication constraint, overriding GSPMD's propagated sharding
    and forcing an all-gather of e.g. batch-sharded MoE activations).
    ``mesh`` may be a Mesh or a Grid, as everywhere in this module."""
    mesh = as_mesh(mesh)
    mesh_axes = set(mesh.axis_names)
    parts = tuple(a if (a is None or a in mesh_axes) else None for a in spec)
    if not any(p is not None for p in parts):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*parts)))
