"""Device-mesh construction: the TPU topology model.

The reference's process topology is rank / local_rank / cross_rank over
GLOBAL / LOCAL / CROSS MPI communicators (``horovod/common/mpi/
mpi_context.cc:147-156``).  On TPU the analog is a ``jax.sharding.Mesh``
whose axes map onto the interconnect hierarchy: in-slice axes ride ICI,
the cross-slice axis rides DCN.  All parallelism in this framework is
expressed as sharding over these named axes.

Canonical axis names (used by ``horovod_tpu.parallel`` and the models):

- ``dp``     data parallelism (gradient psum; the reference's only strategy)
- ``fsdp``   fully-sharded data parallelism (params sharded over dp axis)
- ``tp``     tensor parallelism (matmul sharding)
- ``pp``     pipeline parallelism (layer sharding)
- ``sp``     sequence/context parallelism (ring attention / Ulysses)
- ``ep``     expert parallelism (MoE)
"""

import dataclasses
import math

import numpy as np
import jax
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    DP = "dp"
    FSDP = "fsdp"
    TP = "tp"
    PP = "pp"
    SP = "sp"
    EP = "ep"
    HVD = "hvd"  # the flat rank axis used by the eager collective path


def make_mesh(axis_shapes=None, *, devices=None) -> Mesh:
    """Build a mesh from ``{axis_name: size}``; one axis may be -1 to absorb
    the remaining devices (like a reshape).

    ``make_mesh()`` returns the flat data-parallel mesh over all devices.
    Axis order follows insertion order of ``axis_shapes`` — put the
    fastest-communicating axis (tp/sp) last so it lands on adjacent ICI
    neighbors.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if not axis_shapes:
        axis_shapes = {MeshAxes.DP: n}
    names = list(axis_shapes.keys())
    sizes = list(axis_shapes.values())
    for name, s in zip(names, sizes):
        if not isinstance(s, int) or isinstance(s, bool):
            raise ValueError(
                f"mesh axis {name!r} size must be an int, got {s!r}")
        if s < 1 and s != -1:
            # a 0 size would divide-by-zero in the -1 absorption below
            # and a negative one would silently reshape garbage
            raise ValueError(
                f"mesh axis {name!r} size must be a positive int (or -1 "
                f"to absorb the remaining devices), got {s}")
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if n % known != 0:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} need "
            f"{math.prod(sizes)} devices, have {n}")
    array = np.array(devices).reshape(sizes)
    return Mesh(array, tuple(names))


def data_parallel_mesh(devices=None) -> Mesh:
    return make_mesh({MeshAxes.DP: -1}, devices=devices)


def grid_mesh(grid, devices=None) -> Mesh:
    """Device mesh matching a process-group :class:`~horovod_tpu.groups
    .Grid` (docs/groups.md): the SAME axis order and C-order layout
    ``hvd.grid()`` used to partition ranks, so ``grid.group(axis)`` and
    this mesh's axis of the same name always name the same devices —
    eager group collectives and in-graph GSPMD sharding agree on one
    topology."""
    return make_mesh(grid.mesh_axes(), devices=devices)


def as_mesh(mesh_or_grid, devices=None) -> Mesh:
    """Resolve a ``mesh=`` argument that may be a ``jax`` Mesh OR a
    process-group Grid — the hook that lets every parallel module take
    the grid handle directly instead of separate mesh + axis-name
    plumbing (docs/groups.md)."""
    if isinstance(mesh_or_grid, Mesh):
        return mesh_or_grid
    if hasattr(mesh_or_grid, "mesh_axes"):
        return grid_mesh(mesh_or_grid, devices=devices)
    raise TypeError(
        f"expected a jax.sharding.Mesh or hvd.grid(...) Grid, got "
        f"{type(mesh_or_grid).__name__}")


def shard_global_batch(local_batch, mesh=None, axis=MeshAxes.HVD):
    """Assemble a global, mesh-sharded batch from this process's local
    rows.

    Pod jobs load data per host (reference: each Horovod rank reads its
    own shard); under a multi-host global mesh the training step wants
    ONE global ``jax.Array``.  Each process calls this with its local
    rows; the result is the concatenated global batch sharded over
    ``axis`` with this process contributing exactly its devices' shards.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        from horovod_tpu.common import basics
        mesh = basics.mesh()
    sharding = NamedSharding(mesh, PartitionSpec(axis))
    try:
        return jax.make_array_from_process_local_data(sharding, local_batch)
    except (AttributeError, TypeError):  # pragma: no cover — older jax
        local_devices = [d for d in mesh.devices.flat
                         if d.process_index == jax.process_index()]
        if local_batch.shape[0] % len(local_devices) != 0:
            raise ValueError(
                f"local batch rows ({local_batch.shape[0]}) must be "
                f"divisible by this process's device count "
                f"({len(local_devices)})")
        rows = local_batch.shape[0] // len(local_devices)
        bufs = [jax.device_put(local_batch[i * rows:(i + 1) * rows], d)
                for i, d in enumerate(local_devices)]
        n_global = mesh.devices.size
        global_shape = (rows * n_global,) + tuple(local_batch.shape[1:])
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, bufs)


def hierarchical_mesh(local_size=None, devices=None) -> Mesh:
    """2-D (cross, local) mesh mirroring the reference's hierarchical
    allreduce topology (``nccl_operations.cc:162-289``): reduce-scatter over
    ``local`` (ICI), allreduce over ``cross`` (DCN), allgather over
    ``local``."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if local_size is None:
        # devices on the same host share .process_index
        per_proc = {}
        for d in devices:
            per_proc.setdefault(d.process_index, []).append(d)
        local_size = len(next(iter(per_proc.values())))
    if len(devices) % local_size != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by local_size "
            f"{local_size}")
    return make_mesh({"cross": len(devices) // local_size,
                      "local": local_size}, devices=devices)
