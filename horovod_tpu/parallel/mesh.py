"""Device-mesh construction: the TPU topology model.

The reference's process topology is rank / local_rank / cross_rank over
GLOBAL / LOCAL / CROSS MPI communicators (``horovod/common/mpi/
mpi_context.cc:147-156``).  On TPU the analog is a ``jax.sharding.Mesh``
whose axes map onto the interconnect hierarchy: in-slice axes ride ICI,
the cross-slice axis rides DCN.  All parallelism in this framework is
expressed as sharding over these named axes.

Canonical axis names (used by ``horovod_tpu.parallel`` and the models):

- ``dp``     data parallelism (gradient psum; the reference's only strategy)
- ``fsdp``   fully-sharded data parallelism (params sharded over dp axis)
- ``tp``     tensor parallelism (matmul sharding)
- ``pp``     pipeline parallelism (layer sharding)
- ``sp``     sequence/context parallelism (ring attention / Ulysses)
- ``ep``     expert parallelism (MoE)
"""

import dataclasses
import math

import numpy as np
import jax
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    DP = "dp"
    FSDP = "fsdp"
    TP = "tp"
    PP = "pp"
    SP = "sp"
    EP = "ep"
    HVD = "hvd"  # the flat rank axis used by the eager collective path


def make_mesh(axis_shapes=None, *, devices=None) -> Mesh:
    """Build a mesh from ``{axis_name: size}``; one axis may be -1 to absorb
    the remaining devices (like a reshape).

    ``make_mesh()`` returns the flat data-parallel mesh over all devices.
    Axis order follows insertion order of ``axis_shapes`` — put the
    fastest-communicating axis (tp/sp) last so it lands on adjacent ICI
    neighbors.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if not axis_shapes:
        axis_shapes = {MeshAxes.DP: n}
    names = list(axis_shapes.keys())
    sizes = list(axis_shapes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if n % known != 0:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} need "
            f"{math.prod(sizes)} devices, have {n}")
    array = np.array(devices).reshape(sizes)
    return Mesh(array, tuple(names))


def data_parallel_mesh(devices=None) -> Mesh:
    return make_mesh({MeshAxes.DP: -1}, devices=devices)


def hierarchical_mesh(local_size=None, devices=None) -> Mesh:
    """2-D (cross, local) mesh mirroring the reference's hierarchical
    allreduce topology (``nccl_operations.cc:162-289``): reduce-scatter over
    ``local`` (ICI), allreduce over ``cross`` (DCN), allgather over
    ``local``."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if local_size is None:
        # devices on the same host share .process_index
        per_proc = {}
        for d in devices:
            per_proc.setdefault(d.process_index, []).append(d)
        local_size = len(next(iter(per_proc.values())))
    if len(devices) % local_size != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by local_size "
            f"{local_size}")
    return make_mesh({"cross": len(devices) // local_size,
                      "local": local_size}, devices=devices)
