"""Ulysses (DeepSpeed-style) sequence parallelism via all-to-all.

The second long-context strategy alongside ring attention (the reference
framework has neither — SURVEY §5).  Instead of rotating K/V blocks, two
``all_to_all`` collectives re-shard the activations: inbound, the layout
flips from sequence-sharded ``[B, T/P, H, D]`` to head-sharded
``[B, T, H/P, D]`` so each device computes *exact* full-sequence attention
on its subset of heads; outbound, the flip is reversed.  On TPU the
all-to-all is an XLA collective over ICI; total bytes moved are
``2 * B*T*H*D/P`` per direction — independent of sequence length per hop,
which favors Ulysses when H >= P and the attention kernel (e.g. the Pallas
flash kernel) wants the whole sequence locally.

Constraint: the head count must be divisible by the axis size (classic
Ulysses).  For H < P use ring attention instead.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel._compat import axis_size
from horovod_tpu.parallel._compat import shard_map_kernel_body as shard_map
from horovod_tpu.parallel.ring_attention import reference_attention


def seq_to_heads(x, axis_name):
    """[B, T/P, H, D] -> [B, T, H/P, D] via all_to_all over ``axis_name``."""
    # split the head dim across the axis, concat the sequence dim
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x, axis_name):
    """[B, T, H/P, D] -> [B, T/P, H, D] — inverse of :func:`seq_to_heads`."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, *, axis_name, causal=False, scale=None,
                      attn_fn=None):
    """Exact attention with sequence-sharded inputs via head re-sharding.

    Runs inside ``shard_map``.  q/k/v per shard: ``[B, T/P, H, D]``; output
    has the same layout.  ``attn_fn(q, k, v, causal=..., scale=...)`` is the
    local full-sequence attention kernel (defaults to the dense reference;
    pass the Pallas flash kernel on real TPU).
    """
    if attn_fn is None:
        attn_fn = reference_attention
    h = q.shape[2]
    p_size = axis_size(axis_name)
    if h % p_size != 0:
        raise ValueError(
            f"Ulysses needs heads ({h}) divisible by axis size ({p_size}); "
            "use ring_attention for few-head long-context models")
    qh = seq_to_heads(q, axis_name)
    kh = seq_to_heads(k, axis_name)
    vh = seq_to_heads(v, axis_name)
    oh = attn_fn(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(oh, axis_name)


def ulysses_self_attention(q, k, v, mesh, *, axis_name="sp", causal=False,
                           scale=None, attn_fn=None):
    """Global-array convenience wrapper (mirrors ``ring_self_attention``)."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=axis_name,
                          causal=causal, scale=scale, attn_fn=attn_fn),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    sharding = NamedSharding(mesh, spec)
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    return fn(q, k, v)
