"""JAX version compatibility shims shared by the parallel subsystem."""

import inspect

try:
    from jax import shard_map as _shard_map_mod  # jax >= 0.6
    shard_map = _shard_map_mod.shard_map if hasattr(
        _shard_map_mod, "shard_map") else _shard_map_mod
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401

try:
    _check_kw = next(
        (kw for kw in ("check_vma", "check_rep")
         if kw in inspect.signature(shard_map).parameters), None)
except (TypeError, ValueError):  # pragma: no cover
    _check_kw = None


def shard_map_unchecked(*args, **kwargs):
    """shard_map with replication/varying-axes checking disabled — the
    keyword is ``check_vma`` on current jax, ``check_rep`` on older."""
    if _check_kw:
        kwargs.setdefault(_check_kw, False)
    return shard_map(*args, **kwargs)


def axis_size(axis_name):
    """``lax.axis_size`` where it exists (newer jax); ``psum(1, axis)``
    on older releases — equally constant-folded inside shard_map/pmap,
    so call sites can treat the result as a static int either way."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map_kernel_body(*args, **kwargs):
    """shard_map for bodies that may call Pallas kernels: checking stays ON
    when lowering for real TPU, and is disabled only on the CPU backend,
    where kernels run in interpret mode and pallas_call trips the
    varying-manual-axes checker (dynamic_slice mixing varying and unvarying
    operands)."""
    import jax

    if _check_kw and jax.default_backend() == "cpu":
        kwargs.setdefault(_check_kw, False)
    return shard_map(*args, **kwargs)
