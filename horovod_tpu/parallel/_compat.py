"""JAX version compatibility shims shared by the parallel subsystem."""

try:
    from jax import shard_map as _shard_map_mod  # jax >= 0.6
    shard_map = _shard_map_mod.shard_map if hasattr(
        _shard_map_mod, "shard_map") else _shard_map_mod
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401
