from horovod_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    data_parallel_mesh,
    hierarchical_mesh,
    shard_global_batch,
    MeshAxes,
)
from horovod_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_self_attention,
    reference_attention,
)
from horovod_tpu.parallel.zigzag_attention import (  # noqa: F401
    zigzag_ring_attention,
    zigzag_ring_self_attention,
    zigzag_shard,
    zigzag_unshard,
)
from horovod_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_self_attention,
    seq_to_heads,
    heads_to_seq,
)
from horovod_tpu.parallel.tensor_parallel import (  # noqa: F401
    transformer_sharding_rules,
    params_shardings,
    shard_params,
    constrain,
)
from horovod_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipelined,
)
from horovod_tpu.parallel.moe import (  # noqa: F401
    switch_moe,
    switch_route,
    init_moe_params,
)
