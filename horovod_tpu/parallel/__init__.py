from horovod_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    data_parallel_mesh,
    hierarchical_mesh,
    MeshAxes,
)
