"""Expert parallelism: GShard-style switch Mixture-of-Experts.

Absent from the reference (SURVEY §2.7).  TPU-native design follows the
original GShard/Switch recipe, which was *built* for XLA SPMD: routing is
expressed as dense one-hot einsums with static capacity (no gather/scatter,
no dynamic shapes — everything tiles onto the MXU), the expert dimension of
the dispatched activations and of the expert weights is sharded over the
``ep`` mesh axis with sharding constraints, and XLA lowers the dispatch /
combine einsums into ``all_to_all`` collectives over ICI.

Top-1 (switch) routing with capacity factor + auxiliary load-balancing
loss, per Switch Transformer; tokens overflowing an expert's capacity are
passed through the residual (combine weight 0).
"""

import math

import jax
import jax.numpy as jnp


def switch_route(router_logits, n_experts, capacity, valid=None):
    """Top-1 routing tensors from ``[T, E]`` logits.

    ``valid`` (optional ``[T]`` mask) excludes padding tokens: they take no
    expert-queue positions, no capacity, and do not enter the balancing
    loss.  Returns (dispatch ``[T, E, C]`` float, combine ``[T, E, C]``
    float, aux_loss scalar).
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                 # [T]
    expert_gate = jnp.max(probs, axis=-1)                   # [T]
    routed_1h = jax.nn.one_hot(expert_idx, n_experts)       # [T, E] pre-drop
    if valid is not None:
        routed_1h = routed_1h * valid[:, None].astype(routed_1h.dtype)

    # position of each token within its expert's queue
    pos_in_expert = (jnp.cumsum(routed_1h, axis=0) - 1.0) * routed_1h  # [T,E]
    keep = pos_in_expert < capacity
    kept_1h = routed_1h * keep                              # drop overflow
    pos = jnp.sum(pos_in_expert * kept_1h, axis=-1)         # [T]

    pos_1h = jax.nn.one_hot(pos.astype(jnp.int32), capacity)            # [T,C]
    dispatch = kept_1h[:, :, None] * pos_1h[:, None, :]     # [T, E, C]
    combine = dispatch * expert_gate[:, None, None]

    # Switch-Transformer load-balance loss: E * sum_e f_e * p_e, with f
    # from the PRE-drop routing decisions — capacity clamping must not
    # hide imbalance from the balancing gradient.
    if valid is not None:
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        f = jnp.sum(routed_1h, axis=0) / denom
        p = jnp.sum(probs * valid[:, None].astype(probs.dtype),
                    axis=0) / denom
    else:
        f = jnp.mean(routed_1h, axis=0)    # fraction argmax-routed to e
        p = jnp.mean(probs, axis=0)        # mean router prob for e
    aux_loss = n_experts * jnp.sum(f * p)
    return dispatch, combine, aux_loss


def _constrain_ep(y, mesh):
    """Shard the expert dim (axis 1 of [G, E, C, D]) over ``ep``.

    With an explicit mesh, uses it; otherwise applies a bare-axis-name
    constraint against the mesh ambient at trace time (jit with sharded
    inputs), detected explicitly — a no-op only when there is no ambient
    mesh or it has no ``ep`` axis, so real constraint errors still raise.
    """
    from jax.sharding import PartitionSpec as P

    if mesh is not None:
        from horovod_tpu.parallel.tensor_parallel import constrain
        return constrain(y, mesh, None, "ep", None, None)
    try:
        ambient = jax.sharding.get_abstract_mesh()
        ambient_axes = ambient.axis_names if ambient is not None else ()
    except AttributeError:  # older jax: no ambient-mesh introspection
        ambient_axes = ()
    if "ep" not in ambient_axes:
        return y
    return jax.lax.with_sharding_constraint(y, P(None, "ep", None, None))


def switch_moe(x, params, *, capacity_factor=1.25, group_size=4096,
               mesh=None):
    """Apply a switch-MoE FFN to ``x [..., T, D]`` (leading dims folded).

    params: dict with ``router/kernel [D, E]``, ``wi/kernel [E, D, F]``,
    ``wo/kernel [E, F, D]`` (create with :func:`init_moe_params`).

    Tokens are routed in fixed-size **groups** (GShard recipe): the
    dispatch/combine one-hots are ``[G, S, E, C]`` with per-group capacity
    ``C = ceil(cf*S/E)``, so their footprint is linear in total tokens
    (``T*cf*S``) rather than quadratic, and routing never couples tokens
    across groups.  Expert-dim sharding constraints make XLA partition
    experts over ``ep`` and insert the all_to_alls (explicit ``mesh``, or
    the ambient jit mesh when ``mesh`` is None).
    """
    orig_shape = x.shape
    d = orig_shape[-1]
    xt = x.reshape(-1, d)                                   # [T, D]
    t = xt.shape[0]
    wi = params["wi"]["kernel"]
    wo = params["wo"]["kernel"]
    e = wi.shape[0]

    # Pad T up to a multiple of the group size rather than shrinking the
    # groups (a T with no divisor near group_size would otherwise degrade
    # to 1-2-token groups, making capacity and the balancing loss
    # meaningless).  Pad tokens carry zero router weight: their rows of
    # dispatch/combine are zeroed, so they never consume expert capacity.
    s = min(group_size, t)
    pad = (-t) % s
    if pad:
        xt = jnp.concatenate(
            [xt, jnp.zeros((pad, d), xt.dtype)], axis=0)
    g = (t + pad) // s
    xg = xt.reshape(g, s, d)
    capacity = int(math.ceil(capacity_factor * s / e))

    logits = jnp.einsum("gsd,de->gse", xg,
                        params["router"]["kernel"])         # [G, S, E]
    valid = (jnp.arange(g * s) < t).reshape(g, s)           # pad mask
    dispatch, combine, aux = jax.vmap(
        lambda lg, vg: switch_route(lg, e, capacity, valid=vg))(logits,
                                                                valid)
    aux = jnp.mean(aux)

    expert_in = jnp.einsum("gsd,gsec->gecd", xg.astype(jnp.float32),
                           dispatch)                        # [G, E, C, D]
    expert_in = _constrain_ep(expert_in, mesh)
    h = jnp.einsum("gecd,edf->gecf", expert_in, wi.astype(jnp.float32))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("gecf,efd->gecd", h, wo.astype(jnp.float32))
    expert_out = _constrain_ep(expert_out, mesh)
    out = jnp.einsum("gecd,gsec->gsd", expert_out, combine)  # [G, S, D]
    out = out.reshape(-1, d)[:t]                            # drop padding
    return out.astype(x.dtype).reshape(orig_shape), aux


def moe_param_shapes(d_model, d_ff, n_experts):
    """The switch_moe parameter contract — single source of truth shared by
    :func:`init_moe_params` and the flax ``MoeMlp`` module."""
    return {
        "router": (d_model, n_experts),
        "wi": (n_experts, d_model, d_ff),
        "wo": (n_experts, d_ff, d_model),
    }


def moe_kernel_init(rng, shape, dtype=jnp.float32):
    """Normal(0, 1/fan_in) where fan_in is the contracted (second-to-last)
    dimension."""
    scale = 1.0 / math.sqrt(shape[-2])
    return (jax.random.normal(rng, shape) * scale).astype(dtype)


def init_moe_params(rng, d_model, d_ff, n_experts, dtype=jnp.float32):
    shapes = moe_param_shapes(d_model, d_ff, n_experts)
    keys = jax.random.split(rng, len(shapes))
    return {name: {"kernel": moe_kernel_init(k, shape, dtype)}
            for k, (name, shape) in zip(keys, shapes.items())}
