"""Expert parallelism: GShard-style switch Mixture-of-Experts.

Absent from the reference (SURVEY §2.7).  TPU-native design follows the
original GShard/Switch recipe, which was *built* for XLA SPMD: routing is
expressed as dense one-hot einsums with static capacity (no gather/scatter,
no dynamic shapes — everything tiles onto the MXU), the expert dimension of
the dispatched activations and of the expert weights is sharded over the
``ep`` mesh axis with sharding constraints, and XLA lowers the dispatch /
combine einsums into ``all_to_all`` collectives over ICI.

Top-1 (switch) routing with capacity factor + auxiliary load-balancing
loss, per Switch Transformer; tokens overflowing an expert's capacity are
passed through the residual (combine weight 0).
"""

import math

import jax
import jax.numpy as jnp


def switch_route(router_logits, n_experts, capacity):
    """Top-1 routing tensors from ``[T, E]`` logits.

    Returns (dispatch ``[T, E, C]`` float, combine ``[T, E, C]`` float,
    aux_loss scalar).
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                 # [T]
    expert_gate = jnp.max(probs, axis=-1)                   # [T]
    routed_1h = jax.nn.one_hot(expert_idx, n_experts)       # [T, E] pre-drop

    # position of each token within its expert's queue
    pos_in_expert = (jnp.cumsum(routed_1h, axis=0) - 1.0) * routed_1h  # [T,E]
    keep = pos_in_expert < capacity
    kept_1h = routed_1h * keep                              # drop overflow
    pos = jnp.sum(pos_in_expert * kept_1h, axis=-1)         # [T]

    pos_1h = jax.nn.one_hot(pos.astype(jnp.int32), capacity)            # [T,C]
    dispatch = kept_1h[:, :, None] * pos_1h[:, None, :]     # [T, E, C]
    combine = dispatch * expert_gate[:, None, None]

    # Switch-Transformer load-balance loss: E * sum_e f_e * p_e, with f
    # from the PRE-drop routing decisions — capacity clamping must not
    # hide imbalance from the balancing gradient.
    f = jnp.mean(routed_1h, axis=0)        # fraction argmax-routed to e
    p = jnp.mean(probs, axis=0)            # mean router prob for e
    aux_loss = n_experts * jnp.sum(f * p)
    return dispatch, combine, aux_loss


def switch_moe(x, params, *, capacity_factor=1.25, mesh=None):
    """Apply a switch-MoE FFN to ``x [..., T, D]`` (leading dims folded).

    params: dict with ``router/kernel [D, E]``, ``wi/kernel [E, D, F]``,
    ``wo/kernel [E, F, D]`` (create with :func:`init_moe_params`).
    When ``mesh`` is given, expert-dim sharding constraints are applied so
    XLA partitions experts over ``ep`` and inserts the all_to_alls.
    """
    from horovod_tpu.parallel.tensor_parallel import constrain

    orig_shape = x.shape
    d = orig_shape[-1]
    xt = x.reshape(-1, d)                                   # [T, D]
    t = xt.shape[0]
    wi = params["wi"]["kernel"]
    wo = params["wo"]["kernel"]
    e = wi.shape[0]
    capacity = int(math.ceil(capacity_factor * t / e))

    logits = xt @ params["router"]["kernel"]                # [T, E]
    dispatch, combine, aux = switch_route(logits, e, capacity)

    expert_in = jnp.einsum("td,tec->ecd", xt.astype(jnp.float32),
                           dispatch)                        # [E, C, D]
    if mesh is not None:
        expert_in = constrain(expert_in, mesh, "ep", None, None)
    h = jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(jnp.float32))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, wo.astype(jnp.float32))
    if mesh is not None:
        expert_out = constrain(expert_out, mesh, "ep", None, None)
    out = jnp.einsum("ecd,tec->td", expert_out, combine)    # [T, D]
    return out.astype(x.dtype).reshape(orig_shape), aux


def moe_param_shapes(d_model, d_ff, n_experts):
    """The switch_moe parameter contract — single source of truth shared by
    :func:`init_moe_params` and the flax ``MoeMlp`` module."""
    return {
        "router": (d_model, n_experts),
        "wi": (n_experts, d_model, d_ff),
        "wo": (n_experts, d_ff, d_model),
    }


def moe_kernel_init(rng, shape, dtype=jnp.float32):
    """Normal(0, 1/fan_in) where fan_in is the contracted (second-to-last)
    dimension."""
    scale = 1.0 / math.sqrt(shape[-2])
    return (jax.random.normal(rng, shape) * scale).astype(dtype)


def init_moe_params(rng, d_model, d_ff, n_experts, dtype=jnp.float32):
    shapes = moe_param_shapes(d_model, d_ff, n_experts)
    keys = jax.random.split(rng, len(shapes))
    return {name: {"kernel": moe_kernel_init(k, shape, dtype)}
            for k, (name, shape) in zip(keys, shapes.items())}
