"""Elastic membership: survive rank loss, re-form the ring, converge
(docs/elastic.md).

Reference: Elastic Horovod (``horovod/run/elastic/``, Sergeev & Del
Balso 1802.05799 follow-up) — here layered on the fault-tolerant TCP
runtime's coordinated abort: with ``HVD_TPU_ELASTIC=1`` the coordinator
rewrites a survivable failure into a membership-reconfiguration
directive (a marked abort reason carried by the existing fan-out), and
:func:`run` catches the resulting :class:`HvdReconfigureError`,
re-forms the world at the next epoch, restores committed state, and
retries the step.

Surface::

    state = hvd.elastic.State(params=params, optimizer_state=opt)
    hvd.elastic.run(train_fn, state)      # incumbents (after hvd.init())

    hvd.elastic.wait_for_membership()     # late joiner (INSTEAD of init)
    hvd.elastic.run(train_fn, state)
"""

import time

from horovod_tpu.common import basics
from horovod_tpu.common.handles import (HvdAbortedError,
                                        HvdDrainedError,
                                        HvdReconfigureError)
from horovod_tpu.elastic.membership import (ELASTIC_SCOPE, JOIN_SCOPE,
                                            MEMBERSHIP_KEY,
                                            ElasticContext,
                                            decode_membership)
from horovod_tpu.elastic.state import State
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

__all__ = ["State", "run", "reconfigure", "wait_for_membership",
           "worker_id", "DRAINED", "HvdReconfigureError",
           "HvdDrainedError", "ElasticContext"]


class _Drained:
    """Falsy singleton ``run`` returns when THIS rank left via a granted
    drain — distinguishable from a train function that returns None."""

    def __repr__(self):
        return "hvd.elastic.DRAINED"

    def __bool__(self):
        return False


DRAINED = _Drained()


def worker_id() -> int:
    """This process's stable elastic identity: the launcher-assigned
    initial rank, unchanged by reconfiguration (``hvd.rank()`` is
    re-keyed at every membership epoch; this never is)."""
    return basics.worker_id()


def reconfigure(exc: HvdReconfigureError):
    """Apply a received reconfiguration directive: survivors re-form at
    the directive's epoch; a worker voted out of the membership raises
    the underlying abort instead — unless it left on PURPOSE (a granted
    drain after a preemption notice, docs/checkpoint.md), which tears
    down quietly and raises :class:`HvdDrainedError` so ``run`` can
    report a clean exit instead of a failure."""
    from horovod_tpu.common import drain as drain_mod

    wid = basics.worker_id()
    if wid not in exc.members:
        if (getattr(exc, "drain", False)
                and (wid in exc.dead or drain_mod.requested())):
            basics._drained_teardown()
            raise HvdDrainedError(wid) from exc
        raise HvdAbortedError(
            exc.origin_rank,
            f"worker {wid} evicted from elastic membership at epoch "
            f"{exc.epoch} ({exc.cause})") from exc
    basics._elastic_reinit(exc.epoch, exc.members)


def run(fn, state, *args, **kwargs):
    """Drive ``fn(state, *args, **kwargs)`` elastically: sync state to
    every member first, then on each reconfiguration signal re-form the
    world, roll back to the last commit, re-sync, and retry ``fn``.
    Any other error (including a fatal ``HvdAbortedError``) propagates
    unchanged — elastic never swallows a non-survivable failure.

    Durable checkpointing (docs/checkpoint.md): when ``ckpt_dir`` is
    configured a :class:`~horovod_tpu.checkpoint.CheckpointManager` is
    attached to ``state`` for the duration of the call, the sync root
    auto-resumes from the newest complete checkpoint before the first
    sync (the broadcast distributes it), and a granted drain flushes
    pending writes before ``run`` returns :data:`DRAINED`."""
    from horovod_tpu import checkpoint as ckpt_mod

    log = get_logger()
    manager = None
    if state._ckpt is None:
        manager = ckpt_mod.manager_from_env()
        if manager is not None:
            state.attach_checkpoint(manager)
            # only the sync root reads the checkpoint; everyone else
            # receives the resumed state through the first sync()
            if not basics.is_initialized() or basics.rank() == 0:
                manager.restore_latest(state)
    try:
        pending_sync = True
        while True:
            try:
                if pending_sync:
                    state.sync()
                    pending_sync = False
                return fn(state, *args, **kwargs)
            except HvdReconfigureError as exc:
                log.warning(
                    "elastic: reconfiguration signal at step %s "
                    "(epoch %d, members %s); re-forming",
                    getattr(state, "step", "?"), exc.epoch, exc.members)
                reconfigure(exc)
                state.restore()
                pending_sync = True
    except HvdDrainedError as exc:
        log.warning("elastic: %s; leaving run cleanly", exc)
        return DRAINED
    finally:
        if manager is not None:
            state.attach_checkpoint(None)
            manager.close()


def _rendezvous_contract():
    addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
    port = env_util.get_str(env_util.HVD_RENDEZVOUS_PORT)
    if addr is None or port is None:
        raise RuntimeError(
            "elastic join requires the rendezvous env contract "
            "(HVD_RENDEZVOUS_ADDR/PORT — launch with hvdrun)")
    return addr, int(port)


def wait_for_membership(timeout=None, poll_interval=0.25):
    """Late-joiner entry point, called INSTEAD of ``hvd.init()``:
    register this worker's id with the rendezvous server, poll the
    published membership until an epoch admits it, then initialize the
    runtime directly at that epoch (catching up state is ``run``'s
    first sync).  Admission only happens at a reconfiguration window —
    a healthy job never readmits mid-flight."""
    from horovod_tpu.run import http_client

    addr, port = _rendezvous_contract()
    wid = env_util.get_int(env_util.HVD_RANK, 0)
    if timeout is None:
        timeout = env_util.get_float(
            env_util.HVD_TPU_RECONFIG_TIMEOUT,
            env_util.DEFAULT_RECONFIG_TIMEOUT_SECONDS)
    http_client.put(addr, port, JOIN_SCOPE, str(wid), b"1")
    log = get_logger()
    log.info("elastic: worker %d registered, waiting for admission",
             wid)
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(
                f"worker {wid} was not admitted to any membership "
                f"within {timeout:g}s")
        try:
            blob = http_client.get(addr, port, ELASTIC_SCOPE,
                                   MEMBERSHIP_KEY, timeout=remaining)
        except KeyError:
            raise TimeoutError(
                f"worker {wid} saw no reconfiguration window within "
                f"{timeout:g}s")
        epoch, members = decode_membership(blob)
        if wid in members:
            basics._elastic_join_init(epoch, members)
            return epoch
        # published membership predates our registration: wait for the
        # next window (sleep-poll; there is nothing to wake on — the
        # membership blob only changes at a reconfiguration)
        time.sleep(poll_interval)
