"""User-facing elastic training state (docs/elastic.md).

Reference: ``horovod/common/elastic.py`` — ``State`` with
``commit``/``restore``/``sync`` driven by ``elastic.run``.  Here the
state holds a params pytree, an optional optimizer-state pytree, and
integer counters; ``sync`` replays everything from the designated
survivor (new rank 0) over the existing broadcast path using
DETERMINISTIC tensor names (the eager auto-name counters diverge
between incumbents and late joiners, so sync must never rely on them).
"""

import numpy as np


def _tree_copy(tree):
    """Deep value copy of a pytree of arrays (jax arrays land as numpy:
    a committed snapshot must be immune to later in-place updates AND
    to device-buffer invalidation across a controller rebuild)."""
    import jax

    return jax.tree_util.tree_map(lambda x: np.array(x), tree)


class State:
    """Training state that survives membership reconfiguration.

    - ``commit()`` snapshots (params, optimizer_state, counters); call
      it at step boundaries you are willing to roll back to.
    - ``restore()`` rolls back to the last commit — the ``run`` driver
      calls it after a reconfiguration, because the interrupted step
      may have partially applied on some survivors.
    - ``sync(root_rank=0)`` replays the state from ``root_rank`` to
      every member (incumbents AND admitted joiners) over broadcast.

    ZeRO interplay (docs/sharding.md): with ``zero_n_params`` set, the
    live ``optimizer_state`` is the rank's 1/N shard, so the committed
    snapshot holds the FULL (allgathered) state instead — a lost rank
    takes its live shard with it, and a shard committed at world N
    cannot be re-assembled at world N-1.  ``restore``/``sync`` re-shard
    the full snapshot at whatever the CURRENT world size is.
    """

    def __init__(self, params=None, optimizer_state=None, step=0,
                 epoch=0, zero_n_params=None):
        self.params = params
        self.optimizer_state = optimizer_state
        self.step = int(step)
        self.epoch = int(epoch)   # user-level epoch counter, NOT the
        # membership epoch (that lives on the runtime)
        self.zero_n_params = (None if zero_n_params is None
                              else int(zero_n_params))
        self._committed = None
        self._opt_full = False   # committed opt tree is gathered (full)
        self._ckpt = None        # CheckpointManager (docs/checkpoint.md)
        # the constructor snapshot is LOCAL (no collectives): a late
        # joiner builds its State while incumbents are elsewhere, so a
        # gather here could not pair; the first in-loop commit() (or the
        # driver's first sync()) establishes the recoverable snapshot
        self.commit(_local=True)

    def _reshard_opt(self, full):
        """Live view of a committed FULL optimizer state: this rank's
        shard at the CURRENT (possibly reconfigured) world size."""
        from horovod_tpu.sharding.zero import reshard_zero_state

        return reshard_zero_state(_tree_copy(full), self.zero_n_params)

    def commit(self, _local=False):
        """Snapshot the state.  With ``zero_n_params`` set this is a
        COLLECTIVE (the shard-form optimizer state is allgathered into
        the snapshot), so every member must commit at the same point —
        which the step-boundary contract already implies."""
        # snapshot params first: if the gather is interrupted by a
        # reconfiguration, _committed keeps the previous complete tuple
        params = _tree_copy(self.params)
        if (self.zero_n_params is None or _local
                or self.optimizer_state is None):
            opt, full = _tree_copy(self.optimizer_state), False
        else:
            from horovod_tpu.sharding.zero import gather_zero_state

            opt = _tree_copy(gather_zero_state(
                self.optimizer_state, self.zero_n_params,
                name_prefix="elastic.zero.gather"))
            full = True
        self._committed = (params, opt, self.step, self.epoch)
        self._opt_full = full
        # durable checkpointing piggybacks on the commit snapshot: the
        # writer thread serializes the SAME double buffer the elastic
        # rollback uses, so no extra copy and no torn reads.  Local
        # (constructor) commits are skipped — nothing recoverable yet.
        if self._ckpt is not None and not _local:
            self._ckpt.maybe_save(self)

    def attach_checkpoint(self, manager):
        """Wire a :class:`horovod_tpu.checkpoint.CheckpointManager` into
        the commit path (``elastic.run`` does this when ``ckpt_dir`` is
        configured).  Returns the previous manager, if any."""
        prev, self._ckpt = self._ckpt, manager
        return prev

    def restore(self):
        params, opt, step, epoch = self._committed
        self.params = _tree_copy(params)
        if self._opt_full:
            self.optimizer_state = self._reshard_opt(opt)
        else:
            self.optimizer_state = _tree_copy(opt)
        self.step = step
        self.epoch = epoch

    def sync(self, root_rank=0):
        """Broadcast the designated survivor's committed view to every
        member.  Names are deterministic (tree-order indices under a
        fixed prefix), so a joiner that never issued the incumbents'
        earlier collectives still pairs correctly."""
        from horovod_tpu import jax_api
        from horovod_tpu.common import objects

        if self.params is not None:
            self.params = jax_api.broadcast_parameters(
                self.params, root_rank=root_rank,
                name_prefix="elastic.sync.params")
        if self.optimizer_state is not None:
            if self.zero_n_params is not None:
                # shard shapes differ across ranks (np.array_split
                # remainder), so the wire view is the committed FULL
                # state, shipped as an object: a joiner's own committed
                # tree is shard-form and could not template a tensor
                # broadcast.  Every member participates unconditionally
                # (a flag-gated send would deadlock joiner vs incumbent)
                # and the root's full/local status rides the payload.
                is_full, full = objects.broadcast_object(
                    (self._opt_full, self._committed[1]),
                    root_rank=root_rank, name="elastic.sync.zero_opt")
                if is_full:
                    self.optimizer_state = self._reshard_opt(full)
                # else: the root never committed past its freshly-
                # initialized state, which every member (re)derives
                # identically by construction — keep the local shard
            else:
                self.optimizer_state = jax_api.broadcast_parameters(
                    self.optimizer_state, root_rank=root_rank,
                    name_prefix="elastic.sync.opt")
        self.step, self.epoch = objects.broadcast_object(
            (self.step, self.epoch), root_rank=root_rank,
            name="elastic.sync.counters")
        self.commit()

    def __repr__(self):
        return (f"State(step={self.step}, epoch={self.epoch}, "
                f"params={'set' if self.params is not None else 'None'}, "
                f"optimizer_state="
                f"{'set' if self.optimizer_state is not None else 'None'})")
