"""User-facing elastic training state (docs/elastic.md).

Reference: ``horovod/common/elastic.py`` — ``State`` with
``commit``/``restore``/``sync`` driven by ``elastic.run``.  Here the
state holds a params pytree, an optional optimizer-state pytree, and
integer counters; ``sync`` replays everything from the designated
survivor (new rank 0) over the existing broadcast path using
DETERMINISTIC tensor names (the eager auto-name counters diverge
between incumbents and late joiners, so sync must never rely on them).
"""

import numpy as np


def _tree_copy(tree):
    """Deep value copy of a pytree of arrays (jax arrays land as numpy:
    a committed snapshot must be immune to later in-place updates AND
    to device-buffer invalidation across a controller rebuild)."""
    import jax

    return jax.tree_util.tree_map(lambda x: np.array(x), tree)


class State:
    """Training state that survives membership reconfiguration.

    - ``commit()`` snapshots (params, optimizer_state, counters); call
      it at step boundaries you are willing to roll back to.
    - ``restore()`` rolls back to the last commit — the ``run`` driver
      calls it after a reconfiguration, because the interrupted step
      may have partially applied on some survivors.
    - ``sync(root_rank=0)`` replays the state from ``root_rank`` to
      every member (incumbents AND admitted joiners) over broadcast.
    """

    def __init__(self, params=None, optimizer_state=None, step=0,
                 epoch=0):
        self.params = params
        self.optimizer_state = optimizer_state
        self.step = int(step)
        self.epoch = int(epoch)   # user-level epoch counter, NOT the
        # membership epoch (that lives on the runtime)
        self._committed = None
        self.commit()

    def commit(self):
        self._committed = (_tree_copy(self.params),
                           _tree_copy(self.optimizer_state),
                           self.step, self.epoch)

    def restore(self):
        params, opt, step, epoch = self._committed
        self.params = _tree_copy(params)
        self.optimizer_state = _tree_copy(opt)
        self.step = step
        self.epoch = epoch

    def sync(self, root_rank=0):
        """Broadcast the designated survivor's committed view to every
        member.  Names are deterministic (tree-order indices under a
        fixed prefix), so a joiner that never issued the incumbents'
        earlier collectives still pairs correctly."""
        from horovod_tpu import jax_api
        from horovod_tpu.common import objects

        if self.params is not None:
            self.params = jax_api.broadcast_parameters(
                self.params, root_rank=root_rank,
                name_prefix="elastic.sync.params")
        if self.optimizer_state is not None:
            self.optimizer_state = jax_api.broadcast_parameters(
                self.optimizer_state, root_rank=root_rank,
                name_prefix="elastic.sync.opt")
        self.step, self.epoch = objects.broadcast_object(
            (self.step, self.epoch), root_rank=root_rank,
            name="elastic.sync.counters")
        self.commit()

    def __repr__(self):
        return (f"State(step={self.step}, epoch={self.epoch}, "
                f"params={'set' if self.params is not None else 'None'}, "
                f"optimizer_state="
                f"{'set' if self.optimizer_state is not None else 'None'})")
