"""Elastic membership planning (coordinator side) and the rendezvous
contract joiners use to register (docs/elastic.md).

The reference's Elastic Horovod (``horovod/run/elastic/driver.py``)
re-discovers hosts and rebuilds the worker set when a slot is lost;
here membership is a list of **stable worker ids** (the launcher-
assigned initial ranks) in new-rank order, and the decision point is
the coordinator's ``_initiate_abort``: an attached :class:`ElasticContext`
rewrites a survivable failure into a reconfiguration directive that the
EXISTING abort fan-out (peer pushes, heartbeat replies, negotiation
responses) delivers to every survivor.
"""

import json
import threading

from horovod_tpu.common.handles import encode_reconfig_reason
from horovod_tpu.utils.logging import get_logger

# rendezvous scopes of the elastic contract (shared with joiners):
# the coordinator publishes admitted membership under
# ``elastic/membership``; a candidate joiner registers its worker id as
# a key in ``elastic-join`` and polls the membership blob until admitted
ELASTIC_SCOPE = "elastic"
MEMBERSHIP_KEY = "membership"
JOIN_SCOPE = "elastic-join"

# an explicit ``hvd.abort()`` is a kill switch, never rescued
# (common/basics.py uses this default reason prefix)
USER_ABORT_PREFIX = "aborted by user"


def encode_membership(epoch, members) -> bytes:
    return json.dumps({"epoch": epoch,
                       "members": list(members)}).encode()


def decode_membership(blob):
    d = json.loads(blob.decode())
    return int(d["epoch"]), [int(m) for m in d["members"]]


class ElasticContext:
    """Rank 0's membership planner, attached to the CoordinatorService.

    ``plan(origin_rank, reason)`` decides whether a failure is
    survivable and, if so, returns the encoded reconfiguration
    directive (the rewritten abort reason).  Sticky: the first plan
    wins, racing aborts read the cached directive — mirroring the
    coordinator's own sticky abort flag.
    """

    def __init__(self, members, epoch, min_ranks=1, max_ranks=0,
                 rendezvous=None, coord_failover=False):
        self._members = list(members)   # worker ids, current-rank order
        self._epoch = epoch
        self._min_ranks = min_ranks
        self._max_ranks = max_ranks
        self._rendezvous = rendezvous   # (addr, port) | None
        # coordinator fail-over armed (docs/elastic.md): a rank-0 loss
        # or drain is plannable like any other — survivors re-elect
        self._coord_failover = coord_failover
        self._lock = threading.Lock()
        # encoded directive once planned (None: fatal); sticky once
        # ``_decided`` is set; guarded by self._lock
        self._planned = None
        self._decided = False
        self._log = get_logger()

    def plan(self, origin_rank, reason):
        with self._lock:
            if not self._decided:
                self._planned = self._plan_locked(origin_rank, reason)
                self._decided = True
            return self._planned

    def plan_drain(self, origin_rank, cause=None):
        """Plan a PLANNED departure (graceful drain after a preemption
        notice, docs/checkpoint.md — or a straggler exclusion,
        docs/fault_tolerance.md): same survivor math as :meth:`plan`
        but the directive is drain-marked — nothing failed, nobody is
        blamed, delivery skips the abort fan-out.  ``cause`` overrides
        the recorded reason (default: the preemption-notice wording).
        A drain racing an already-decided plan is refused (None): the
        membership change in flight wins and the preempted rank leaves
        as an ordinary loss."""
        with self._lock:
            if self._decided:
                return None
            wid = (self._members[origin_rank]
                   if 0 <= origin_rank < len(self._members)
                   else origin_rank)
            if cause is None:
                cause = (f"worker {wid} drained after preemption "
                         f"notice (SIGTERM)")
            self._planned = self._plan_locked(origin_rank, cause,
                                              drain=True)
            self._decided = True
            return self._planned

    def _plan_locked(self, origin_rank, reason,
                     drain=False):  # holds: self._lock
        if isinstance(reason, str) \
                and reason.startswith(USER_ABORT_PREFIX):
            return None  # explicit kill switch: never rescued
        if not (0 <= origin_rank < len(self._members)):
            return None  # can't attribute the loss to a member
        if origin_rank == 0 and not self._coord_failover:
            # rank 0 hosts the coordinator itself: unless fail-over is
            # armed, the component that would orchestrate the rescue
            # is the casualty
            return None
        dead_wid = self._members[origin_rank]
        survivors = [w for w in self._members if w != dead_wid]
        if len(survivors) < self._min_ranks:
            self._log.error(
                "elastic: %d survivors < --min-ranks %d; failure of "
                "worker %d is fatal", len(survivors), self._min_ranks,
                dead_wid)
            return None
        joiners = self._registered_joiners(
            exclude=set(survivors) | {dead_wid})
        if self._max_ranks > 0:
            joiners = joiners[:max(0,
                                   self._max_ranks - len(survivors))]
        new_members = survivors + joiners
        new_epoch = self._epoch + 1
        self._publish(new_epoch, new_members, admitted=joiners)
        self._log.warning(
            "elastic: worker %d %s (%s); reconfiguring to epoch %d "
            "with members %s", dead_wid,
            "draining" if drain else "lost", reason, new_epoch,
            new_members)
        directive = encode_reconfig_reason(new_epoch, new_members,
                                           [dead_wid], reason,
                                           drain=drain)
        if origin_rank == 0:
            # durable handoff (docs/elastic.md#coordinator-fail-over):
            # this coordinator is the one leaving, so a survivor that
            # misses the directive's fan-out has nobody left to re-pull
            # it from.  Recording it at the epoch's election key means
            # such a survivor — timing out against the departed
            # coordinator and racing the fail-over election — adopts
            # THIS directive instead of proposing its own, and both
            # delivery paths converge on the identical epoch N+1 world.
            self._record_handoff(directive)
        return directive

    def _record_handoff(self, directive):
        """Best-effort CAS of a rank-0 departure directive at the
        election key; a failure only costs the backstop — survivors
        that elect without it compute the same successor membership."""
        if self._rendezvous is None:
            return
        from horovod_tpu.elastic import election
        from horovod_tpu.run import http_client
        addr, port = self._rendezvous
        try:
            http_client.cas_put(addr, port, election.ELECTION_SCOPE,
                                election.election_key(self._epoch),
                                directive.encode(), retry_for=2.0)
        except Exception:  # noqa: BLE001 — see docstring
            self._log.warning(
                "elastic: could not record the coordinator handoff "
                "directive for epoch %d", self._epoch, exc_info=True)

    def _registered_joiners(self, exclude):
        """Worker ids registered under the join scope, admission order
        = sorted (deterministic across racing registrations)."""
        if self._rendezvous is None:
            return []
        from horovod_tpu.run import http_client
        addr, port = self._rendezvous
        try:
            names = http_client.list_keys(addr, port, JOIN_SCOPE,
                                          retry_for=2.0)
        except Exception:  # noqa: BLE001 — no joiners this window
            return []
        out = []
        for name in names:
            try:
                wid = int(name)
            except ValueError:
                continue
            if wid not in exclude:
                out.append(wid)
        return sorted(out)

    def _publish(self, epoch, members, admitted=()):
        """Advertise the admitted membership for polling joiners.  A
        publish failure only costs this window's admissions — survivors
        get the directive via the abort fan-out regardless.  Admitted
        joiners' registration keys are dropped from the join scope so a
        LATER reconfiguration can't re-admit an id that is already a
        member (and the scope doesn't accumulate for the job's life)."""
        if self._rendezvous is None:
            return
        from horovod_tpu.run import http_client
        addr, port = self._rendezvous
        try:
            http_client.put(addr, port, ELASTIC_SCOPE, MEMBERSHIP_KEY,
                            encode_membership(epoch, members),
                            retry_for=5.0)
        except Exception:  # noqa: BLE001 — see docstring
            self._log.warning(
                "elastic: could not publish membership for epoch %d",
                epoch, exc_info=True)
        for wid in admitted:
            try:
                http_client.delete(addr, port, JOIN_SCOPE, str(wid),
                                   retry_for=2.0)
            except Exception:  # noqa: BLE001 — a stale join key is
                # filtered by the exclude set next window anyway
                pass
