"""Coordinator fail-over election (docs/elastic.md#coordinator-fail-over).

Rank 0 hosts the :class:`~horovod_tpu.ops.tcp_controller.CoordinatorService`,
so its loss used to be fatal by design: the component that orchestrates
the rescue was the casualty.  With ``HVD_TPU_COORD_FAILOVER=1`` the
survivors rescue THEMSELVES — the launcher-hosted rendezvous server
(run/http_server.py) outlives rank 0, and its atomic put-if-absent
endpoint is enough shared state for a leader election:

- every survivor that decides the coordinator is unreachable computes
  the SAME successor membership deterministically from its current
  ``(epoch, members)``: the dead coordinator's worker id (``members[0]``)
  drops out, survivor order is preserved — so the new rank 0 is the
  lowest surviving worker id, exactly the rank the PR 7 reconfiguration
  path would have made the state-sync root anyway;
- each survivor POSTs its proposed reconfiguration directive at the
  epoch-scoped key ``election/e<epoch>``; the rendezvous server keeps
  the FIRST value and answers every poster with it, so exactly one
  proposal wins and every loser ADOPTS the winning directive verbatim
  (split-brain is structurally impossible: there is one key);
- the winning directive then rides the ordinary abort machinery
  (`HvdReconfigureError` → ``hvd.elastic.run`` → ``_elastic_reinit``),
  and the new rank 0 starts a fresh CoordinatorService when the
  re-formed world gang-starts at epoch N+1.  Coordinator soft state
  (response caches, negotiation entries, liveness last-seen, RTT EWMAs)
  is rebuilt from scratch — none of it outlives a membership epoch.

Epoch fencing: the key embeds the elector's CURRENT epoch, so a
straggler still living at epoch N-1 cannot race an election for epoch
N's coordinator, and a directive adopted twice is idempotent
(``_elastic_reinit`` ignores ``epoch <= current``).

The same key doubles as the drain-handoff record: when rank 0 drains
gracefully (SIGTERM with fail-over armed), the membership planner
records its handoff directive here BEFORE fan-out — a survivor that
misses the pull-only drain delivery and later times out against the
departed coordinator elects, finds the recorded directive, and adopts
it, converging on the identical epoch N+1 membership.
"""

import time

from horovod_tpu.common.handles import (RECONFIG_MARKER,
                                        encode_reconfig_reason)
from horovod_tpu.utils.logging import get_logger

# rendezvous scope for the per-epoch election keys (key: ``e<epoch>``)
ELECTION_SCOPE = "election"


def election_key(epoch) -> str:
    return f"e{epoch}"


def propose_directive(epoch, members, reason, proposer_wid) -> str:
    """The directive this survivor would install if it wins: epoch N+1,
    the current membership minus the dead coordinator's worker id,
    order preserved.  Every survivor computes the same successor world;
    only the cause text (which names the proposer) differs — so the CAS
    has exactly one winner and the winner is identifiable."""
    dead_wid = members[0]
    survivors = list(members[1:])
    cause = (f"coordinator (worker {dead_wid}) lost: {reason}; "
             f"fail-over elected by worker {proposer_wid}")
    return encode_reconfig_reason(epoch + 1, survivors, [dead_wid],
                                  cause)


def elect(addr, port, epoch, members, reason, proposer_wid,
          timeout=10.0):
    """Race the epoch-scoped CAS election and return the winning
    reconfiguration directive (this proposer's own, or an adopted
    one), or ``None`` when the election is not winnable — rendezvous
    unreachable within ``timeout``, or the recorded winner is not a
    well-formed directive.  ``None`` means the caller falls back to
    today's fatal "coordinator unreachable" abort."""
    from horovod_tpu.run import http_client

    log = get_logger()
    deadline = time.monotonic() + timeout
    proposal = propose_directive(epoch, members, reason, proposer_wid)
    try:
        winner = http_client.cas_put(
            addr, port, ELECTION_SCOPE, election_key(epoch),
            proposal.encode(), deadline=deadline).decode()
    except Exception as exc:  # noqa: BLE001 — no rendezvous, no quorum
        log.error("fail-over: election at epoch %d unreachable within "
                  "%gs (%s); falling back to fatal abort", epoch,
                  timeout, exc)
        return None
    if not winner.startswith(RECONFIG_MARKER):
        log.error("fail-over: election key e%d holds a malformed "
                  "directive; falling back to fatal abort", epoch)
        return None
    if winner == proposal:
        log.warning("fail-over: worker %d won the epoch-%d election; "
                    "re-forming without worker %d", proposer_wid,
                    epoch, members[0])
    else:
        log.warning("fail-over: worker %d adopted the epoch-%d "
                    "election result", proposer_wid, epoch)
    return winner
