"""Per-rank process launch: local subprocesses or ssh fan-out.

Reference: ``horovod/run/gloo_run.py:237`` ``launch_gloo`` — one thread per
rank runs the (possibly ssh-prefixed) command with the env contract
(``gloo_run.py:152-157,261-273``); the first nonzero exit terminates every
other rank.
"""

import os
import shlex
import signal as signal_mod
import sys
import threading

from horovod_tpu.run import safe_shell_exec
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger


def describe_exit(code) -> str:
    """Human-readable exit status: negative Popen codes are signal
    deaths and deserve the signal's name, not a bare '-9'."""
    if code < 0:
        try:
            name = signal_mod.Signals(-code).name
        except ValueError:
            name = f"signal {-code}"
        return f"killed by {name}"
    return f"exit code {code}"

LOCAL_HOSTS = ("localhost", "127.0.0.1")


class _Tee:
    """Write to a rank's output file AND the launcher console.

    Reference: ``gloo_run.py`` ``MultiFile`` — ``--output-filename``
    captures per-rank files without silencing the console.  The file
    is the primary sink; a dead console (e.g. BrokenPipeError after
    ``hvdrun ... | head`` exits) must not truncate the file capture.
    A merely *blocked* console (paused pager) stalls the forwarder —
    same as the reference's MultiFile and as the plain inherit-console
    path, where the child itself blocks."""

    def __init__(self, primary, *mirrors):
        self._primary = primary
        self._mirrors = mirrors

    def write(self, data):
        self._primary.write(data)
        for s in self._mirrors:
            try:
                s.write(data)
            except (OSError, ValueError):
                pass

    def flush(self):
        self._primary.flush()
        for s in self._mirrors:
            try:
                s.flush()
            except (OSError, ValueError):
                pass


def slot_env(slot, rendezvous_addr, rendezvous_port, extra_env=None):
    """The worker env contract for one rank."""
    env = {
        env_util.HVD_RANK: str(slot.rank),
        env_util.HVD_SIZE: str(slot.size),
        env_util.HVD_LOCAL_RANK: str(slot.local_rank),
        env_util.HVD_LOCAL_SIZE: str(slot.local_size),
        env_util.HVD_CROSS_RANK: str(slot.cross_rank),
        env_util.HVD_CROSS_SIZE: str(slot.cross_size),
        env_util.HVD_RENDEZVOUS_ADDR: rendezvous_addr,
        env_util.HVD_RENDEZVOUS_PORT: str(rendezvous_port),
    }
    if extra_env:
        env.update(extra_env)
    return env


SECRET_ENV_VARS = (env_util.HVD_SECRET_KEY,)


def fault_crash_ranks(extra_env):
    """Ranks the job's own fault spec arms with a ``crash``: when the
    launcher injected the failure itself, the culprit is known by
    construction and no timing evidence can outvote it."""
    spec_text = (extra_env or {}).get(env_util.HVD_TPU_FAULT_SPEC)
    if not spec_text:
        return frozenset()
    from horovod_tpu.common.faults import parse_fault_spec

    try:
        specs = parse_fault_spec(spec_text)
    except ValueError:
        return frozenset()  # the workers will fail loudly at init
    # preempt counts: with drain disabled it kills the rank just like a
    # crash, and with drain enabled the rank exits 0 and never appears
    # in the failure list at all
    return frozenset(s.rank for s in specs
                     if s.action in ("crash", "preempt")
                     and s.rank is not None)


def pick_culprit(failures, crash_ranks=frozenset()):
    """(rank, code) of the rank that broke the job.

    ``failures``: [(rank, code, was_victim, exit_ts)] in REAP order —
    which under machine load is not death order: a survivor that exits
    nonzero because of the coordinated abort can be reaped before the
    rank whose death caused it (stream-forwarder drains and thread
    scheduling sit between a child dying and its failure being
    recorded).  Attribution therefore ranks by evidence, not arrival:

    1. a rank that exited 0 is never the culprit — a drained rank
       leaves cleanly by design and must not be named the casualty
       (callers only record nonzero exits, so this guard is defensive);
    2. victims of the kill fan-out are never culprits (all-victims is a
       launcher-interrupt edge case: fall back to the full list);
    3. a rank the job's own ``HVD_TPU_FAULT_SPEC`` armed with a crash
       is the culprit by construction;
    4. otherwise the earliest ``exit_ts`` wins — the child observed
       dead first is the closest thing to the true first death.
    """
    failures = [f for f in failures if f[1] != 0] or list(failures)
    candidates = [f for f in failures if not f[2]] or list(failures)
    armed = [f for f in candidates if f[0] in crash_ranks]
    pool = armed or candidates
    first = min(enumerate(pool),
                key=lambda item: (item[1][3] is None,
                                  item[1][3], item[0]))[1]
    return first[0], first[1]


def _ssh_command(slot, command, env, ssh_port=None):
    """Build the remote launch command.  Secrets never appear on the remote
    command line (visible in ps/verbose logs); they travel over ssh stdin
    into a `read -r` in the remote shell.  Returns (command, stdin_data)."""
    secrets = {k: v for k, v in env.items() if k in SECRET_ENV_VARS}
    public = {k: v for k, v in env.items() if k not in SECRET_ENV_VARS}
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in public.items())
    port = f"-p {ssh_port} " if ssh_port else ""
    stdin_lines = "".join(f"{k}={v}\n" for k, v in secrets.items())
    reads = "".join(
        f"IFS= read -r {k}; export {k}=\"${{{k}#{k}=}}\"; "
        for k in secrets)
    inner = (f"{reads}cd {shlex.quote(os.getcwd())} && "
             f"{exports} {command}")
    cmd = (f"ssh -o StrictHostKeyChecking=no {port}"
           f"{slot.hostname} {shlex.quote(inner)}")
    return cmd, stdin_lines.encode() if stdin_lines else None


def launch_job(slots, command, rendezvous_addr, rendezvous_port,
               extra_env=None, ssh_port=None, verbose=False,
               output_filename=None, elastic=False, min_ranks=1,
               coord_failover=False) -> int:
    """Launch one process per slot; kill everything on first failure.
    Returns the CULPRIT's exit code (or 0): the first rank that failed
    on its own — ranks the kill-on-first-failure fan-out subsequently
    terminated report as victims (they die with signal codes like -15
    that would mask the real error if arrival order decided).

    With ``elastic=True`` (docs/elastic.md) a non-rank-0 failure does
    NOT trigger the kill fan-out: the in-job runtime re-forms the ring
    around the survivors, so the launcher's job is to supervise them to
    completion.  The fan-out still fires when rank 0 dies (it hosts the
    coordinator — nothing can orchestrate a rescue) or when fewer than
    ``min_ranks`` workers remain.  With ``coord_failover=True``
    (docs/elastic.md#coordinator-fail-over) even a rank-0 loss is
    survivable: the workers elect a replacement coordinator at the
    rendezvous, so the launcher supervises the survivors exactly as for
    any other rank's death.

    A SIGTERM delivered to the launcher itself (the platform preempting
    the whole allocation) is forwarded once to every worker process
    group so workers can drain (docs/checkpoint.md); an escalation
    timer then fires the ordinary kill fan-out after the
    HVD_TPU_TERM_GRACE window for anything still running."""
    log = get_logger()
    failure = threading.Event()
    drain = threading.Event()
    # [(rank, code, was_victim, exit_ts)] in reap order — culprit
    # attribution re-ranks by evidence, see pick_culprit
    failures = []
    failures_lock = threading.Lock()
    alive = [len(slots)]  # guarded by failures_lock

    def run_rank(slot):
        info = {}
        try:
            env = slot_env(slot, rendezvous_addr, rendezvous_port,
                           extra_env)
            stdin_data = None
            if slot.hostname in LOCAL_HOSTS:
                # local: secrets ride the process env, never a command
                # line
                full_env = dict(os.environ)
                full_env.update(env)
                cmd = command
            else:
                full_env = dict(os.environ)
                cmd, stdin_data = _ssh_command(slot, command, env,
                                               ssh_port)
            if verbose:
                log.warning("launching rank %d on %s: %s", slot.rank,
                            slot.hostname, cmd)
            out_f = err_f = None
            stdout, stderr = sys.stdout, sys.stderr
            try:
                if output_filename:
                    # reference layout (gloo_run.py MultiFile): write
                    # <dir>/rank.<NN>/stdout|stderr AND tee to the
                    # console; rank dir zero-padded to num_proc-1 width
                    pad = len(str(max(len(slots) - 1, 1)))
                    rank_dir = os.path.join(
                        output_filename, f"rank.{slot.rank:0{pad}d}")
                    os.makedirs(rank_dir, exist_ok=True)
                    out_f = open(os.path.join(rank_dir, "stdout"), "w")
                    err_f = open(os.path.join(rank_dir, "stderr"), "w")
                    stdout = _Tee(out_f, sys.stdout)
                    stderr = _Tee(err_f, sys.stderr)
                code = safe_shell_exec.execute(
                    cmd, env=full_env, stdout=stdout, stderr=stderr,
                    events=[failure], stdin_data=stdin_data, info=info,
                    term_events=[drain])
            finally:
                for f in (out_f, err_f):
                    if f is not None:
                        f.close()
        except Exception as exc:  # noqa: BLE001 — a thread dying
            # silently would record no failure (reported success) while
            # sibling ranks hang waiting for this one
            log.error("launching rank %d failed: %s", slot.rank, exc)
            code = 1
        if code != 0:
            with failures_lock:
                # a rank that died nonzero AFTER the launcher forwarded
                # its drain SIGTERM is a victim of that signal, not a
                # failure of its own
                failures.append((slot.rank, code,
                                 info.get("terminated_by_event", False)
                                 or info.get("drained", False),
                                 info.get("exit_ts")))
                alive[0] -= 1
                survivors = alive[0]
            if (elastic and (slot.rank != 0 or coord_failover)
                    and survivors >= min_ranks):
                # survivable under elastic: the runtime re-forms around
                # the remaining ranks (a rank-0 loss only with fail-over
                # armed — the survivors elect a replacement coordinator);
                # keep supervising, don't kill
                log.warning(
                    "rank %d failed (%s); elastic mode: supervising "
                    "%d surviving rank(s)", slot.rank,
                    describe_exit(code), survivors)
            else:
                failure.set()
        else:
            with failures_lock:
                alive[0] -= 1

    escalation = []  # [threading.Timer] so the success path can cancel

    def _on_sigterm(signum, frame):
        grace = safe_shell_exec.termination_grace_seconds()
        log.warning("SIGTERM: forwarding to all ranks, escalating to "
                    "the kill fan-out in %.1fs", grace)
        drain.set()
        timer = threading.Timer(grace, failure.set)
        timer.daemon = True
        timer.start()
        escalation.append(timer)

    prev_sigterm = None
    try:
        # signal.signal only works on the main thread; a launcher
        # embedded somewhere else simply doesn't get drain forwarding
        prev_sigterm = signal_mod.signal(signal_mod.SIGTERM,
                                         _on_sigterm)
    except ValueError:
        pass

    threads = [threading.Thread(target=run_rank, args=(s,), daemon=True)
               for s in slots]
    for t in threads:
        t.start()
    try:
        for t in threads:
            t.join()
    except KeyboardInterrupt:
        # the interrupt lands HERE (main thread), not in the launcher
        # threads — without this, the driver exits and every child
        # (started in its own session, so it never sees the terminal's
        # SIGINT) keeps running, holding chips and ports
        log.warning("interrupted: terminating all ranks")
        failure.set()
        for t in threads:
            t.join(timeout=15)
        raise
    finally:
        for timer in escalation:
            timer.cancel()
        if prev_sigterm is not None:
            try:
                signal_mod.signal(signal_mod.SIGTERM, prev_sigterm)
            except ValueError:
                pass

    if drain.is_set() and not failures:
        log.warning("all ranks drained cleanly after SIGTERM")
    if failures and elastic and not failure.is_set():
        # every loss was absorbed by a reconfiguration and the
        # survivors ran to completion: the job succeeded
        log.warning("%d rank(s) were lost but the surviving ranks "
                    "completed after elastic reconfiguration",
                    len(failures))
        return 0
    if failures:
        # name the culprit: the first rank that failed on its OWN, not
        # a victim the fan-out terminated, ranked by when each child
        # was observed dead (and by the fault spec's own crash ranks
        # when the failure was injected) — see pick_culprit.  Reap
        # order decided before, and a survivor exiting nonzero because
        # of the coordinated abort could out-race the true origin
        # under machine load.
        rank, code = pick_culprit(failures,
                                  fault_crash_ranks(extra_env))
        log.error("rank %d failed first (%s); %d other rank(s) were "
                  "terminated", rank, describe_exit(code),
                  len(failures) - 1)
        return code
    return 0
