"""Per-rank process launch: local subprocesses or ssh fan-out.

Reference: ``horovod/run/gloo_run.py:237`` ``launch_gloo`` — one thread per
rank runs the (possibly ssh-prefixed) command with the env contract
(``gloo_run.py:152-157,261-273``); the first nonzero exit terminates every
other rank.
"""

import os
import shlex
import sys
import threading

from horovod_tpu.run import safe_shell_exec
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

LOCAL_HOSTS = ("localhost", "127.0.0.1")


def slot_env(slot, rendezvous_addr, rendezvous_port, extra_env=None):
    """The worker env contract for one rank."""
    env = {
        env_util.HVD_RANK: str(slot.rank),
        env_util.HVD_SIZE: str(slot.size),
        env_util.HVD_LOCAL_RANK: str(slot.local_rank),
        env_util.HVD_LOCAL_SIZE: str(slot.local_size),
        env_util.HVD_CROSS_RANK: str(slot.cross_rank),
        env_util.HVD_CROSS_SIZE: str(slot.cross_size),
        env_util.HVD_RENDEZVOUS_ADDR: rendezvous_addr,
        env_util.HVD_RENDEZVOUS_PORT: str(rendezvous_port),
    }
    if extra_env:
        env.update(extra_env)
    return env


SECRET_ENV_VARS = (env_util.HVD_SECRET_KEY,)


def _ssh_command(slot, command, env, ssh_port=None):
    """Build the remote launch command.  Secrets never appear on the remote
    command line (visible in ps/verbose logs); they travel over ssh stdin
    into a `read -r` in the remote shell.  Returns (command, stdin_data)."""
    secrets = {k: v for k, v in env.items() if k in SECRET_ENV_VARS}
    public = {k: v for k, v in env.items() if k not in SECRET_ENV_VARS}
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in public.items())
    port = f"-p {ssh_port} " if ssh_port else ""
    stdin_lines = "".join(f"{k}={v}\n" for k, v in secrets.items())
    reads = "".join(
        f"IFS= read -r {k}; export {k}=\"${{{k}#{k}=}}\"; "
        for k in secrets)
    inner = (f"{reads}cd {shlex.quote(os.getcwd())} && "
             f"{exports} {command}")
    cmd = (f"ssh -o StrictHostKeyChecking=no {port}"
           f"{slot.hostname} {shlex.quote(inner)}")
    return cmd, stdin_lines.encode() if stdin_lines else None


def launch_job(slots, command, rendezvous_addr, rendezvous_port,
               extra_env=None, ssh_port=None, verbose=False) -> int:
    """Launch one process per slot; kill everything on first failure.
    Returns the first nonzero exit code (or 0)."""
    log = get_logger()
    failure = threading.Event()
    exit_codes = [0] * len(slots)

    def run_rank(i, slot):
        env = slot_env(slot, rendezvous_addr, rendezvous_port, extra_env)
        stdin_data = None
        if slot.hostname in LOCAL_HOSTS:
            # local: secrets ride the process env, never a command line
            full_env = dict(os.environ)
            full_env.update(env)
            cmd = command
        else:
            full_env = dict(os.environ)
            cmd, stdin_data = _ssh_command(slot, command, env, ssh_port)
        if verbose:
            log.warning("launching rank %d on %s: %s", slot.rank,
                        slot.hostname, cmd)
        code = safe_shell_exec.execute(
            cmd, env=full_env, stdout=sys.stdout, stderr=sys.stderr,
            events=[failure], stdin_data=stdin_data)
        exit_codes[i] = code
        if code != 0:
            failure.set()

    threads = [threading.Thread(target=run_rank, args=(i, s), daemon=True)
               for i, s in enumerate(slots)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for code in exit_codes:
        if code != 0:
            return code
    return 0
