"""Programmatic ``run(fn)`` API.

Reference: ``horovod/run/runner.py:648-669,742`` — ship a pickled function
to every rank through the rendezvous KV store, execute it under the full
env contract, and collect per-rank return values.
"""

import base64
import pickle
import sys

from horovod_tpu.run import allocate as allocate_mod
from horovod_tpu.run.http_server import RendezvousServer
from horovod_tpu.run.launch import launch_job
from horovod_tpu.run.service import secret as secret_mod
from horovod_tpu.utils import env as env_util

try:
    import cloudpickle as _pickler
except ImportError:  # cloudpickle not in the image; plain pickle handles
    _pickler = pickle  # module-level functions, which covers the API's use


FN_SCOPE = "runfunc"
RESULT_SCOPE = "results"


def run(fn, args=(), kwargs=None, np=1, hosts=None, extra_env=None,
        verbose=False, use_tpu=False, elastic=False, min_ranks=1):
    """Run ``fn(*args, **kwargs)`` on ``np`` ranks; returns the list of
    per-rank return values (rank order)."""
    kwargs = kwargs or {}

    if hosts:
        host_list = allocate_mod.parse_hosts(hosts)
    else:
        host_list = [allocate_mod.HostInfo("localhost", np)]
    slots = allocate_mod.allocate(host_list, np)

    rendezvous = RendezvousServer()
    port = rendezvous.start()
    try:
        # the KV store is an unauthenticated HTTP server bound on
        # 0.0.0.0; the pickled-fn and pickled-result channels through it
        # are HMAC-signed with the job secret so a network peer cannot
        # inject pickles into the workers or the driver
        supplied = (extra_env or {}).get(env_util.HVD_SECRET_KEY) \
            or env_util.get_str(env_util.HVD_SECRET_KEY)
        key = base64.b64decode(supplied) if supplied \
            else secret_mod.make_secret_key()

        payload = _pickler.dumps((fn, args, kwargs))
        signed = secret_mod.sign(key, payload) + payload
        with rendezvous._server.kv_lock:
            rendezvous._server.kv.setdefault(FN_SCOPE, {})["fn"] = signed

        env = dict(extra_env or {})
        env.setdefault("HVD_RUN_FUNC", "1")
        # force-set: workers must hold the SAME key the driver signs with
        env[env_util.HVD_SECRET_KEY] = base64.b64encode(key).decode()
        if np > 1:
            env.setdefault(env_util.HVD_CONTROLLER, "tcp")
        if use_tpu:
            env.setdefault("HVD_TPU", "1")

        # remote workers must reach the driver's KV store; honor the
        # same override + discovery the CLI path uses
        addr = env_util.get_str(env_util.HVD_RENDEZVOUS_HOST_ADDR)
        if addr is None:
            from horovod_tpu.run.runner import _routable_addr

            addr = _routable_addr(slots)

        if elastic:
            env.setdefault(env_util.HVD_TPU_ELASTIC, "1")
        command = f"{sys.executable} -m horovod_tpu.run.task_runner"
        code = launch_job(slots, command, addr, port, extra_env=env,
                          verbose=verbose, elastic=elastic,
                          min_ranks=min_ranks)
        if code != 0:
            raise RuntimeError(f"hvdrun job failed with exit code {code}")
        results = []
        for rank in range(np):
            blob = rendezvous.get(RESULT_SCOPE, str(rank))
            if blob is None:
                raise RuntimeError(f"rank {rank} produced no result")
            digest, payload = (blob[:secret_mod.DIGEST_LEN],
                               blob[secret_mod.DIGEST_LEN:])
            if not secret_mod.check(key, payload, digest):
                raise PermissionError(
                    f"rank {rank} result failed HMAC verification")
            status, value = pickle.loads(payload)
            if status == "error":
                raise RuntimeError(f"rank {rank} failed: {value}")
            results.append(value)
        return results
    finally:
        rendezvous.stop()
