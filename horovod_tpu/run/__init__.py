from horovod_tpu.run.api import run  # noqa: F401
