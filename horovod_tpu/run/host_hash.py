"""Host identity (reference: ``horovod/run/util/host_hash.py``): ranks
sharing a host_hash share local (fast-interconnect) topology.  The hash
folds in an optional salt (``HVD_HOSTNAME_HASH_SALT``) so containerized
deployments where every container reports the same hostname can force
distinct identities."""

import hashlib
import os
import socket


def host_hash(salt=None) -> str:
    hostname = socket.gethostname()
    salt = salt if salt is not None else os.environ.get(
        "HVD_HOSTNAME_HASH_SALT", "")
    digest = hashlib.md5(f"{hostname}-{salt}".encode()).hexdigest()
    return f"{hostname.split('.')[0]}-{digest[:8]}"
