"""Host identity (reference: ``horovod/run/util/host_hash.py``): ranks
sharing a host_hash share local (fast-interconnect) topology.  The hash
folds in an optional salt (``HVD_HOSTNAME_HASH_SALT``) so containerized
deployments where every container reports the same hostname can force
distinct identities."""

import hashlib
import socket

from horovod_tpu.utils import env as env_util


def host_hash(salt=None) -> str:
    hostname = socket.gethostname()
    if salt is None:
        salt = env_util.get_str(env_util.HVD_HOSTNAME_HASH_SALT, "")
    digest = hashlib.md5(f"{hostname}-{salt}".encode()).hexdigest()
    return f"{hostname.split('.')[0]}-{digest[:8]}"
