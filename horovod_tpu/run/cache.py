"""On-disk memo for launcher-side checks (reference:
``horovod/run/util/cache.py`` — 60-minute cache of ssh reachability and
NIC discovery results so repeated ``horovodrun`` invocations skip the
slow probes)."""

import json
import os
import threading
import time

DEFAULT_TTL_SECONDS = 60 * 60


class Cache:
    def __init__(self, path=None, ttl_seconds=DEFAULT_TTL_SECONDS,
                 parameters_hash=""):
        if path is None:
            # one file per parameter set: alternating configurations
            # (e.g. different ssh ports) must not clobber each other
            import hashlib
            tag = hashlib.md5(parameters_hash.encode()).hexdigest()[:8]
            path = os.path.join(os.path.expanduser("~"),
                                ".horovod_tpu", f"cache-{tag}.json")
        self._path = path
        self._ttl = ttl_seconds
        self._params = parameters_hash
        self._lock = threading.Lock()
        self._content = self._load()

    def _load(self):
        try:
            with open(self._path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {}
        # a changed parameter set (e.g. different ssh port) invalidates
        # everything, like the reference's parameters-hash guard
        if data.get("__params__") != self._params:
            return {}
        return data

    def get(self, key):
        with self._lock:
            entry = self._content.get(key)
            if entry is None:
                return None
            value, ts = entry
            if time.time() - ts > self._ttl:
                del self._content[key]
                return None
            return value

    def put(self, key, value):
        with self._lock:
            self._content[key] = (value, time.time())
            self._content["__params__"] = self._params
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                tmp = f"{self._path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(self._content, f)
                os.replace(tmp, self._path)
            except OSError:
                pass  # cache is best-effort
