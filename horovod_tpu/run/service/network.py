"""Secret-keyed pickled-message TCP services (reference:
``horovod/run/common/service/__init__.py`` + ``horovod/run/common/util/
network.py`` — a threaded socket server exchanging HMAC-signed pickled
request/response objects, plus interface enumeration helpers used for
routable-NIC discovery).

Wire format per message: ``[4-byte big-endian length][32-byte HMAC-SHA256
digest][pickled (direction, object)]``.  The digest is verified BEFORE
unpickling — an unauthenticated peer cannot reach the unpickler — and the
claimed length is capped before any buffering, so an unauthenticated peer
cannot make the service hold gigabytes either.  The signed envelope
carries a direction tag ("q" request / "r" response) so a reflected
frame cannot answer a request, and mux request ids start at a random
64-bit offset so a frame recorded from an earlier connection cannot pair
with a live request.  (An on-path adversary that can splice into the TCP
stream in real time is outside this threat model — that requires TLS.)
"""

import pickle
import random
import secrets as _secrets
import socket
import socketserver
import struct
import sys
import threading
import time

from horovod_tpu.run.service import secret
from horovod_tpu.utils import env as env_util

# Largest frame accepted before authentication.  Generous: the tcp star
# data plane ships whole tensors (the bench sweep goes to 256 MB).
MAX_FRAME_BYTES = 1 << 30

# Bulk (raw-bytes) frame: the high bit of the length word flags a frame
# whose payload travels as raw bytes AFTER a small pickled header —
#   [4B RAW_FRAME_FLAG|header_len][32B HMAC][4B payload_len]
#   [pickled (direction, obj)][payload bytes]
# The HMAC covers [header_len][payload_len][header][payload] — the
# length words are bound in so an on-path attacker can't shift the
# header/payload boundary into a silently truncated payload — and is
# verified before unpickling; the payload is never pickled (no
# serialize copy on the send side, a single recv_into buffer on the
# receive side).  MAX_FRAME_BYTES < 2^30 keeps the flag bit
# unambiguous.
RAW_FRAME_FLAG = 0x80000000
# the pickled header of a bulk frame is a tag + rank, never big
MAX_RAW_HEADER_BYTES = 1 << 16


# ------------------------------------------------------------- base messages
class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name):
        self.service_name = service_name


class AckResponse:
    pass


# Fault-tolerance control messages, shared by the tcp and global-mesh
# coordinators (docs/fault_tolerance.md): any rank can broadcast an
# abort for the in-flight round; heartbeats keep the coordinator's
# last-seen table fresh and carry the abort state back.
# epoch-exempt: the abort channel is epoch-agnostic by design — a
# world dying at epoch N must be able to kill collectives on ranks that
# already adopted N+1; fencing it would strand exactly the straggler
# ranks an abort exists to release (docs/fault_tolerance.md)
class AbortMsg:
    def __init__(self, origin_rank, reason):
        self.origin_rank = origin_rank
        self.reason = reason


# epoch-exempt: liveness must keep flowing across reconfiguration
# boundaries — the coordinator's last-seen table is how a rank that
# died MID-reconfiguration gets detected, so heartbeats deliberately
# cross epochs (docs/fault_tolerance.md)
class HeartbeatMsg:
    def __init__(self, rank, busy=False, rtt=None, host=None,
                 reconnecting=None):
        self.rank = rank
        # peers this rank is currently healing a session toward
        # (docs/fault_tolerance.md "connection blips vs dead peers"):
        # the coordinator treats a healing rank like a busy one — wider
        # liveness deadline, no straggler verdicts — so a link blip is
        # never converted into an exclusion or an abort
        self.reconnecting = reconnecting
        # sender's launcher host hash (run/host_hash.py): the
        # coordinator groups co-located ranks from these when planning
        # the hierarchical collective schedule (docs/tuning.md)
        self.host = host
        # rank is inside a known-slow-but-alive window (checkpoint
        # write, drain teardown): the coordinator widens its liveness
        # deadline so disk I/O can't read as death (docs/checkpoint.md)
        self.busy = busy
        # sender's worst observed link RTT EWMA in seconds (heartbeat
        # round trips + ring chunk sends): the coordinator adds an
        # RTT-proportional slack to this rank's liveness window so a
        # slow-but-alive link never reads as death
        # (docs/fault_tolerance.md "degraded networks")
        self.rtt = rtt


class HeartbeatReply:
    def __init__(self, abort=None):
        self.abort = abort  # (origin_rank, reason) | None


# -------------------------------------------------------- session messages
# Reliable session layer (docs/fault_tolerance.md "connection blips vs
# dead peers"): every long-lived peer connection opens with a hello /
# welcome exchange that names a stable session id, and every frame the
# client writes carries a monotonic sequence number inside its request
# id.  On a mid-stream break the client reconnects inside the
# HVD_TPU_RECONNECT_BUDGET window, re-offers the same session, learns
# from the welcome which frames the service already delivered, and
# retransmits only the tail — the service dedups by seq, so a collective
# in flight completes without any rank observing an error.  The layer is
# entirely inert (zero extra frames, request ids unchanged) when the
# budget is 0.
class SessionHello:
    def __init__(self, session_id, epoch, rx_seen):
        self.session_id = session_id
        # the sender's view of the controller epoch: a hello from before
        # a reconfiguration must NOT resume into the new epoch's service
        # (the welcome comes back refused and the client escalates)
        self.epoch = epoch
        self.rx_seen = rx_seen  # reserved: client->service direction only


class SessionWelcome:
    def __init__(self, rx_seen, refused=False):
        # highest contiguous client seq this service delivered — the
        # client prunes its replay buffer to here and retransmits the
        # rest
        self.rx_seen = rx_seen
        self.refused = refused  # epoch fence: do not resume, escalate


class SessionAck:
    def __init__(self, seen):
        self.seen = seen  # cumulative: every seq <= seen is delivered


# session knobs resolve from the env contract at client construction
# (tests pass explicit ctor kwargs instead to avoid env mutation)
def default_reconnect_budget():
    return env_util.get_float(env_util.HVD_TPU_RECONNECT_BUDGET,
                              env_util.DEFAULT_RECONNECT_BUDGET_SECONDS)


def default_replay_bytes():
    return env_util.get_int(env_util.HVD_TPU_REPLAY_BUFFER_BYTES,
                            env_util.DEFAULT_REPLAY_BUFFER_BYTES)


# service acks every Nth delivered frame (piggybacked on the existing
# connection, never a new one); the sender prunes its replay buffer on
# each — so steady-state overhead is one tiny frame per N, not per write
_SESSION_ACK_EVERY = 16
# responses the service retains per session for redelivery after a heal
# (a response can vanish in the kernel buffer of a dying socket without
# the write erroring — the resume flush covers that window)
_SESSION_RESP_KEEP = 256
# replay-buffer byte estimate for a control frame (the exact pickled
# size isn't known until write time; control messages are tiny and the
# bound only needs the right order of magnitude)
_CTRL_FRAME_EST = 1024

# session-id sanity bound: ours are 16 hex chars (token_hex(8)); a
# verified-but-hostile hello must not intern megabyte strings as dict
# keys
_MAX_SESSION_ID_LEN = 64
# sessions retained per service: sessions outlive sockets by design, so
# without a cap a peer re-helloing with fresh ids would grow the table
# forever.  At the cap, admission first evicts sessions with no live
# socket (oldest first), then refuses.
_MAX_SESSIONS = 1024


def _valid_seq(value):
    """True for a trustworthy sequence/ack number: a real int (bool is
    an int subclass but never a seq) in the non-negative range a
    well-behaved peer can produce.  Everything in a session record —
    seq, ack ``seen``, welcome ``rx_seen`` — arrives inside a VERIFIED
    envelope, but verified only means the peer holds the key, not that
    the field is sane: these values reach dict keys, comparisons and
    replay-buffer arithmetic, so they are bounds-checked like any other
    wire input."""
    return (isinstance(value, int) and not isinstance(value, bool)
            and 0 <= value < (1 << 62))


# process-wide session telemetry (soak gates + bench read these)
_session_stats_lock = threading.Lock()
_session_stats = {"reconnects_healed": 0, "reconnects_failed": 0,
                  "frames_replayed": 0}


def _session_note(kind, n=1):
    with _session_stats_lock:
        _session_stats[kind] = _session_stats.get(kind, 0) + n


def session_stats():
    """Snapshot of the process-wide session-layer counters."""
    with _session_stats_lock:
        return dict(_session_stats)


# peers with a heal in flight RIGHT NOW: the worker's heartbeat reports
# these so the coordinator widens the liveness deadline instead of
# reading the recovery pause as death
_healing_lock = threading.Lock()
_healing = {}  # peer -> nesting depth


def _healing_enter(peer):
    with _healing_lock:
        _healing[peer] = _healing.get(peer, 0) + 1


def _healing_exit(peer):
    with _healing_lock:
        depth = _healing.get(peer, 0) - 1
        if depth <= 0:
            _healing.pop(peer, None)
        else:
            _healing[peer] = depth


def healing_peers():
    """Sorted ranks this process is currently healing a session toward."""
    with _healing_lock:
        return sorted(p for p in _healing if p is not None)


class _SessionResumeRefused(ConnectionError):
    """The service fenced the resume (stale epoch) or the replay buffer
    no longer holds a frame the service needs — healing would leave a
    silent gap, so the ORIGINAL transport error must escalate."""


class _SessionSender:
    """Client half of a transport session: assigns the per-direction
    sequence numbers, retains every unacknowledged frame in a
    byte-bounded replay buffer (drop-oldest), prunes on cumulative
    acks.  Callers serialize access under their own write lock so
    replay order always equals wire order."""

    def __init__(self, epoch, replay_bytes):
        self.session_id = _secrets.token_hex(8)
        self.epoch = epoch
        self._limit = max(0, int(replay_bytes))
        self._frames = {}      # seq -> (record, nbytes); insertion-ordered
        self._bytes = 0
        self._next = 1
        self._oldest = 1       # oldest seq still retained
        self.acked = 0

    def append(self, make_record, nbytes):
        """Assign the next seq, build the frame record via
        ``make_record(seq)`` and retain it for replay.  Returns
        ``(seq, record)``."""
        seq = self._next
        self._next += 1
        record = make_record(seq)
        self._frames[seq] = (record, nbytes)
        self._bytes += nbytes
        while self._bytes > self._limit and self._frames:
            old = next(iter(self._frames))
            _, nb = self._frames.pop(old)
            self._bytes -= nb
            self._oldest = old + 1
        return seq, record

    def ack(self, seen):
        """Cumulative ack: drop every retained frame with seq <= seen."""
        while self._frames:
            seq = next(iter(self._frames))
            if seq > seen:
                break
            _, nb = self._frames.pop(seq)
            self._bytes -= nb
        if seen + 1 > self._oldest:
            self._oldest = seen + 1
        if seen > self.acked:
            self.acked = seen

    def replayable_from(self, rx_seen):
        """Frame records to retransmit after a heal — everything newer
        than what the service delivered.  None when the service needs a
        frame the byte bound already evicted (resuming would skip it
        silently, so the caller must escalate instead)."""
        self.ack(rx_seen)
        if rx_seen + 1 < self._oldest:
            return None
        return [rec for rec, _ in self._frames.values()]


class _SessionState:
    """Service half of a transport session.  Outlives any one socket:
    ``sock``/``write_lock`` always point at the session's CURRENT
    connection, so in-flight handler threads route their responses to
    wherever the client is now, not to the socket their request arrived
    on."""

    __slots__ = ("session_id", "epoch", "seen", "dup_drops", "lock",
                 "sock", "write_lock", "responses",
                 "delivered_since_ack")

    def __init__(self, session_id, epoch):
        self.session_id = session_id
        self.epoch = epoch
        self.seen = 0            # highest contiguous seq delivered
        self.dup_drops = 0
        self.lock = threading.Lock()
        self.sock = None         # live socket; guarded by self.lock
        self.write_lock = None   # its write lock; guarded by self.lock
        # req_id -> (req_id, resp) wire tuples retained for redelivery
        # after a resume; bounded at _SESSION_RESP_KEEP
        self.responses = {}
        self.delivered_since_ack = 0


def _session_handshake_client(sock, key, session, timeout):
    """Open or resume ``session`` on a freshly connected socket: write
    the hello, synchronously await the welcome (no reader thread exists
    yet, so this read races nothing)."""
    write_message(sock, key, (None, SessionHello(
        session.session_id, session.epoch, 0)), "q")
    old_timeout = sock.gettimeout()
    sock.settimeout(timeout)
    try:
        frame = read_message(sock, key, "r")
    finally:
        sock.settimeout(old_timeout)
    if not (isinstance(frame, tuple) and len(frame) == 2
            and isinstance(frame[1], SessionWelcome)):
        raise ConnectionError(
            "session handshake expected SessionWelcome, got "
            f"{type(frame).__name__}")
    welcome = frame[1]
    # rx_seen flows into replay-buffer arithmetic; a verified welcome
    # carrying garbage there must fail the handshake typed, not raise
    # TypeError inside the sender's ack bookkeeping
    if not welcome.refused and not _valid_seq(welcome.rx_seen):
        raise ConnectionError(
            f"session welcome carried invalid rx_seen "
            f"({type(welcome.rx_seen).__name__})")
    return welcome


# ------------------------------------------------------- retry / backoff
def backoff_delay(attempt, base=0.05, cap=2.0):
    """Exponential backoff with jitter (50-100% of the exponential
    step): simultaneous rank retries after a shared blip decorrelate
    instead of synchronizing into a thundering herd."""
    return min(cap, base * (1 << min(attempt, 16))) * \
        (0.5 + random.random() * 0.5)


def default_connect_retry():
    return env_util.get_float(env_util.HVD_TPU_CONNECT_RETRY_SECONDS,
                              env_util.DEFAULT_CONNECT_RETRY_SECONDS)


def connect(addr, timeout, peer=None):
    """All control/data-plane TCP connects funnel through here: one
    fault-injection point ("connect") covers rendezvous, negotiation and
    the ring transport.  A "drop" at this point is a dropped SYN, which
    the caller can only observe as a failed connect — same surface as
    "refuse".  ``peer`` scopes per-link faults: a reconnect toward a
    peer whose blip window is still open is refused (the flap is still
    down), so the session layer's backoff loop rides it out."""
    from horovod_tpu.common import faults

    if faults.check("connect", peer=peer):
        raise ConnectionRefusedError(
            "injected connection drop at connect (HVD_TPU_FAULT_SPEC)")
    return socket.create_connection(addr, timeout=timeout)


class _RetryableSendError(ConnectionError):
    """Internal marker: the request may be safely retried in full
    (nothing reached the service, or the request is idempotent)."""


# ------------------------------------------------- degraded-link injection
# bound on one injected sleep: a chaos cell must slow the job, never
# wedge it past its own deadlines' ability to tell slow from dead
_MAX_DEGRADE_SLEEP = 5.0
_flaky_noted = set()    # peers already logged; guarded by _flaky_note_lock
_reset_noted = set()    # peers already logged; guarded by _flaky_note_lock
_flaky_note_lock = threading.Lock()


def _note_flaky(peer):
    with _flaky_note_lock:
        if peer in _flaky_noted:
            return
        _flaky_noted.add(peer)
    print(f"[hvd-fault] flaky link toward peer {peer}: dropping writes, "
          f"transport resends (injected)", file=sys.stderr, flush=True)


def _note_reset(peer):
    with _flaky_note_lock:
        if peer in _reset_noted:
            return
        _reset_noted.add(peer)
    print(f"[hvd-fault] mid-stream reset toward peer {peer}: cutting "
          f"the connection, session layer heals (injected)",
          file=sys.stderr, flush=True)


def _apply_link_faults(peer, nbytes=None, sock=None):
    """Client-side framing-layer chaos (docs/fault_tolerance.md
    "degraded networks"): every client frame write — control mux,
    bulk-stripe, mailbox — funnels through here, so an armed
    degradation is felt by all three paths.  ``peer`` is the remote's
    rank (None: unknown, e.g. rendezvous); ``nbytes`` sizes the
    throttle pacing for bulk payloads.

    A flaky drop loses the write BEFORE any byte leaves the socket, so
    the resend here is always safe — the peer never saw a partial
    frame (the TCP-retransmit analog, surfaced once per peer for the
    chaos log).  A partition fails the write outright, exactly like an
    unreachable host.  A mid-stream ``reset``/``blip`` verdict puts a
    PARTIAL frame prefix on the wire first (when ``sock`` is given),
    hard-closes the socket and raises ConnectionResetError — the one
    failure mode the session layer's reconnect + replay path exists
    for."""
    from horovod_tpu.common import faults

    state = faults.link(peer)
    if state is None:
        return
    attempts = 0
    while state is not None and state.drop and not state.reset:
        _note_flaky(peer)
        attempts += 1
        if attempts >= 1000:
            raise ConnectionResetError(
                f"injected flaky link toward peer {peer} dropped "
                f"{attempts} consecutive writes (HVD_TPU_FAULT_SPEC)")
        time.sleep(0.002)
        state = faults.link(peer)
    if state is None:
        return
    if state.partitioned:
        raise ConnectionResetError(
            f"injected network partition toward peer {peer} "
            f"(HVD_TPU_FAULT_SPEC)")
    if state.reset:
        if sock is not None:
            # two bytes of a frame header, then a hard close: the peer's
            # reader blocks mid-header and sees the cut exactly the way
            # a real RST lands — genuinely mid-stream, never a cleanly
            # framed boundary
            try:
                # wire-safe: deliberately UNSIGNED garbage — this IS the
                # injected fault (a torn frame), not a protocol message
                sock.sendall(b"\x15\x03")
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        _note_reset(peer)
        raise ConnectionResetError(
            f"injected mid-stream connection reset toward peer {peer} "
            f"(HVD_TPU_FAULT_SPEC)")
    sleep_s = state.delay_s
    if state.throttle_bps > 0 and nbytes:
        sleep_s += nbytes / state.throttle_bps
    if sleep_s > 0:
        time.sleep(min(sleep_s, _MAX_DEGRADE_SLEEP))


# ---------------------------------------------------------------- wire codec
def write_message(sock, key, obj, direction):
    payload = pickle.dumps((direction, obj))
    if len(payload) > MAX_FRAME_BYTES:
        # fail HERE with a clear error — the receiver would just drop
        # the connection and the sender would see a mute timeout
        raise ValueError(
            f"frame of {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte transport limit")
    digest = secret.sign(key, payload)
    frame = struct.pack(">I", len(payload)) + digest + payload
    sock.sendall(frame)
    return len(frame)


def write_bulk_message(sock, key, obj, payload, direction):
    """Raw-bytes bulk frame: ``obj`` is a small header object (pickled;
    its ``payload`` attribute must be None — the receiver injects the
    raw bytes there), ``payload`` is bytes-like and goes on the wire
    verbatim via scatter-gather, never through pickle.  Returns the
    frame size in bytes."""
    hdr = pickle.dumps((direction, obj))
    payload = memoryview(payload).cast("B")
    if len(hdr) > MAX_RAW_HEADER_BYTES:
        raise ValueError(
            f"bulk frame header of {len(hdr)} bytes exceeds the "
            f"{MAX_RAW_HEADER_BYTES}-byte limit")
    if payload.nbytes > MAX_FRAME_BYTES:
        raise ValueError(
            f"bulk payload of {payload.nbytes} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte transport limit")
    lengths = struct.pack(">II", len(hdr), payload.nbytes)
    digest = secret.sign_parts(key, lengths, hdr, payload)
    prefix = (struct.pack(">I", RAW_FRAME_FLAG | len(hdr)) + digest +
              struct.pack(">I", payload.nbytes) + hdr)
    _sendall_vec(sock, [prefix, payload])
    return len(prefix) + payload.nbytes


def _sendall_vec(sock, buffers):
    """sendall over a list of buffers without concatenating them (one
    sendmsg syscall per iteration; falls back to per-buffer sendall).
    Only ever called with complete pre-signed frames built by
    :func:`write_bulk_message`."""
    bufs = [memoryview(b).cast("B") for b in buffers if len(b)]
    if not hasattr(sock, "sendmsg"):
        for b in bufs:
            sock.sendall(b)  # wire-safe: frame signed by the caller
        return
    while bufs:
        sent = sock.sendmsg(bufs)  # wire-safe: frame signed by caller
        while sent:
            if sent >= bufs[0].nbytes:
                sent -= bufs[0].nbytes
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0


def read_message(sock, key, expected_direction):
    header = _read_exact(sock, 4 + secret.DIGEST_LEN)
    (length,) = struct.unpack(">I", header[:4])
    digest = header[4:]
    if length & RAW_FRAME_FLAG:
        return _read_bulk(sock, key, expected_direction,
                          length & (RAW_FRAME_FLAG - 1), digest)
    if length > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"frame length {length} exceeds limit {MAX_FRAME_BYTES}")
    payload = _read_exact(sock, length)
    if not secret.check(key, payload, digest):
        raise PermissionError("message failed HMAC verification")
    envelope = _loads_checked(payload)
    if not (isinstance(envelope, tuple) and len(envelope) == 2
            and envelope[0] == expected_direction):
        raise PermissionError(
            "message direction mismatch (reflected frame?)")
    return envelope[1]


def _loads_checked(payload):
    """Unpickle an HMAC-verified envelope, converting any decode failure
    into the transport's typed rejection.  A signed-but-undecodable
    frame (a peer running different code, or stream corruption that
    survived by chance) must surface exactly like any other malformed
    frame — a connection-scoped error the read loops already sever on —
    never an arbitrary exception type escaping into handler threads."""
    try:
        return pickle.loads(payload)
    except Exception as exc:  # noqa: BLE001 — unpickler raises freely
        raise PermissionError(
            f"verified frame failed to decode: "
            f"{type(exc).__name__}") from exc


def _read_bulk(sock, key, expected_direction, hdr_len, digest):
    """Read the remainder of a raw bulk frame (both length caps are
    checked before any buffering; the HMAC — covering the length words
    plus header plus payload — is verified before the header reaches
    the unpickler)."""
    if hdr_len > MAX_RAW_HEADER_BYTES:
        raise ConnectionError(
            f"bulk header length {hdr_len} exceeds limit "
            f"{MAX_RAW_HEADER_BYTES}")
    (payload_len,) = struct.unpack(">I", _read_exact(sock, 4))
    if payload_len > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"bulk payload length {payload_len} exceeds limit "
            f"{MAX_FRAME_BYTES}")
    hdr = _read_exact(sock, hdr_len)
    payload = _read_exact_into(sock, payload_len)
    lengths = struct.pack(">II", hdr_len, payload_len)
    if not secret.check_parts(key, digest, lengths, hdr, payload):
        raise PermissionError("bulk message failed HMAC verification")
    envelope = _loads_checked(hdr)
    if not (isinstance(envelope, tuple) and len(envelope) == 2
            and envelope[0] == expected_direction):
        raise PermissionError(
            "message direction mismatch (reflected frame?)")
    obj = envelope[1]
    # payload injection: the carrier (the mux (req_id, obj) pair's
    # second element, or the bare object) declared a ``payload`` slot
    carrier = obj[1] if isinstance(obj, tuple) and len(obj) == 2 else obj
    try:
        carrier.payload = payload
    except (AttributeError, TypeError) as exc:
        # a verified header whose carrier can't accept the payload
        # (wrong type, slots without a payload slot) is still a
        # malformed frame — typed rejection, not an AttributeError
        # escaping into the reader loop
        raise PermissionError(
            f"bulk frame carrier {type(carrier).__name__} cannot "
            f"accept a payload") from exc
    return obj


def _read_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        # wakeable: closing the socket (peer abort/purge teardown, or
        # the owner's close()) breaks the blocked recv with an OSError;
        # callers set read timeouts where the protocol demands one
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return bytes(buf)


def _read_exact_into(sock, n):
    """One preallocated buffer filled by recv_into — the bulk payload is
    copied exactly once off the socket."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        # wakeable: socket close breaks the blocked recv (see
        # _read_exact)
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed connection")
        got += r
    return buf


# ------------------------------------------------------------------- service
class BasicService:
    """Threaded TCP service answering one signed request per connection
    (reference: ``network.BasicService``)."""

    def __init__(self, name, key):
        self._name = name
        self._key = key
        self._start_server(self._make_handler())

    def _make_handler(self):
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    # wakeable: server shutdown closes the listener and
                    # every accepted socket, breaking this read
                    req = read_message(self.request, service._key, "q")
                except (PermissionError, ConnectionError, EOFError):
                    return  # drop unauthenticated/broken peers silently
                try:
                    resp = service._handle(req, self.client_address)
                except Exception as exc:  # noqa: BLE001 — ship to client
                    resp = exc
                try:
                    write_message(self.request, service._key, resp, "r")
                except OSError:
                    pass  # client went away
                except Exception as exc:  # noqa: BLE001 — unpicklable resp
                    try:
                        write_message(
                            self.request, service._key,
                            RuntimeError(
                                f"response serialization failed: {exc}"),
                            "r")
                    except Exception:  # noqa: BLE001
                        pass

        return Handler

    def _start_server(self, handler):
        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server(("0.0.0.0", 0), handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"{self._name}-service")
        self._thread.start()

    @property
    def port(self):
        return self._server.server_address[1]

    def addresses(self):
        """{interface: [(ip, port)]} for every non-loopback interface
        (reference: ``network.get_local_host_addresses``)."""
        out = {}
        for iface, ip in local_interfaces().items():
            out[iface] = [(ip, self.port)]
        return out

    def _handle(self, req, client_address):
        if isinstance(req, PingRequest):
            return PingResponse(self._name)
        raise ValueError(f"unknown request type {type(req).__name__}")

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class BasicClient:
    """One-connection-per-request client (reference:
    ``network.BasicClient``): tries each known (ip, port) until one
    answers, remembers the winner."""

    def __init__(self, addresses, key, timeout=10, read_timeout="same",
                 retry_for=None, peer=None):
        # addresses: {iface: [(ip, port)]} or flat [(ip, port)].
        # ``timeout`` bounds connection establishment; ``read_timeout``
        # bounds the response wait (None = wait forever — collectives
        # legitimately block until every rank contributes, and the
        # coordinator owns stall detection).  ``retry_for`` is the
        # deadline budget for connect-phase retries with backoff+jitter
        # (None = HVD_TPU_CONNECT_RETRY_SECONDS; 0 = a single sweep) —
        # one RST during rendezvous must not kill the job.  ``peer`` is
        # the remote's rank when known, for link-level fault targeting.
        if isinstance(addresses, dict):
            flat = [a for addrs in addresses.values() for a in addrs]
        else:
            flat = list(addresses)
        if not flat:
            raise ValueError("no addresses to connect to")
        self._addresses = flat
        self._good = None
        self._key = key
        self._timeout = timeout
        self._peer = peer
        self._read_timeout = timeout if read_timeout == "same" \
            else read_timeout
        self._retry_for = (default_connect_retry() if retry_for is None
                           else retry_for)

    def _send_one(self, addr, req):
        with connect(addr, self._timeout, peer=self._peer) as sock:
            sock.settimeout(self._read_timeout)
            _apply_link_faults(self._peer, sock=sock)
            write_message(sock, self._key, req, "q")
            resp = read_message(sock, self._key, "r")
        if isinstance(resp, Exception):
            raise resp
        return resp

    def send(self, req, idempotent=False):
        """Address failover happens ONLY at the connect phase.  Once a
        request has been written, any error propagates — retransmitting a
        non-idempotent message (e.g. a collective contribution that is
        merely slow to complete) would hit the coordinator's
        duplicate-request detection and fail the job.  ``idempotent=True``
        (registrations, probes, polls) lifts that rule: the whole request
        is retried under the deadline budget even after a post-write
        failure.  A cached winner whose CONNECT fails is safe to fail
        over from (nothing was sent), so the other addresses are retried
        then; when every address refuses, the sweep repeats with
        exponential backoff + jitter until the ``retry_for`` budget is
        spent."""
        deadline = time.monotonic() + self._retry_for
        attempt = 0
        while True:
            try:
                return self._send_sweep(req, idempotent)
            except _RetryableSendError as exc:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(str(exc)) from exc
                time.sleep(min(backoff_delay(attempt), max(remaining, 0.0)))
                attempt += 1

    def _send_sweep(self, req, idempotent):
        """One pass over the candidate addresses."""
        candidates = list(self._addresses)
        if self._good is not None and self._good in candidates:
            candidates.remove(self._good)
            candidates.insert(0, self._good)
        last_error = None
        for addr in candidates:
            try:
                sock = connect(addr, self._timeout, peer=self._peer)
            except OSError as exc:
                last_error = exc
                if addr == self._good:
                    self._good = None
                continue
            try:
                with sock:
                    sock.settimeout(self._read_timeout)
                    _apply_link_faults(self._peer, sock=sock)
                    write_message(sock, self._key, req, "q")
                    resp = read_message(sock, self._key, "r")
            except OSError as exc:
                if idempotent:
                    # safe to resend in full: surface as retryable
                    raise _RetryableSendError(
                        f"idempotent request to {addr} failed after "
                        f"write: {exc}") from exc
                raise  # sent — do NOT failover to another address
            self._good = addr
            if isinstance(resp, Exception):
                raise resp
            return resp
        raise _RetryableSendError(
            f"could not reach service at any of {self._addresses}: "
            f"{last_error}")

    def probe(self):
        """Which of the candidate addresses actually answer a Ping
        (reference: the task-to-task address check,
        ``driver_service.py:156``)."""
        good = []
        for addr in self._addresses:
            try:
                resp = self._send_one(addr, PingRequest())
                if isinstance(resp, PingResponse):
                    good.append(addr)
            except (OSError, ConnectionError, PermissionError):
                continue
        return good


def _connect_any(addresses, timeout, retry_for, peer=None):
    """Connect sweep over the address list with exponential backoff +
    jitter under the ``retry_for`` deadline budget; returns a connected
    TCP_NODELAY socket (shared by the mux control connection, its bulk
    companion, and the ring stripe pool)."""
    deadline = time.monotonic() + retry_for
    attempt = 0
    last_error = None
    while True:
        for addr in addresses:
            try:
                sock = connect(addr, timeout, peer=peer)
                sock.settimeout(None)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except OSError as exc:
                last_error = exc
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise ConnectionError(
                f"could not reach service at any of {addresses}: "
                f"{last_error}")
        time.sleep(min(backoff_delay(attempt), max(remaining, 0.0)))
        attempt += 1


# ------------------------------------------------- persistent mux transport
class MuxService(BasicService):
    """Persistent-connection variant: each connection carries a stream of
    ``(req_id, request)`` frames; every request is handled on its own
    thread and the ``(req_id, response)`` frame is written back whenever
    it completes — so slow (blocking) requests don't head-of-line-block
    the connection.  Fire-and-forget posts (``req_id`` None) are handled
    inline on the reader loop instead: their handlers are quick and a
    thread spawn per bulk segment would dominate the striped data path.
    The reference keeps persistent Gloo pairs the same way; round 1's
    one-connection-per-request client was the analog of re-running
    rendezvous per collective.

    When a connection's FIRST frame is a :class:`SessionHello` the
    connection becomes a session (docs/fault_tolerance.md "connection
    blips vs dead peers"): frames carry seq numbers inside their
    request ids, the service dedups and acks cumulatively, and a later
    connection offering the same session id resumes exactly where the
    broken one stopped."""

    def __init__(self, name, key):
        self._inflight = 0   # guarded by self._inflight_cv
        self._inflight_cv = threading.Condition()
        # session_id -> _SessionState; sessions survive their sockets —
        # that's the whole point
        self._sessions = {}
        self._sessions_lock = threading.Lock()
        self.sessions_resumed = 0     # guarded by self._sessions_lock
        self.session_dup_drops = 0    # guarded by self._sessions_lock
        super().__init__(name, key)

    def session_epoch(self):
        """Controller epoch a hello must match to be admitted; services
        without reconfiguration epochs (the coordinator control plane)
        stay at 0.  PeerService overrides with its live epoch."""
        return 0

    def _make_handler(self):
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                write_lock = threading.Lock()
                sock = self.request
                first = True
                while True:
                    try:
                        # wakeable: shutdown() and a session resume both
                        # close this socket, breaking the blocked read
                        frame = read_message(sock, service._key, "q")
                    except (PermissionError, ConnectionError, EOFError,
                            OSError):
                        return
                    if not (isinstance(frame, tuple) and len(frame) == 2):
                        return
                    req_id, req = frame
                    if first:
                        first = False
                        if isinstance(req, SessionHello):
                            service._session_serve(sock, write_lock, req,
                                                   self.client_address)
                            return
                    with service._inflight_cv:
                        service._inflight += 1
                    if req_id is None:
                        # fire-and-forget: no response is ever written
                        # and the handlers behind these posts (mailbox
                        # insert, abort flag) are quick — dispatch
                        # inline rather than paying a thread spawn per
                        # bulk segment on the striped data path
                        try:
                            service._handle(req, self.client_address)
                        except Exception:  # noqa: BLE001 — nowhere to
                            pass           # report without a req_id
                        finally:
                            with service._inflight_cv:
                                service._inflight -= 1
                                service._inflight_cv.notify_all()
                        continue

                    def run(req_id=req_id, req=req):
                        try:
                            try:
                                resp = service._handle(
                                    req, self.client_address)
                            except Exception as exc:  # noqa: BLE001
                                resp = exc
                            service._write_response(sock, write_lock,
                                                    req_id, resp)
                        finally:
                            with service._inflight_cv:
                                service._inflight -= 1
                                service._inflight_cv.notify_all()

                    # lifecycle: ends with its single _handle call;
                    # shutdown() drains in-flight handlers through the
                    # _inflight_cv barrier before the socket closes
                    threading.Thread(target=run, daemon=True,
                                     name=f"{service._name}-req").start()

        return Handler

    # ------------------------------------------------------ session side
    def _session_serve(self, sock, write_lock, hello, client_address):
        """Admit (or resume) a session offered by a fresh connection:
        fence stale epochs, install this socket as the session's live
        one, tell the client how far delivery got (it retransmits the
        rest), redeliver retained responses the dying socket may have
        swallowed, then serve frames until the connection breaks."""
        # the hello is HMAC-verified, but its FIELDS are still wire
        # input: the id becomes a dict key (unhashable -> handler
        # crash; unbounded -> memory held per session), so reject
        # anything but a short string before touching the table
        if not (isinstance(hello.session_id, str)
                and 0 < len(hello.session_id) <= _MAX_SESSION_ID_LEN) \
                or hello.epoch != self.session_epoch():
            try:
                with write_lock:
                    write_message(sock, self._key,
                                  (None, SessionWelcome(0, refused=True)),
                                  "r")
            except OSError:
                pass
            return
        with self._sessions_lock:
            state = self._sessions.get(hello.session_id)
            resumed = state is not None
            if not resumed:
                if len(self._sessions) >= _MAX_SESSIONS:
                    self._evict_dead_session_locked()
                if len(self._sessions) >= _MAX_SESSIONS:
                    # table full of LIVE sessions: refuse rather than
                    # grow without bound (a keyed-but-misbehaving peer
                    # minting a fresh id per connect lands here)
                    state = None
                else:
                    state = _SessionState(hello.session_id, hello.epoch)
                    self._sessions[hello.session_id] = state
            else:
                self.sessions_resumed += 1
        if state is None:
            try:
                with write_lock:
                    write_message(sock, self._key,
                                  (None, SessionWelcome(0, refused=True)),
                                  "r")
            except OSError:
                pass
            return
        with state.lock:
            old_sock = state.sock
            state.sock = sock
            state.write_lock = write_lock
            seen = state.seen
            stash = list(state.responses.values()) if resumed else []
        if old_sock is not None and old_sock is not sock:
            # break the dead connection's blocked reader, if it hasn't
            # noticed yet
            try:
                old_sock.close()
            except OSError:
                pass
        try:
            with write_lock:
                write_message(sock, self._key,
                              (None, SessionWelcome(seen)), "r")
            for wire in stash:
                with write_lock:
                    write_message(sock, self._key, wire, "r")
        except OSError:
            return  # this socket died too; the client will be back
        self._session_loop(sock, write_lock, state, client_address)

    def _evict_dead_session_locked(self):  # holds: self._sessions_lock
        """Drop one session with no live socket (insertion order, so
        oldest first).  Returns True when something was evicted."""
        for sid, st in list(self._sessions.items()):
            with st.lock:
                # the server closes each handler's socket when its
                # handle() returns, so a session whose connection died
                # (and hasn't resumed) holds a closed socket
                dead = st.sock is None or st.sock.fileno() == -1
            if dead:
                del self._sessions[sid]
                return True
        return False

    def _session_loop(self, sock, write_lock, state, client_address):
        """Frame pump for one live session connection: deliver exactly
        the next-in-sequence frames, drop duplicates a replay sent
        again, ack cumulatively every few deliveries."""
        while True:
            try:
                # wakeable: the next resume for this session (and
                # shutdown) closes this socket, breaking the read
                frame = read_message(sock, self._key, "q")
            except (PermissionError, ConnectionError, EOFError, OSError):
                return
            if not (isinstance(frame, tuple) and len(frame) == 2):
                return
            rid, req = frame
            if not (isinstance(rid, tuple) and len(rid) in (2, 3)
                    and rid[0] == "sq" and _valid_seq(rid[1])):
                return  # not session-framed: protocol violation, sever
            seq = rid[1]
            need_ack = False
            with state.lock:
                if seq <= state.seen:
                    state.dup_drops += 1
                    verdict = "dup"
                elif seq == state.seen + 1:
                    state.seen = seq
                    state.delivered_since_ack += 1
                    if state.delivered_since_ack >= _SESSION_ACK_EVERY:
                        state.delivered_since_ack = 0
                        need_ack = True
                    verdict = "deliver"
                else:
                    # a gap means the sender replayed past a frame we
                    # never got — resuming would corrupt; sever and let
                    # the sender's next heal (or escalation) decide
                    verdict = "gap"
                seen = state.seen
            if verdict == "gap":
                try:
                    sock.close()
                except OSError:
                    pass
                return
            if verdict == "dup":
                with self._sessions_lock:
                    self.session_dup_drops += 1
                continue
            with self._inflight_cv:
                self._inflight += 1
            if len(rid) == 2:
                # fire-and-forget (the bulk/mailbox path): inline, like
                # the legacy req_id-None dispatch
                try:
                    self._handle(req, client_address)
                except Exception:  # noqa: BLE001 — nowhere to report
                    pass
                finally:
                    with self._inflight_cv:
                        self._inflight -= 1
                        self._inflight_cv.notify_all()
            else:
                base_id = rid[2]

                def run(base_id=base_id, req=req):
                    try:
                        try:
                            resp = self._handle(req, client_address)
                        except Exception as exc:  # noqa: BLE001
                            resp = exc
                        self._write_session_response(state, base_id, resp)
                    finally:
                        with self._inflight_cv:
                            self._inflight -= 1
                            self._inflight_cv.notify_all()

                # lifecycle: ends with its single _handle call;
                # shutdown() drains in-flight handlers through the
                # _inflight_cv barrier before the socket closes
                threading.Thread(target=run, daemon=True,
                                 name=f"{self._name}-req").start()
            if need_ack:
                try:
                    with write_lock:
                        write_message(sock, self._key,
                                      (None, SessionAck(seen)), "r")
                except OSError:
                    pass  # connection dying; the reader will notice

    def _write_session_response(self, state, req_id, resp):
        """Route a response to the session's CURRENT socket (the one the
        request arrived on may be long dead by completion time) and
        retain it for redelivery at the next resume — a write into a
        dying socket's kernel buffer can vanish without erroring."""
        wire = (req_id, resp)
        with state.lock:
            state.responses[req_id] = wire
            while len(state.responses) > _SESSION_RESP_KEEP:
                state.responses.pop(next(iter(state.responses)))
            sock, wlock = state.sock, state.write_lock
        if sock is None:
            return
        try:
            with wlock:
                write_message(sock, self._key, wire, "r")
        except OSError:
            pass  # retained; the resume flush redelivers
        except Exception as exc:  # noqa: BLE001 — e.g. unpicklable resp
            wire = (req_id,
                    RuntimeError(f"response serialization failed: {exc}"))
            with state.lock:
                state.responses[req_id] = wire
            try:
                with wlock:
                    write_message(sock, self._key, wire, "r")
            except Exception:  # noqa: BLE001
                try:
                    sock.close()
                except OSError:
                    pass

    def _write_response(self, sock, write_lock, req_id, resp):
        try:
            with write_lock:
                write_message(sock, self._key, (req_id, resp), "r")
        except OSError:
            pass  # client went away
        except Exception as exc:  # noqa: BLE001 — e.g. unpicklable resp
            # a silently-dropped frame would hang the client's send()
            # forever; ship an error, or kill the connection so the
            # client fails fast
            try:
                with write_lock:
                    write_message(
                        sock, self._key,
                        (req_id,
                         RuntimeError(
                             f"response serialization failed: {exc}")),
                        "r")
            except Exception:  # noqa: BLE001
                try:
                    sock.close()
                except OSError:
                    pass

    def shutdown(self):
        """Drain in-flight requests before closing: a coordinator whose
        own rank finishes first must not tear down the socket while
        response frames to other ranks are still being written."""
        import time as _time

        deadline = _time.monotonic() + 10
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                self._inflight_cv.wait(timeout=remaining)
        super().shutdown()


class MuxClient:
    """Client for :class:`MuxService`: ONE persistent socket, concurrent
    in-flight requests demultiplexed by id.  Thread-safe."""

    def __init__(self, addresses, key, timeout=10, retry_for=None,
                 peer=None, epoch=0, reconnect_budget=None,
                 replay_bytes=None):
        if isinstance(addresses, dict):
            flat = [a for addrs in addresses.values() for a in addrs]
        else:
            flat = list(addresses)
        if not flat:
            raise ValueError("no addresses to connect to")
        self._addresses = flat
        self._key = key
        self._timeout = timeout
        # remote's rank when known (coordinator: 0, ring mailboxes:
        # the peer rank) — link-level fault targeting needs the
        # identity, the transport itself never does
        self._peer = peer
        self._retry_for = (default_connect_retry() if retry_for is None
                           else retry_for)
        # self-healing session (docs/fault_tolerance.md "connection
        # blips vs dead peers"): active iff the reconnect budget is
        # positive; at 0 (the default) this client is frame-for-frame
        # identical to the pre-session transport
        budget = (default_reconnect_budget() if reconnect_budget is None
                  else reconnect_budget)
        self._budget = max(0.0, float(budget))
        self._epoch = epoch
        self._replay_bytes = (default_replay_bytes() if replay_bytes
                              is None else replay_bytes)
        # replay buffer + seq assignment; guarded by self._send_lock
        self._session = (_SessionSender(epoch, self._replay_bytes)
                         if self._budget > 0 else None)
        self._sock = None     # guarded by self._state_lock
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        # req_id -> [event, response]; guarded by self._state_lock
        self._pending = {}
        # random start: a (req_id, resp) frame recorded from an earlier
        # connection/run cannot collide with a live request id
        self._next_id = _secrets.randbits(48)  # guarded by self._state_lock
        self._reader = None   # guarded by self._state_lock
        self._broken = None   # guarded by self._state_lock
        self._closed = False  # guarded by self._state_lock
        # bulk companion: a StripeClient to the same service that
        # carries ONLY fire-and-forget raw frames, under its own lock —
        # a pending control request (heartbeat, negotiation, abort)
        # never waits behind an in-progress multi-MB bulk write
        self._bulk = None     # guarded by self._bulk_lock
        self._bytes_sent = 0  # control bytes; guarded by self._send_lock
        self._bulk_lock = threading.Lock()

    def _connect_locked(self, retry_for=None):  # holds: self._state_lock
        """Establish the socket + reader (caller holds _state_lock).
        Sweeps the address list with exponential backoff + jitter under
        the ``retry_for`` deadline budget: a refused/reset connection
        during rendezvous or negotiation is retried, not fatal.  With a
        session active, the handshake + replay of unacked frames happen
        here, BEFORE the reader thread exists — so the welcome read
        races nothing and the retransmits precede any new frame."""
        sock = _connect_any(self._addresses, self._timeout,
                            self._retry_for if retry_for is None
                            else retry_for, peer=self._peer)
        # the _session REFERENCE is set once at construction and never
        # reassigned — only its contents need _send_lock; the handshake
        # reads the immutable id/epoch fields
        if self._session is not None:  # hvd-lint: ignore[lock-discipline]
            try:
                welcome = _session_handshake_client(
                    sock, self._key, self._session, self._timeout)  # hvd-lint: ignore[lock-discipline]
            except Exception:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            if welcome.refused:
                try:
                    sock.close()
                except OSError:
                    pass
                raise _SessionResumeRefused(
                    f"service fenced session resume toward peer "
                    f"{self._peer} (stale epoch {self._epoch})")
            with self._send_lock:
                frames = self._session.replayable_from(welcome.rx_seen)
                if frames is None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    raise _SessionResumeRefused(
                        f"replay buffer no longer holds frames the "
                        f"service needs (peer {self._peer}; raise "
                        f"{env_util.HVD_TPU_REPLAY_BUFFER_BYTES})")
                try:
                    for wire in frames:
                        _apply_link_faults(self._peer, sock=sock)
                        self._bytes_sent += write_message(
                            sock, self._key, wire, "q")
                except OSError:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    raise
                if frames:
                    _session_note("frames_replayed", len(frames))
        self._sock = sock
        self._broken = None
        # lifecycle: exits when its socket dies — close() closes the
        # socket, which breaks the blocked read_message and returns
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,), daemon=True,
            name="mux-client-reader")
        self._reader.start()

    def _try_heal(self, dead_sock, exc):
        """Transparent in-place session heal after a mid-stream break.
        Returns True when the session is live again (this call healed
        it, or another thread already did) — the caller's frame is in
        the replay buffer, so it was (or will be) retransmitted; the
        caller may also just rewrite it, the service dedups by seq.
        Returns False when healing is off, fenced, or out of budget —
        the caller escalates the ORIGINAL error, exactly the
        pre-session abort path."""
        # reference set once at construction, never reassigned
        if self._session is None or self._budget <= 0:  # hvd-lint: ignore[lock-discipline]
            return False
        deadline = time.monotonic() + self._budget
        with self._state_lock:
            if self._closed:
                return False
            if self._sock is not None and self._sock is not dead_sock:
                return True  # someone else already healed
            if self._sock is None and self._broken is not None:
                return False  # an earlier heal already gave up
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            from horovod_tpu.common import busy

            _healing_enter(self._peer)
            try:
                # busy window: the coordinator widens this rank's
                # liveness deadline while the heal is in flight — a
                # recovering link must never read as a dead rank
                with busy.window():
                    while True:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            self._broken = exc
                            _session_note("reconnects_failed")
                            return False
                        try:
                            self._connect_locked(retry_for=remaining)
                        except _SessionResumeRefused:
                            self._broken = exc
                            _session_note("reconnects_failed")
                            return False
                        except (OSError, ConnectionError,
                                PermissionError):
                            self._sock = None
                            continue
                        _session_note("reconnects_healed")
                        with self._send_lock:  # acks land under it
                            acked = self._session.acked
                        print(f"[hvd-session] reconnect healed toward "
                              f"peer {self._peer} (control session, "
                              f"acked {acked})",
                              file=sys.stderr, flush=True)
                        return True
            finally:
                _healing_exit(self._peer)

    def _ensure_connected_locked(self):  # holds: self._state_lock
        """Returns the live socket (caller holds _state_lock).  The
        returned reference — not a re-read of self._sock — must be used
        for the write, so a concurrent reconnect can never route this
        request onto a connection its pending entry isn't tied to."""
        if self._sock is None or self._broken is not None:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            self._connect_locked()
        return self._sock

    def _read_loop(self, sock):
        while True:
            try:
                # wakeable: close() severs this socket, which breaks
                # the blocked read; a heal hands the loop a new socket
                frame = read_message(sock, self._key, "r")
                if not (isinstance(frame, tuple) and len(frame) == 2):
                    raise ConnectionError(
                        f"malformed mux frame {type(frame).__name__}")
                req_id, resp = frame
            except Exception as exc:  # noqa: BLE001 — reader must never
                # die silently: heal the session in place if one is
                # active (pending waiters survive — their responses are
                # redelivered after the resume); otherwise fail every
                # waiter and mark broken, the pre-session behavior
                if isinstance(exc, (OSError, ConnectionError)) \
                        and self._try_heal(sock, exc):
                    return  # a new reader owns the healed socket
                with self._state_lock:
                    if self._broken is None:
                        self._broken = exc
                    pending, self._pending = self._pending, {}
                for event, slot in pending.values():
                    slot[0] = ConnectionError(
                        f"connection to service lost: {exc}")
                    event.set()
                return
            if req_id is None:
                # piggybacked session ack: prune the replay buffer
                # (the seen field is wire input even inside a verified
                # frame — a non-int would TypeError the ack arithmetic
                # and kill this reader)
                if isinstance(resp, SessionAck) and _valid_seq(resp.seen) \
                        and self._session is not None:  # hvd-lint: ignore[lock-discipline] — set-once reference
                    with self._send_lock:
                        self._session.ack(resp.seen)
                continue
            with self._state_lock:
                entry = self._pending.pop(req_id, None)
            if entry is not None:
                entry[1][0] = resp
                entry[0].set()

    def send(self, req, timeout=None):
        with self._state_lock:
            base_id = self._next_id
            self._next_id += 1
            event, slot = threading.Event(), [None]
            self._pending[base_id] = (event, slot)
        wire = None
        sock = None
        while True:
            try:
                with self._state_lock:
                    sock = self._ensure_connected_locked()
                with self._send_lock:
                    if wire is None:
                        if self._session is not None:
                            # seq inside the request id; the response
                            # still answers to base_id, and the replay
                            # buffer retains the frame until acked
                            _, wire = self._session.append(
                                lambda s: (("sq", s, base_id), req),
                                _CTRL_FRAME_EST)
                        else:
                            wire = (base_id, req)
                    _apply_link_faults(self._peer, sock=sock)
                    self._bytes_sent += write_message(
                        sock, self._key, wire, "q")
                break
            except OSError as exc:
                if self._try_heal(sock, exc):
                    # healed: rewrite this frame on the new socket (the
                    # replay may have carried it already — the service
                    # dedups by seq, so the rewrite is harmless)
                    continue
                with self._state_lock:
                    self._pending.pop(base_id, None)
                raise
            except Exception:  # PicklingError, oversize ValueError…
                with self._state_lock:
                    self._pending.pop(base_id, None)
                raise
        if not event.wait(timeout):
            with self._state_lock:
                self._pending.pop(base_id, None)
            raise TimeoutError("no response from service")
        resp = slot[0]
        if isinstance(resp, Exception):
            raise resp
        return resp

    def post(self, req):
        """Fire-and-forget: write the frame without expecting a response
        (req_id None).  TCP ordering + HMAC still apply; used by the ring
        data plane so chunk streams aren't serialized on ack round-trips."""
        wire = None
        sock = None
        while True:
            try:
                with self._state_lock:
                    sock = self._ensure_connected_locked()
                with self._send_lock:
                    if wire is None:
                        if self._session is not None:
                            _, wire = self._session.append(
                                lambda s: (("sq", s), req),
                                _CTRL_FRAME_EST)
                        else:
                            wire = (None, req)
                    _apply_link_faults(self._peer, sock=sock)
                    self._bytes_sent += write_message(sock, self._key,
                                                      wire, "q")
                return
            except OSError as exc:
                if self._try_heal(sock, exc):
                    continue  # rewrite; the service dedups by seq
                raise

    @property
    def bytes_sent(self):
        """Wire bytes written (control + bulk companion, framing
        included) — the own counter and the bulk reference are read
        under their guarding locks; the companion's monotonic counter
        is read staleness-tolerantly (it may lag an in-flight
        post_bulk by one frame, which the quiesced-transfer
        byte-accounting tests never observe)."""
        with self._send_lock:
            total = self._bytes_sent
        with self._bulk_lock:
            bulk = self._bulk
        return total + (bulk.bytes_sent if bulk else 0)

    def post_bulk(self, obj, payload):
        """Fire-and-forget raw bulk frame on the dedicated bulk
        companion connection (a lazily-built :class:`StripeClient` to
        the same service): ``obj`` is the small header carrier (its
        ``payload`` attribute must be None), ``payload`` the raw bytes.
        Control ``send``s keep round-tripping on the main socket while
        this write is in flight."""
        with self._bulk_lock:
            if self._bulk is None:
                self._bulk = StripeClient(
                    self._addresses, self._key, timeout=self._timeout,
                    retry_for=self._retry_for, peer=self._peer,
                    epoch=self._epoch, reconnect_budget=self._budget,
                    replay_bytes=self._replay_bytes)
            bulk = self._bulk
        bulk.post_bulk(obj, payload)

    def close(self):
        with self._state_lock:
            self._closed = True
            sock, self._sock = self._sock, None
        with self._bulk_lock:
            bulk = self._bulk
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if bulk is not None:
            bulk.close()


class StripeClient:
    """One dedicated bulk-data connection to a :class:`MuxService`:
    fire-and-forget raw frames only (req_id None, so the service never
    writes back — no reader thread).  The ring data plane keeps a pool
    of these per peer (``HVD_TPU_RING_STRIPES``), separate from the
    control :class:`MuxClient`, so heartbeats and negotiation never
    queue behind multi-MB chunk writes and high-BDP links get
    multi-stream throughput.  Thread-safe."""

    def __init__(self, addresses, key, timeout=10, retry_for=None,
                 peer=None, epoch=0, reconnect_budget=None,
                 replay_bytes=None):
        if isinstance(addresses, dict):
            flat = [a for addrs in addresses.values() for a in addrs]
        else:
            flat = list(addresses)
        if not flat:
            raise ValueError("no addresses to connect to")
        self._addresses = flat
        self._key = key
        self._timeout = timeout
        self._peer = peer    # remote's rank when known (fault targeting)
        self._retry_for = (default_connect_retry() if retry_for is None
                           else retry_for)
        budget = (default_reconnect_budget() if reconnect_budget is None
                  else reconnect_budget)
        self._budget = max(0.0, float(budget))
        self._epoch = epoch
        replay = (default_replay_bytes() if replay_bytes is None
                  else replay_bytes)
        # session seq/replay state; guarded by self._lock (the payload
        # references are retained zero-copy — the data plane never
        # mutates a posted chunk)
        self._session = (_SessionSender(epoch, replay)
                         if self._budget > 0 else None)
        self._lock = threading.Lock()
        self._sock = None    # guarded by self._lock
        # cumulative frame bytes written by post_bulk; external
        # monotonic reads tolerate staleness; guarded by self._lock
        self.bytes_sent = 0

    def _open_locked(self, retry_for):  # holds: self._lock
        """Connect and, with a session active, handshake + start the
        ack reader before any bulk frame goes out."""
        sock = _connect_any(self._addresses, self._timeout, retry_for,
                            peer=self._peer)
        replayed = 0
        if self._session is not None:
            try:
                welcome = _session_handshake_client(
                    sock, self._key, self._session, self._timeout)
            except Exception:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            if welcome.refused:
                try:
                    sock.close()
                except OSError:
                    pass
                raise _SessionResumeRefused(
                    f"service fenced stripe session resume toward peer "
                    f"{self._peer} (stale epoch {self._epoch})")
            frames = self._session.replayable_from(welcome.rx_seen)
            if frames is None:
                try:
                    sock.close()
                except OSError:
                    pass
                raise _SessionResumeRefused(
                    f"stripe replay buffer no longer holds frames the "
                    f"service needs (peer {self._peer}; raise "
                    f"{env_util.HVD_TPU_REPLAY_BUFFER_BYTES})")
            try:
                for hdr, payload in frames:
                    _apply_link_faults(self._peer,
                                       memoryview(payload).nbytes,
                                       sock=sock)
                    self.bytes_sent += write_bulk_message(
                        sock, self._key, hdr, payload, "q")
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            if frames:
                _session_note("frames_replayed", len(frames))
                replayed = len(frames)
            # lifecycle: exits when its socket dies (read raises); a
            # heal replaces the socket, so each reader is per-socket
            # and the dead one unwinds on its own
            threading.Thread(target=self._ack_loop, args=(sock,),
                             daemon=True,
                             name="stripe-ack-reader").start()
        self._sock = sock
        return replayed

    def _ack_loop(self, sock):
        """Per-socket daemon draining piggybacked session acks; exits
        quietly when its socket dies (the writer path owns healing)."""
        while True:
            try:
                # wakeable: per-socket daemon; the writer path closes
                # this socket on heal/teardown, breaking the read
                frame = read_message(sock, self._key, "r")
            except Exception:  # noqa: BLE001 — socket gone
                return
            if (isinstance(frame, tuple) and len(frame) == 2
                    and isinstance(frame[1], SessionAck)
                    and _valid_seq(frame[1].seen)):
                with self._lock:
                    if self._session is not None:
                        self._session.ack(frame[1].seen)

    def _heal_locked(self, exc):  # holds: self._lock
        """Reconnect + resume the stripe session inside the budget
        window; every retained unacked frame (including the one whose
        write just failed) is retransmitted by :meth:`_open_locked`.
        Escalates the ORIGINAL error on fence, replay gap, or budget
        exhaustion — exactly the pre-session abort surface."""
        deadline = time.monotonic() + self._budget
        from horovod_tpu.common import busy

        _healing_enter(self._peer)
        try:
            with busy.window():
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        _session_note("reconnects_failed")
                        raise exc
                    try:
                        replayed = self._open_locked(remaining)
                    except _SessionResumeRefused:
                        _session_note("reconnects_failed")
                        raise exc
                    except (OSError, ConnectionError, PermissionError):
                        self._sock = None
                        continue
                    _session_note("reconnects_healed")
                    print(f"[hvd-session] reconnect healed toward peer "
                          f"{self._peer} (replayed {replayed} bulk "
                          f"frames)", file=sys.stderr, flush=True)
                    return
        finally:
            _healing_exit(self._peer)

    def post_bulk(self, obj, payload):
        """Write one raw bulk frame (``obj`` the small header carrier
        with a None ``payload`` attribute, ``payload`` the raw bytes).
        With a session active the frame is retained in the replay
        buffer BEFORE the write, so a mid-stream break heals in place —
        reconnect, resume, retransmit the unacked tail — and this call
        still returns success."""
        nbytes = memoryview(payload).nbytes
        with self._lock:
            rec = None
            if self._session is not None:
                _, rec = self._session.append(
                    lambda s: ((("sq", s), obj), payload), nbytes)
            try:
                if self._sock is None:
                    self._open_locked(self._retry_for)
                    if self._session is not None:
                        return  # _open_locked replayed it already
                _apply_link_faults(self._peer, nbytes, sock=self._sock)
                if rec is None:
                    self.bytes_sent += write_bulk_message(
                        self._sock, self._key, (None, obj), payload, "q")
                else:
                    self.bytes_sent += write_bulk_message(
                        self._sock, self._key, rec[0], payload, "q")
            except OSError as exc:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                if self._session is None:
                    raise
                self._heal_locked(exc)

    def close(self):
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


# ----------------------------------------------------------- NIC enumeration
def local_interfaces():
    """{interface_name: ipv4} for every UP non-loopback interface.

    Stdlib-only Linux implementation (ioctl SIOCGIFADDR per interface from
    ``socket.if_nameindex``); falls back to a hostname lookup pinned to a
    pseudo-interface when the ioctl path is unavailable.
    """
    import fcntl

    out = {}
    try:
        ifaces = socket.if_nameindex()
    except OSError:
        ifaces = []
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for _, name in ifaces:
            if name == "lo":
                continue
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", name.encode()[:15]))
                out[name] = socket.inet_ntoa(packed[20:24])
            except OSError:
                continue  # interface without an IPv4 address
    finally:
        s.close()
    if not out:
        try:
            out["_default"] = socket.gethostbyname(socket.gethostname())
        except OSError:
            out["_default"] = "127.0.0.1"
    return out
