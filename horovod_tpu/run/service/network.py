"""Secret-keyed pickled-message TCP services (reference:
``horovod/run/common/service/__init__.py`` + ``horovod/run/common/util/
network.py`` — a threaded socket server exchanging HMAC-signed pickled
request/response objects, plus interface enumeration helpers used for
routable-NIC discovery).

Wire format per message: ``[4-byte big-endian length][32-byte HMAC-SHA256
digest][pickled (direction, object)]``.  The digest is verified BEFORE
unpickling — an unauthenticated peer cannot reach the unpickler — and the
claimed length is capped before any buffering, so an unauthenticated peer
cannot make the service hold gigabytes either.  The signed envelope
carries a direction tag ("q" request / "r" response) so a reflected
frame cannot answer a request, and mux request ids start at a random
64-bit offset so a frame recorded from an earlier connection cannot pair
with a live request.  (An on-path adversary that can splice into the TCP
stream in real time is outside this threat model — that requires TLS.)
"""

import pickle
import random
import secrets as _secrets
import socket
import socketserver
import struct
import sys
import threading
import time

from horovod_tpu.run.service import secret
from horovod_tpu.utils import env as env_util

# Largest frame accepted before authentication.  Generous: the tcp star
# data plane ships whole tensors (the bench sweep goes to 256 MB).
MAX_FRAME_BYTES = 1 << 30

# Bulk (raw-bytes) frame: the high bit of the length word flags a frame
# whose payload travels as raw bytes AFTER a small pickled header —
#   [4B RAW_FRAME_FLAG|header_len][32B HMAC][4B payload_len]
#   [pickled (direction, obj)][payload bytes]
# The HMAC covers [header_len][payload_len][header][payload] — the
# length words are bound in so an on-path attacker can't shift the
# header/payload boundary into a silently truncated payload — and is
# verified before unpickling; the payload is never pickled (no
# serialize copy on the send side, a single recv_into buffer on the
# receive side).  MAX_FRAME_BYTES < 2^30 keeps the flag bit
# unambiguous.
RAW_FRAME_FLAG = 0x80000000
# the pickled header of a bulk frame is a tag + rank, never big
MAX_RAW_HEADER_BYTES = 1 << 16


# ------------------------------------------------------------- base messages
class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name):
        self.service_name = service_name


class AckResponse:
    pass


# Fault-tolerance control messages, shared by the tcp and global-mesh
# coordinators (docs/fault_tolerance.md): any rank can broadcast an
# abort for the in-flight round; heartbeats keep the coordinator's
# last-seen table fresh and carry the abort state back.
class AbortMsg:
    def __init__(self, origin_rank, reason):
        self.origin_rank = origin_rank
        self.reason = reason


class HeartbeatMsg:
    def __init__(self, rank, busy=False, rtt=None, host=None):
        self.rank = rank
        # sender's launcher host hash (run/host_hash.py): the
        # coordinator groups co-located ranks from these when planning
        # the hierarchical collective schedule (docs/tuning.md)
        self.host = host
        # rank is inside a known-slow-but-alive window (checkpoint
        # write, drain teardown): the coordinator widens its liveness
        # deadline so disk I/O can't read as death (docs/checkpoint.md)
        self.busy = busy
        # sender's worst observed link RTT EWMA in seconds (heartbeat
        # round trips + ring chunk sends): the coordinator adds an
        # RTT-proportional slack to this rank's liveness window so a
        # slow-but-alive link never reads as death
        # (docs/fault_tolerance.md "degraded networks")
        self.rtt = rtt


class HeartbeatReply:
    def __init__(self, abort=None):
        self.abort = abort  # (origin_rank, reason) | None


# ------------------------------------------------------- retry / backoff
def backoff_delay(attempt, base=0.05, cap=2.0):
    """Exponential backoff with jitter (50-100% of the exponential
    step): simultaneous rank retries after a shared blip decorrelate
    instead of synchronizing into a thundering herd."""
    return min(cap, base * (1 << min(attempt, 16))) * \
        (0.5 + random.random() * 0.5)


def default_connect_retry():
    return env_util.get_float(env_util.HVD_TPU_CONNECT_RETRY_SECONDS,
                              env_util.DEFAULT_CONNECT_RETRY_SECONDS)


def connect(addr, timeout):
    """All control/data-plane TCP connects funnel through here: one
    fault-injection point ("connect") covers rendezvous, negotiation and
    the ring transport.  A "drop" at this point is a dropped SYN, which
    the caller can only observe as a failed connect — same surface as
    "refuse"."""
    from horovod_tpu.common import faults

    if faults.check("connect"):
        raise ConnectionRefusedError(
            "injected connection drop at connect (HVD_TPU_FAULT_SPEC)")
    return socket.create_connection(addr, timeout=timeout)


class _RetryableSendError(ConnectionError):
    """Internal marker: the request may be safely retried in full
    (nothing reached the service, or the request is idempotent)."""


# ------------------------------------------------- degraded-link injection
# bound on one injected sleep: a chaos cell must slow the job, never
# wedge it past its own deadlines' ability to tell slow from dead
_MAX_DEGRADE_SLEEP = 5.0
_flaky_noted = set()    # peers already logged; guarded by _flaky_note_lock
_flaky_note_lock = threading.Lock()


def _note_flaky(peer):
    with _flaky_note_lock:
        if peer in _flaky_noted:
            return
        _flaky_noted.add(peer)
    print(f"[hvd-fault] flaky link toward peer {peer}: dropping writes, "
          f"transport resends (injected)", file=sys.stderr, flush=True)


def _apply_link_faults(peer, nbytes=None):
    """Client-side framing-layer chaos (docs/fault_tolerance.md
    "degraded networks"): every client frame write — control mux,
    bulk-stripe, mailbox — funnels through here, so an armed
    degradation is felt by all three paths.  ``peer`` is the remote's
    rank (None: unknown, e.g. rendezvous); ``nbytes`` sizes the
    throttle pacing for bulk payloads.

    A flaky drop loses the write BEFORE any byte leaves the socket, so
    the resend here is always safe — the peer never saw a partial
    frame (the TCP-retransmit analog, surfaced once per peer for the
    chaos log).  A partition fails the write outright, exactly like an
    unreachable host."""
    from horovod_tpu.common import faults

    state = faults.link(peer)
    if state is None:
        return
    attempts = 0
    while state is not None and state.drop:
        _note_flaky(peer)
        attempts += 1
        if attempts >= 1000:
            raise ConnectionResetError(
                f"injected flaky link toward peer {peer} dropped "
                f"{attempts} consecutive writes (HVD_TPU_FAULT_SPEC)")
        time.sleep(0.002)
        state = faults.link(peer)
    if state is None:
        return
    if state.partitioned:
        raise ConnectionResetError(
            f"injected network partition toward peer {peer} "
            f"(HVD_TPU_FAULT_SPEC)")
    sleep_s = state.delay_s
    if state.throttle_bps > 0 and nbytes:
        sleep_s += nbytes / state.throttle_bps
    if sleep_s > 0:
        time.sleep(min(sleep_s, _MAX_DEGRADE_SLEEP))


# ---------------------------------------------------------------- wire codec
def write_message(sock, key, obj, direction):
    payload = pickle.dumps((direction, obj))
    if len(payload) > MAX_FRAME_BYTES:
        # fail HERE with a clear error — the receiver would just drop
        # the connection and the sender would see a mute timeout
        raise ValueError(
            f"frame of {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte transport limit")
    digest = secret.sign(key, payload)
    frame = struct.pack(">I", len(payload)) + digest + payload
    sock.sendall(frame)
    return len(frame)


def write_bulk_message(sock, key, obj, payload, direction):
    """Raw-bytes bulk frame: ``obj`` is a small header object (pickled;
    its ``payload`` attribute must be None — the receiver injects the
    raw bytes there), ``payload`` is bytes-like and goes on the wire
    verbatim via scatter-gather, never through pickle.  Returns the
    frame size in bytes."""
    hdr = pickle.dumps((direction, obj))
    payload = memoryview(payload).cast("B")
    if len(hdr) > MAX_RAW_HEADER_BYTES:
        raise ValueError(
            f"bulk frame header of {len(hdr)} bytes exceeds the "
            f"{MAX_RAW_HEADER_BYTES}-byte limit")
    if payload.nbytes > MAX_FRAME_BYTES:
        raise ValueError(
            f"bulk payload of {payload.nbytes} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte transport limit")
    lengths = struct.pack(">II", len(hdr), payload.nbytes)
    digest = secret.sign_parts(key, lengths, hdr, payload)
    prefix = (struct.pack(">I", RAW_FRAME_FLAG | len(hdr)) + digest +
              struct.pack(">I", payload.nbytes) + hdr)
    _sendall_vec(sock, [prefix, payload])
    return len(prefix) + payload.nbytes


def _sendall_vec(sock, buffers):
    """sendall over a list of buffers without concatenating them (one
    sendmsg syscall per iteration; falls back to per-buffer sendall).
    Only ever called with complete pre-signed frames built by
    :func:`write_bulk_message`."""
    bufs = [memoryview(b).cast("B") for b in buffers if len(b)]
    if not hasattr(sock, "sendmsg"):
        for b in bufs:
            sock.sendall(b)  # wire-safe: frame signed by the caller
        return
    while bufs:
        sent = sock.sendmsg(bufs)  # wire-safe: frame signed by caller
        while sent:
            if sent >= bufs[0].nbytes:
                sent -= bufs[0].nbytes
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0


def read_message(sock, key, expected_direction):
    header = _read_exact(sock, 4 + secret.DIGEST_LEN)
    (length,) = struct.unpack(">I", header[:4])
    digest = header[4:]
    if length & RAW_FRAME_FLAG:
        return _read_bulk(sock, key, expected_direction,
                          length & (RAW_FRAME_FLAG - 1), digest)
    if length > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"frame length {length} exceeds limit {MAX_FRAME_BYTES}")
    payload = _read_exact(sock, length)
    if not secret.check(key, payload, digest):
        raise PermissionError("message failed HMAC verification")
    envelope = pickle.loads(payload)
    if not (isinstance(envelope, tuple) and len(envelope) == 2
            and envelope[0] == expected_direction):
        raise PermissionError(
            "message direction mismatch (reflected frame?)")
    return envelope[1]


def _read_bulk(sock, key, expected_direction, hdr_len, digest):
    """Read the remainder of a raw bulk frame (both length caps are
    checked before any buffering; the HMAC — covering the length words
    plus header plus payload — is verified before the header reaches
    the unpickler)."""
    if hdr_len > MAX_RAW_HEADER_BYTES:
        raise ConnectionError(
            f"bulk header length {hdr_len} exceeds limit "
            f"{MAX_RAW_HEADER_BYTES}")
    (payload_len,) = struct.unpack(">I", _read_exact(sock, 4))
    if payload_len > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"bulk payload length {payload_len} exceeds limit "
            f"{MAX_FRAME_BYTES}")
    hdr = _read_exact(sock, hdr_len)
    payload = _read_exact_into(sock, payload_len)
    lengths = struct.pack(">II", hdr_len, payload_len)
    if not secret.check_parts(key, digest, lengths, hdr, payload):
        raise PermissionError("bulk message failed HMAC verification")
    envelope = pickle.loads(hdr)
    if not (isinstance(envelope, tuple) and len(envelope) == 2
            and envelope[0] == expected_direction):
        raise PermissionError(
            "message direction mismatch (reflected frame?)")
    obj = envelope[1]
    # payload injection: the carrier (the mux (req_id, obj) pair's
    # second element, or the bare object) declared a ``payload`` slot
    carrier = obj[1] if isinstance(obj, tuple) and len(obj) == 2 else obj
    carrier.payload = payload
    return obj


def _read_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        # wakeable: closing the socket (peer abort/purge teardown, or
        # the owner's close()) breaks the blocked recv with an OSError;
        # callers set read timeouts where the protocol demands one
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return bytes(buf)


def _read_exact_into(sock, n):
    """One preallocated buffer filled by recv_into — the bulk payload is
    copied exactly once off the socket."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        # wakeable: socket close breaks the blocked recv (see
        # _read_exact)
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed connection")
        got += r
    return buf


# ------------------------------------------------------------------- service
class BasicService:
    """Threaded TCP service answering one signed request per connection
    (reference: ``network.BasicService``)."""

    def __init__(self, name, key):
        self._name = name
        self._key = key
        self._start_server(self._make_handler())

    def _make_handler(self):
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = read_message(self.request, service._key, "q")
                except (PermissionError, ConnectionError, EOFError):
                    return  # drop unauthenticated/broken peers silently
                try:
                    resp = service._handle(req, self.client_address)
                except Exception as exc:  # noqa: BLE001 — ship to client
                    resp = exc
                try:
                    write_message(self.request, service._key, resp, "r")
                except OSError:
                    pass  # client went away
                except Exception as exc:  # noqa: BLE001 — unpicklable resp
                    try:
                        write_message(
                            self.request, service._key,
                            RuntimeError(
                                f"response serialization failed: {exc}"),
                            "r")
                    except Exception:  # noqa: BLE001
                        pass

        return Handler

    def _start_server(self, handler):
        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server(("0.0.0.0", 0), handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"{self._name}-service")
        self._thread.start()

    @property
    def port(self):
        return self._server.server_address[1]

    def addresses(self):
        """{interface: [(ip, port)]} for every non-loopback interface
        (reference: ``network.get_local_host_addresses``)."""
        out = {}
        for iface, ip in local_interfaces().items():
            out[iface] = [(ip, self.port)]
        return out

    def _handle(self, req, client_address):
        if isinstance(req, PingRequest):
            return PingResponse(self._name)
        raise ValueError(f"unknown request type {type(req).__name__}")

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class BasicClient:
    """One-connection-per-request client (reference:
    ``network.BasicClient``): tries each known (ip, port) until one
    answers, remembers the winner."""

    def __init__(self, addresses, key, timeout=10, read_timeout="same",
                 retry_for=None, peer=None):
        # addresses: {iface: [(ip, port)]} or flat [(ip, port)].
        # ``timeout`` bounds connection establishment; ``read_timeout``
        # bounds the response wait (None = wait forever — collectives
        # legitimately block until every rank contributes, and the
        # coordinator owns stall detection).  ``retry_for`` is the
        # deadline budget for connect-phase retries with backoff+jitter
        # (None = HVD_TPU_CONNECT_RETRY_SECONDS; 0 = a single sweep) —
        # one RST during rendezvous must not kill the job.  ``peer`` is
        # the remote's rank when known, for link-level fault targeting.
        if isinstance(addresses, dict):
            flat = [a for addrs in addresses.values() for a in addrs]
        else:
            flat = list(addresses)
        if not flat:
            raise ValueError("no addresses to connect to")
        self._addresses = flat
        self._good = None
        self._key = key
        self._timeout = timeout
        self._peer = peer
        self._read_timeout = timeout if read_timeout == "same" \
            else read_timeout
        self._retry_for = (default_connect_retry() if retry_for is None
                           else retry_for)

    def _send_one(self, addr, req):
        with connect(addr, self._timeout) as sock:
            sock.settimeout(self._read_timeout)
            _apply_link_faults(self._peer)
            write_message(sock, self._key, req, "q")
            resp = read_message(sock, self._key, "r")
        if isinstance(resp, Exception):
            raise resp
        return resp

    def send(self, req, idempotent=False):
        """Address failover happens ONLY at the connect phase.  Once a
        request has been written, any error propagates — retransmitting a
        non-idempotent message (e.g. a collective contribution that is
        merely slow to complete) would hit the coordinator's
        duplicate-request detection and fail the job.  ``idempotent=True``
        (registrations, probes, polls) lifts that rule: the whole request
        is retried under the deadline budget even after a post-write
        failure.  A cached winner whose CONNECT fails is safe to fail
        over from (nothing was sent), so the other addresses are retried
        then; when every address refuses, the sweep repeats with
        exponential backoff + jitter until the ``retry_for`` budget is
        spent."""
        deadline = time.monotonic() + self._retry_for
        attempt = 0
        while True:
            try:
                return self._send_sweep(req, idempotent)
            except _RetryableSendError as exc:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(str(exc)) from exc
                time.sleep(min(backoff_delay(attempt), max(remaining, 0.0)))
                attempt += 1

    def _send_sweep(self, req, idempotent):
        """One pass over the candidate addresses."""
        candidates = list(self._addresses)
        if self._good is not None and self._good in candidates:
            candidates.remove(self._good)
            candidates.insert(0, self._good)
        last_error = None
        for addr in candidates:
            try:
                sock = connect(addr, self._timeout)
            except OSError as exc:
                last_error = exc
                if addr == self._good:
                    self._good = None
                continue
            try:
                with sock:
                    sock.settimeout(self._read_timeout)
                    _apply_link_faults(self._peer)
                    write_message(sock, self._key, req, "q")
                    resp = read_message(sock, self._key, "r")
            except OSError as exc:
                if idempotent:
                    # safe to resend in full: surface as retryable
                    raise _RetryableSendError(
                        f"idempotent request to {addr} failed after "
                        f"write: {exc}") from exc
                raise  # sent — do NOT failover to another address
            self._good = addr
            if isinstance(resp, Exception):
                raise resp
            return resp
        raise _RetryableSendError(
            f"could not reach service at any of {self._addresses}: "
            f"{last_error}")

    def probe(self):
        """Which of the candidate addresses actually answer a Ping
        (reference: the task-to-task address check,
        ``driver_service.py:156``)."""
        good = []
        for addr in self._addresses:
            try:
                resp = self._send_one(addr, PingRequest())
                if isinstance(resp, PingResponse):
                    good.append(addr)
            except (OSError, ConnectionError, PermissionError):
                continue
        return good


def _connect_any(addresses, timeout, retry_for):
    """Connect sweep over the address list with exponential backoff +
    jitter under the ``retry_for`` deadline budget; returns a connected
    TCP_NODELAY socket (shared by the mux control connection, its bulk
    companion, and the ring stripe pool)."""
    deadline = time.monotonic() + retry_for
    attempt = 0
    last_error = None
    while True:
        for addr in addresses:
            try:
                sock = connect(addr, timeout)
                sock.settimeout(None)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except OSError as exc:
                last_error = exc
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise ConnectionError(
                f"could not reach service at any of {addresses}: "
                f"{last_error}")
        time.sleep(min(backoff_delay(attempt), max(remaining, 0.0)))
        attempt += 1


# ------------------------------------------------- persistent mux transport
class MuxService(BasicService):
    """Persistent-connection variant: each connection carries a stream of
    ``(req_id, request)`` frames; every request is handled on its own
    thread and the ``(req_id, response)`` frame is written back whenever
    it completes — so slow (blocking) requests don't head-of-line-block
    the connection.  Fire-and-forget posts (``req_id`` None) are handled
    inline on the reader loop instead: their handlers are quick and a
    thread spawn per bulk segment would dominate the striped data path.
    The reference keeps persistent Gloo pairs the same way; round 1's
    one-connection-per-request client was the analog of re-running
    rendezvous per collective."""

    def __init__(self, name, key):
        self._inflight = 0   # guarded by self._inflight_cv
        self._inflight_cv = threading.Condition()
        super().__init__(name, key)

    def _make_handler(self):
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                write_lock = threading.Lock()
                sock = self.request
                while True:
                    try:
                        frame = read_message(sock, service._key, "q")
                    except (PermissionError, ConnectionError, EOFError,
                            OSError):
                        return
                    if not (isinstance(frame, tuple) and len(frame) == 2):
                        return
                    req_id, req = frame
                    with service._inflight_cv:
                        service._inflight += 1
                    if req_id is None:
                        # fire-and-forget: no response is ever written
                        # and the handlers behind these posts (mailbox
                        # insert, abort flag) are quick — dispatch
                        # inline rather than paying a thread spawn per
                        # bulk segment on the striped data path
                        try:
                            service._handle(req, self.client_address)
                        except Exception:  # noqa: BLE001 — nowhere to
                            pass           # report without a req_id
                        finally:
                            with service._inflight_cv:
                                service._inflight -= 1
                                service._inflight_cv.notify_all()
                        continue

                    def run(req_id=req_id, req=req):
                        try:
                            try:
                                resp = service._handle(
                                    req, self.client_address)
                            except Exception as exc:  # noqa: BLE001
                                resp = exc
                            service._write_response(sock, write_lock,
                                                    req_id, resp)
                        finally:
                            with service._inflight_cv:
                                service._inflight -= 1
                                service._inflight_cv.notify_all()

                    # lifecycle: ends with its single _handle call;
                    # shutdown() drains in-flight handlers through the
                    # _inflight_cv barrier before the socket closes
                    threading.Thread(target=run, daemon=True,
                                     name=f"{service._name}-req").start()

        return Handler

    def _write_response(self, sock, write_lock, req_id, resp):
        try:
            with write_lock:
                write_message(sock, self._key, (req_id, resp), "r")
        except OSError:
            pass  # client went away
        except Exception as exc:  # noqa: BLE001 — e.g. unpicklable resp
            # a silently-dropped frame would hang the client's send()
            # forever; ship an error, or kill the connection so the
            # client fails fast
            try:
                with write_lock:
                    write_message(
                        sock, self._key,
                        (req_id,
                         RuntimeError(
                             f"response serialization failed: {exc}")),
                        "r")
            except Exception:  # noqa: BLE001
                try:
                    sock.close()
                except OSError:
                    pass

    def shutdown(self):
        """Drain in-flight requests before closing: a coordinator whose
        own rank finishes first must not tear down the socket while
        response frames to other ranks are still being written."""
        import time as _time

        deadline = _time.monotonic() + 10
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                self._inflight_cv.wait(timeout=remaining)
        super().shutdown()


class MuxClient:
    """Client for :class:`MuxService`: ONE persistent socket, concurrent
    in-flight requests demultiplexed by id.  Thread-safe."""

    def __init__(self, addresses, key, timeout=10, retry_for=None,
                 peer=None):
        if isinstance(addresses, dict):
            flat = [a for addrs in addresses.values() for a in addrs]
        else:
            flat = list(addresses)
        if not flat:
            raise ValueError("no addresses to connect to")
        self._addresses = flat
        self._key = key
        self._timeout = timeout
        # remote's rank when known (coordinator: 0, ring mailboxes:
        # the peer rank) — link-level fault targeting needs the
        # identity, the transport itself never does
        self._peer = peer
        self._retry_for = (default_connect_retry() if retry_for is None
                           else retry_for)
        self._sock = None     # guarded by self._state_lock
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        # req_id -> [event, response]; guarded by self._state_lock
        self._pending = {}
        # random start: a (req_id, resp) frame recorded from an earlier
        # connection/run cannot collide with a live request id
        self._next_id = _secrets.randbits(48)  # guarded by self._state_lock
        self._reader = None   # guarded by self._state_lock
        self._broken = None   # guarded by self._state_lock
        # bulk companion: a StripeClient to the same service that
        # carries ONLY fire-and-forget raw frames, under its own lock —
        # a pending control request (heartbeat, negotiation, abort)
        # never waits behind an in-progress multi-MB bulk write
        self._bulk = None     # guarded by self._bulk_lock
        self._bytes_sent = 0  # control bytes; guarded by self._send_lock
        self._bulk_lock = threading.Lock()

    def _connect_locked(self):  # holds: self._state_lock
        """Establish the socket + reader (caller holds _state_lock).
        Sweeps the address list with exponential backoff + jitter under
        the ``retry_for`` deadline budget: a refused/reset connection
        during rendezvous or negotiation is retried, not fatal."""
        sock = _connect_any(self._addresses, self._timeout,
                            self._retry_for)
        self._sock = sock
        self._broken = None
        # lifecycle: exits when its socket dies — close() closes the
        # socket, which breaks the blocked read_message and returns
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,), daemon=True,
            name="mux-client-reader")
        self._reader.start()

    def _ensure_connected_locked(self):  # holds: self._state_lock
        """Returns the live socket (caller holds _state_lock).  The
        returned reference — not a re-read of self._sock — must be used
        for the write, so a concurrent reconnect can never route this
        request onto a connection its pending entry isn't tied to."""
        if self._sock is None or self._broken is not None:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            self._connect_locked()
        return self._sock

    def _read_loop(self, sock):
        while True:
            try:
                frame = read_message(sock, self._key, "r")
                if not (isinstance(frame, tuple) and len(frame) == 2):
                    raise ConnectionError(
                        f"malformed mux frame {type(frame).__name__}")
                req_id, resp = frame
            except Exception as exc:  # noqa: BLE001 — reader must never
                # die silently: fail every waiter and mark broken
                with self._state_lock:
                    self._broken = exc
                    pending, self._pending = self._pending, {}
                for event, slot in pending.values():
                    slot[0] = ConnectionError(
                        f"connection to service lost: {exc}")
                    event.set()
                return
            with self._state_lock:
                entry = self._pending.pop(req_id, None)
            if entry is not None:
                entry[1][0] = resp
                entry[0].set()

    def send(self, req, timeout=None):
        with self._state_lock:
            sock = self._ensure_connected_locked()
            req_id = self._next_id
            self._next_id += 1
            event, slot = threading.Event(), [None]
            self._pending[req_id] = (event, slot)
        try:
            with self._send_lock:
                _apply_link_faults(self._peer)
                self._bytes_sent += write_message(
                    sock, self._key, (req_id, req), "q")
        except Exception:  # OSError, PicklingError, oversize ValueError…
            with self._state_lock:
                self._pending.pop(req_id, None)
            raise
        if not event.wait(timeout):
            with self._state_lock:
                self._pending.pop(req_id, None)
            raise TimeoutError("no response from service")
        resp = slot[0]
        if isinstance(resp, Exception):
            raise resp
        return resp

    def post(self, req):
        """Fire-and-forget: write the frame without expecting a response
        (req_id None).  TCP ordering + HMAC still apply; used by the ring
        data plane so chunk streams aren't serialized on ack round-trips."""
        with self._state_lock:
            sock = self._ensure_connected_locked()
        with self._send_lock:
            _apply_link_faults(self._peer)
            self._bytes_sent += write_message(sock, self._key,
                                              (None, req), "q")

    @property
    def bytes_sent(self):
        """Wire bytes written (control + bulk companion, framing
        included) — the own counter and the bulk reference are read
        under their guarding locks; the companion's monotonic counter
        is read staleness-tolerantly (it may lag an in-flight
        post_bulk by one frame, which the quiesced-transfer
        byte-accounting tests never observe)."""
        with self._send_lock:
            total = self._bytes_sent
        with self._bulk_lock:
            bulk = self._bulk
        return total + (bulk.bytes_sent if bulk else 0)

    def post_bulk(self, obj, payload):
        """Fire-and-forget raw bulk frame on the dedicated bulk
        companion connection (a lazily-built :class:`StripeClient` to
        the same service): ``obj`` is the small header carrier (its
        ``payload`` attribute must be None), ``payload`` the raw bytes.
        Control ``send``s keep round-tripping on the main socket while
        this write is in flight."""
        with self._bulk_lock:
            if self._bulk is None:
                self._bulk = StripeClient(
                    self._addresses, self._key, timeout=self._timeout,
                    retry_for=self._retry_for, peer=self._peer)
            bulk = self._bulk
        bulk.post_bulk(obj, payload)

    def close(self):
        with self._state_lock:
            sock, self._sock = self._sock, None
        with self._bulk_lock:
            bulk = self._bulk
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if bulk is not None:
            bulk.close()


class StripeClient:
    """One dedicated bulk-data connection to a :class:`MuxService`:
    fire-and-forget raw frames only (req_id None, so the service never
    writes back — no reader thread).  The ring data plane keeps a pool
    of these per peer (``HVD_TPU_RING_STRIPES``), separate from the
    control :class:`MuxClient`, so heartbeats and negotiation never
    queue behind multi-MB chunk writes and high-BDP links get
    multi-stream throughput.  Thread-safe."""

    def __init__(self, addresses, key, timeout=10, retry_for=None,
                 peer=None):
        if isinstance(addresses, dict):
            flat = [a for addrs in addresses.values() for a in addrs]
        else:
            flat = list(addresses)
        if not flat:
            raise ValueError("no addresses to connect to")
        self._addresses = flat
        self._key = key
        self._timeout = timeout
        self._peer = peer    # remote's rank when known (fault targeting)
        self._retry_for = (default_connect_retry() if retry_for is None
                           else retry_for)
        self._lock = threading.Lock()
        self._sock = None    # guarded by self._lock
        # cumulative frame bytes written by post_bulk; external
        # monotonic reads tolerate staleness; guarded by self._lock
        self.bytes_sent = 0

    def post_bulk(self, obj, payload):
        """Write one raw bulk frame (``obj`` the small header carrier
        with a None ``payload`` attribute, ``payload`` the raw bytes)."""
        with self._lock:
            if self._sock is None:
                self._sock = _connect_any(self._addresses, self._timeout,
                                          self._retry_for)
            try:
                _apply_link_faults(self._peer,
                                   memoryview(payload).nbytes)
                self.bytes_sent += write_bulk_message(
                    self._sock, self._key, (None, obj), payload, "q")
            except OSError:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                raise

    def close(self):
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


# ----------------------------------------------------------- NIC enumeration
def local_interfaces():
    """{interface_name: ipv4} for every UP non-loopback interface.

    Stdlib-only Linux implementation (ioctl SIOCGIFADDR per interface from
    ``socket.if_nameindex``); falls back to a hostname lookup pinned to a
    pseudo-interface when the ioctl path is unavailable.
    """
    import fcntl

    out = {}
    try:
        ifaces = socket.if_nameindex()
    except OSError:
        ifaces = []
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for _, name in ifaces:
            if name == "lo":
                continue
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", name.encode()[:15]))
                out[name] = socket.inet_ntoa(packed[20:24])
            except OSError:
                continue  # interface without an IPv4 address
    finally:
        s.close()
    if not out:
        try:
            out["_default"] = socket.gethostbyname(socket.gethostname())
        except OSError:
            out["_default"] = "127.0.0.1"
    return out
