"""Driver/task coordination services (reference: ``horovod/run/common/
service/`` + ``horovod/run/driver/driver_service.py`` + ``horovod/run/
task/task_service.py``): secret-keyed pickled-message TCP services used by
the launcher for task registration and routable-NIC discovery."""

from horovod_tpu.run.service.network import (  # noqa: F401
    AckResponse,
    BasicClient,
    BasicService,
    PingRequest,
    PingResponse,
)
from horovod_tpu.run.service.driver_service import (  # noqa: F401
    DriverClient,
    DriverService,
    find_common_interfaces,
)
from horovod_tpu.run.service.task_service import (  # noqa: F401
    TaskClient,
    TaskService,
)
from horovod_tpu.run.service import secret  # noqa: F401
