"""Task-side service (reference: ``horovod/run/task/task_service.py`` +
``task_fn.py``): runs on every host slot during launch, registers with the
driver, answers address probes from peers, and (for cluster glue) executes
a command on behalf of the driver."""

import subprocess
import threading

from horovod_tpu.run.service import network


class ProbeAddressesRequest:
    def __init__(self, addresses):
        self.addresses = addresses  # {iface: [(ip, port)]}


class ProbeAddressesResponse:
    def __init__(self, reachable):
        self.reachable = reachable  # {iface: [(ip, port)]} subset


class RunCommandRequest:
    def __init__(self, command, env=None):
        self.command = command
        self.env = env


class CommandExitCodeRequest:
    pass


class CommandExitCodeResponse:
    def __init__(self, terminated, exit_code):
        self.terminated = terminated
        self.exit_code = exit_code


class ShutdownTaskRequest:
    pass


class TaskService(network.BasicService):
    NAME = "horovod_tpu task service"

    def __init__(self, index, key):
        self.index = index
        self._command_proc = None
        self._command_exit = None
        self._lock = threading.Lock()
        self.shutdown_requested = threading.Event()
        super().__init__(f"{self.NAME} {index}", key)

    def _handle(self, req, client_address):
        if isinstance(req, ProbeAddressesRequest):
            # retry_for=0: a probe's whole job is to report unreachable
            # addresses quickly — backing off and retrying would turn
            # every dead NIC into a multi-sweep stall
            client = network.BasicClient(req.addresses, self._key,
                                         timeout=3, retry_for=0)
            good = set(client.probe())
            reachable = {
                iface: [a for a in addrs if a in good]
                for iface, addrs in req.addresses.items()}
            reachable = {i: a for i, a in reachable.items() if a}
            return ProbeAddressesResponse(reachable)
        if isinstance(req, RunCommandRequest):
            with self._lock:
                if self._command_proc is not None:
                    raise RuntimeError("a command is already running")
                self._command_exit = None
                self._command_proc = subprocess.Popen(
                    req.command, shell=True, env=req.env)

                def wait(proc=self._command_proc):
                    code = proc.wait()
                    with self._lock:
                        self._command_exit = code
                        self._command_proc = None

                # lifecycle: ends when the launched command exits; the
                # command is killed (terminate_executor) on shutdown,
                # which unblocks the wait
                threading.Thread(target=wait, daemon=True).start()
            return network.AckResponse()
        if isinstance(req, CommandExitCodeRequest):
            with self._lock:
                return CommandExitCodeResponse(
                    self._command_exit is not None, self._command_exit)
        if isinstance(req, ShutdownTaskRequest):
            self.shutdown_requested.set()
            return network.AckResponse()
        return super()._handle(req, client_address)


class TaskClient(network.BasicClient):
    def probe_addresses(self, addresses):
        return self.send(ProbeAddressesRequest(addresses),
                         idempotent=True).reachable

    def run_command(self, command, env=None):
        # NOT idempotent: a replay would double-start the command and
        # the service rejects concurrent runs — post-write failures
        # must surface, never retry
        self.send(RunCommandRequest(command, env))

    def command_exit_code(self):
        resp = self.send(CommandExitCodeRequest(), idempotent=True)
        return resp.exit_code if resp.terminated else None

    def shutdown_task(self):
        self.send(ShutdownTaskRequest(), idempotent=True)
