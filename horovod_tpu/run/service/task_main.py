"""Task-server process entry (reference: ``horovod/run/task_fn.py`` — one
short-lived server per host during launch, used by the driver for address
registration and NIC probing).  Launched as

    python -m horovod_tpu.run.service.task_main

with the contract: ``HVD_TASK_INDEX`` and ``HVD_DRIVER_ADDRS``
(``ip:port;ip:port``) in env vars, and the base64 job secret as the first
line of stdin — never on a command line or remote env export, where it
would be ps-visible (the secret authenticates a service that can run
commands)."""

import base64
import sys
import time

from horovod_tpu.run.service.driver_service import DriverClient
from horovod_tpu.run.service.task_service import TaskService
from horovod_tpu.utils import env as env_util


def main():
    index = int(env_util.get_required(env_util.HVD_TASK_INDEX))
    key = base64.b64decode(sys.stdin.readline().strip())
    if not key:
        sys.stderr.write("task server: no secret on stdin\n")
        return 1
    driver_addrs = []
    for part in env_util.get_required(env_util.HVD_DRIVER_ADDRS) \
            .split(";"):
        ip, port = part.rsplit(":", 1)
        driver_addrs.append((ip, int(port)))
    timeout = env_util.get_float(env_util.HVD_TASK_TIMEOUT, 120.0)

    task = TaskService(index, key)
    try:
        from horovod_tpu.run.host_hash import host_hash

        client = DriverClient(driver_addrs, key)
        client.register_task(index, task.addresses(),
                             host_hash=host_hash())
        deadline = time.time() + timeout
        while not task.shutdown_requested.is_set():
            if time.time() > deadline:
                sys.stderr.write(
                    f"task server {index}: driver did not finish within "
                    f"{timeout}s\n")
                return 1
            time.sleep(0.1)
        return 0
    finally:
        task.shutdown()


if __name__ == "__main__":
    sys.exit(main())
