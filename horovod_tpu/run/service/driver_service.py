"""Driver-side coordination service (reference:
``horovod/run/driver/driver_service.py``): tasks register their service
addresses with the driver; the driver asks each task to probe the next
task's addresses and intersects the reachable interfaces to find NICs that
are routable between every pair of hosts (``driver_service.py:156,225``)."""

import threading

from horovod_tpu.run.service import network


# ------------------------------------------------------------------ messages
class RegisterTaskRequest:
    def __init__(self, index, task_addresses, host_hash=None):
        self.index = index
        self.task_addresses = task_addresses  # {iface: [(ip, port)]}
        # machine identity (reference: host_hash.py) — co-located tasks
        # skip the pairwise NIC probe, every interface is loopback-reachable
        self.host_hash = host_hash


class AllTaskAddressesRequest:
    def __init__(self, index):
        self.index = index


class AllTaskAddressesResponse:
    def __init__(self, all_task_addresses):
        self.all_task_addresses = all_task_addresses


class RegisterTaskToTaskAddressesRequest:
    def __init__(self, index, reachable_addresses):
        self.index = index
        self.reachable_addresses = reachable_addresses


class WaitDoneRequest:
    pass


class WaitDoneResponse:
    def __init__(self, done):
        self.done = done


# ------------------------------------------------------------------- service
class DriverService(network.BasicService):
    NAME = "horovod_tpu driver service"

    def __init__(self, num_proc, key):
        self._num_proc = num_proc
        # index -> {iface: [(ip, port)]}; guarded by self._cv
        self._registered = {}
        self._host_hashes = {}         # index -> hash; guarded by self._cv
        # index -> {iface: [(ip, port)]}; guarded by self._cv
        self._task_to_task = {}
        self._cv = threading.Condition()
        super().__init__(self.NAME, key)

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskRequest):
            with self._cv:
                self._registered[req.index] = req.task_addresses
                self._host_hashes[req.index] = req.host_hash
                self._cv.notify_all()
            return network.AckResponse()
        if isinstance(req, AllTaskAddressesRequest):
            with self._cv:
                return AllTaskAddressesResponse(
                    dict(self._registered)
                    if req.index < 0 else self._registered[req.index])
        if isinstance(req, RegisterTaskToTaskAddressesRequest):
            with self._cv:
                self._task_to_task[req.index] = req.reachable_addresses
                self._cv.notify_all()
            return network.AckResponse()
        if isinstance(req, WaitDoneRequest):
            with self._cv:
                return WaitDoneResponse(
                    len(self._task_to_task) == self._num_proc)
        return super()._handle(req, client_address)

    # ------------------------------------------------------------ driver side
    def wait_for_initial_registration(self, timeout=60):
        with self._cv:
            if not self._cv.wait_for(
                    lambda: len(self._registered) == self._num_proc,
                    timeout=timeout):
                missing = [i for i in range(self._num_proc)
                           if i not in self._registered]
                raise TimeoutError(
                    f"tasks {missing} did not register within {timeout}s")

    def task_addresses(self, index):
        with self._cv:
            return self._registered[index]

    def common_interfaces(self):
        """Interfaces of each task that its predecessor could reach; the
        job-wide usable NIC set is their name intersection (reference:
        ``_driver_fn`` common-intersection logic)."""
        with self._cv:
            iface_sets = [set(addrs.keys())
                          for addrs in self._task_to_task.values()]
        if not iface_sets:
            return set()
        common = set.intersection(*iface_sets)
        if not common:
            raise RuntimeError(
                "no network interface is routable between all hosts; "
                "set HVD_IFACE to force one")
        return common


class DriverClient(network.BasicClient):
    """Every driver request is idempotent (registrations overwrite the
    same value, the rest are reads), so the transport may replay them in
    full after a mid-request failure — rendezvous survives transient
    RSTs instead of killing the worker."""

    def register_task(self, index, task_addresses, host_hash=None):
        self.send(RegisterTaskRequest(index, task_addresses, host_hash),
                  idempotent=True)

    def all_task_addresses(self, index=-1):
        return self.send(AllTaskAddressesRequest(index),
                         idempotent=True).all_task_addresses

    def register_task_to_task_addresses(self, index, reachable):
        self.send(RegisterTaskToTaskAddressesRequest(index, reachable),
                  idempotent=True)

    def wait_done(self):
        return self.send(WaitDoneRequest(), idempotent=True).done


def find_common_interfaces(driver, key, num_proc, timeout=60):
    """Driver-side orchestration: after every task registered, instruct
    task i to probe task (i+1) % n and intersect the reachable interface
    names (reference: ``driver_service.get_common_interfaces``,
    ``driver_service.py:225``)."""
    from horovod_tpu.run.service.task_service import TaskClient

    driver.wait_for_initial_registration(timeout=timeout)
    for i in range(num_proc):
        nxt = (i + 1) % num_proc
        hh_i = driver._host_hashes.get(i)
        if hh_i is not None and hh_i == driver._host_hashes.get(nxt):
            # co-located tasks (same host_hash): every interface is
            # trivially routable; skip the network probe
            reachable = driver.task_addresses(nxt)
        else:
            client = TaskClient(driver.task_addresses(i), key)
            reachable = client.probe_addresses(driver.task_addresses(nxt))
        driver._handle(
            RegisterTaskToTaskAddressesRequest(i, reachable), None)
    return driver.common_interfaces()
