"""Shared-secret generation and message signing (reference:
``horovod/run/common/util/secret.py`` — an HMAC key minted by the driver
and passed to tasks through the environment so that only processes of this
job can talk to its services)."""

import hmac
import hashlib
import os

DIGEST_LEN = 32  # sha256


def make_secret_key() -> bytes:
    return os.urandom(32)


def sign(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, hashlib.sha256).digest()


def check(key: bytes, payload: bytes, digest: bytes) -> bool:
    return hmac.compare_digest(sign(key, payload), digest)
