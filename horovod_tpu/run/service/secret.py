"""Shared-secret generation and message signing (reference:
``horovod/run/common/util/secret.py`` — an HMAC key minted by the driver
and passed to tasks through the environment so that only processes of this
job can talk to its services)."""

import hmac
import hashlib
import os

DIGEST_LEN = 32  # sha256


def make_secret_key() -> bytes:
    return os.urandom(32)


def sign(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, hashlib.sha256).digest()


def check(key: bytes, payload: bytes, digest: bytes) -> bool:
    return hmac.compare_digest(sign(key, payload), digest)


def sign_parts(key: bytes, *parts) -> bytes:
    """HMAC over the concatenation of ``parts`` without materializing
    it — the bulk frame path signs [header][payload] where the payload
    is a multi-MB memoryview a join would copy."""
    mac = hmac.new(key, digestmod=hashlib.sha256)
    for part in parts:
        mac.update(part)
    return mac.digest()


def check_parts(key: bytes, digest: bytes, *parts) -> bool:
    return hmac.compare_digest(sign_parts(key, *parts), digest)
