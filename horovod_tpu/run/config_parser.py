"""Tri-surface configuration: YAML config file <-> CLI args <-> env vars.

Reference: ``horovod/run/common/util/config_parser.py`` — a YAML file sets
the same knobs as CLI flags; CLI flags override the file; everything lands
in the worker env contract (``set_env_from_args``).
"""

from horovod_tpu.utils import env as env_util

# arg name -> (env var, yaml path)
_PARAMS = {
    "fusion_threshold_mb": (env_util.HVD_FUSION_THRESHOLD, "params.fusion_threshold_mb"),
    "cycle_time_ms": (env_util.HVD_CYCLE_TIME, "params.cycle_time_ms"),
    "cache_capacity": (env_util.HVD_CACHE_CAPACITY, "params.cache_capacity"),
    "hierarchical_allreduce": (env_util.HVD_HIERARCHICAL_ALLREDUCE, "params.hierarchical_allreduce"),
    "hierarchical_allgather": (env_util.HVD_HIERARCHICAL_ALLGATHER, "params.hierarchical_allgather"),
    "hier_local_size": (env_util.HVD_HIER_LOCAL_SIZE,
                        "params.hier_local_size"),
    "adasum_hierarchical": (env_util.HVD_ADASUM_HIERARCHICAL, "params.adasum_hierarchical"),
    "compression": (env_util.HVD_TPU_COMPRESSION, "params.compression"),
    "ring_segment_bytes": (env_util.HVD_TPU_RING_SEGMENT_BYTES,
                           "params.ring_segment_bytes"),
    "ring_stripes": (env_util.HVD_TPU_RING_STRIPES,
                     "params.ring_stripes"),
    "tcp_ring_threshold": (env_util.HVD_TCP_RING_THRESHOLD,
                           "params.tcp_ring_threshold"),
    "schedule": (env_util.HVD_TPU_SCHEDULE, "params.schedule"),
    "autotune": (env_util.HVD_AUTOTUNE, "autotune.enabled"),
    "autotune_log_file": (env_util.HVD_AUTOTUNE_LOG, "autotune.log_file"),
    "autotune_warmup_samples": (env_util.HVD_AUTOTUNE_WARMUP_SAMPLES, "autotune.warmup_samples"),
    "autotune_steady_state_samples": (env_util.HVD_AUTOTUNE_STEADY_STATE_SAMPLES, "autotune.steady_state_samples"),
    "autotune_bayes_opt_max_samples": (env_util.HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, "autotune.bayes_opt_max_samples"),
    "autotune_gaussian_process_noise": (env_util.HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, "autotune.gaussian_process_noise"),
    "timeline_filename": (env_util.HVD_TIMELINE, "timeline.filename"),
    "timeline_mark_cycles": (env_util.HVD_TIMELINE_MARK_CYCLES, "timeline.mark_cycles"),
    "no_stall_check": (env_util.HVD_STALL_CHECK_DISABLE, "stall_check.disabled"),
    "stall_check_warning_time_seconds": (env_util.HVD_STALL_CHECK_TIME_SECONDS, "stall_check.warning_time_seconds"),
    "stall_check_shutdown_time_seconds": (env_util.HVD_STALL_SHUTDOWN_TIME_SECONDS, "stall_check.shutdown_time_seconds"),
    "log_level": (env_util.HVD_LOG_LEVEL, "logging.level"),
    "log_hide_timestamp": (env_util.HVD_LOG_HIDE_TIME, "logging.hide_timestamp"),
    "controller": (env_util.HVD_CONTROLLER, "params.controller"),
    "start_timeout": (env_util.HVD_START_TIMEOUT, "timeouts.start_timeout"),
    "network_interface": (env_util.HVD_IFACE, "network.interface"),
    "abort_timeout": (env_util.HVD_TPU_ABORT_TIMEOUT,
                      "fault_tolerance.abort_timeout"),
    "heartbeat_interval": (env_util.HVD_TPU_HEARTBEAT_INTERVAL,
                           "fault_tolerance.heartbeat_interval"),
    "liveness_timeout": (env_util.HVD_TPU_LIVENESS_TIMEOUT,
                         "fault_tolerance.liveness_timeout"),
    "connect_retry_seconds": (env_util.HVD_TPU_CONNECT_RETRY_SECONDS,
                              "fault_tolerance.connect_retry_seconds"),
    "fault_spec": (env_util.HVD_TPU_FAULT_SPEC, "fault_tolerance.spec"),
    "rtt_alpha": (env_util.HVD_TPU_RTT_ALPHA,
                  "fault_tolerance.rtt_alpha"),
    "reconnect_budget": (env_util.HVD_TPU_RECONNECT_BUDGET,
                         "fault_tolerance.reconnect_budget"),
    "replay_buffer_bytes": (env_util.HVD_TPU_REPLAY_BUFFER_BYTES,
                            "fault_tolerance.replay_buffer_bytes"),
    "straggler_factor": (env_util.HVD_TPU_STRAGGLER_FACTOR,
                         "fault_tolerance.straggler_factor"),
    "straggler_windows": (env_util.HVD_TPU_STRAGGLER_WINDOWS,
                          "fault_tolerance.straggler_windows"),
    "straggler_exclude": (env_util.HVD_TPU_STRAGGLER_EXCLUDE,
                          "fault_tolerance.straggler_exclude"),
    "soak_ranks": (env_util.HVD_TPU_SOAK_RANKS, "soak.ranks"),
    "soak_steps": (env_util.HVD_TPU_SOAK_STEPS, "soak.steps"),
    "soak_seed": (env_util.HVD_TPU_SOAK_SEED, "soak.seed"),
    "soak_report": (env_util.HVD_TPU_SOAK_REPORT, "soak.report_prefix"),
    "soak_reconfig_bound": (env_util.HVD_TPU_SOAK_RECONFIG_BOUND,
                            "soak.reconfig_bound"),
    "elastic": (env_util.HVD_TPU_ELASTIC, "elastic.enabled"),
    "min_ranks": (env_util.HVD_TPU_MIN_RANKS, "elastic.min_ranks"),
    "max_ranks": (env_util.HVD_TPU_MAX_RANKS, "elastic.max_ranks"),
    "reconfig_timeout": (env_util.HVD_TPU_RECONFIG_TIMEOUT,
                         "elastic.reconfig_timeout"),
    "coord_failover": (env_util.HVD_TPU_COORD_FAILOVER,
                       "elastic.coord_failover"),
    "election_timeout": (env_util.HVD_TPU_ELECTION_TIMEOUT,
                         "elastic.election_timeout"),
    "term_grace": (env_util.HVD_TPU_TERM_GRACE,
                   "fault_tolerance.term_grace"),
    "drain": (env_util.HVD_TPU_DRAIN, "fault_tolerance.drain"),
    "ckpt_dir": (env_util.HVD_TPU_CKPT_DIR, "checkpoint.dir"),
    "ckpt_interval": (env_util.HVD_TPU_CKPT_INTERVAL,
                      "checkpoint.interval"),
    "ckpt_keep": (env_util.HVD_TPU_CKPT_KEEP, "checkpoint.keep"),
    "zero": (env_util.HVD_TPU_ZERO, "sharding.zero"),
    "zero_min_size": (env_util.HVD_TPU_ZERO_MIN_SIZE, "sharding.zero_min_size"),
    "executor": (env_util.HVD_TPU_EXECUTOR, "sharding.executor"),
    "group_max": (env_util.HVD_TPU_GROUP_MAX, "groups.max"),
    "race": (env_util.HVD_TPU_RACE, "race.enabled"),
    "race_seed": (env_util.HVD_TPU_RACE_SEED, "race.seed"),
    "race_scope": (env_util.HVD_TPU_RACE_SCOPE, "race.scope"),
    "race_report": (env_util.HVD_TPU_RACE_REPORT, "race.report_prefix"),
    "proto_depth": (env_util.HVD_TPU_PROTO_DEPTH, "proto.depth"),
    "proto_seed": (env_util.HVD_TPU_PROTO_SEED, "proto.seed"),
    "fuzz_seed": (env_util.HVD_TPU_FUZZ_SEED, "fuzz.seed"),
    "fuzz_iters": (env_util.HVD_TPU_FUZZ_ITERS, "fuzz.iters"),
}

# negation flags -> env var forced to "0" (reference: --no-autotune etc.)
_NEGATIONS = {
    "no_autotune": env_util.HVD_AUTOTUNE,
    "no_hierarchical_allreduce": env_util.HVD_HIERARCHICAL_ALLREDUCE,
    "no_hierarchical_allgather": env_util.HVD_HIERARCHICAL_ALLGATHER,
    "stall_check": env_util.HVD_STALL_CHECK_DISABLE,  # enable = disable-var 0
    # drain defaults ON; the negation is the interesting direction
    "no_drain": env_util.HVD_TPU_DRAIN,
    "no_straggler_exclude": env_util.HVD_TPU_STRAGGLER_EXCLUDE,
}


def _dig(tree, dotted):
    node = tree
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def load_config_file(path):
    """Parse the YAML config file into a flat {arg_name: value} dict.

    Uses a minimal built-in YAML-subset parser (two-level ``key: value``
    maps) when PyYAML is unavailable, matching the reference's file schema.
    """
    try:
        import yaml
        with open(path) as f:
            try:
                tree = yaml.safe_load(f) or {}
            except yaml.YAMLError as exc:
                # surface the same typed error a hand-rolled-parser
                # failure would: the runner reports it and exits
                # instead of a raw ScannerError traceback
                raise ValueError(f"config file {path}: {exc}") from exc
    except ImportError:
        tree = _parse_simple_yaml(path)
    if not isinstance(tree, dict):
        # a YAML file whose top level is a list/scalar has no sections
        # to dig into — reject it by name rather than returning nothing
        raise ValueError(
            f"config file {path}: top level must be a mapping, got "
            f"{type(tree).__name__}")

    out = {}
    for arg, (_env, dotted) in _PARAMS.items():
        value = _dig(tree, dotted)
        if value is not None:
            out[arg] = value
    return out


def _parse_simple_yaml(path):
    """Two-level ``section:\\n  key: value`` parser for the config schema."""
    tree = {}
    section = None
    with open(path) as f:
        for raw in f:
            # strip comments only when the ' #' occurs OUTSIDE quotes
            # ('/tmp/run#3' and "a #3" keep their hashes)
            line = raw.rstrip("\n")
            if line.lstrip().startswith("#"):
                continue
            in_quote = None
            for i, ch in enumerate(line):
                if in_quote:
                    if ch == in_quote:
                        in_quote = None
                elif ch in "'\"":
                    in_quote = ch
                elif ch == "#" and i > 0 and line[i - 1] == " ":
                    line = line[:i]
                    break
            line = line.rstrip()
            if not line.strip():
                continue
            indented = line.startswith((" ", "\t"))
            key, _, value = line.strip().partition(":")
            value = value.strip()
            if not indented:
                section = key
                tree[section] = {}
            elif section is not None:
                tree[section][key] = _coerce(value)
    return tree


def _coerce(value: str):
    if len(value) >= 2 and value[0] == value[-1] and value[0] in "'\"":
        return value[1:-1]  # quoted string: verbatim (like PyYAML)
    low = value.lower()
    if low in ("true", "yes", "on"):
        return True
    if low in ("false", "no", "off"):
        return False
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            pass
    return value


def apply_config_to_args(args, config: dict):
    """File values fill in args the CLI left at default (None).

    Identity comparison, not equality: an EXPLICIT ``--flag 0`` /
    ``0.0`` compares equal to False and would be silently overridden by
    the file, violating CLI-over-file precedence.  (Store-true flags
    use ``default=None`` in the parser, so ``False`` never appears as a
    default here; ``None`` is the only unset sentinel.)"""
    for key, value in config.items():
        if getattr(args, key, None) is None:
            setattr(args, key, value)


def env_from_args(args) -> dict:
    """Build the worker env contract from parsed args (reference:
    config_parser.set_env_from_args)."""
    env = {}

    def setenv(var, value):
        if value is None:
            return
        if isinstance(value, bool):
            if value:
                env[var] = "1"
        else:
            env[var] = str(value)

    for arg, (var, _path) in _PARAMS.items():
        value = getattr(args, arg, None)
        if arg == "fusion_threshold_mb" and value is not None:
            value = int(float(value) * 1024 * 1024)
        setenv(var, value)
    if getattr(args, "disable_cache", None):
        env[env_util.HVD_CACHE_CAPACITY] = "0"
    for arg, var in _NEGATIONS.items():
        if getattr(args, arg, None):
            env[var] = "0"
    return env
