"""Process-tree-safe command execution.

Reference: ``horovod/run/common/util/safe_shell_exec.py`` — spawn the child
in its own process group, stream stdout/stderr, and on termination (parent
death, interrupt, sibling failure) kill the WHOLE tree so no orphan workers
linger on remote hosts.
"""

import os
import signal
import subprocess
import threading
import time

GRACEFUL_TERMINATION_TIME_S = 5


def _forward_stream(pipe, sink):
    for line in iter(pipe.readline, b""):
        sink.write(line.decode(errors="replace"))
        sink.flush()
    pipe.close()


def terminate_process_group(proc):
    """SIGTERM the child's process group, escalate to SIGKILL."""
    if proc.poll() is not None:
        return
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
        proc.wait(timeout=GRACEFUL_TERMINATION_TIME_S)
    except (subprocess.TimeoutExpired, ProcessLookupError):
        try:
            os.killpg(pgid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def execute(command, env=None, stdout=None, stderr=None,
            events=None, stdin_data=None, info=None) -> int:
    """Run ``command`` (shell string or argv list) in a new process group.

    ``events``: optional list of ``threading.Event``; if any fires, the
    process tree is terminated (the launcher uses this to kill all ranks
    when one fails, reference: gloo_run.py:300-308).
    ``stdin_data``: bytes written to the child's stdin then closed (used to
    ship the job secret to ssh-launched ranks without putting it on the
    remote command line).
    ``info``: optional dict; ``info["terminated_by_event"]`` is set True
    when the tree was killed by a fired event while still running — the
    launcher uses it to tell the CULPRIT rank (failed on its own) from
    the VICTIMS it subsequently terminated, so the job's reported
    failure names the rank that actually broke.  ``info["exit_ts"]`` is
    the monotonic time ``wait()`` observed the child dead — recorded
    BEFORE the stream forwarders drain (their joins take seconds under
    load), so the launcher can rank failures by when ranks actually
    died instead of by reap order.
    Returns the exit code.
    """

    shell = isinstance(command, str)
    proc = subprocess.Popen(
        command, shell=shell, env=env, start_new_session=True,
        stdin=subprocess.PIPE if stdin_data is not None else None,
        stdout=subprocess.PIPE if stdout is not None else None,
        stderr=subprocess.PIPE if stderr is not None else None)
    if stdin_data is not None:
        try:
            proc.stdin.write(stdin_data)
            proc.stdin.close()
        except BrokenPipeError:
            pass

    forwarders = []
    if stdout is not None:
        t = threading.Thread(target=_forward_stream,
                             args=(proc.stdout, stdout), daemon=True)
        t.start()
        forwarders.append(t)
    if stderr is not None:
        t = threading.Thread(target=_forward_stream,
                             args=(proc.stderr, stderr), daemon=True)
        t.start()
        forwarders.append(t)

    stop_watch = threading.Event()
    watchers = []
    for event in events or []:
        def watch(event=event):
            while not stop_watch.is_set():
                if event.wait(timeout=0.1):
                    if info is not None and proc.poll() is None:
                        info["terminated_by_event"] = True
                    terminate_process_group(proc)
                    return
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        watchers.append(t)

    try:
        proc.wait()
    except KeyboardInterrupt:
        terminate_process_group(proc)
        raise
    finally:
        stop_watch.set()
        if info is not None:
            info["exit_ts"] = time.monotonic()
    for t in forwarders:
        t.join(timeout=5)
    return proc.returncode
