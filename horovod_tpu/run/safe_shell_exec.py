"""Process-tree-safe command execution.

Reference: ``horovod/run/common/util/safe_shell_exec.py`` — spawn the child
in its own process group, stream stdout/stderr, and on termination (parent
death, interrupt, sibling failure) kill the WHOLE tree so no orphan workers
linger on remote hosts.
"""

import os
import signal
import subprocess
import threading
import time

from horovod_tpu.utils import env as env_util

GRACEFUL_TERMINATION_TIME_S = 5


def termination_grace_seconds() -> float:
    """The SIGTERM->SIGKILL escalation window.  Read at escalation time
    (not import time) so HVD_TPU_TERM_GRACE set by the runner's config
    surface is honored; a drain needs this long to announce departure
    and flush its checkpoint shard (docs/checkpoint.md)."""
    return env_util.get_float(env_util.HVD_TPU_TERM_GRACE,
                              float(GRACEFUL_TERMINATION_TIME_S))


def _forward_stream(pipe, sink):
    for line in iter(pipe.readline, b""):
        sink.write(line.decode(errors="replace"))
        sink.flush()
    pipe.close()


def signal_process_group(proc, sig) -> bool:
    """Deliver ``sig`` to the child's process group without escalation.

    The launcher's drain path uses this to forward its own SIGTERM (the
    preemption notice) to workers that are expected to exit 0 on their
    own; returns False when the group is already gone."""
    if proc.poll() is not None:
        return False
    try:
        os.killpg(os.getpgid(proc.pid), sig)
        return True
    except ProcessLookupError:
        return False


def terminate_process_group(proc, grace=None):
    """SIGTERM the child's process group, escalate to SIGKILL after the
    grace window (HVD_TPU_TERM_GRACE, default 5s)."""
    if proc.poll() is not None:
        return
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    if grace is None:
        grace = termination_grace_seconds()
    try:
        os.killpg(pgid, signal.SIGTERM)
        proc.wait(timeout=grace)
    except (subprocess.TimeoutExpired, ProcessLookupError):
        try:
            os.killpg(pgid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def execute(command, env=None, stdout=None, stderr=None,
            events=None, stdin_data=None, info=None,
            term_events=None) -> int:
    """Run ``command`` (shell string or argv list) in a new process group.

    ``events``: optional list of ``threading.Event``; if any fires, the
    process tree is terminated (the launcher uses this to kill all ranks
    when one fails, reference: gloo_run.py:300-308).
    ``term_events``: like ``events`` but drain-grade — the fired event
    forwards ONE SIGTERM to the process group and does NOT escalate:
    the worker is trusted to drain and exit 0 on its own (the launcher's
    escalation timer, armed with the HVD_TPU_TERM_GRACE window, is the
    backstop).  Sets ``info["drained"]`` True when forwarded.
    ``stdin_data``: bytes written to the child's stdin then closed (used to
    ship the job secret to ssh-launched ranks without putting it on the
    remote command line).
    ``info``: optional dict; ``info["terminated_by_event"]`` is set True
    when the tree was killed by a fired event while still running — the
    launcher uses it to tell the CULPRIT rank (failed on its own) from
    the VICTIMS it subsequently terminated, so the job's reported
    failure names the rank that actually broke.  ``info["exit_ts"]`` is
    the monotonic time ``wait()`` observed the child dead — recorded
    BEFORE the stream forwarders drain (their joins take seconds under
    load), so the launcher can rank failures by when ranks actually
    died instead of by reap order.
    Returns the exit code.
    """

    shell = isinstance(command, str)
    proc = subprocess.Popen(
        command, shell=shell, env=env, start_new_session=True,
        stdin=subprocess.PIPE if stdin_data is not None else None,
        stdout=subprocess.PIPE if stdout is not None else None,
        stderr=subprocess.PIPE if stderr is not None else None)
    if stdin_data is not None:
        try:
            proc.stdin.write(stdin_data)
            proc.stdin.close()
        except BrokenPipeError:
            pass

    forwarders = []
    if stdout is not None:
        t = threading.Thread(target=_forward_stream,
                             args=(proc.stdout, stdout), daemon=True)
        t.start()
        forwarders.append(t)
    if stderr is not None:
        t = threading.Thread(target=_forward_stream,
                             args=(proc.stderr, stderr), daemon=True)
        t.start()
        forwarders.append(t)

    stop_watch = threading.Event()
    # monotonic time a drain SIGTERM was forwarded (None: never).  The
    # escalation watcher CLIPS its grace to what is left of the window
    # that started at this instant: without the clip, drain-then-
    # escalate granted the tree TWO full grace windows (one armed by
    # the launcher's timer after the forward, then a fresh one inside
    # terminate_process_group) — a preempted-but-wedged worker held the
    # whole job for 2x HVD_TPU_TERM_GRACE.
    term_state = {"ts": None}
    watchers = []
    for event in events or []:
        def watch(event=event):
            while not stop_watch.is_set():
                if event.wait(timeout=0.1):
                    if info is not None and proc.poll() is None:
                        info["terminated_by_event"] = True
                    grace = None
                    if term_state["ts"] is not None:
                        grace = max(0.0, term_state["ts"]
                                    + termination_grace_seconds()
                                    - time.monotonic())
                    terminate_process_group(proc, grace=grace)
                    return
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        watchers.append(t)
    for event in term_events or []:
        def watch_term(event=event):
            while not stop_watch.is_set():
                if event.wait(timeout=0.1):
                    if signal_process_group(proc, signal.SIGTERM):
                        term_state["ts"] = time.monotonic()
                        if info is not None:
                            info["drained"] = True
                            info["term_ts"] = term_state["ts"]
                    return
        t = threading.Thread(target=watch_term, daemon=True)
        t.start()
        watchers.append(t)

    try:
        proc.wait()
    except KeyboardInterrupt:
        terminate_process_group(proc)
        raise
    finally:
        stop_watch.set()
        if info is not None:
            info["exit_ts"] = time.monotonic()
    for t in forwarders:
        t.join(timeout=5)
    return proc.returncode
