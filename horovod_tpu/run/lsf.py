"""LSF allocation discovery (reference: ``horovod/run/util/lsf.py`` —
derive the host list and process count from the LSF batch environment
so ``hvdrun`` needs no ``-H`` inside an LSF job)."""

import collections
import os


def using_lsf() -> bool:
    return "LSB_JOBID" in os.environ


def get_compute_hosts():
    """Ordered unique compute hosts of this allocation.

    Prefers ``LSB_MCPU_HOSTS`` ("host1 ncores1 host2 ncores2 ...");
    falls back to ``LSB_HOSTS`` (one entry per slot).  The first host is
    commonly the batch/launch node when it appears with zero compute
    slots — LSF already excludes it from these variables when so.
    """
    mcpu = os.environ.get("LSB_MCPU_HOSTS", "")
    if mcpu:
        fields = mcpu.split()
        return [fields[i] for i in range(0, len(fields) - 1, 2)]
    hosts = os.environ.get("LSB_HOSTS", "").split()
    return list(collections.OrderedDict.fromkeys(hosts))


def get_slots_per_host():
    """host -> slot count from the LSF env (for ``-H host:slots``)."""
    mcpu = os.environ.get("LSB_MCPU_HOSTS", "")
    if mcpu:
        fields = mcpu.split()
        return {fields[i]: int(fields[i + 1])
                for i in range(0, len(fields) - 1, 2)}
    counts = collections.Counter(os.environ.get("LSB_HOSTS", "").split())
    return dict(counts)


def get_num_processes():
    """Total slots in the allocation."""
    return sum(get_slots_per_host().values()) or None


def host_spec():
    """The ``hvdrun -H`` string for this allocation, or None outside LSF."""
    slots = get_slots_per_host()
    if not slots:
        return None
    return ",".join(f"{h}:{n}" for h, n in slots.items())
