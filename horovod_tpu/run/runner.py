"""``hvdrun`` — the launcher CLI.

Reference: ``horovod/run/runner.py`` — every core tunable is exposed as a
CLI flag mapped onto the worker env contract; hosts come from ``-H`` or a
hostfile; the config file fills in whatever the CLI left unset.  Usage:

    hvdrun -np 4 python train.py
    hvdrun -np 8 -H host1:4,host2:4 python train.py
    hvdrun -np 4 --tpu python train.py      # one process per TPU host
"""

import argparse
import os
import sys

from horovod_tpu.run import allocate as allocate_mod
from horovod_tpu.run import config_parser
from horovod_tpu.run.http_server import RendezvousServer
from horovod_tpu.run.launch import launch_job
from horovod_tpu.utils import env as env_util


def make_parser():
    parser = argparse.ArgumentParser(
        # derive from argv[0]: the launcher answers to both its own
        # name (hvdrun) and the reference's (horovodrun alias)
        prog=os.path.basename(sys.argv[0]) or "hvdrun",
        description="Launch a horovod_tpu distributed job.")
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="Total number of training processes.")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host:slots[,host:slots,...]; default "
                             "localhost with np slots.")
    parser.add_argument("--hostfile", default=None,
                        help="File with one 'hostname slots=N' per line.")
    parser.add_argument("--ssh-port", type=int, default=None)
    parser.add_argument("--mpi-args", default=None,
                        help="Extra arguments appended to the delegated "
                             "mpirun command (--launcher mpirun), e.g. "
                             "--mpi-args='--mca btl_tcp_if_include eth0'")
    parser.add_argument("--launcher", choices=["ssh", "mpirun", "jsrun"],
                        default="ssh",
                        help="Process placement: built-in ssh fan-out "
                             "(default), one mpirun invocation, or jsrun "
                             "on LSF (workers derive ranks from the MPI "
                             "runtime env).")
    parser.add_argument("--tpu", action="store_true",
                        help="TPU pod mode: one process per host; ranks map "
                             "onto pod-slice coordinates and in-process "
                             "chips become the local axis.  Implies "
                             "--global-mesh.")
    parser.add_argument("--global-mesh", action="store_true",
                        help="Join all processes into one jax.distributed "
                             "runtime: every chip is a logical rank and "
                             "collectives run as compiled XLA programs "
                             "over the global mesh (metadata-only control "
                             "plane).")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--version", action="store_true",
                        help="Print the framework version and exit.")
    parser.add_argument("--start-timeout", type=float, default=None,
                        help="Gang-start deadline in seconds: workers "
                             "that cannot reach the rendezvous/"
                             "controller within this window fail with "
                             "a clear message (default 120).")
    parser.add_argument("--output-filename", default=None,
                        help="Directory for per-rank logs: each rank's "
                             "stdout/stderr are captured to "
                             "<dir>/rank.<NN>/stdout|stderr (rank "
                             "zero-padded to the width of np-1) while "
                             "still teeing to the console (reference: "
                             "horovodrun --output-filename).")
    parser.add_argument("--network-interface", default=None,
                        help="NIC name override for the data/control "
                             "plane (maps to HVD_IFACE; default: "
                             "auto-discovered + intersected across "
                             "hosts).")
    parser.add_argument("--config-file", default=None,
                        help="YAML config file (CLI flags take precedence).")

    group = parser.add_argument_group("tunable parameters")
    group.add_argument("--fusion-threshold-mb", type=float, default=None)
    group.add_argument("--cycle-time-ms", type=float, default=None)
    group.add_argument("--cache-capacity", type=int, default=None)
    group.add_argument("--disable-cache", action="store_true",
                       default=None,
                       help="Disable the response cache entirely "
                            "(HVD_CACHE_CAPACITY=0).")
    group.add_argument("--no-hierarchical-allreduce",
                       action="store_true", default=None,
                       help="Force flat allreduce, overriding "
                            "env/config.")
    group.add_argument("--no-hierarchical-allgather",
                       action="store_true", default=None,
                       help="Force flat allgather, overriding "
                            "env/config.")
    group.add_argument("--hierarchical-allreduce", action="store_true",
                       default=None)
    group.add_argument("--hierarchical-allgather", action="store_true",
                       default=None)
    group.add_argument("--hier-local-size", type=int, default=None,
                       help="Ranks per fast (ICI) group for "
                            "hierarchical collectives "
                            "(HVD_HIER_LOCAL_SIZE; default: the "
                            "topology's local size).")
    group.add_argument("--adasum-hierarchical", action="store_true",
                       default=None,
                       help="Opt into the reference's NCCL+MPI-style "
                            "hierarchical Adasum (adasum of per-group "
                            "averages — numerically different from flat "
                            "Adasum)")
    group.add_argument("--compression",
                       choices=["none", "bf16", "fp16", "int8"],
                       default=None,
                       help="Default on-the-wire allreduce compression "
                            "(HVD_TPU_COMPRESSION); int8 is block-scaled "
                            "quantization — see docs/compression.md.")
    group.add_argument("--ring-segment-bytes", type=int, default=None,
                       help="TCP-ring pipeline segment size in bytes "
                            "(HVD_TPU_RING_SEGMENT_BYTES; 0 disables "
                            "segment pipelining — see docs/tuning.md).")
    group.add_argument("--ring-stripes", type=int, default=None,
                       help="Dedicated bulk-data connections per ring "
                            "peer (HVD_TPU_RING_STRIPES); control "
                            "traffic always rides its own connection.")
    group.add_argument("--tcp-ring-threshold", type=int, default=None,
                       help="Payload bytes at/above which tcp-mode "
                            "collectives ride the p2p ring instead of "
                            "the coordinator star "
                            "(HVD_TCP_RING_THRESHOLD, default 1 MB).")
    group.add_argument("--schedule",
                       choices=["auto", "flat_ring", "hierarchical",
                                "rhd", "star"],
                       default=None,
                       help="Collective schedule for the tcp data plane "
                            "(HVD_TPU_SCHEDULE): 'auto' picks per tensor "
                            "size/topology; 'hierarchical' is the "
                            "two-level intra-group + delegate-ring plan; "
                            "'rhd' is recursive halving/doubling for the "
                            "latency-bound regime — see docs/tuning.md.")
    group.add_argument("--controller", choices=["native", "python", "tcp"],
                       default=None)

    shard = parser.add_argument_group("sharding")
    shard.add_argument("--zero", action="store_true", default=None,
                       help="Enable the ZeRO-sharded weight update "
                            "(HVD_TPU_ZERO): gradients are reduce-scattered, "
                            "each rank updates its 1/N parameter shard with "
                            "optimizer state allocated for that shard only, "
                            "and updated shards are allgathered back — see "
                            "docs/sharding.md.")
    shard.add_argument("--zero-min-size", type=int, default=None,
                       help="Parameter-count threshold below which the "
                            "sharded update falls back to the replicated "
                            "path (HVD_TPU_ZERO_MIN_SIZE, default 1024).")
    shard.add_argument("--executor", choices=["psum", "mesh"], default=None,
                       help="XLA executor flavour (HVD_TPU_EXECUTOR): "
                            "'psum' is the shard_map ring executor; 'mesh' "
                            "builds the program over a NamedSharding mesh "
                            "(parallel.mesh axis vocabulary) so tensor/"
                            "pipeline parallel layers can compose on the "
                            "same mesh.")
    shard.add_argument("--group-max", type=int, default=None,
                       help="Cap on live process groups per job "
                            "(HVD_TPU_GROUP_MAX, default 64): each "
                            "hvd.new_group()/hvd.grid() group owns "
                            "negotiation state, signature caches and a "
                            "tcp ring plane, so an unbounded registry "
                            "is a leak — see docs/groups.md.")

    auto = parser.add_argument_group("autotune")
    auto.add_argument("--autotune", action="store_true", default=None)
    auto.add_argument("--no-autotune", action="store_true", default=None,
                      help="Force autotune off, overriding env/config.")
    auto.add_argument("--autotune-log-file", default=None)
    auto.add_argument("--autotune-warmup-samples", type=int, default=None)
    auto.add_argument("--autotune-steady-state-samples", type=int,
                      default=None)
    auto.add_argument("--autotune-bayes-opt-max-samples", type=int,
                      default=None)
    auto.add_argument("--autotune-gaussian-process-noise", type=float,
                      default=None)

    timeline = parser.add_argument_group("timeline")
    timeline.add_argument("--timeline-filename", default=None)
    timeline.add_argument("--timeline-mark-cycles", action="store_true",
                          default=None)

    fault = parser.add_argument_group("fault tolerance")
    fault.add_argument("--abort-timeout", type=float, default=None,
                       help="Bound (seconds) on 'abort initiated -> "
                            "every rank raises HvdAbortedError' "
                            "(HVD_TPU_ABORT_TIMEOUT; see "
                            "docs/fault_tolerance.md).")
    fault.add_argument("--heartbeat-interval", type=float, default=None,
                       help="Peer/coordinator heartbeat period in "
                            "seconds (HVD_TPU_HEARTBEAT_INTERVAL).")
    fault.add_argument("--liveness-timeout", type=float, default=None,
                       help="Missed-heartbeat window in seconds before a "
                            "silent rank is declared dead and the round "
                            "is aborted (HVD_TPU_LIVENESS_TIMEOUT; 0 "
                            "disables).")
    fault.add_argument("--connect-retry-seconds", type=float,
                       default=None,
                       help="Deadline budget in seconds for "
                            "connection-establishment retries with "
                            "backoff + jitter "
                            "(HVD_TPU_CONNECT_RETRY_SECONDS).")
    fault.add_argument("--fault-spec", default=None,
                       help="Deterministic fault injection spec "
                            "(HVD_TPU_FAULT_SPEC), e.g. "
                            "'rank1:allreduce:2:crash'; see "
                            "docs/fault_tolerance.md for the grammar. "
                            "bin/hvd-chaos generates seeded random "
                            "specs for soak runs.")
    fault.add_argument("--term-grace", type=float, default=None,
                       help="Grace window in seconds between the "
                            "SIGTERM the launcher forwards to a worker "
                            "process group and the SIGKILL escalation "
                            "(HVD_TPU_TERM_GRACE, default 5; see "
                            "docs/checkpoint.md).")
    fault.add_argument("--drain", action="store_true", default=None,
                       help="Workers convert SIGTERM (the preemption "
                            "notice) into a graceful drain: announce "
                            "departure to the coordinator, reconfigure "
                            "at the next collective boundary, exit 0 "
                            "(HVD_TPU_DRAIN, default on; see "
                            "docs/checkpoint.md).")
    fault.add_argument("--no-drain", action="store_true", default=None,
                       help="Force the drain handler off: SIGTERM "
                            "keeps its default kill disposition.")
    fault.add_argument("--reconnect-budget", type=float, default=None,
                       help="Reconnect window in seconds: a mid-stream "
                            "connection break is healed in place "
                            "(reconnect + session handshake + replay "
                            "of unacked frames) for up to this long "
                            "before the break escalates to the abort/"
                            "elastic path (HVD_TPU_RECONNECT_BUDGET, "
                            "default 0 = off; see "
                            "docs/fault_tolerance.md 'connection "
                            "blips vs dead peers').")
    fault.add_argument("--replay-buffer-bytes", type=int, default=None,
                       help="Bound on the sender-side replay buffer "
                            "of unacknowledged session frames "
                            "(HVD_TPU_REPLAY_BUFFER_BYTES, default "
                            "64 MiB); a heal needing an evicted frame "
                            "escalates instead of resuming with a "
                            "gap.")
    fault.add_argument("--rtt-alpha", type=float, default=None,
                       help="EWMA smoothing factor for the per-peer "
                            "RTT estimates behind the adaptive "
                            "liveness deadlines (HVD_TPU_RTT_ALPHA, "
                            "default 0.25; see docs/fault_tolerance.md "
                            "'degraded networks').")
    fault.add_argument("--straggler-factor", type=float, default=None,
                       help="A rank is a straggler when its reported "
                            "RTT exceeds this multiple of the median "
                            "across reporting ranks "
                            "(HVD_TPU_STRAGGLER_FACTOR, default 4). "
                            "The same factor caps the extra deadline "
                            "slack a slow rank may earn.")
    fault.add_argument("--straggler-windows", type=int, default=None,
                       help="Consecutive liveness-scan windows a rank "
                            "must exceed the straggler threshold "
                            "before the verdict is recorded "
                            "(HVD_TPU_STRAGGLER_WINDOWS, default 3).")
    fault.add_argument("--straggler-exclude", action="store_true",
                       default=None,
                       help="Under --elastic, propose a confirmed "
                            "straggler for drain-style exclusion at "
                            "the next collective boundary "
                            "(HVD_TPU_STRAGGLER_EXCLUDE, default "
                            "off: verdicts are log-only).")
    fault.add_argument("--no-straggler-exclude", action="store_true",
                       default=None,
                       help="Force straggler exclusion off (verdicts "
                            "stay log-only).")

    soak = parser.add_argument_group("soak rig")
    soak.add_argument("--soak-ranks", type=int, default=None,
                      help="World size for bin/hvd-soak "
                           "(HVD_TPU_SOAK_RANKS, default 16; see "
                           "docs/soak.md).")
    soak.add_argument("--soak-steps", type=int, default=None,
                      help="Training steps per soak leg "
                           "(HVD_TPU_SOAK_STEPS, default 20).")
    soak.add_argument("--soak-seed", type=int, default=None,
                      help="Chaos-schedule seed for the soak rig "
                           "(HVD_TPU_SOAK_SEED, default 11).")
    soak.add_argument("--soak-report", default=None,
                      help="Path prefix for the per-run SOAK_r*.json "
                           "gate artifacts (HVD_TPU_SOAK_REPORT).")
    soak.add_argument("--soak-reconfig-bound", type=float, default=None,
                      help="Regression gate: every elastic "
                           "reconfiguration observed during the soak "
                           "must complete within this many seconds "
                           "(HVD_TPU_SOAK_RECONFIG_BOUND, default "
                           "45).")

    ckpt = parser.add_argument_group("checkpointing")
    ckpt.add_argument("--ckpt-dir", default=None,
                      help="Durable checkpoint directory "
                           "(HVD_TPU_CKPT_DIR): each rank writes its "
                           "parameter/optimizer shard from the elastic "
                           "commit snapshot on a background thread; "
                           "elastic.run auto-resumes from the newest "
                           "complete manifest, re-sharding to the "
                           "current world size (docs/checkpoint.md). "
                           "Unset: checkpointing off.")
    ckpt.add_argument("--ckpt-interval", type=int, default=None,
                      help="Checkpoint every N committed steps "
                           "(HVD_TPU_CKPT_INTERVAL, default 10).")
    ckpt.add_argument("--ckpt-keep", type=int, default=None,
                      help="Retain the newest N checkpoints, pruning "
                           "older shards/manifests after each write "
                           "(HVD_TPU_CKPT_KEEP, default 2; 0 keeps "
                           "everything).")

    elastic = parser.add_argument_group("elastic membership")
    elastic.add_argument("--elastic", action="store_true", default=None,
                         help="Survive rank loss: re-form the ring "
                              "around the survivors at a new "
                              "membership epoch instead of killing "
                              "the job (HVD_TPU_ELASTIC; see "
                              "docs/elastic.md).")
    elastic.add_argument("--min-ranks", type=int, default=None,
                         help="Smallest membership the job may shrink "
                              "to; below this a rank loss is fatal "
                              "(HVD_TPU_MIN_RANKS, default 1).")
    elastic.add_argument("--max-ranks", type=int, default=None,
                         help="Cap on membership size when admitting "
                              "late joiners (HVD_TPU_MAX_RANKS; 0 = "
                              "unlimited).")
    elastic.add_argument("--reconfig-timeout", type=float, default=None,
                         help="Deadline in seconds for survivors to "
                              "re-form the world at the new epoch "
                              "(HVD_TPU_RECONFIG_TIMEOUT, default "
                              "60).")
    elastic.add_argument("--coord-failover", action="store_true",
                         default=None,
                         help="Survive rank-0 (coordinator) loss too: "
                              "survivors race a CAS election at the "
                              "rendezvous server and re-form under a "
                              "new coordinator instead of dying "
                              "(HVD_TPU_COORD_FAILOVER; requires "
                              "--elastic; see docs/elastic.md).")
    elastic.add_argument("--election-timeout", type=float, default=None,
                         help="Budget in seconds for one fail-over "
                              "election round — the CAS race plus "
                              "directive adoption "
                              "(HVD_TPU_ELECTION_TIMEOUT, default "
                              "10).")

    race = parser.add_argument_group("race detection")
    race.add_argument("--race", action="store_true", default=None,
                      help="Run every rank under the hvd-race shim "
                           "(HVD_TPU_RACE): traced threading/queue "
                           "primitives + instrumented attribute access "
                           "on the concurrency-scoped modules; see "
                           "docs/race_detection.md.")
    race.add_argument("--race-seed", type=int, default=None,
                      help="Schedule-fuzz seed (HVD_TPU_RACE_SEED): "
                           "deterministic preemptions at "
                           "instrumentation points — same seed, same "
                           "interleaving perturbation, same report.")
    race.add_argument("--race-scope", default=None,
                      help="Comma-separated module relpath suffixes to "
                           "instrument (HVD_TPU_RACE_SCOPE; 'all' = "
                           "every horovod_tpu module).")
    race.add_argument("--race-report", default=None,
                      help="Report-file prefix (HVD_TPU_RACE_REPORT): "
                           "each rank writes its race findings to "
                           "<prefix>.<pid>.json at exit.")

    proto = parser.add_argument_group("protocol checking")
    proto.add_argument("--proto-depth", type=int, default=None,
                       help="bin/hvd-proto model-checker exploration "
                            "bound in steps (HVD_TPU_PROTO_DEPTH, "
                            "default 10); see "
                            "docs/protocol_checking.md.")
    proto.add_argument("--proto-seed", type=int, default=None,
                       help="bin/hvd-proto exploration tie-break seed "
                            "(HVD_TPU_PROTO_SEED, default 0): same "
                            "seed + depth give a byte-identical "
                            "report.")

    fuzz = parser.add_argument_group("fuzzing")
    fuzz.add_argument("--fuzz-seed", type=int, default=None,
                      help="bin/hvd-fuzz mutation seed "
                           "(HVD_TPU_FUZZ_SEED, default 0): same seed "
                           "+ iters give a byte-identical run "
                           "summary; see docs/fuzzing.md.")
    fuzz.add_argument("--fuzz-iters", type=int, default=None,
                      help="bin/hvd-fuzz mutation iterations per "
                           "target (HVD_TPU_FUZZ_ITERS, default "
                           "300).")

    stall = parser.add_argument_group("stall check")
    stall.add_argument("--no-stall-check", action="store_true", default=None)
    stall.add_argument("--stall-check", action="store_true", default=None,
                       help="Force the stall check on, overriding "
                            "env/config.")
    stall.add_argument("--stall-check-warning-time-seconds", type=float,
                       default=None)
    stall.add_argument("--stall-check-shutdown-time-seconds", type=float,
                       default=None)

    logg = parser.add_argument_group("logging")
    logg.add_argument("--log-level", default=None,
                      choices=["trace", "debug", "info", "warning", "error",
                               "fatal"])
    logg.add_argument("--log-hide-timestamp", action="store_true",
                      default=None)

    parser.add_argument("-cb", "--check-build", action="store_true",
                        help="Print available frameworks, controllers "
                             "and data planes, then exit (reference: "
                             "horovodrun --check-build).")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="Training command to run on each rank.")
    return parser


def check_build(verbose=False):
    """The reference's ``horovodrun --check-build`` diagnostic
    (``runner.py:118``), in this framework's idiom: frameworks are
    import-probed, controllers/data planes are what the build ships."""
    import importlib.util
    import textwrap

    import horovod_tpu

    def have(mod):
        try:
            return importlib.util.find_spec(mod) is not None
        except (ImportError, ValueError):
            return False

    def native_core():
        try:
            from horovod_tpu.ops.native_controller import _load_lib
            return _load_lib() is not None
        except Exception:  # noqa: BLE001 — diagnostic must not crash
            return False

    x = lambda v: "X" if v else " "
    out = f"""\
    horovod_tpu v{horovod_tpu.__version__}:

    Available Frameworks:
        [{x(have('jax'))}] JAX (native)
        [{x(have('tensorflow'))}] TensorFlow / Keras
        [{x(have('torch'))}] PyTorch
        [{x(have('mxnet'))}] MXNet

    Available Controllers:
        [{x(native_core())}] native (C++ core)
        [X] python (in-process)
        [X] tcp (process coordinator)
        [X] gmesh (pod global mesh)

    Available Data Planes:
        [X] XLA (fused compiled collectives; ICI on TPU)
        [X] tcp ring (numpy p2p, process mode)
    """
    print(textwrap.dedent(out))
    if verbose:
        import os

        from horovod_tpu.ops import native_controller as nc

        print(f"package: {os.path.dirname(horovod_tpu.__file__)}")
        print(f"native core: {nc._LIB_PATH} "
              f"({'present' if os.path.exists(nc._LIB_PATH) else 'absent'})")
        try:
            import jax

            # version only — default_backend() would initialize the
            # backend and can block behind a dead TPU relay
            print(f"jax version: {jax.__version__}")
        except Exception as exc:  # noqa: BLE001
            print(f"jax: unavailable ({exc!r})")
    return 0


def build_slots(args):
    if args.hostfile:
        hosts = allocate_mod.parse_hostfile(args.hostfile)
    elif args.hosts:
        hosts = allocate_mod.parse_hosts(args.hosts)
    else:
        from horovod_tpu.run import lsf
        spec = lsf.host_spec() if lsf.using_lsf() else None
        if spec:
            # inside an LSF job the allocation is the host list
            # (reference: runner.py LSF auto-discovery via util/lsf.py)
            hosts = allocate_mod.parse_hosts(spec)
            if args.num_proc is None:
                args.num_proc = lsf.get_num_processes()
        else:
            hosts = [allocate_mod.HostInfo("localhost", args.num_proc)]
    if args.tpu:
        # one process per host; each process drives that host's chips as its
        # local ranks (device-rank mode under the hood)
        hosts = [allocate_mod.HostInfo(h.hostname, 1) for h in hosts]
        np_total = len(hosts)
    else:
        np_total = args.num_proc
    return allocate_mod.allocate(hosts, np_total)


def run_commandline(argv=None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)

    if args.version:
        import horovod_tpu

        print(horovod_tpu.__version__)
        return 0
    if args.check_build:
        return check_build(verbose=args.verbose)
    if not args.command:
        parser.error("no training command given")
    if args.num_proc is None and not args.tpu:
        from horovod_tpu.run import lsf
        if lsf.using_lsf():
            args.num_proc = lsf.get_num_processes()
        if args.num_proc is None:
            parser.error("-np is required (or use --tpu, or run inside "
                         "an LSF allocation)")

    if args.config_file:
        config_parser.apply_config_to_args(
            args, config_parser.load_config_file(args.config_file))

    extra_env = config_parser.env_from_args(args)
    if env_util.HVD_TPU_TERM_GRACE in extra_env:
        # the grace window is read by THIS process (the launcher's
        # SIGTERM forwarding, run/launch.py), not by the workers —
        # flag/YAML values must land in the launcher's own environment
        os.environ[env_util.HVD_TPU_TERM_GRACE] = \
            extra_env[env_util.HVD_TPU_TERM_GRACE]
    slots = build_slots(args)
    global_mesh = args.tpu or args.global_mesh
    if global_mesh:
        extra_env[env_util.HVD_GLOBAL_MESH] = "1"
    if len(slots) > 1 and not global_mesh \
            and env_util.HVD_CONTROLLER not in extra_env:
        extra_env[env_util.HVD_CONTROLLER] = "tcp"
    if env_util.HVD_SECRET_KEY not in extra_env:
        import base64
        from horovod_tpu.run.service import secret
        extra_env[env_util.HVD_SECRET_KEY] = base64.b64encode(
            secret.make_secret_key()).decode()

    if args.launcher != "ssh":
        return _delegate_launch(args, slots, extra_env)

    # fail fast with the full unreachable-host list before launching
    # anything (reference: runner.py:568-643 parallel cached ssh check)
    remote_hosts = sorted({s.hostname for s in slots})
    from horovod_tpu.run.ssh_check import check_all_hosts_ssh_successful
    check_all_hosts_ssh_successful(remote_hosts, ssh_port=args.ssh_port)

    rendezvous = RendezvousServer()
    port = rendezvous.start()
    addr = env_util.get_str(env_util.HVD_RENDEZVOUS_HOST_ADDR)
    if addr is None:
        from horovod_tpu.run.driver_discovery import maybe_discover
        discovered = maybe_discover(slots, ssh_port=args.ssh_port)
        if discovered is not None:
            ifaces, addr = discovered
            extra_env.setdefault(env_util.HVD_IFACE, sorted(ifaces)[0])
        else:
            addr = _routable_addr(slots)
    # Quote each token so arguments with spaces/quotes survive the shell
    # (reference: runner.py quotes the unknown args the same way).
    import shlex
    command = " ".join(shlex.quote(c) for c in args.command)
    try:
        code = launch_job(slots, command, addr, port, extra_env=extra_env,
                          ssh_port=args.ssh_port, verbose=args.verbose,
                          output_filename=args.output_filename,
                          elastic=bool(args.elastic),
                          min_ranks=args.min_ranks or 1,
                          coord_failover=bool(args.coord_failover))
    finally:
        rendezvous.stop()
    # a signal death surfaces as Popen's negative code; exit statuses
    # are unsigned, so report it in the shell's 128+signum convention
    # instead of the truncated-to-255 garbage sys.exit(-15) produces
    return 128 - code if code < 0 else code


def _delegate_launch(args, slots, extra_env):
    """mpirun / jsrun placement: start the rendezvous here, export the
    constant env contract (per-rank values come from the MPI runtime —
    ``common/topology._mpi_placed``), run ONE placement command."""
    rendezvous = RendezvousServer()
    port = rendezvous.start()
    addr = env_util.get_str(env_util.HVD_RENDEZVOUS_HOST_ADDR) \
        or _routable_addr(slots)
    env = dict(os.environ)
    env.update(extra_env)
    env[env_util.HVD_SIZE] = str(len(slots))
    env[env_util.HVD_RENDEZVOUS_ADDR] = addr
    env[env_util.HVD_RENDEZVOUS_PORT] = str(port)
    hosts_spec = ",".join(
        f"{h}:{n}" for h, n in
        _slots_by_host(slots).items())
    try:
        if args.launcher == "mpirun":
            import shlex

            from horovod_tpu.run import mpi_run
            extra = shlex.split(args.mpi_args) if args.mpi_args else None
            return mpi_run.mpi_run(len(slots), hosts_spec, args.command,
                                   env=env, extra_args=extra)
        from horovod_tpu.run import js_run
        return js_run.js_run(len(slots), args.command, env=env)
    finally:
        rendezvous.stop()


def _slots_by_host(slots):
    out = {}
    for s in slots:
        out[s.hostname] = out.get(s.hostname, 0) + 1
    return out


def _routable_addr(slots):
    """Pick the address remote workers use to reach the rendezvous server
    (reference: driver NIC discovery, simplified: hostname resolution; for
    all-local jobs, loopback)."""
    import socket

    if all(s.hostname in ("localhost", "127.0.0.1") for s in slots):
        return "127.0.0.1"
    return socket.gethostbyname(socket.gethostname())


def main():
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
