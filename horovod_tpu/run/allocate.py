"""Host/slot allocation: map ranks onto hosts.

Reference: ``horovod/run/gloo_run.py:54`` ``_allocate`` — parse
``host:slots`` specs and assign rank / local_rank / cross_rank per process,
and ``runner.py`` hostfile parsing.  On TPU pods the same table maps ranks
onto (host, chip) pod-slice coordinates.
"""

import dataclasses


@dataclasses.dataclass(frozen=True)
class HostInfo:
    hostname: str
    slots: int


@dataclasses.dataclass(frozen=True)
class SlotInfo:
    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def parse_hosts(hosts: str):
    """Parse ``"h1:4,h2:4"`` (slots default 1)."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostInfo(name, int(slots)))
        else:
            out.append(HostInfo(part, 1))
    if not out:
        raise ValueError(f"no hosts found in spec '{hosts}'")
    return out


def parse_hostfile(path: str):
    """Hostfile format: one ``hostname slots=N`` (or ``hostname:N``) per
    line; '#' comments."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, slots = line.partition("slots=")
                hosts.append(HostInfo(name.strip(), int(slots)))
            elif ":" in line:
                name, _, slots = line.rpartition(":")
                hosts.append(HostInfo(name, int(slots)))
            else:
                hosts.append(HostInfo(line, 1))
    if not hosts:
        raise ValueError(f"hostfile '{path}' contains no hosts")
    return hosts


def allocate(hosts, np_total: int):
    """Assign ``np_total`` ranks round-filling hosts in order; returns one
    SlotInfo per rank (reference: _allocate fills each host's slots before
    moving on)."""
    # coalesce duplicate hostnames (their slots add up) and drop
    # zero-slot entries: bookkeeping below keys by hostname, so
    # duplicates would double-bind local_ranks to one device, and a
    # drained 0-slot host would become a phantom cross-peer that no
    # process owns (hanging cross collectives)
    merged = {}
    order = []
    for h in hosts:
        if h.slots <= 0:
            continue
        if h.hostname in merged:
            merged[h.hostname] = HostInfo(
                h.hostname, merged[h.hostname].slots + h.slots)
        else:
            merged[h.hostname] = h
            order.append(h.hostname)
    hosts = [merged[name] for name in order]
    if not hosts:
        raise ValueError("no hosts with available slots")
    capacity = sum(h.slots for h in hosts)
    if np_total > capacity:
        raise ValueError(
            f"requested {np_total} processes but hosts only provide "
            f"{capacity} slots")
    # which hosts actually get ranks (for cross_size)
    assignments = []  # (host, local_rank)
    remaining = np_total
    used_hosts = []
    for host in hosts:
        if remaining <= 0:
            break
        n = min(host.slots, remaining)
        used_hosts.append((host, n))
        for local_rank in range(n):
            assignments.append((host, local_rank))
        remaining -= n
    cross_size = len(used_hosts)
    host_index = {h.hostname: i for i, (h, _) in enumerate(used_hosts)}
    host_local_size = {h.hostname: n for h, n in used_hosts}

    slots = []
    for rank, (host, local_rank) in enumerate(assignments):
        slots.append(SlotInfo(
            hostname=host.hostname,
            rank=rank,
            size=np_total,
            local_rank=local_rank,
            local_size=host_local_size[host.hostname],
            cross_rank=host_index[host.hostname],
            cross_size=cross_size,
        ))
    return slots
