"""Rendezvous key-value HTTP server.

Reference: ``horovod/run/http/http_server.py`` (``KVStoreHandler`` :36,
``RendezvousServer`` :179) — a threaded HTTP server storing values under
``/scope/key``, used by workers for address exchange (the Gloo HTTPStore
role) and by the programmatic ``run()`` API for result collection.
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from horovod_tpu.utils.logging import get_logger


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _split(self):
        parts = self.path.strip("/").split("/", 1)
        if len(parts) != 2:
            return None, None
        return parts[0], parts[1]

    def do_PUT(self):
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if scope is None:
            self.send_response(400)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        with self.server.kv_lock:
            self.server.kv.setdefault(scope, {})[key] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_POST(self):
        # atomic compare-and-set, put-if-absent flavor (coordinator
        # fail-over election, docs/elastic.md#coordinator-fail-over):
        # the FIRST value posted under /scope/key sticks; every POST —
        # winner and loser alike — answers with the winning value, and
        # X-Hvd-Created says whether THIS request created it.  A
        # replayed winner's POST therefore reads back its own value
        # (created: false) — retry-idempotent by construction.
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if scope is None:
            self.send_response(400)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        with self.server.kv_lock:
            bucket = self.server.kv.setdefault(scope, {})
            created = key not in bucket
            if created:
                bucket[key] = value
            winner = bucket[key]
        self.send_response(200)
        self.send_header("Content-Length", str(len(winner)))
        self.send_header("X-Hvd-Created", "true" if created else "false")
        self.end_headers()
        self.wfile.write(winner)

    def do_GET(self):
        scope, key = self._split()
        if scope == "__list__":
            # key enumeration for a scope (reference analog: the elastic
            # driver's discovered-hosts poll): newline-joined key names,
            # 200 + empty body when the scope holds nothing — callers
            # distinguish "no keys yet" from a dead server
            with self.server.kv_lock:
                names = sorted(self.server.kv.get(key, {}))
            body = "\n".join(names).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        with self.server.kv_lock:
            value = self.server.kv.get(scope, {}).get(key)
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):
        scope, key = self._split()
        with self.server.kv_lock:
            if scope == "__scope__":
                # whole-scope purge (mirrors the __list__ enumeration
                # spelling): elastic reconfiguration drops the dead
                # epochs' suffixed scopes in one request per scope
                self.server.kv.pop(key, None)
            else:
                self.server.kv.get(scope, {}).pop(key, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, fmt, *args):  # quiet by default
        get_logger().debug("rendezvous: " + fmt, *args)


class RendezvousServer:
    """Threaded KV server; bind to an ephemeral port and share the address
    with workers through the env contract."""

    def __init__(self, host="0.0.0.0"):
        self._server = ThreadingHTTPServer((host, 0), _KVHandler)
        self._server.kv = {}
        self._server.kv_lock = threading.Lock()
        self._thread = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="hvd-rendezvous")
        self._thread.start()
        return self.port

    def get(self, scope, key):
        with self._server.kv_lock:
            return self._server.kv.get(scope, {}).get(key)

    def scope_size(self, scope) -> int:
        with self._server.kv_lock:
            return len(self._server.kv.get(scope, {}))

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
