"""mpirun delegation for ``hvdrun`` (reference: ``horovod/run/mpi_run.py``
— builds a single mpirun invocation carrying the rank env contract so
sites whose job launcher is MPI can use it for process placement).

The data plane stays this framework's own (XLA collectives / TCP
controller); mpirun only *places processes* and propagates environment.
Workers read ``OMPI_COMM_WORLD_RANK`` / ``PMI_RANK`` when the hvdrun
env contract is absent (``common/topology.py``).
"""

import os
import shutil
import subprocess

from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

OPENMPI = "OpenMPI"
SPECTRUM = "SpectrumMPI"
MPICH = "MPICH"
UNKNOWN = "Unknown"
MISSING = "Missing"

# beyond this many hosts, OpenMPI's tree spawn needs tuning off
# (reference behavior for >64-host clusters)
LARGE_CLUSTER_THRESHOLD = 64

# env prefixes the workers need replicated on every host
_PASS_PREFIXES = ("HVD_", "JAX_", "XLA_", "TPU_", "PYTHON", "PATH",
                  "LD_LIBRARY_PATH", "HOROVOD_")


def detect_impl(runner=subprocess.run):
    """Identify the MPI implementation from ``mpirun --version``."""
    if shutil.which("mpirun") is None:
        return MISSING
    try:
        proc = runner(["mpirun", "--version"], capture_output=True,
                      text=True, timeout=20)
    except Exception:  # noqa: BLE001 — any probe failure means unusable
        return MISSING
    text = (proc.stdout or "") + (proc.stderr or "")
    if "Open MPI" in text or "OpenRTE" in text:
        return OPENMPI
    if "IBM Spectrum MPI" in text:
        return SPECTRUM
    if "MPICH" in text or "HYDRA" in text:
        return MPICH
    return UNKNOWN


def mpi_available(runner=subprocess.run):
    return detect_impl(runner) not in (UNKNOWN, MISSING)


def _env_args(env):
    args = []
    for key in sorted(env):
        if key.startswith(_PASS_PREFIXES):
            args += ["-x", key]
    return args


def build_mpirun_command(num_proc, hosts, command, env=None, impl=None,
                         extra_args=None):
    """argv for one mpirun invocation placing ``num_proc`` processes.

    ``hosts``: "host1:slots,host2:slots" (same syntax as ``hvdrun -H``).
    The command is returned, not executed, so unit tests assert on it
    (reference test style: ``test_run.py`` string-level launcher tests).
    """
    env = env if env is not None else os.environ
    impl = impl or detect_impl()
    if impl in (UNKNOWN, MISSING):
        raise RuntimeError(
            "no usable MPI found (mpirun missing or unrecognized); "
            "use plain `hvdrun` (ssh fan-out) instead")

    if impl == MPICH:
        # Hydra syntax: no --allow-run-as-root / -x / host:slots
        argv = ["mpirun", "-np", str(num_proc)]
        if hosts:
            argv += ["-hosts",
                     ",".join(h.split(":")[0] for h in hosts.split(","))]
        passed = [k for k in sorted(env) if k.startswith(_PASS_PREFIXES)]
        if passed:
            argv += ["-envlist", ",".join(passed)]
        argv += list(extra_args or [])
        argv += list(command)
        return argv

    argv = ["mpirun", "--allow-run-as-root", "-np", str(num_proc)]
    if hosts:
        argv += ["-H", hosts]
    if impl == OPENMPI:
        argv += ["--bind-to", "none", "--map-by", "slot"]
        n_hosts = len(hosts.split(",")) if hosts else 1
        if n_hosts >= LARGE_CLUSTER_THRESHOLD:
            argv += ["--mca", "plm_rsh_no_tree_spawn", "true",
                     "--mca", "plm_rsh_num_concurrent", str(n_hosts)]
    elif impl == SPECTRUM:
        argv += ["-tcp"]
    argv += _env_args(env)
    argv += list(extra_args or [])
    argv += list(command)
    return argv


def mpi_run(num_proc, hosts, command, env=None, extra_args=None):
    """Exec the mpirun command (blocking); returns the exit code."""
    impl = detect_impl()
    run_env = dict(env or os.environ)
    # Export the exact rank-block layout so workers derive
    # cross_rank/cross_size correctly under unequal slots per host
    # (topology._from_host_slots) — but ONLY where the command line
    # enforces that layout: OpenMPI/Spectrum honor `-H host:slots
    # --map-by slot` (block fill).  Hydra (MPICH) gets bare hostnames
    # and places by core count, so asserting a layout there would
    # override the runtime's CORRECT per-rank variables with a lie.
    # Must be in the env BEFORE argv is built: the `-x`/`-envlist`
    # forwarding flags are emitted from the keys present at build time,
    # and remote-host ranks only receive forwarded variables.
    if hosts and impl in (OPENMPI, SPECTRUM):
        run_env[env_util.HVD_HOST_SLOTS] = hosts
    argv = build_mpirun_command(num_proc, hosts, command, env=run_env,
                                impl=impl, extra_args=extra_args)
    get_logger().info("mpirun delegation: %s", " ".join(argv))
    return subprocess.call(argv, env=run_env)
