"""Worker-side entry for the programmatic ``run(fn)`` API (reference:
``horovod/run/run_task.py`` / task exec fns): fetch the pickled function
from the rendezvous KV, execute it, post the result."""

import os
import pickle
import sys
import traceback

from horovod_tpu.run import http_client
from horovod_tpu.run.api import FN_SCOPE, RESULT_SCOPE
from horovod_tpu.utils import env as env_util


def main():
    addr = os.environ[env_util.HVD_RENDEZVOUS_ADDR]
    port = int(os.environ[env_util.HVD_RENDEZVOUS_PORT])
    rank = int(os.environ[env_util.HVD_RANK])

    try:
        fn, args, kwargs = pickle.loads(
            http_client.get(addr, port, FN_SCOPE, "fn", timeout=60))
        result = ("ok", fn(*args, **kwargs))
    except BaseException:  # noqa: BLE001 — reported to the driver
        result = ("error", traceback.format_exc())
    http_client.put(addr, port, RESULT_SCOPE, str(rank),
                    pickle.dumps(result))
    if result[0] == "error":
        sys.stderr.write(result[1])
        sys.exit(1)


if __name__ == "__main__":
    main()
