"""Worker-side entry for the programmatic ``run(fn)`` API (reference:
``horovod/run/run_task.py`` / task exec fns): fetch the pickled function
from the rendezvous KV, execute it, post the result."""

import base64
import os
import pickle
import sys
import threading
import time
import traceback

from horovod_tpu.run import http_client
from horovod_tpu.run.api import FN_SCOPE, RESULT_SCOPE
from horovod_tpu.run.service import secret as secret_mod
from horovod_tpu.utils import env as env_util

# a worker whose driver has been unreachable this long is orphaned
# (driver crashed / Ctrl-C killed it without the remote kill reaching
# us) and must exit rather than hold chips and ports forever
_DRIVER_LOST_AFTER_S = 60.0


def _driver_watchdog(addr, port):
    lost_since = None
    while True:
        time.sleep(10.0)
        try:
            # retry_for=0: the watchdog IS the retry loop — the verb's
            # built-in transport retry would stretch the driver-lost
            # window far past _DRIVER_LOST_AFTER_S
            http_client.get(addr, port, "ping", "ping", timeout=None,
                            retry_for=0)
            lost_since = None
        except KeyError:
            lost_since = None  # server answered (404): driver alive
        except Exception:  # noqa: BLE001 — unreachable
            now = time.monotonic()
            if lost_since is None:
                lost_since = now
            elif now - lost_since > _DRIVER_LOST_AFTER_S:
                sys.stderr.write(
                    "driver rendezvous unreachable for "
                    f"{int(now - lost_since)}s; exiting orphaned "
                    "worker\n")
                os._exit(1)


def main():
    addr = env_util.get_required(env_util.HVD_RENDEZVOUS_ADDR)
    port = int(env_util.get_required(env_util.HVD_RENDEZVOUS_PORT))
    rank = int(env_util.get_required(env_util.HVD_RANK))
    key = base64.b64decode(
        env_util.get_required(env_util.HVD_SECRET_KEY))

    # lifecycle: deliberately abandoned — the watchdog polls the driver
    # for the life of the worker process and os._exit()s it if the
    # driver disappears; process exit is its only end
    threading.Thread(target=_driver_watchdog, args=(addr, port),
                     daemon=True, name="hvd-driver-watchdog").start()

    try:
        blob = http_client.get(addr, port, FN_SCOPE, "fn", timeout=60)
        digest, payload = (blob[:secret_mod.DIGEST_LEN],
                           blob[secret_mod.DIGEST_LEN:])
        if not secret_mod.check(key, payload, digest):
            raise PermissionError(
                "run-function payload failed HMAC verification")
        fn, args, kwargs = pickle.loads(payload)
        result = ("ok", fn(*args, **kwargs))
    except BaseException:  # noqa: BLE001 — reported to the driver
        result = ("error", traceback.format_exc())
    payload = pickle.dumps(result)
    http_client.put(addr, port, RESULT_SCOPE, str(rank),
                    secret_mod.sign(key, payload) + payload)
    if result[0] == "error":
        sys.stderr.write(result[1])
        sys.exit(1)


if __name__ == "__main__":
    main()
