"""Rendezvous KV client (reference: ``horovod/run/http/http_client.py``)."""

import time
import urllib.error
import urllib.request


def put(addr, port, scope, key, value: bytes):
    req = urllib.request.Request(
        f"http://{addr}:{port}/{scope}/{key}", data=value, method="PUT")
    with urllib.request.urlopen(req, timeout=30):
        pass


def get(addr, port, scope, key, timeout=None):
    """GET; if ``timeout`` is set, poll until the key appears."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        try:
            with urllib.request.urlopen(
                    f"http://{addr}:{port}/{scope}/{key}",
                    timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise
            if deadline is None or time.monotonic() > deadline:
                raise KeyError(f"{scope}/{key} not found in rendezvous")
            time.sleep(0.05)
