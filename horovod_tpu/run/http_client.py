"""Rendezvous KV client (reference: ``horovod/run/http/http_client.py``).

Every verb rides the same bounded transient-failure retry with
exponential backoff + jitter: a single TCP blip (driver briefly
saturated, RST mid-handshake) must not lose a worker's result after
hours of training, and the jitter keeps N ranks that hit the same blip
from re-knocking in lockstep (a thundering herd the fixed-interval
retry used to produce).
"""

import time
import urllib.error
import urllib.request

DEFAULT_RETRY_FOR = 30.0


def _backoff_delay(attempt):
    # one jitter policy for the whole transport layer
    from horovod_tpu.run.service.network import backoff_delay

    return backoff_delay(attempt, cap=1.0)


def request(method, addr, port, scope, key, data=None,
            retry_for=DEFAULT_RETRY_FOR) -> bytes:
    """One KV request with bounded transient-failure retry (any verb).

    HTTP errors are NOT retried — the server spoke, so the failure is
    semantic (404 missing key, 400 bad path) and the caller owns it.
    """
    url = f"http://{addr}:{port}/{scope}/{key}"
    deadline = time.monotonic() + retry_for
    attempt = 0
    while True:
        req = urllib.request.Request(url, data=data, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError:
            raise
        except (urllib.error.URLError, OSError):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise
            time.sleep(min(_backoff_delay(attempt), max(remaining, 0.0)))
            attempt += 1


def _clip(retry_for, deadline):
    """Clip a static retry budget to the caller's dynamic deadline (an
    absolute ``time.monotonic()`` timestamp).  The adaptive-deadline
    layer (docs/fault_tolerance.md "degraded networks") made caller
    budgets dynamic: a reconfiguration window bounded by the reconfig
    budget must not overshoot it by up to DEFAULT_RETRY_FOR just
    because one rendezvous verb hit a transport blip."""
    if deadline is None:
        return retry_for
    return max(0.0, min(retry_for, deadline - time.monotonic()))


def put(addr, port, scope, key, value: bytes, retry_for=DEFAULT_RETRY_FOR,
        deadline=None):
    request("PUT", addr, port, scope, key, data=value,
            retry_for=_clip(retry_for, deadline))


def delete(addr, port, scope, key, retry_for=DEFAULT_RETRY_FOR,
           deadline=None):
    request("DELETE", addr, port, scope, key,
            retry_for=_clip(retry_for, deadline))


def delete_scope(addr, port, scope, retry_for=DEFAULT_RETRY_FOR,
                 deadline=None):
    """Drop ``scope`` and every key in it — the server's
    ``/__scope__/<scope>`` purge endpoint (dead-epoch rendezvous
    cleanup, docs/elastic.md)."""
    request("DELETE", addr, port, "__scope__", scope,
            retry_for=_clip(retry_for, deadline))


def list_keys(addr, port, scope, retry_for=DEFAULT_RETRY_FOR,
              deadline=None):
    """Key names currently present in ``scope`` (may be empty) — the
    server's ``/__list__/<scope>`` enumeration endpoint."""
    body = request("GET", addr, port, "__list__", scope,
                   retry_for=_clip(retry_for, deadline))
    return [name for name in body.decode().split("\n") if name]


def cas_put(addr, port, scope, key, value: bytes,
            retry_for=DEFAULT_RETRY_FOR, deadline=None) -> bytes:
    """Atomic put-if-absent returning the WINNING value — the server's
    POST endpoint (coordinator fail-over election, docs/elastic.md).

    The first value posted under ``scope/key`` sticks; every caller
    gets the winner back, so ``cas_put(...) == value`` means this
    caller won the race.  Safe to retry across transport blips: a
    replayed POST of the winner's own value reads it straight back.
    """
    return request("POST", addr, port, scope, key, data=value,
                   retry_for=_clip(retry_for, deadline))


def get(addr, port, scope, key, timeout=None, retry_for=DEFAULT_RETRY_FOR):
    """GET; if ``timeout`` is set, poll until the key appears.

    Two independent budgets: ``retry_for`` bounds transport-blip
    retries inside each attempt, ``timeout`` bounds the 404 wait for a
    key another rank has not published yet.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        # clip the transport-retry budget to the caller's deadline: a
        # poll bounded by HVD_START_TIMEOUT must not overshoot it just
        # because the server is unreachable rather than missing the key
        budget = retry_for if deadline is None else max(
            0.0, min(retry_for, deadline - time.monotonic()))
        try:
            return request("GET", addr, port, scope, key,
                           retry_for=budget)
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise
            if deadline is None or time.monotonic() > deadline:
                raise KeyError(f"{scope}/{key} not found in rendezvous")
            time.sleep(0.05)
