"""Rendezvous KV client (reference: ``horovod/run/http/http_client.py``)."""

import time
import urllib.error
import urllib.request


def put(addr, port, scope, key, value: bytes, retry_for=30.0):
    """PUT with a bounded transient-failure retry: a single TCP blip
    must not lose a worker's result after hours of training."""
    deadline = time.monotonic() + retry_for
    while True:
        req = urllib.request.Request(
            f"http://{addr}:{port}/{scope}/{key}", data=value,
            method="PUT")
        try:
            with urllib.request.urlopen(req, timeout=30):
                return
        except (urllib.error.URLError, OSError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.25)


def get(addr, port, scope, key, timeout=None):
    """GET; if ``timeout`` is set, poll until the key appears."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        try:
            with urllib.request.urlopen(
                    f"http://{addr}:{port}/{scope}/{key}",
                    timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise
            if deadline is None or time.monotonic() > deadline:
                raise KeyError(f"{scope}/{key} not found in rendezvous")
            time.sleep(0.05)
        except (urllib.error.URLError, OSError):
            # transient transport blip (driver briefly saturated, TCP
            # RST): retry within the budget instead of crashing the
            # worker — a spurious crash tears down the whole job
            if deadline is None or time.monotonic() > deadline:
                raise
            time.sleep(0.25)
