"""Launcher-side NIC discovery (reference:
``horovod/run/driver/driver_service.py:225 get_common_interfaces`` used by
``runner.py:568-643``): start a task server on every remote host, let each
probe its successor, and intersect the interface names that are routable
between every pair.  The winning interface provides the rendezvous bind
address and is exported as ``HVD_IFACE`` to the workers."""

import base64
import os
import shlex
import subprocess
import sys

from horovod_tpu.run.service import secret
from horovod_tpu.run.service.driver_service import (DriverService,
                                                    find_common_interfaces)
from horovod_tpu.run.service.network import local_interfaces
from horovod_tpu.run.service.task_service import TaskClient
from horovod_tpu.utils.logging import get_logger

LOCAL_HOSTS = ("localhost", "127.0.0.1")


def _task_server_command(index, driver_addrs, ssh_port=None, host=None):
    """The secret stays OFF the command line (ps-visible on every host) —
    task_main reads it from stdin, which ssh forwards."""
    env = {
        "HVD_TASK_INDEX": str(index),
        "HVD_DRIVER_ADDRS": ";".join(f"{ip}:{port}"
                                     for ip, port in driver_addrs),
    }
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    inner = (f"cd {shlex.quote(os.getcwd())} && {exports} "
             f"{shlex.quote(sys.executable)} -m "
             "horovod_tpu.run.service.task_main")
    if host is None or host in LOCAL_HOSTS:
        return inner
    port = f"-p {ssh_port} " if ssh_port else ""
    return (f"ssh -o StrictHostKeyChecking=no {port}{host} "
            f"{shlex.quote(inner)}")


def discover_common_interfaces(hostnames, ssh_port=None, timeout=60):
    """Run the discovery round over the given hosts.

    Returns ``(iface_names, rendezvous_ip)``; raises on failure (callers
    fall back to hostname resolution).
    """
    key = secret.make_secret_key()
    driver = DriverService(len(hostnames), key)
    procs = []
    try:
        driver_addrs = [(ip, driver.port)
                        for ip in local_interfaces().values()]
        key_line = base64.b64encode(key) + b"\n"
        for i, host in enumerate(hostnames):
            cmd = _task_server_command(i, driver_addrs,
                                       ssh_port=ssh_port, host=host)
            proc = subprocess.Popen(cmd, shell=True,
                                    stdin=subprocess.PIPE)
            try:
                proc.stdin.write(key_line)
                proc.stdin.close()
            except BrokenPipeError:
                pass
            procs.append(proc)

        common = find_common_interfaces(driver, key, len(hostnames),
                                        timeout=timeout)
        iface = sorted(common)[0]
        ip = local_interfaces().get(iface)
        if ip is None:  # driver host names its NICs differently
            ip = next(iter(local_interfaces().values()))

        # release the task servers
        for i in range(len(hostnames)):
            try:
                TaskClient(driver.task_addresses(i), key,
                           timeout=5).shutdown_task()
            except (OSError, ConnectionError):
                pass
        return common, ip
    finally:
        driver.shutdown()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def maybe_discover(slots, ssh_port=None):
    """Best-effort discovery for multi-host jobs; ``None`` for all-local
    jobs or when discovery fails (caller falls back)."""
    hostnames = []
    for s in slots:
        if s.hostname not in hostnames:
            hostnames.append(s.hostname)
    if all(h in LOCAL_HOSTS for h in hostnames):
        return None
    try:
        return discover_common_interfaces(hostnames, ssh_port=ssh_port)
    except Exception as exc:  # noqa: BLE001 — discovery is best-effort
        get_logger().warning(
            "NIC discovery failed (%s); falling back to hostname "
            "resolution for the rendezvous address", exc)
        return None
