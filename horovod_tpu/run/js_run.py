"""jsrun delegation (reference: ``horovod/run/js_run.py`` — on LSF
systems with IBM Job Step Manager, one jsrun invocation places the
workers; an explicit rank file pins ranks to hosts)."""

import os
import shutil
import subprocess
import tempfile

from horovod_tpu.run import lsf
from horovod_tpu.utils.logging import get_logger


def js_available() -> bool:
    return lsf.using_lsf() and shutil.which("jsrun") is not None


def generate_rankfile(slots_per_host, path=None):
    """Explicit resource file: one rank per line, cyclic by host
    (jsrun ERF syntax: ``rank: N: { host: H }``)."""
    if path is None:
        fd, path = tempfile.mkstemp(prefix="hvd_jsrun_", suffix=".erf")
        os.close(fd)
    lines = ["overlapping_rs: allow", "cpu_index_using: logical", ""]
    rank = 0
    for host, slots in slots_per_host.items():
        for _ in range(slots):
            lines.append(f"rank: {rank}: {{ hostname: {host}; cpu: * }}")
            rank += 1
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def build_jsrun_command(num_proc, command, rankfile=None,
                        extra_args=None):
    argv = ["jsrun"]
    if rankfile:
        argv += ["--erf_input", rankfile]
    else:
        argv += ["--nrs", str(num_proc), "--tasks_per_rs", "1",
                 "--cpu_per_rs", "ALL_CPUS"]
    argv += ["--stdio_stderr", "prepended", "--stdio_stdout", "prepended"]
    argv += list(extra_args or [])
    argv += list(command)
    return argv


def _trim_allocation(slots_per_host, num_proc):
    """First ``num_proc`` slots of the allocation, host-major — the
    rankfile must describe exactly the requested world size or the
    MPI-derived size on the workers disagrees with the driver's
    contract."""
    out = {}
    remaining = num_proc
    for host, slots in slots_per_host.items():
        if remaining <= 0:
            break
        take = min(slots, remaining)
        out[host] = take
        remaining -= take
    if remaining > 0:
        raise RuntimeError(
            f"LSF allocation has only {num_proc - remaining} slots; "
            f"{num_proc} requested")
    return out


def js_run(num_proc, command, env=None, extra_args=None):
    """Place workers with jsrun using a rank file derived from the LSF
    allocation (trimmed to ``num_proc`` ranks); returns the exit code."""
    if not js_available():
        raise RuntimeError(
            "jsrun delegation requires an LSF job (LSB_JOBID) with "
            "jsrun on PATH")
    trimmed = _trim_allocation(lsf.get_slots_per_host(), num_proc)
    rankfile = generate_rankfile(trimmed)
    argv = build_jsrun_command(num_proc, command, rankfile=rankfile,
                               extra_args=extra_args)
    get_logger().info("jsrun delegation: %s", " ".join(argv))
    run_env = dict(env or os.environ)
    # the rankfile is the authoritative rank-block layout (the trimmed
    # last host may carry fewer ranks); export it so every worker
    # derives the same cross_rank/cross_size (topology._from_host_slots)
    from horovod_tpu.utils import env as env_util
    run_env[env_util.HVD_HOST_SLOTS] = ",".join(
        f"{h}:{n}" for h, n in trimmed.items())
    try:
        return subprocess.call(argv, env=run_env)
    finally:
        try:
            os.unlink(rankfile)
        except OSError:
            pass
