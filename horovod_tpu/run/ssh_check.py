"""Parallel ssh reachability pre-check (reference:
``horovod/run/runner.py:568-643`` — probe every remote host with a
trivial ssh command on threads, memoized on disk, and fail fast with the
full list of unreachable hosts before any worker is launched)."""

import subprocess
import threading

from horovod_tpu.run.cache import Cache
from horovod_tpu.run.launch import LOCAL_HOSTS  # shared local-host list
from horovod_tpu.utils.logging import get_logger

SSH_TIMEOUT_S = 15


def _probe(hostname, ssh_port=None, runner=subprocess.run):
    port = ["-p", str(ssh_port)] if ssh_port else []
    cmd = ["ssh", "-o", "BatchMode=yes",
           "-o", "StrictHostKeyChecking=no",
           "-o", f"ConnectTimeout={SSH_TIMEOUT_S}",
           *port, hostname, "true"]
    try:
        return runner(cmd, capture_output=True,
                      timeout=SSH_TIMEOUT_S + 5).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def check_all_hosts_ssh_successful(hostnames, ssh_port=None, cache=None,
                                   runner=subprocess.run):
    """Probe every remote host in parallel; raise with the complete
    unreachable list (not just the first failure).  Results are memoized
    (60 min) so back-to-back launches skip the probes."""
    if cache is None:
        cache = Cache(parameters_hash=f"ssh_port={ssh_port}")
    remote = [h for h in dict.fromkeys(hostnames)
              if h not in LOCAL_HOSTS]
    if not remote:
        return True

    results = {}
    lock = threading.Lock()

    def probe(host):
        key = f"ssh:{host}"
        ok = cache.get(key)
        if ok is None:
            ok = _probe(host, ssh_port=ssh_port, runner=runner)
            if ok:  # only cache successes; failures should re-probe
                cache.put(key, True)
        with lock:
            results[host] = bool(ok)

    threads = [threading.Thread(target=probe, args=(h,), daemon=True)
               for h in remote]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=SSH_TIMEOUT_S + 10)

    unreachable = sorted(h for h in remote if not results.get(h))
    if unreachable:
        raise RuntimeError(
            "SSH was unable to reach the following hosts: "
            f"{unreachable}. Verify passwordless ssh (BatchMode) works "
            "to every host in the job.")
    get_logger().debug("ssh reachability verified for %d host(s)",
                       len(remote))
    return True
