"""Seeded chaos-spec generation (the engine behind ``bin/hvd-chaos``).

Generates a random-but-reproducible HVD_TPU_FAULT_SPEC (grammar:
docs/fault_tolerance.md) from a fixed seed.  The replay contract is the
whole point: same seed -> same spec -> same failure step, so a failing
soak run is replayed exactly.  That contract extends ACROSS versions —
every new draw (the elastic ``preempt`` cell, the degraded-network
cells, the coordinator-kill cell, the group-collective cell) is taken
from the RNG stream strictly AFTER all pre-existing draws, so a seed
that produced a given spec in an older tree produces a byte-identical
spec today unless the new feature is explicitly requested.
"""

import random

# the knobs a chaos spec draws from; "connect" exercises the transport
# retry path, the op/ring points exercise coordinated abort + liveness
_POINTS = ("allreduce", "broadcast", "allgather", "ring", "send",
           "connect")
_ACTIONS = ("crash", "drop", "refuse", "preempt")

# degraded-network cells (docs/fault_tolerance.md "degraded networks"):
# all injected at the link point, duration-scoped.  Parameter menus are
# coarse on purpose — the rig wants qualitatively distinct regimes
# (mild / nasty), not a smooth sweep that no single soak could cover.
# ``partition`` is deliberately NOT in the random pool: a random rank
# range can isolate the coordinator, turning a soak whose success
# criterion is "no false-positive abort" into a guaranteed real abort.
# Partitions are injected explicitly (tests, bin/hvd-soak's scripted
# legs) where the expected outcome is pinned.
_DEGRADE_ACTIONS = ("delay", "jitter", "throttle", "flaky")
_DELAY_MS = (5, 20, 50)
_THROTTLE_MBPS = (4, 16, 64)
_FLAKY_P = (0.05, 0.2)

# mid-stream connection-break cells (--blips; docs/fault_tolerance.md
# "connection blips vs dead peers"): a probabilistic RST storm or a
# one-shot link flap, both absorbed by the session layer when
# HVD_TPU_RECONNECT_BUDGET grants a window.  Rank 0 stays out of the
# pool — cutting the coordinator's links turns a heal soak into a
# liveness test.
_MIDSTREAM_ACTIONS = ("reset", "blip")
_RESET_P = (0.1, 0.3)
_BLIP_MS = (200, 1000, 3000)


def generate_spec(seed, num_ranks, num_faults, elastic=False,
                  degrade=0, coord_failover=False, groups=False,
                  blips=0):
    rng = random.Random(seed)
    specs = []
    for _ in range(num_faults):
        point = rng.choice(_POINTS)
        # refuse only makes sense at the transport; crash/drop at the
        # collective layer.  preempt (SIGTERM-to-self -> graceful drain,
        # docs/checkpoint.md) only joins the pool for elastic soaks:
        # without elastic the drain is refused and the cell degenerates
        # into a crash with extra steps.  NOTE: adding the elastic-only
        # draw AFTER the common ones keeps non-elastic specs identical
        # for a given seed across versions (the replay contract).
        if point == "connect":
            action = "refuse"
        else:
            action = rng.choice(("crash", "drop"))
            if elastic and rng.random() < 0.5:
                action = "preempt"
        rank = rng.randrange(num_ranks)
        step = rng.randint(1, 5)
        specs.append(f"rank{rank}:{point}:{step}:{action}")
    # degraded-network cells draw AFTER every binary-fault draw (same
    # cross-version contract as the elastic cell above): a seed's
    # binary cells are byte-identical whether or not --degrade is used
    for _ in range(degrade):
        action = rng.choice(_DEGRADE_ACTIONS)
        rank = rng.randrange(num_ranks)
        step = rng.randint(1, 5)
        if action in ("delay", "jitter"):
            param = str(rng.choice(_DELAY_MS))
        elif action == "throttle":
            param = str(rng.choice(_THROTTLE_MBPS))
        else:
            param = str(rng.choice(_FLAKY_P))
        duration = rng.randint(2, 8)
        specs.append(f"rank{rank}:link:{step}:{action}:{param}:"
                     f"{duration}")
    # coordinator-kill cell (--coord-failover): rank 0 joins the
    # crash/preempt pool via ONE dedicated cell whose draws come
    # strictly AFTER every pre-existing draw — the same cross-version
    # replay contract as the elastic and degrade cells, so a seed's
    # spec without the flag is byte-identical to every older tree.
    # The survivors are expected to elect a new coordinator
    # (docs/elastic.md#coordinator-fail-over), so this cell only makes
    # sense with fail-over armed in the job under test.
    if coord_failover:
        point = rng.choice(("allreduce", "broadcast", "allgather",
                            "ring"))
        action = rng.choice(("crash", "preempt"))
        step = rng.randint(2, 5)   # after warmup: epoch-0 world forms
        specs.append(f"rank0:{point}:{step}:{action}")
    # group-collective cell (--groups): one fault landing inside a
    # sub-group collective of a job that runs process groups
    # (docs/groups.md) — sub-group collectives flow through the same
    # instrumented points (the submit path and the group's own ring
    # plane), so the grammar is unchanged; what the cell tests is the
    # group-scoped abort/purge path.  Its draws come strictly AFTER
    # every pre-existing draw (binary, degrade, coord-failover), the
    # same cross-version replay contract: a seed's spec without
    # --groups is byte-identical to every older tree.  Rank 0 stays
    # out of the pool for the same reason as the degrade cells —
    # killing the coordinator turns the cell into a different test.
    if groups:
        point = rng.choice(("allreduce", "ring"))
        action = rng.choice(("crash", "drop"))
        rank = rng.randrange(1, num_ranks) if num_ranks > 1 else 0
        step = rng.randint(2, 5)   # after warmup: groups have formed
        specs.append(f"rank{rank}:{point}:{step}:{action}")
    # mid-stream break cells (--blips): reset/blip at the link point.
    # Their draws come strictly AFTER every pre-existing draw (binary,
    # degrade, coord-failover, groups) — the same cross-version replay
    # contract: a seed's spec without --blips is byte-identical to
    # every older tree.
    for _ in range(blips):
        action = rng.choice(_MIDSTREAM_ACTIONS)
        rank = rng.randrange(1, num_ranks) if num_ranks > 1 else 0
        step = rng.randint(1, 5)
        if action == "reset":
            param = str(rng.choice(_RESET_P))
            duration = rng.randint(2, 8)
            specs.append(f"rank{rank}:link:{step}:reset:{param}:"
                         f"{duration}")
        else:
            param = str(rng.choice(_BLIP_MS))
            specs.append(f"rank{rank}:link:{step}:blip:{param}")
    return ",".join(specs)
