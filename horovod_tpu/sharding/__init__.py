"""ZeRO-sharded weight update + GSPMD-native executor path.

Two halves of one idea — stop replicating what can be sharded:

- :mod:`horovod_tpu.sharding.zero` — the ZeRO-1 weight-update
  decomposition (arXiv:2004.13336): reduce-scatter gradients, run the
  optimizer on this rank's 1/N shard (optimizer state allocated for
  that shard only), allgather updated parameters.  Available on both
  data planes: in-graph via :func:`ShardedDistributedOptimizer`
  (shard_map/psum_scatter, compiled into the step) and eagerly via
  :func:`ZeroDistributedOptimizer` (the named reduce_scatter/allgather
  collectives, so the TCP ring and the coordinator star serve it too).
- :mod:`horovod_tpu.sharding.mesh_executor` — a NamedSharding-native
  executor over the :mod:`horovod_tpu.parallel.mesh` axis vocabulary,
  selected with ``HVD_TPU_EXECUTOR=mesh``, so tensor/pipeline/MoE
  parallelism can later compose on the same mesh.

See docs/sharding.md.
"""

from horovod_tpu.sharding.mesh_executor import MeshExecutor  # noqa: F401
from horovod_tpu.sharding.zero import (  # noqa: F401
    ShardedDistributedOptimizer,
    ZeroDistributedOptimizer,
    gather_zero_state,
    reshard_zero_state,
    shard_chunk_size,
    sharded_state_unwrap,
    sharded_state_wrap,
    zero_shard_layout,
)
