"""GSPMD-native executor: the XLA data plane over a NamedSharding mesh.

:class:`~horovod_tpu.ops.xla_executor.XlaExecutor` builds its own
private 1-D ``Mesh`` over the axis name ``"hvd"``.  That is fine for a
pure data-parallel job, but it is a topology island: the model-parallel
modules (``horovod_tpu.parallel.{tensor_parallel,pipeline,moe}``)
express THEIR sharding over the :class:`horovod_tpu.parallel.mesh
.MeshAxes` vocabulary (``dp``/``fsdp``/``tp``/...), so a training step
that wants eager collectives AND in-graph model parallelism would juggle
two meshes over the same devices.

``MeshExecutor`` closes the gap: the same compiled collective programs
(it inherits every ``allreduce_fused``/``allgather``/``reduce_scatter``
/... implementation unchanged) run over a ``parallel.mesh.make_mesh``
mesh whose rank axis is ``MeshAxes.DP``, and the executor can hand out
:class:`~jax.sharding.NamedSharding` specs on that mesh for the model's
own arrays.  Select it with ``HVD_TPU_EXECUTOR=mesh`` (tri-surface:
``hvdrun --executor``, YAML ``sharding.executor``); see
docs/sharding.md.
"""

from horovod_tpu.ops.xla_executor import XlaExecutor
from horovod_tpu.parallel.mesh import MeshAxes, make_mesh


class MeshExecutor(XlaExecutor):
    """XlaExecutor whose mesh speaks the ``parallel.mesh`` axis
    vocabulary.

    The rank-enumerating axis is ``MeshAxes.DP`` (``"dp"``) by default —
    gradients psum over ``dp`` exactly like every sharding-annotated
    model in ``horovod_tpu.parallel`` expects — so eager collectives and
    GSPMD model code agree on one topology object
    (:attr:`mesh`).
    """

    def __init__(self, devices, hier_local_size=None,
                 axis_name=MeshAxes.DP):
        self._axis_name = axis_name
        super().__init__(devices, hier_local_size=hier_local_size)

    def _build_mesh(self, devices):
        mesh = make_mesh({self._axis_name: len(devices)}, devices=devices)
        return mesh, self._axis_name

    def named_sharding(self, *spec):
        """A :class:`~jax.sharding.NamedSharding` over this executor's
        mesh — the hook the parallel modules use to place model arrays
        on the SAME topology the eager collectives run on.  ``spec``
        elements are axis names (or ``None``) exactly as for
        :class:`~jax.sharding.PartitionSpec`."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(*spec))
