"""ZeRO-1 sharded weight update on both data planes.

Cross-replica sharding of the weight update (arXiv:2004.13336 — the
technique is TPU-native in origin; the reference framework has no
analog): instead of every replica reducing the FULL gradient and holding
the FULL optimizer state,

1. gradients are **reduce-scattered** — each rank receives its 1/N block
   already reduced (half the wire traffic of a full allreduce),
2. the inner optimizer runs on that block only — optimizer state is 1/N
   per rank (Adam on a P-param model stores 2P/N here),
3. the updated parameter block is **allgathered** back.

Two bindings of the same decomposition:

- :func:`ShardedDistributedOptimizer` — in-graph: ``psum_scatter`` /
  ``all_gather`` inside ``shard_map``, compiled into the step program
  (the XLA executor's native plane).
- :func:`ZeroDistributedOptimizer` — eager: the named
  ``hvd.reduce_scatter`` / ``hvd.allgather`` collectives, so the same
  update runs over the TCP ring and the coordinator star, participates
  in negotiation/fusion, and survives elastic reconfiguration
  (:func:`gather_zero_state` / :func:`reshard_zero_state`).

See docs/sharding.md for the decomposition diagram and knob table.
"""

import jax
import optax

from horovod_tpu.common.compression import (Compression,
                                            quantized_reduce_scatter)
from horovod_tpu.common.ops_enum import (Adasum, Average, ReduceOp,
                                         reduce_scatter_split_sizes)


# --------------------------------------------------------------- shard layout
def shard_chunk_size(n_params, axis_size):
    """Per-replica flat-shard length the in-graph sharded optimizer uses
    (ceil-divided so the last shard is zero-padded)."""
    return -(-n_params // axis_size)


def zero_shard_layout(n_params, world_size, rank):
    """``(counts, offset, count)`` for the EAGER ZeRO layout: the
    np.array_split row partition shared with ``hvd.reduce_scatter``
    (``reduce_scatter_split_sizes``) — no padding, the first
    ``n_params % world_size`` ranks take one extra element."""
    counts = reduce_scatter_split_sizes(n_params, world_size)
    offset = sum(counts[:rank])
    return counts, offset, counts[rank]


def _resolve_min_size(min_size):
    """Threshold below which the update stays replicated.  Resolution:
    explicit arg > runtime config (``HVD_TPU_ZERO_MIN_SIZE`` /
    ``--zero-min-size`` / YAML ``sharding.zero_min_size``) > default.
    Deterministic across ranks — every rank sees the same flat size and
    the same config, so all take the same branch."""
    if min_size is not None:
        return int(min_size)
    from horovod_tpu.common import basics
    from horovod_tpu.utils import env as env_util

    state = basics._state
    if state is not None:
        return state.config.zero_min_size
    return env_util.DEFAULT_ZERO_MIN_SIZE


# ----------------------------------------------------- in-graph (XLA) binding
def ShardedDistributedOptimizer(optimizer, axis_name="hvd", op=Average,
                                compression=Compression.none):
    """In-graph ZeRO-1 on the data-parallel axis.

    Both ``init`` and ``update`` must run INSIDE ``shard_map`` over
    ``axis_name`` (init the state in a jitted sharded step — see
    ``tests/test_spmd.py``).  Use
    ``horovod_tpu.parallel._compat.shard_map_unchecked``: the gathered
    updates ARE replicated, but jax's varying-manual-axes checker cannot
    infer replication through ``all_gather`` (no public un-vary
    annotation exists), so the check must be off for the step.  Average
    divides by the axis size; Adasum is not supported (its combination
    needs full vectors).
    """
    from jax.flatten_util import ravel_pytree

    import jax.numpy as jnp

    op_ = ReduceOp(op)
    if op_ == Adasum:
        raise ValueError(
            "ShardedDistributedOptimizer does not support Adasum; use "
            "DistributedOptimizer(op=Adasum)")
    quantized = getattr(compression, "block_quantized", False)

    def _layout(flat):
        n = jax.lax.psum(1, axis_name)  # concrete inside shard_map
        chunk = shard_chunk_size(flat.size, n)
        if quantized:
            # block-align the shard so the quantized reduce-scatter's
            # per-destination chunks land on scale-block boundaries;
            # init and update share this layout, so the optimizer-state
            # shape is stable either way
            chunk = -(-chunk // compression.block) * compression.block
        return n, chunk

    def _my_shard(flat):
        n, chunk = _layout(flat)
        padded = jnp.pad(flat, (0, n * chunk - flat.size))
        return jax.lax.dynamic_slice(
            padded, (jax.lax.axis_index(axis_name) * chunk,), (chunk,))

    def init_fn(params):
        flat, _ = ravel_pytree(params)
        return optimizer.init(_my_shard(flat))

    def update_fn(grads, state, params=None):
        flat_g, unravel = ravel_pytree(grads)
        n, chunk = _layout(flat_g)

        if quantized and jnp.issubdtype(flat_g.dtype, jnp.floating):
            # quantized reduce-scatter: each rank's contribution to every
            # shard travels as int8 + block scales, the owned shard
            # accumulates in fp32 — half of the quantized allreduce (the
            # allgather of UPDATES below stays full precision)
            padded = jnp.pad(flat_g.astype(jnp.float32),
                             (0, n * chunk - flat_g.size))
            g_shard = quantized_reduce_scatter(
                padded.reshape(n, chunk), axis_name,
                compression.block).astype(flat_g.dtype)
        else:
            compressed, ctx = compression.compress(flat_g)
            padded = jnp.pad(compressed, (0, n * chunk - flat_g.size))
            g_shard = jax.lax.psum_scatter(
                padded.reshape(n, chunk), axis_name, scatter_dimension=0)
            g_shard = compression.decompress(g_shard, ctx)
        if op_ == Average:
            g_shard = g_shard / n

        p_shard = None
        if params is not None:
            flat_p, _ = ravel_pytree(params)
            p_shard = _my_shard(flat_p)
        upd_shard, new_state = optimizer.update(g_shard, state, p_shard)

        full = jax.lax.all_gather(upd_shard, axis_name,
                                  tiled=True)[:flat_g.size]
        return unravel(full), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def sharded_state_wrap(state):
    """Prepare a ShardedDistributedOptimizer state to LEAVE a
    ``shard_map`` region: every leaf (including scalar counters) gains a
    leading length-1 per-rank axis so ``out_specs=P(axis)`` can
    concatenate the per-replica shards."""
    import jax.numpy as jnp

    return jax.tree.map(lambda a: jnp.asarray(a)[None], state)


def sharded_state_unwrap(state):
    """Inverse of :func:`sharded_state_wrap` on ENTRY to the region
    (``in_specs=P(axis)`` hands each replica its own length-1 slice)."""
    return jax.tree.map(lambda a: a[0], state)


# --------------------------------------------------------------- eager binding
def ZeroDistributedOptimizer(optimizer, op=Average, compression=None,
                             min_size=None, group=None):
    """Eager ZeRO-1: the named-collective binding of the sharded update.

    Wraps an optax optimizer so that ``update`` reduce-scatters the
    flattened gradient (``hvd.reduce_scatter`` — TCP ring, coordinator
    star, or XLA plane, whichever the runtime negotiated), runs
    ``optimizer`` on this rank's block (state is allocated for that
    block only — ``init`` never materializes full-size state), and
    allgathers the updated block.  Models whose flat parameter count is
    below ``min_size`` (default: config ``zero_min_size``) fall back to
    a replicated allreduce-then-update — the branch is deterministic
    across ranks, so no negotiation mismatch is possible.

    ``op`` may be Average or Sum (Adasum needs full vectors);
    ``compression`` is a wire-compression name (``"bf16"`` / ``"fp16"``
    / ``"int8"``) applied to the gradient reduce-scatter — parameter
    allgather always travels at full precision, matching the in-graph
    binding.

    The returned transformation's state is the inner optimizer's state
    on the block; :func:`gather_zero_state` / :func:`reshard_zero_state`
    convert it to/from the full-size form for checkpointing and elastic
    reconfiguration.

    ``group`` scopes the whole decomposition to a
    :class:`~horovod_tpu.groups.ProcessGroup` — the DATA-PARALLEL group
    of a DP x TP x PP grid (docs/groups.md): the shard layout, the
    gradient reduce-scatter and the parameter allgather all run over
    the group's members, concurrently with other groups' collectives.
    """
    op_ = ReduceOp(op)
    if op_ == Adasum:
        raise ValueError(
            "ZeroDistributedOptimizer does not support Adasum; use "
            "DistributedOptimizer(op=Adasum)")
    comp = compression  # eager resolves names/classes/None uniformly

    def _topology():
        from horovod_tpu.common import basics

        if group is not None:
            # group-local view: the shard partition lives over the DP
            # group's members, re-read per call so an elastic re-form
            # is picked up (or fails typed) at the next step
            return group.rank(), group.size
        return basics.rank(), basics.size()

    def _sharded(n_params, world):
        return world > 1 and n_params >= _resolve_min_size(min_size)

    def init_fn(params):
        from jax.flatten_util import ravel_pytree

        flat, _ = ravel_pytree(params)
        rank, world = _topology()
        if not _sharded(flat.size, world):
            return optimizer.init(flat)
        _, off, cnt = zero_shard_layout(flat.size, world, rank)
        return optimizer.init(jax.lax.slice(flat, (off,), (off + cnt,)))

    def update_fn(grads, state, params=None):
        from jax.flatten_util import ravel_pytree

        from horovod_tpu.ops import eager

        flat_g, unravel = ravel_pytree(grads)
        rank, world = _topology()

        if not _sharded(flat_g.size, world):
            reduced = flat_g
            if world > 1:
                reduced = eager.allreduce(
                    flat_g, op=op_, name="zero.allreduce",
                    compression=comp, group=group)
            flat_p = None
            if params is not None:
                flat_p, _ = ravel_pytree(params)
            upd, new_state = optimizer.update(reduced, state, flat_p)
            return unravel(upd), new_state

        _, off, cnt = zero_shard_layout(flat_g.size, world, rank)
        g_block = eager.reduce_scatter(
            flat_g, op=op_, name="zero.reduce_scatter", compression=comp,
            group=group)
        p_block = None
        if params is not None:
            flat_p, _ = ravel_pytree(params)
            p_block = jax.lax.slice(flat_p, (off,), (off + cnt,))
        upd_block, new_state = optimizer.update(g_block, state, p_block)
        # variable-dim0 allgather: blocks differ by one row when
        # world_size does not divide the parameter count
        full = eager.allgather(upd_block, name="zero.allgather",
                               group=group)
        return unravel(full), new_state

    return optax.GradientTransformation(init_fn, update_fn)


# ------------------------------------------------- elastic / checkpoint glue
def flat_shard(flat, world_size, rank):
    """``rank``'s block of a flat vector under the eager ZeRO row
    partition (:func:`zero_shard_layout`).  The durable checkpoint
    writer (docs/checkpoint.md) shards every rank's param/optimizer
    vector with THIS partition so a checkpoint written at world N and a
    live ZeRO shard at world N agree bit-for-bit — and a resume at a
    different world size only re-slices, never re-pads."""
    import numpy as np

    _, off, cnt = zero_shard_layout(len(flat), world_size, rank)
    return np.asarray(flat)[off:off + cnt]


def gather_zero_state(state, n_params, name_prefix="zero.state_gather",
                      group=None):
    """Assemble the FULL optimizer state from every rank's block.

    Tree-maps the eager-ZeRO state: a 1-D leaf whose length equals this
    rank's block size is a sharded moment vector — allgather it
    (deterministic leaf-index names, so every rank pairs leaf-for-leaf
    even during elastic replay); anything else (step counters, already
    full-size leaves from a replicated fallback) is left alone.  The
    result is rank-independent: safe to checkpoint, broadcast, or
    re-shard at a different world size with :func:`reshard_zero_state`.
    """
    from horovod_tpu.common import basics
    from horovod_tpu.ops import eager

    rank, world = _topology_of(basics, group)
    if world <= 1:
        return state
    _, _, cnt = zero_shard_layout(int(n_params), world, rank)

    leaves, treedef = jax.tree.flatten(state)
    out = []
    for i, leaf in enumerate(leaves):
        arr = jax.numpy.asarray(leaf)
        if arr.ndim == 1 and arr.shape[0] == cnt and cnt != int(n_params):
            out.append(eager.allgather(arr, name=f"{name_prefix}.{i}",
                                       group=group))
        else:
            out.append(leaf)
    return jax.tree.unflatten(treedef, out)


def reshard_zero_state(full_state, n_params, group=None):
    """Inverse of :func:`gather_zero_state` at the CURRENT topology:
    slice every full-size 1-D leaf down to this rank's block.  Called
    after elastic reconfiguration (possibly at a different world size
    than the state was gathered at) and after checkpoint restore."""
    from horovod_tpu.common import basics

    rank, world = _topology_of(basics, group)
    if world <= 1:
        return full_state
    n_params = int(n_params)
    _, off, cnt = zero_shard_layout(n_params, world, rank)

    def reshard(leaf):
        arr = jax.numpy.asarray(leaf)
        if arr.ndim == 1 and arr.shape[0] == n_params:
            return jax.lax.slice(arr, (off,), (off + cnt,))
        return leaf

    return jax.tree.map(reshard, full_state)


def _topology_of(basics, group=None):
    if group is not None:
        return group.rank(), group.size
    return basics.rank(), basics.size()
