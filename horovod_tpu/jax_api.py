"""JAX-native training API: the TPU-first ``DistributedOptimizer``.

The reference wraps framework optimizers so gradient exchange is transparent
(``horovod/torch/__init__.py:67`` ``_DistributedOptimizer``,
``horovod/tensorflow/__init__.py:271``).  The TPU-native analog is an
``optax`` gradient transformation: inside a ``shard_map``/``pjit`` training
step, gradients are reduced across the data-parallel mesh axes with
``lax.psum``/``pmean`` — XLA compiles the reduction into the step program and
schedules it on ICI, which subsumes the reference's tensor-fusion machinery
(all grads are one fused program by construction).

Two usage styles:

- **shard_map / explicit SPMD** (default): pass the mesh axis names the
  gradients are sharded over; the wrapper inserts the collective.
- **GSPMD / jit-with-shardings**: pass ``named_axes=None``; XLA already
  inserts gradient reductions, and the wrapper contributes compression and
  local gradient aggregation only.
"""

import jax
import optax

from horovod_tpu.common.compression import (Compression,
                                            quantized_allreduce,
                                            quantized_reduce_scatter)
from horovod_tpu.common.ops_enum import Adasum, Average, ReduceOp, Sum


def _single_axis(named_axes, what):
    """The quantized collectives decompose the reduction into
    all_to_all + all_gather over ONE mesh axis; reject multi-axis
    reductions loudly instead of silently falling back."""
    if isinstance(named_axes, str):
        return named_axes
    if len(named_axes) == 1:
        return named_axes[0]
    raise ValueError(
        f"{what} with int8 compression requires a single mesh axis, got "
        f"{tuple(named_axes)}; reduce over a flattened axis or use bf16 "
        f"compression")


def allreduce_gradients(grads, named_axes=("hvd",), op=Average,
                        compression=Compression.none):
    """Reduce a gradient pytree across the given mesh axes.

    Must be called inside a context where ``named_axes`` are bound
    (``shard_map`` / ``pmap``).  Cast compression (bf16/fp16) narrows
    leaves before the collective and restores dtype after, trading
    HBM/ICI bandwidth for precision exactly like the reference's fp16
    compression (``horovod/torch/compression.py:45``) — but bf16-native.
    ``Compression.int8`` runs the block-scaled quantized decomposition
    instead (quantized reduce-scatter + fp32 accumulate + quantized
    allgather): per-rank block scales cannot ride a plain ``psum``.
    """
    op = ReduceOp(op)
    if op == Adasum:
        from horovod_tpu.ops.adasum import adasum_reduce_pytree
        return adasum_reduce_pytree(grads, named_axes=named_axes,
                                    compression=compression)

    if getattr(compression, "block_quantized", False):
        axis = _single_axis(named_axes, "allreduce_gradients")
        block = compression.block

        def reduce_quantized(g):
            if not jax.numpy.issubdtype(g.dtype, jax.numpy.floating) \
                    or g.size < block:
                # exact passthrough, same gate as the eager executor
                return (jax.lax.pmean(g, named_axes) if op == Average
                        else jax.lax.psum(g, named_axes))
            red = quantized_allreduce(g.reshape(-1), axis, block)
            if op == Average:
                red = red / jax.lax.psum(1, axis)
            return red.astype(g.dtype).reshape(g.shape)

        return jax.tree.map(reduce_quantized, grads)

    def reduce_leaf(g):
        compressed, ctx = compression.compress(g)
        if op == Average:
            reduced = jax.lax.pmean(compressed, named_axes)
        else:
            reduced = jax.lax.psum(compressed, named_axes)
        return compression.decompress(reduced, ctx)

    return jax.tree.map(reduce_leaf, grads)


def DistributedOptimizer(optimizer, named_axes=("hvd",), op=Average,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         average_aggregated_gradients=True):
    """Wrap an optax optimizer so updates consume globally-reduced gradients.

    ``backward_passes_per_step`` accumulates gradients locally for N micro
    steps and performs ONE reduction per N (reference:
    ``horovod/tensorflow/gradient_aggregation.py``,
    ``backward_passes_per_step`` in torch).  With
    ``average_aggregated_gradients`` the accumulated gradient is averaged
    over the N passes, else summed.
    """
    op = ReduceOp(op)

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(grads, state, params=None):
        del params
        reduced = grads
        if named_axes:
            reduced = allreduce_gradients(
                grads, named_axes=named_axes, op=op, compression=compression)
        return reduced, state

    reduce_transform = optax.GradientTransformation(init_fn, update_fn)
    chained = optax.chain(reduce_transform, optimizer)
    if backward_passes_per_step > 1:
        if not average_aggregated_gradients:
            k = float(backward_passes_per_step)
            chained = optax.chain(optax.scale(k), chained)
        chained = optax.MultiSteps(
            chained, every_k_schedule=backward_passes_per_step)
    return chained


def ShardedDistributedOptimizer(optimizer, axis_name="hvd", op=Average,
                                compression=Compression.none):
    """Cross-replica sharded weight update — ZeRO-1 on the data-parallel
    axis (the technique is TPU-native in origin: arXiv:2004.13336,
    "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training"; the reference framework has no analog).

    Instead of every replica reducing the FULL gradient and holding the
    FULL optimizer state, each replica:

    1. ``psum_scatter``s the flattened gradient — one 1/N shard arrives
       reduced (half the ICI traffic of a full allreduce),
    2. applies the inner optimizer to its shard only (optimizer state is
       1/N per replica — Adam on a P-param model stores 2P/N here),
    3. ``all_gather``s the update shards back to apply everywhere.

    Both ``init`` and ``update`` must run INSIDE ``shard_map`` over
    ``axis_name`` (init the state in a jitted sharded step — see
    ``tests/test_spmd.py``).  Use
    ``horovod_tpu.parallel._compat.shard_map_unchecked``: the gathered
    updates ARE replicated, but jax's varying-manual-axes checker cannot
    infer replication through ``all_gather`` (no public un-vary
    annotation exists), so the check must be off for the step.  Average
    divides by the axis size; Adasum is not supported (its combination
    needs full vectors).
    """
    from jax.flatten_util import ravel_pytree

    import jax.numpy as jnp

    op_ = ReduceOp(op)
    if op_ == Adasum:
        raise ValueError(
            "ShardedDistributedOptimizer does not support Adasum; use "
            "DistributedOptimizer(op=Adasum)")
    quantized = getattr(compression, "block_quantized", False)

    def _layout(flat):
        n = jax.lax.psum(1, axis_name)  # concrete inside shard_map
        chunk = shard_chunk_size(flat.size, n)
        if quantized:
            # block-align the shard so the quantized reduce-scatter's
            # per-destination chunks land on scale-block boundaries;
            # init and update share this layout, so the optimizer-state
            # shape is stable either way
            chunk = -(-chunk // compression.block) * compression.block
        return n, chunk

    def _my_shard(flat):
        n, chunk = _layout(flat)
        padded = jnp.pad(flat, (0, n * chunk - flat.size))
        return jax.lax.dynamic_slice(
            padded, (jax.lax.axis_index(axis_name) * chunk,), (chunk,))

    def init_fn(params):
        flat, _ = ravel_pytree(params)
        return optimizer.init(_my_shard(flat))

    def update_fn(grads, state, params=None):
        flat_g, unravel = ravel_pytree(grads)
        n, chunk = _layout(flat_g)

        if quantized and jnp.issubdtype(flat_g.dtype, jnp.floating):
            # quantized reduce-scatter: each rank's contribution to every
            # shard travels as int8 + block scales, the owned shard
            # accumulates in fp32 — half of the quantized allreduce (the
            # allgather of UPDATES below stays full precision)
            padded = jnp.pad(flat_g.astype(jnp.float32),
                             (0, n * chunk - flat_g.size))
            g_shard = quantized_reduce_scatter(
                padded.reshape(n, chunk), axis_name,
                compression.block).astype(flat_g.dtype)
        else:
            compressed, ctx = compression.compress(flat_g)
            padded = jnp.pad(compressed, (0, n * chunk - flat_g.size))
            g_shard = jax.lax.psum_scatter(
                padded.reshape(n, chunk), axis_name, scatter_dimension=0)
            g_shard = compression.decompress(g_shard, ctx)
        if op_ == Average:
            g_shard = g_shard / n

        p_shard = None
        if params is not None:
            flat_p, _ = ravel_pytree(params)
            p_shard = _my_shard(flat_p)
        upd_shard, new_state = optimizer.update(g_shard, state, p_shard)

        full = jax.lax.all_gather(upd_shard, axis_name,
                                  tiled=True)[:flat_g.size]
        return unravel(full), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def shard_chunk_size(n_params, axis_size):
    """Per-replica flat-shard length the sharded optimizer uses
    (ceil-divided so the last shard is zero-padded)."""
    return -(-n_params // axis_size)


def sharded_state_wrap(state):
    """Prepare a ShardedDistributedOptimizer state to LEAVE a
    ``shard_map`` region: every leaf (including scalar counters) gains a
    leading length-1 per-rank axis so ``out_specs=P(axis)`` can
    concatenate the per-replica shards."""
    import jax.numpy as jnp

    return jax.tree.map(lambda a: jnp.asarray(a)[None], state)


def sharded_state_unwrap(state):
    """Inverse of :func:`sharded_state_wrap` on ENTRY to the region
    (``in_specs=P(axis)`` hands each replica its own length-1 slice)."""
    return jax.tree.map(lambda a: a[0], state)


def broadcast_parameters(params, root_rank=0, name_prefix=None):
    """Broadcast a parameter pytree from ``root_rank`` to all ranks via the
    eager collective path (reference: ``horovod/torch/__init__.py:452``).

    In single-controller SPMD mode parameters are already consistent; this is
    the eager-mode / process-mode synchronization primitive, used after
    checkpoint restore or at train start.

    ``name_prefix`` overrides the default tensor-name prefix.  Elastic
    state sync uses it to keep replay rounds in their own namespace:
    names here are DETERMINISTIC (tree-order indices), never the eager
    auto-name counters — a late joiner that skipped the incumbents'
    earlier collectives must still pair leaf-for-leaf.
    """
    from horovod_tpu.common import basics
    from horovod_tpu.ops import eager

    state = basics._get_state()
    if state.config.controller != "tcp":
        # Device-rank mode: every logical rank lives in this process and
        # shares the caller's pytree — already root_rank's values.  Only a
        # per-rank thread context (run_parallel) can legally block on an
        # eager broadcast here.
        if getattr(basics._tls, "local_rank", None) is None:
            return params

    prefix = name_prefix or "broadcast.parameters"
    leaves, treedef = jax.tree.flatten(params)
    handles = [
        eager.broadcast_async(leaf, root_rank,
                              name=f"{prefix}.{i}")
        for i, leaf in enumerate(leaves)
    ]
    # drain EVERY handle before raising: abandoning the rest mid-pytree
    # on the first failure (e.g. an HvdAbortedError) would leave their
    # completions unobserved and, on the tcp plane, chunks parked in the
    # peer mailbox
    from horovod_tpu.common.handles import HvdError

    results, first_error = [], None
    for handle in handles:
        try:
            results.append(eager.synchronize(handle))
        except HvdError as exc:
            if first_error is None:
                first_error = exc
    if first_error is not None:
        raise first_error
    return jax.tree.unflatten(treedef, results)


def broadcast_optimizer_state(opt_state, root_rank=0, name_prefix=None):
    """Broadcast optimizer state from ``root_rank`` (reference:
    ``horovod/torch/__init__.py:484`` broadcast_optimizer_state)."""
    return broadcast_parameters(opt_state, root_rank=root_rank,
                                name_prefix=name_prefix)
