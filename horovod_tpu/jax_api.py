"""JAX-native training API: the TPU-first ``DistributedOptimizer``.

The reference wraps framework optimizers so gradient exchange is transparent
(``horovod/torch/__init__.py:67`` ``_DistributedOptimizer``,
``horovod/tensorflow/__init__.py:271``).  The TPU-native analog is an
``optax`` gradient transformation: inside a ``shard_map``/``pjit`` training
step, gradients are reduced across the data-parallel mesh axes with
``lax.psum``/``pmean`` — XLA compiles the reduction into the step program and
schedules it on ICI, which subsumes the reference's tensor-fusion machinery
(all grads are one fused program by construction).

Two usage styles:

- **shard_map / explicit SPMD** (default): pass the mesh axis names the
  gradients are sharded over; the wrapper inserts the collective.
- **GSPMD / jit-with-shardings**: pass ``named_axes=None``; XLA already
  inserts gradient reductions, and the wrapper contributes compression and
  local gradient aggregation only.
"""

import jax
import optax

from horovod_tpu.common.compression import Compression, quantized_allreduce
from horovod_tpu.common.ops_enum import Adasum, Average, ReduceOp, Sum
# The ZeRO-sharded weight update grew into its own subsystem
# (docs/sharding.md); these stay importable here for API continuity.
from horovod_tpu.sharding.zero import (  # noqa: F401
    ShardedDistributedOptimizer,
    ZeroDistributedOptimizer,
    shard_chunk_size,
    sharded_state_unwrap,
    sharded_state_wrap,
)


def _single_axis(named_axes, what):
    """The quantized collectives decompose the reduction into
    all_to_all + all_gather over ONE mesh axis; reject multi-axis
    reductions loudly instead of silently falling back."""
    if isinstance(named_axes, str):
        return named_axes
    if len(named_axes) == 1:
        return named_axes[0]
    raise ValueError(
        f"{what} with int8 compression requires a single mesh axis, got "
        f"{tuple(named_axes)}; reduce over a flattened axis or use bf16 "
        f"compression")


def allreduce_gradients(grads, named_axes=("hvd",), op=Average,
                        compression=Compression.none):
    """Reduce a gradient pytree across the given mesh axes.

    Must be called inside a context where ``named_axes`` are bound
    (``shard_map`` / ``pmap``).  Cast compression (bf16/fp16) narrows
    leaves before the collective and restores dtype after, trading
    HBM/ICI bandwidth for precision exactly like the reference's fp16
    compression (``horovod/torch/compression.py:45``) — but bf16-native.
    ``Compression.int8`` runs the block-scaled quantized decomposition
    instead (quantized reduce-scatter + fp32 accumulate + quantized
    allgather): per-rank block scales cannot ride a plain ``psum``.
    """
    op = ReduceOp(op)
    if op == Adasum:
        from horovod_tpu.ops.adasum import adasum_reduce_pytree
        return adasum_reduce_pytree(grads, named_axes=named_axes,
                                    compression=compression)

    if getattr(compression, "block_quantized", False):
        axis = _single_axis(named_axes, "allreduce_gradients")
        block = compression.block

        def reduce_quantized(g):
            if not jax.numpy.issubdtype(g.dtype, jax.numpy.floating) \
                    or g.size < block:
                # exact passthrough, same gate as the eager executor
                return (jax.lax.pmean(g, named_axes) if op == Average
                        else jax.lax.psum(g, named_axes))
            red = quantized_allreduce(g.reshape(-1), axis, block)
            if op == Average:
                red = red / jax.lax.psum(1, axis)
            return red.astype(g.dtype).reshape(g.shape)

        return jax.tree.map(reduce_quantized, grads)

    def reduce_leaf(g):
        compressed, ctx = compression.compress(g)
        if op == Average:
            reduced = jax.lax.pmean(compressed, named_axes)
        else:
            reduced = jax.lax.psum(compressed, named_axes)
        return compression.decompress(reduced, ctx)

    return jax.tree.map(reduce_leaf, grads)


def DistributedOptimizer(optimizer, named_axes=("hvd",), op=Average,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         average_aggregated_gradients=True):
    """Wrap an optax optimizer so updates consume globally-reduced gradients.

    ``backward_passes_per_step`` accumulates gradients locally for N micro
    steps and performs ONE reduction per N (reference:
    ``horovod/tensorflow/gradient_aggregation.py``,
    ``backward_passes_per_step`` in torch).  With
    ``average_aggregated_gradients`` the accumulated gradient is averaged
    over the N passes, else summed.
    """
    op = ReduceOp(op)

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(grads, state, params=None):
        del params
        reduced = grads
        if named_axes:
            reduced = allreduce_gradients(
                grads, named_axes=named_axes, op=op, compression=compression)
        return reduced, state

    reduce_transform = optax.GradientTransformation(init_fn, update_fn)
    chained = optax.chain(reduce_transform, optimizer)
    if backward_passes_per_step > 1:
        if not average_aggregated_gradients:
            k = float(backward_passes_per_step)
            chained = optax.chain(optax.scale(k), chained)
        chained = optax.MultiSteps(
            chained, every_k_schedule=backward_passes_per_step)
    return chained


def broadcast_parameters(params, root_rank=0, name_prefix=None):
    """Broadcast a parameter pytree from ``root_rank`` to all ranks via the
    eager collective path (reference: ``horovod/torch/__init__.py:452``).

    In single-controller SPMD mode parameters are already consistent; this is
    the eager-mode / process-mode synchronization primitive, used after
    checkpoint restore or at train start.

    ``name_prefix`` overrides the default tensor-name prefix.  Elastic
    state sync uses it to keep replay rounds in their own namespace:
    names here are DETERMINISTIC (tree-order indices), never the eager
    auto-name counters — a late joiner that skipped the incumbents'
    earlier collectives must still pair leaf-for-leaf.
    """
    from horovod_tpu.common import basics
    from horovod_tpu.ops import eager

    state = basics._get_state()
    if state.config.controller != "tcp":
        # Device-rank mode: every logical rank lives in this process and
        # shares the caller's pytree — already root_rank's values.  Only a
        # per-rank thread context (run_parallel) can legally block on an
        # eager broadcast here.
        if getattr(basics._tls, "local_rank", None) is None:
            return params

    prefix = name_prefix or "broadcast.parameters"
    leaves, treedef = jax.tree.flatten(params)
    handles = [
        eager.broadcast_async(leaf, root_rank,
                              name=f"{prefix}.{i}")
        for i, leaf in enumerate(leaves)
    ]
    # drain EVERY handle before raising: abandoning the rest mid-pytree
    # on the first failure (e.g. an HvdAbortedError) would leave their
    # completions unobserved and, on the tcp plane, chunks parked in the
    # peer mailbox
    from horovod_tpu.common.handles import HvdError

    results, first_error = [], None
    for handle in handles:
        try:
            results.append(eager.synchronize(handle))
        except HvdError as exc:
            if first_error is None:
                first_error = exc
    if first_error is not None:
        raise first_error
    return jax.tree.unflatten(treedef, results)


def broadcast_optimizer_state(opt_state, root_rank=0, name_prefix=None):
    """Broadcast optimizer state from ``root_rank`` (reference:
    ``horovod/torch/__init__.py:484`` broadcast_optimizer_state)."""
    return broadcast_parameters(opt_state, root_rank=root_rank,
                                name_prefix=name_prefix)
