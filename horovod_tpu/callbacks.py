"""Training-loop utilities: the Keras-callback surface, JAX-style.

The reference ships Keras callbacks (``horovod/_keras/callbacks.py``:
BroadcastGlobalVariablesCallback :22, MetricAverageCallback :48,
LearningRateScheduleCallback :89, LearningRateWarmupCallback :172).  In a
functional JAX training loop these become helpers and optax schedules rather
than callback objects; the torch binding can use them directly too.
"""

import jax.numpy as jnp
import optax

from horovod_tpu.common import basics
from horovod_tpu.common.ops_enum import Average
from horovod_tpu.ops import eager


def broadcast_global_variables(variables, root_rank=0):
    """Start-of-training state sync (reference:
    BroadcastGlobalVariablesCallback / BroadcastGlobalVariablesHook)."""
    from horovod_tpu.jax_api import broadcast_parameters

    return broadcast_parameters(variables, root_rank=root_rank)


def metric_average(value, name):
    """Average a scalar metric across ranks at epoch end (reference:
    MetricAverageCallback averages logged metrics via allreduce)."""
    tensor = jnp.asarray(value, dtype=jnp.float32)
    return float(eager.allreduce(tensor, op=Average,
                                 name=f"metric.{name}"))


def scaled_lr(base_lr, scale=None):
    """Linear LR scaling rule: lr * size (reference docs recommend scaling
    the learning rate by the number of workers)."""
    return base_lr * (scale if scale is not None else basics.size())


def warmup_schedule(base_lr, warmup_steps, scale=None, initial_factor=None):
    """LR warmup from ``base_lr`` (single-worker rate) up to
    ``base_lr * size`` over ``warmup_steps`` (reference:
    LearningRateWarmupCallback — 'gradually increases from the initial small
    rate to the scaled target over the warmup period').

    Returns an optax schedule (step -> lr).
    """
    target = scaled_lr(base_lr, scale)
    start = base_lr * (initial_factor if initial_factor is not None else 1.0)
    if warmup_steps <= 0:
        return optax.constant_schedule(target)
    return optax.linear_schedule(init_value=start, end_value=target,
                                 transition_steps=warmup_steps)


def piecewise_schedule(base_lr, boundaries_and_scales, scale=None):
    """Epoch/step-boundary LR schedule (reference:
    LearningRateScheduleCallback with staircase multipliers).

    ``boundaries_and_scales``: {step: multiplier} applied multiplicatively,
    e.g. ``{30_000: 0.1, 60_000: 0.1}`` for the classic /10 staircase.
    """
    target = scaled_lr(base_lr, scale)
    return optax.piecewise_constant_schedule(
        init_value=target, boundaries_and_scales=boundaries_and_scales)


def warmup_then_piecewise(base_lr, warmup_steps, boundaries_and_scales,
                          scale=None):
    """The classic ImageNet recipe: warmup to size-scaled LR, then
    staircase decay (reference: examples/keras_imagenet_resnet50.py)."""
    return optax.join_schedules(
        [warmup_schedule(base_lr, warmup_steps, scale),
         piecewise_schedule(base_lr, boundaries_and_scales, scale)],
        boundaries=[warmup_steps])
