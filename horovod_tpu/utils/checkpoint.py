"""Checkpoint/resume utilities.

The reference has no core checkpointing — its conventions are rank-0-writes
plus broadcast-on-resume (``examples/keras_imagenet_resnet50.py``:
``resume_from_epoch = hvd.broadcast(resume_from_epoch, 0)``;
``torch/__init__.py:452,484`` broadcast_parameters /
broadcast_optimizer_state).  This module packages those conventions:

- :func:`save_checkpoint` — rank 0 serializes the pytree (flax msgpack)
  and renames atomically; other ranks no-op.  Old checkpoints pruned.
- :func:`restore_checkpoint` — load the latest (or a specific) step.
- :func:`resume_step` — the broadcast convention: every rank receives
  rank 0's view of the latest step so all ranks resume identically.
"""

import os
import re

from flax import serialization

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")


def _ckpt_path(directory, step):
    return os.path.join(directory, f"ckpt_{step}.msgpack")


def _steps_in(directory):
    try:
        entries = os.listdir(directory)
    except FileNotFoundError:
        return []
    steps = []
    for e in entries:
        m = _CKPT_RE.match(e)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory):
    """Highest checkpoint step in ``directory``, or None."""
    steps = _steps_in(directory)
    return steps[-1] if steps else None


def save_checkpoint(directory, target, step, keep=3, rank=None):
    """Rank-0-writes checkpoint of ``target`` (any pytree of arrays).

    ``rank`` defaults to :func:`horovod_tpu.rank` when initialized, else 0.
    Returns the written path on rank 0, None elsewhere.
    """
    if rank is None:
        rank = _current_rank()
    if rank != 0:
        return None
    os.makedirs(directory, exist_ok=True)
    data = serialization.to_bytes(target)
    path = _ckpt_path(directory, step)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic publish
    if keep is not None:
        # keep == 0 would slice [:-0] == nothing; it means "keep only
        # the checkpoint just written"
        steps = _steps_in(directory)
        old_steps = [s for s in steps if s != step] if keep == 0 \
            else steps[:-keep]
        for old in old_steps:
            try:
                os.remove(_ckpt_path(directory, old))
            except FileNotFoundError:
                pass
    return path


def restore_checkpoint(directory, target, step=None):
    """Load checkpoint ``step`` (default: latest) into the structure of
    ``target``.  Returns (restored, step) or (target, None) when no
    checkpoint exists."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            return target, None
    with open(_ckpt_path(directory, step), "rb") as f:
        data = f.read()
    return serialization.from_bytes(target, data), step


def resume_step(directory):
    """The resume convention: rank 0 reads the latest step and every rank
    receives it via broadcast, so a rank with a stale filesystem view
    cannot resume from a different step (reference:
    ``examples/keras_imagenet_resnet50.py`` resume broadcast)."""
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import basics

    step = latest_step(directory)
    state = basics._state
    multiprocess = state is not None and \
        state.config.controller in ("tcp", "gmesh")
    if not multiprocess and (
            state is None
            or getattr(basics._tls, "local_rank", None) is None):
        # single-process device mode (or not initialized): the local
        # filesystem view IS the global view.  Multi-process modes
        # (tcp AND gmesh pods) must broadcast — each host has its own
        # filesystem view
        return step

    def _bcast(_rank=None):
        out = hvd.broadcast(
            np.asarray([-1 if step is None else step], dtype=np.int64),
            root_rank=0, name="checkpoint.resume_step")
        return int(np.asarray(out)[0])

    if state.config.controller == "gmesh" \
            and getattr(basics._tls, "local_rank", None) is None:
        # pod mode from the main thread: every local device rank must
        # participate in the eager broadcast
        val = basics.run_parallel(_bcast)[0]
    else:
        val = _bcast()
    return None if val < 0 else val


def _current_rank():
    from horovod_tpu.common import basics

    try:
        return basics.rank()
    except Exception:  # noqa: BLE001 — not initialized: single process
        return 0


class AsyncCheckpointManager:
    """Orbax-backed ASYNC checkpointing — the save returns as soon as
    the pytree is snapshotted; serialization and the filesystem write
    happen on a background thread, so the training step never blocks on
    I/O.  A TPU-native improvement over the reference's synchronous
    per-framework saves (large-model checkpoints take seconds to
    minutes; async hides that behind compute).

    Same conventions as :func:`save_checkpoint`: rank 0 writes, other
    ranks no-op; ``keep`` prunes old steps.  Call :meth:`wait` before
    shutdown (and before reading a just-written step back).

    Falls back to the synchronous msgpack writer when orbax is
    unavailable — the API is identical either way.
    """

    def __init__(self, directory, keep=3, rank=None):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self._rank = rank
        self._mgr = None
        try:
            import jax
            import orbax.checkpoint as ocp

            if jax.process_count() > 1:
                # orbax's save is a COLLECTIVE in multi-process JAX
                # (sync_global_processes barrier) — the rank-0-writes
                # contract below would deadlock rank 0 against ranks
                # that never call it.  Multi-process pods use the
                # synchronous rank-0 msgpack path until all-rank
                # orbax save is wired.
                raise RuntimeError("multi-process: use msgpack path")
            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=keep, enable_async_checkpointing=True))
        except Exception:  # noqa: BLE001 — orbax absent/unusable
            self._ocp = None

    def _is_writer(self):
        rank = self._rank if self._rank is not None else _current_rank()
        return rank == 0

    def save(self, step, target):
        """Queue an async save of ``target`` at ``step`` (rank 0 only).
        Returns True when a save was queued/written."""
        if not self._is_writer():
            return False
        if self._mgr is None:
            save_checkpoint(self.directory, target, step,
                            keep=self.keep, rank=0)
            return True
        return bool(self._mgr.save(
            step, args=self._ocp.args.StandardSave(target)))

    def restore(self, target, step=None):
        """Restore ``step`` (default latest) into ``target``'s
        structure; returns ``(restored, step)`` or ``(target, None)``."""
        if self._mgr is None:
            return restore_checkpoint(self.directory, target, step)
        self._mgr.wait_until_finished()
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                return target, None
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(target))
        return restored, step

    def latest_step(self):
        if self._mgr is None:
            return latest_step(self.directory)
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def wait(self):
        """Block until every queued save is durably on disk."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def close(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
