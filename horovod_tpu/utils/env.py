"""Environment-variable configuration surface.

Mirrors the reference's knob list (``horovod/common/common.h:61-88`` and
``horovod/common/utils/env_parser.cc``) under the ``HVD_`` prefix, with
TPU-appropriate defaults.  The launcher additionally exposes every knob as an
``hvdrun`` CLI flag and a YAML config-file key, keeping the reference's
tri-surface config model.

``bin/hvd-lint`` (docs/linting.md) machine-checks the model: every env
read in the framework must go through a constant declared here plus a
typed getter below, and every knob constant NOT listed in
``LAUNCHER_CONTRACT`` must have an ``hvdrun`` flag, a YAML key in
``run/config_parser.py`` and a mention under ``docs/``.
"""

import logging
import os
import threading

# --- knob names (reference: horovod/common/common.h:61-88) -------------------
HVD_FUSION_THRESHOLD = "HVD_FUSION_THRESHOLD"          # bytes, default 64 MB
HVD_CYCLE_TIME = "HVD_CYCLE_TIME"                      # ms, default 1.0
HVD_CACHE_CAPACITY = "HVD_CACHE_CAPACITY"              # default 1024
HVD_TIMELINE = "HVD_TIMELINE"                          # path -> enable timeline
HVD_TIMELINE_MARK_CYCLES = "HVD_TIMELINE_MARK_CYCLES"
HVD_STALL_CHECK_DISABLE = "HVD_STALL_CHECK_DISABLE"
HVD_STALL_CHECK_TIME_SECONDS = "HVD_STALL_CHECK_TIME_SECONDS"
HVD_STALL_SHUTDOWN_TIME_SECONDS = "HVD_STALL_SHUTDOWN_TIME_SECONDS"
HVD_HIERARCHICAL_ALLREDUCE = "HVD_HIERARCHICAL_ALLREDUCE"
HVD_HIERARCHICAL_ALLGATHER = "HVD_HIERARCHICAL_ALLGATHER"
HVD_HIER_LOCAL_SIZE = "HVD_HIER_LOCAL_SIZE"    # ranks per fast (ICI) group
HVD_ADASUM_HIERARCHICAL = "HVD_ADASUM_HIERARCHICAL"  # opt-in: different math
HVD_AUTOTUNE = "HVD_AUTOTUNE"
HVD_AUTOTUNE_LOG = "HVD_AUTOTUNE_LOG"
HVD_AUTOTUNE_WARMUP_SAMPLES = "HVD_AUTOTUNE_WARMUP_SAMPLES"
HVD_AUTOTUNE_STEADY_STATE_SAMPLES = "HVD_AUTOTUNE_STEADY_STATE_SAMPLES"
HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HVD_LOG_LEVEL = "HVD_LOG_LEVEL"
HVD_LOG_HIDE_TIME = "HVD_LOG_HIDE_TIME"
HVD_CONTROLLER = "HVD_CONTROLLER"                      # native | python | tcp
# reference-parity placeholders (common.h knob list): declared so the
# names stay reserved, but nothing reads them yet — exempted from the
# tri-surface rule until they grow a reader
HVD_CPU_OPERATIONS = "HVD_CPU_OPERATIONS"  # hvd-lint: ignore[config-surface]
HVD_ADASUM_CHUNK_SIZE = "HVD_ADASUM_CHUNK_SIZE"  # hvd-lint: ignore[config-surface]
HVD_NUM_STREAMS = "HVD_NUM_STREAMS"  # hvd-lint: ignore[config-surface]
# default on-the-wire allreduce compression: none | bf16 | fp16 | int8
# (block-scaled int8, EQuARX arXiv:2506.17615)
HVD_TPU_COMPRESSION = "HVD_TPU_COMPRESSION"
# TCP-ring pipeline segment size in bytes (0 = unsegmented): each ring
# step's chunk is split into segments so the send of segment k+1
# overlaps the recv+accumulate of segment k (docs/tuning.md)
HVD_TPU_RING_SEGMENT_BYTES = "HVD_TPU_RING_SEGMENT_BYTES"
# dedicated bulk-data connections per ring peer, separate from the
# control connection (heartbeats never queue behind chunk writes)
HVD_TPU_RING_STRIPES = "HVD_TPU_RING_STRIPES"
# payload size at/above which tcp-mode collectives ride the p2p ring
# instead of the coordinator star (docs/tuning.md)
HVD_TCP_RING_THRESHOLD = "HVD_TCP_RING_THRESHOLD"
# tcp-plane collective schedule: auto | flat_ring | hierarchical | rhd
# | star — "auto" lets the coordinator pick per tensor size/topology
# (docs/tuning.md)
HVD_TPU_SCHEDULE = "HVD_TPU_SCHEDULE"

# --- process groups (docs/groups.md) -----------------------------------------
# cap on live process groups per job: each group owns negotiation
# state, signature caches and (tcp) a ring plane, so an unbounded
# registry is a leak — new_group past the cap raises
HVD_TPU_GROUP_MAX = "HVD_TPU_GROUP_MAX"

# --- ZeRO sharding + executor selection (docs/sharding.md) -------------------
# shard the weight update ZeRO-1 style: reduce-scatter gradients, run
# the optimizer on this rank's 1/N shard, allgather updated params
HVD_TPU_ZERO = "HVD_TPU_ZERO"
# flat parameter count below which the sharded update falls back to the
# replicated path (tiny models pay more in collective latency than they
# save in state memory)
HVD_TPU_ZERO_MIN_SIZE = "HVD_TPU_ZERO_MIN_SIZE"
# XLA data-plane executor: "psum" (flat hvd-axis mesh) | "mesh"
# (NamedSharding executor over the parallel.mesh dp-axis vocabulary)
HVD_TPU_EXECUTOR = "HVD_TPU_EXECUTOR"

# --- race detection (docs/race_detection.md) ---------------------------------
# install the hvd-race shim at import: traced threading/queue
# primitives + instrumented attribute access on the concurrency-scoped
# modules.  Off (the default) leaves the stock classes untouched and
# never imports the shim.
HVD_TPU_RACE = "HVD_TPU_RACE"
# schedule-fuzz seed: deterministic preemptions at instrumentation
# points (same contract as HVD_TPU_FAULT_SPEC — same seed, same
# decisions, same report)
HVD_TPU_RACE_SEED = "HVD_TPU_RACE_SEED"
# comma-separated module relpath suffixes to instrument ("all" =
# every horovod_tpu module outside tools/)
HVD_TPU_RACE_SCOPE = "HVD_TPU_RACE_SCOPE"
# report-file prefix: each shimmed process dumps its findings to
# <prefix>.<pid>.json at exit so the tier-1 gate can collect reports
# from launcher-spawned worker ranks
HVD_TPU_RACE_REPORT = "HVD_TPU_RACE_REPORT"

# --- protocol checking (docs/protocol_checking.md) ---------------------------
# bounded model-checker exploration depth, in steps: how far bin/hvd-proto
# explores each protocol's state graph before declaring it clean
HVD_TPU_PROTO_DEPTH = "HVD_TPU_PROTO_DEPTH"
# exploration tie-break seed — same seed + same depth give a
# byte-identical hvd-proto report (the hvd-race determinism contract)
HVD_TPU_PROTO_SEED = "HVD_TPU_PROTO_SEED"

# --- parser fuzzing (docs/fuzzing.md) ----------------------------------------
# deterministic mutation seed for bin/hvd-fuzz — same seed + same
# iteration count give a byte-identical run summary (the
# hvd-race/hvd-proto determinism contract)
HVD_TPU_FUZZ_SEED = "HVD_TPU_FUZZ_SEED"
# mutation iterations per fuzz target
HVD_TPU_FUZZ_ITERS = "HVD_TPU_FUZZ_ITERS"

# --- fault-tolerant collective runtime (docs/fault_tolerance.md) -------------
# bound on "abort initiated anywhere -> every rank raises HvdAbortedError"
HVD_TPU_ABORT_TIMEOUT = "HVD_TPU_ABORT_TIMEOUT"
# peer/coordinator heartbeat period on the persistent connections, seconds
HVD_TPU_HEARTBEAT_INTERVAL = "HVD_TPU_HEARTBEAT_INTERVAL"
# missed-heartbeat window: a rank silent for longer is declared dead and
# the coordinator converts the silence into a coordinated abort (0 = off)
HVD_TPU_LIVENESS_TIMEOUT = "HVD_TPU_LIVENESS_TIMEOUT"
# deadline budget for connection-establishment retry (backoff + jitter)
HVD_TPU_CONNECT_RETRY_SECONDS = "HVD_TPU_CONNECT_RETRY_SECONDS"
# deterministic fault injection spec (common/faults.py grammar)
HVD_TPU_FAULT_SPEC = "HVD_TPU_FAULT_SPEC"
# launcher escalation grace window: seconds between the SIGTERM it
# forwards to a worker process group and the SIGKILL follow-up — long
# enough for a drain + final checkpoint flush (docs/checkpoint.md)
HVD_TPU_TERM_GRACE = "HVD_TPU_TERM_GRACE"
# graceful drain: workers convert a SIGTERM (the preemption notice)
# into a planned departure instead of dying as a crash (default on;
# docs/checkpoint.md)
HVD_TPU_DRAIN = "HVD_TPU_DRAIN"

# --- degraded-network tolerance (docs/fault_tolerance.md) --------------------
# EWMA smoothing factor for per-peer RTT tracking (weight of the newest
# sample); the liveness window widens by an RTT-proportional slack so a
# slow-but-alive peer is not aborted as dead
HVD_TPU_RTT_ALPHA = "HVD_TPU_RTT_ALPHA"
# k of the straggler verdict (rank RTT > k x median for m windows) AND
# the multiplier of the RTT slack added to the liveness window
HVD_TPU_STRAGGLER_FACTOR = "HVD_TPU_STRAGGLER_FACTOR"
# m of the straggler verdict: consecutive liveness-scan windows a rank
# must exceed k x median before the verdict is recorded
HVD_TPU_STRAGGLER_WINDOWS = "HVD_TPU_STRAGGLER_WINDOWS"
# under elastic, a confirmed straggler is proposed for drain-style
# exclusion (boundary reconfiguration, no abort) instead of only logged
HVD_TPU_STRAGGLER_EXCLUDE = "HVD_TPU_STRAGGLER_EXCLUDE"

# --- self-healing transport (docs/fault_tolerance.md "connection blips") -----
# reconnect window: on a mid-stream connection break the sender heals
# the session in place (reconnect with backoff + session handshake +
# replay of the unacknowledged frames) for up to this many seconds
# before surfacing the original transport error to the abort/elastic
# path (0 = off: every break escalates immediately, the pre-session
# behavior, byte-identical on the wire)
HVD_TPU_RECONNECT_BUDGET = "HVD_TPU_RECONNECT_BUDGET"
# bound on the sender-side replay buffer of unacknowledged session
# frames (bytes); a heal that would need a frame older than the oldest
# retained one escalates instead of resuming with a silent gap
HVD_TPU_REPLAY_BUFFER_BYTES = "HVD_TPU_REPLAY_BUFFER_BYTES"

# --- elastic membership (docs/elastic.md) ------------------------------------
# survive rank loss: reconfigure membership instead of raising on abort
HVD_TPU_ELASTIC = "HVD_TPU_ELASTIC"
# budget for one reconfiguration window: survivors must re-rendezvous,
# rebuild the ring, and replay state within this many seconds
HVD_TPU_RECONFIG_TIMEOUT = "HVD_TPU_RECONFIG_TIMEOUT"
# below this many survivors the failure is fatal even under elastic
HVD_TPU_MIN_RANKS = "HVD_TPU_MIN_RANKS"
# cap on admitted membership after rejoins (0 = unlimited)
HVD_TPU_MAX_RANKS = "HVD_TPU_MAX_RANKS"
# coordinator fail-over: survive rank-0 loss via a CAS election at the
# rendezvous server instead of the fatal "coordinator unreachable" abort
HVD_TPU_COORD_FAILOVER = "HVD_TPU_COORD_FAILOVER"
# budget for one fail-over election round (CAS + directive adoption)
HVD_TPU_ELECTION_TIMEOUT = "HVD_TPU_ELECTION_TIMEOUT"

# --- durable sharded checkpointing (docs/checkpoint.md) ----------------------
# checkpoint directory (empty/unset = durable checkpointing off): each
# rank writes its param/optimizer shard there from the commit snapshot
HVD_TPU_CKPT_DIR = "HVD_TPU_CKPT_DIR"
# commit-steps between checkpoint snapshots (default 10)
HVD_TPU_CKPT_INTERVAL = "HVD_TPU_CKPT_INTERVAL"
# complete checkpoints retained before pruning (default 2; 0 = keep all)
HVD_TPU_CKPT_KEEP = "HVD_TPU_CKPT_KEEP"

# --- soak rig (bin/hvd-soak, docs/soak.md) -----------------------------------
# world size of the chaos soak (oversubscribed CPU mesh, multi-host
# simulated via per-rank host-hash salts)
HVD_TPU_SOAK_RANKS = "HVD_TPU_SOAK_RANKS"
# training steps each soak worker drives through elastic run()
HVD_TPU_SOAK_STEPS = "HVD_TPU_SOAK_STEPS"
# chaos seed for the soak's fault/degradation draw (bin/hvd-chaos)
HVD_TPU_SOAK_SEED = "HVD_TPU_SOAK_SEED"
# directory the SOAK_r*.json regression artifact is written to
# (empty/unset: repo root)
HVD_TPU_SOAK_REPORT = "HVD_TPU_SOAK_REPORT"
# gate: a reconfiguration slower than this many seconds fails the soak
HVD_TPU_SOAK_RECONFIG_BOUND = "HVD_TPU_SOAK_RECONFIG_BOUND"

# --- launcher -> worker contract (reference: gloo_run.py:152-157,261-273) ----
HVD_RANK = "HVD_RANK"
HVD_SIZE = "HVD_SIZE"
HVD_LOCAL_RANK = "HVD_LOCAL_RANK"
HVD_LOCAL_SIZE = "HVD_LOCAL_SIZE"
HVD_CROSS_RANK = "HVD_CROSS_RANK"
HVD_CROSS_SIZE = "HVD_CROSS_SIZE"
HVD_SECRET_KEY = "HVD_SECRET_KEY"              # base64 job secret (HMAC)
HVD_RENDEZVOUS_ADDR = "HVD_RENDEZVOUS_ADDR"
HVD_RENDEZVOUS_PORT = "HVD_RENDEZVOUS_PORT"
HVD_CONTROLLER_ADDR = "HVD_CONTROLLER_ADDR"
HVD_IFACE = "HVD_IFACE"
HVD_GLOBAL_MESH = "HVD_GLOBAL_MESH"            # pod mode: one global jax mesh
HVD_HOST_SLOTS = "HVD_HOST_SLOTS"      # "h1:n1,h2:n2" rank-block layout
HVD_COORDINATOR_ADDR = "HVD_COORDINATOR_ADDR"  # jax.distributed coordinator
HVD_START_TIMEOUT = "HVD_START_TIMEOUT"  # gang-start deadline, s (default 120)
# explicit rendezvous-reachability override for the launcher host
HVD_RENDEZVOUS_HOST_ADDR = "HVD_RENDEZVOUS_HOST_ADDR"
# task-server bootstrap (run/service/task_main.py; secret rides stdin)
HVD_TASK_INDEX = "HVD_TASK_INDEX"
HVD_DRIVER_ADDRS = "HVD_DRIVER_ADDRS"          # "ip:port;ip:port"
HVD_TASK_TIMEOUT = "HVD_TASK_TIMEOUT"          # seconds, default 120
# optional host-identity salt: containerized deployments where every
# container reports the same hostname force distinct host hashes —
# set in the deployment environment, deliberately not an hvdrun flag
HVD_HOSTNAME_HASH_SALT = "HVD_HOSTNAME_HASH_SALT"  # hvd-lint: ignore[config-surface]

# The launcher -> worker contract above is exempt from the tri-surface
# rule: these variables are how hvdrun TALKS to workers, not user
# knobs, so they deliberately have no CLI flag or YAML key.
# (hvd-lint's config-surface checker reads this declaration.)
LAUNCHER_CONTRACT = frozenset({
    HVD_RANK, HVD_SIZE, HVD_LOCAL_RANK, HVD_LOCAL_SIZE,
    HVD_CROSS_RANK, HVD_CROSS_SIZE, HVD_SECRET_KEY,
    HVD_RENDEZVOUS_ADDR, HVD_RENDEZVOUS_PORT, HVD_CONTROLLER_ADDR,
    HVD_GLOBAL_MESH, HVD_HOST_SLOTS, HVD_COORDINATOR_ADDR,
    HVD_RENDEZVOUS_HOST_ADDR, HVD_TASK_INDEX, HVD_DRIVER_ADDRS,
    HVD_TASK_TIMEOUT,
})

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024
DEFAULT_CYCLE_TIME_MS = 1.0
DEFAULT_RING_SEGMENT_BYTES = 1 << 20
DEFAULT_RING_STRIPES = 2
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECONDS = 60
DEFAULT_ABORT_TIMEOUT_SECONDS = 30.0
DEFAULT_HEARTBEAT_INTERVAL_SECONDS = 2.0
DEFAULT_LIVENESS_TIMEOUT_SECONDS = 15.0
DEFAULT_CONNECT_RETRY_SECONDS = 30.0
DEFAULT_RECONFIG_TIMEOUT_SECONDS = 60.0
DEFAULT_MIN_RANKS = 1
DEFAULT_MAX_RANKS = 0  # unlimited
DEFAULT_ELECTION_TIMEOUT_SECONDS = 10.0
DEFAULT_ZERO_MIN_SIZE = 1024  # flat params below this stay replicated
DEFAULT_GROUP_MAX = 64  # live process groups per job
DEFAULT_TERM_GRACE_SECONDS = 5.0
DEFAULT_CKPT_INTERVAL_STEPS = 10
DEFAULT_CKPT_KEEP = 2
DEFAULT_RTT_ALPHA = 0.25
# session heal is opt-in: a dead peer must keep surfacing through the
# abort/liveness path with the seed-era timings until a deployment
# explicitly grants a reconnect window
DEFAULT_RECONNECT_BUDGET_SECONDS = 0.0
DEFAULT_REPLAY_BUFFER_BYTES = 64 << 20
DEFAULT_STRAGGLER_FACTOR = 4.0
DEFAULT_STRAGGLER_WINDOWS = 3
DEFAULT_SOAK_RANKS = 16
DEFAULT_SOAK_STEPS = 20
DEFAULT_SOAK_SEED = 11
DEFAULT_SOAK_RECONFIG_BOUND = 45.0


# A malformed knob value must not silently vanish into the default
# (HVD_TPU_RING_STRIPES="two" looking exactly like an unset knob cost
# real debugging time) — warn ONCE per variable, naming the bad value
# and the default actually used.  Stdlib logging on the framework's
# logger name: utils/logging.py configures that logger (and imports
# this module, so this module must not import it back); unconfigured
# processes still see the warning through logging's last-resort
# stderr handler.
_warned = set()
_warned_lock = threading.Lock()

_TRUE_WORDS = ("1", "true", "yes", "on")
_FALSE_WORDS = ("0", "false", "no", "off")


def _warn_malformed(name, value, default):
    with _warned_lock:
        if name in _warned:
            return
        # mark BEFORE logging: a handler that itself reads this knob
        # re-enters quietly instead of recursing
        _warned.add(name)
    logging.getLogger("horovod_tpu").warning(
        "ignoring malformed %s=%r: using default %r", name, value,
        default)


def _reset_warnings():
    """Test hook: forget which knobs have already warned."""
    with _warned_lock:
        _warned.clear()


def get_int(name, default=0):
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return int(value)
    except ValueError:
        _warn_malformed(name, value, default)
        return default


def get_float(name, default=0.0):
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return float(value)
    except ValueError:
        _warn_malformed(name, value, default)
        return default


def get_bool(name, default=False):
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    word = value.strip().lower()
    if word in _TRUE_WORDS:
        return True
    if word not in _FALSE_WORDS:
        _warn_malformed(name, value, default)
        return default
    return False


def get_str(name, default=None):
    value = os.environ.get(name)
    return default if value in (None, "") else value


def get_required(name):
    """A launcher-contract variable that MUST be present (task/worker
    entry points): missing means the process was started outside its
    launcher — fail with the contract named instead of a KeyError."""
    value = os.environ.get(name)
    if value in (None, ""):
        raise RuntimeError(
            f"required environment variable {name} is not set — this "
            f"process expects the hvdrun launcher env contract")
    return value
