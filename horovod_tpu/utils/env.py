"""Environment-variable configuration surface.

Mirrors the reference's knob list (``horovod/common/common.h:61-88`` and
``horovod/common/utils/env_parser.cc``) under the ``HVD_`` prefix, with
TPU-appropriate defaults.  The launcher additionally exposes every knob as an
``hvdrun`` CLI flag and a YAML config-file key, keeping the reference's
tri-surface config model.
"""

import os

# --- knob names (reference: horovod/common/common.h:61-88) -------------------
HVD_FUSION_THRESHOLD = "HVD_FUSION_THRESHOLD"          # bytes, default 64 MB
HVD_CYCLE_TIME = "HVD_CYCLE_TIME"                      # ms, default 1.0
HVD_CACHE_CAPACITY = "HVD_CACHE_CAPACITY"              # default 1024
HVD_TIMELINE = "HVD_TIMELINE"                          # path -> enable timeline
HVD_TIMELINE_MARK_CYCLES = "HVD_TIMELINE_MARK_CYCLES"
HVD_STALL_CHECK_DISABLE = "HVD_STALL_CHECK_DISABLE"
HVD_STALL_CHECK_TIME_SECONDS = "HVD_STALL_CHECK_TIME_SECONDS"
HVD_STALL_SHUTDOWN_TIME_SECONDS = "HVD_STALL_SHUTDOWN_TIME_SECONDS"
HVD_HIERARCHICAL_ALLREDUCE = "HVD_HIERARCHICAL_ALLREDUCE"
HVD_HIERARCHICAL_ALLGATHER = "HVD_HIERARCHICAL_ALLGATHER"
HVD_HIER_LOCAL_SIZE = "HVD_HIER_LOCAL_SIZE"    # ranks per fast (ICI) group
HVD_ADASUM_HIERARCHICAL = "HVD_ADASUM_HIERARCHICAL"  # opt-in: different math
HVD_AUTOTUNE = "HVD_AUTOTUNE"
HVD_AUTOTUNE_LOG = "HVD_AUTOTUNE_LOG"
HVD_AUTOTUNE_WARMUP_SAMPLES = "HVD_AUTOTUNE_WARMUP_SAMPLES"
HVD_AUTOTUNE_STEADY_STATE_SAMPLES = "HVD_AUTOTUNE_STEADY_STATE_SAMPLES"
HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HVD_LOG_LEVEL = "HVD_LOG_LEVEL"
HVD_LOG_HIDE_TIME = "HVD_LOG_HIDE_TIME"
HVD_CONTROLLER = "HVD_CONTROLLER"                      # native | python | tcp
HVD_CPU_OPERATIONS = "HVD_CPU_OPERATIONS"              # xla | ring | python
HVD_ADASUM_CHUNK_SIZE = "HVD_ADASUM_CHUNK_SIZE"
HVD_NUM_STREAMS = "HVD_NUM_STREAMS"
# default on-the-wire allreduce compression: none | bf16 | fp16 | int8
# (block-scaled int8, EQuARX arXiv:2506.17615)
HVD_TPU_COMPRESSION = "HVD_TPU_COMPRESSION"
# TCP-ring pipeline segment size in bytes (0 = unsegmented): each ring
# step's chunk is split into segments so the send of segment k+1
# overlaps the recv+accumulate of segment k (docs/tuning.md)
HVD_TPU_RING_SEGMENT_BYTES = "HVD_TPU_RING_SEGMENT_BYTES"
# dedicated bulk-data connections per ring peer, separate from the
# control connection (heartbeats never queue behind chunk writes)
HVD_TPU_RING_STRIPES = "HVD_TPU_RING_STRIPES"

# --- fault-tolerant collective runtime (docs/fault_tolerance.md) -------------
# bound on "abort initiated anywhere -> every rank raises HvdAbortedError"
HVD_TPU_ABORT_TIMEOUT = "HVD_TPU_ABORT_TIMEOUT"
# peer/coordinator heartbeat period on the persistent connections, seconds
HVD_TPU_HEARTBEAT_INTERVAL = "HVD_TPU_HEARTBEAT_INTERVAL"
# missed-heartbeat window: a rank silent for longer is declared dead and
# the coordinator converts the silence into a coordinated abort (0 = off)
HVD_TPU_LIVENESS_TIMEOUT = "HVD_TPU_LIVENESS_TIMEOUT"
# deadline budget for connection-establishment retry (backoff + jitter)
HVD_TPU_CONNECT_RETRY_SECONDS = "HVD_TPU_CONNECT_RETRY_SECONDS"
# deterministic fault injection spec (common/faults.py grammar)
HVD_TPU_FAULT_SPEC = "HVD_TPU_FAULT_SPEC"

# --- launcher -> worker contract (reference: gloo_run.py:152-157,261-273) ----
HVD_RANK = "HVD_RANK"
HVD_SIZE = "HVD_SIZE"
HVD_LOCAL_RANK = "HVD_LOCAL_RANK"
HVD_LOCAL_SIZE = "HVD_LOCAL_SIZE"
HVD_CROSS_RANK = "HVD_CROSS_RANK"
HVD_CROSS_SIZE = "HVD_CROSS_SIZE"
HVD_SECRET_KEY = "HVD_SECRET_KEY"              # base64 job secret (HMAC)
HVD_RENDEZVOUS_ADDR = "HVD_RENDEZVOUS_ADDR"
HVD_RENDEZVOUS_PORT = "HVD_RENDEZVOUS_PORT"
HVD_CONTROLLER_ADDR = "HVD_CONTROLLER_ADDR"
HVD_IFACE = "HVD_IFACE"
HVD_GLOBAL_MESH = "HVD_GLOBAL_MESH"            # pod mode: one global jax mesh
HVD_HOST_SLOTS = "HVD_HOST_SLOTS"      # "h1:n1,h2:n2" rank-block layout
HVD_COORDINATOR_ADDR = "HVD_COORDINATOR_ADDR"  # jax.distributed coordinator
HVD_START_TIMEOUT = "HVD_START_TIMEOUT"  # gang-start deadline, s (default 120)

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024
DEFAULT_CYCLE_TIME_MS = 1.0
DEFAULT_RING_SEGMENT_BYTES = 1 << 20
DEFAULT_RING_STRIPES = 2
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECONDS = 60
DEFAULT_ABORT_TIMEOUT_SECONDS = 30.0
DEFAULT_HEARTBEAT_INTERVAL_SECONDS = 2.0
DEFAULT_LIVENESS_TIMEOUT_SECONDS = 15.0
DEFAULT_CONNECT_RETRY_SECONDS = 30.0


def get_int(name, default=0):
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return int(value)
    except ValueError:
        return default


def get_float(name, default=0.0):
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return float(value)
    except ValueError:
        return default


def get_bool(name, default=False):
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    return value.strip().lower() in ("1", "true", "yes", "on")


def get_str(name, default=None):
    value = os.environ.get(name)
    return default if value in (None, "") else value
