"""Chrome-tracing timeline for per-tensor collective lifecycles.

Mirrors the reference Timeline (``horovod/common/timeline.{h,cc}``): enabled
by ``HVD_TIMELINE=<file>``, one trace row (pid) per tensor name, phases
NEGOTIATE_<OP> (with per-rank ready ticks) → QUEUE → <OP> with nested
activities (fusion-buffer staging, XLA dispatch), ending with an output-size
annotation.  A dedicated writer thread drains an unbounded queue so the hot
path never blocks on file IO (reference uses a boost lockfree SPSC queue,
``timeline.h:68``).  Load the output in ``chrome://tracing`` / Perfetto.

The native (C++) core has its own writer; this Python implementation backs the
``python`` controller and is also used as the fallback when the native core is
not built.
"""

import json
import queue
import threading
import time


class TimelineWriter:
    """Background JSON writer (reference: TimelineWriter, timeline.cc:47)."""

    def __init__(self, path):
        self._path = path
        self._queue = queue.Queue()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-timeline-writer")
        self._running = True
        self._thread.start()

    def enqueue(self, record: dict):
        if self._running:
            self._queue.put(record)

    def _run(self):
        while True:
            record = self._queue.get()
            if record is None:
                break
            if not self._first:
                self._file.write(",\n")
            self._first = False
            self._file.write(json.dumps(record))
        self._file.write("\n]\n")
        self._file.close()

    def close(self):
        if self._running:
            self._running = False
            self._queue.put(None)
            self._thread.join(timeout=5)


class Timeline:
    """Per-tensor lifecycle recorder. All ranks share rank-0's file, as in the
    reference (rank 0 writes for everyone)."""

    def __init__(self, path=None, mark_cycles=False):
        self._writer = TimelineWriter(path) if path else None
        self._closed = False
        self._mark_cycles = mark_cycles
        self._lock = threading.Lock()
        self._pids = {}
        self._next_pid = 1
        self._start = time.monotonic()
        if self._writer is not None:
            # wall-clock epoch of ts==0, so multi-rank merges can align
            # traces from processes that started at different times
            # (hosts are assumed NTP-synced, as chrome tracing itself
            # assumes for multi-process captures)
            self._writer.enqueue({
                "name": "hvd_epoch", "ph": "M", "pid": 0,
                "args": {"epoch_us": int(time.time() * 1e6)},
            })

    @property
    def enabled(self):
        return self._writer is not None and not self._closed

    def _ts(self):
        return int((time.monotonic() - self._start) * 1e6)

    def _pid(self, tensor_name):
        with self._lock:
            pid = self._pids.get(tensor_name)
            if pid is None:
                pid = self._next_pid
                self._next_pid += 1
                self._pids[tensor_name] = pid
                self._writer.enqueue({
                    "name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": tensor_name},
                })
            return pid

    def begin(self, tensor_name, phase):
        if not self.enabled:
            return
        self._writer.enqueue({"name": phase, "ph": "B", "ts": self._ts(),
                              "pid": self._pid(tensor_name), "tid": 0})

    def end(self, tensor_name, args=None):
        if not self.enabled:
            return
        record = {"ph": "E", "ts": self._ts(),
                  "pid": self._pid(tensor_name), "tid": 0}
        if args:
            record["args"] = args
        self._writer.enqueue(record)

    def instant(self, tensor_name, name):
        """Per-rank ready tick during negotiation (reference:
        controller.cc:797-809 RecordNegotiate ticks)."""
        if not self.enabled:
            return
        self._writer.enqueue({"name": name, "ph": "i", "ts": self._ts(),
                              "pid": self._pid(tensor_name), "tid": 0,
                              "s": "p"})

    def mark_cycle(self):
        """Background-loop cycle marker (HVD_TIMELINE_MARK_CYCLES; reference:
        operations.cc:562-565)."""
        if self.enabled and self._mark_cycles:
            pid = self._pid("CYCLE")
            self._writer.enqueue({"name": "CYCLE", "ph": "i", "ts": self._ts(),
                                  "pid": pid, "tid": 0, "s": "g"})

    def close(self):
        # a recorder thread may have passed its `enabled` check already;
        # keep the writer object reachable (enqueue after close is a
        # no-op) instead of nulling it under their feet
        writer = self._writer
        if writer:
            self._closed = True
            writer.close()


def publish_and_merge(rank, size, base_path, timeline, scope="timeline"):
    """Rank-0 aggregation over the rendezvous KV: every rank uploads its
    per-process trace; rank 0 merges them into ``base_path`` (reference:
    rank 0 writes one timeline for all ranks, ``timeline.cc``).  Used by
    both the tcp and global-mesh controllers at shutdown."""
    from horovod_tpu.run import http_client
    from horovod_tpu.utils import env as env_util
    from horovod_tpu.utils.logging import get_logger

    addr = env_util.get_str(env_util.HVD_RENDEZVOUS_ADDR)
    if not base_path or addr is None:
        return
    port = env_util.get_int(env_util.HVD_RENDEZVOUS_PORT, 0)

    timeline.close()
    my_path = f"{base_path}.rank{rank}"
    try:
        with open(my_path) as f:
            content = f.read()
    except OSError:
        content = "[]"
    try:
        http_client.put(addr, port, scope, str(rank), content.encode())
    except OSError as exc:
        from horovod_tpu.utils.logging import get_logger as _gl

        _gl().warning("timeline publish failed for rank %d: %s", rank, exc)
        if rank != 0:
            return
        # rank 0 already holds its own content — the merge of every
        # OTHER rank's trace does not depend on this upload
    if rank == 0:
        contents = {0: content}
        for r in range(1, size):
            try:
                contents[r] = http_client.get(addr, port, scope, str(r),
                                              timeout=20).decode()
            except (OSError, TimeoutError, KeyError):
                get_logger().warning(
                    "timeline merge: rank %d trace unavailable", r)
        try:
            merge_timeline_contents(contents, base_path)
        except (ValueError, OSError) as exc:
            get_logger().warning("timeline merge failed: %s", exc)


def merge_timeline_contents(contents, out_path):
    """Merge per-rank chrome traces into one file (reference: rank 0
    writes a single timeline for all ranks, ``timeline.cc``).

    ``contents``: {rank: json_text}.  Tensor rows (pids) are offset per
    rank and process_name metadata is prefixed with the rank so every
    rank's lifecycle is visible side by side in chrome://tracing.
    """
    parsed = {}
    epochs = {}
    for rank in sorted(contents):
        try:
            events = json.loads(contents[rank])
        except json.JSONDecodeError:
            from horovod_tpu.utils.logging import get_logger as _gl

            _gl().warning(
                "timeline merge: rank %d trace is not valid JSON "
                "(truncated flush?) — omitted from the merged view",
                rank)
            continue
        parsed[rank] = events
        for event in events:
            if event.get("name") == "hvd_epoch":
                epochs[rank] = event.get("args", {}).get("epoch_us", 0)
                break
    base_epoch = min(epochs.values()) if epochs else 0

    merged = []
    for rank, events in parsed.items():
        offset = (rank + 1) * 100000
        # shift each rank's relative timestamps onto the shared epoch so
        # concurrent events line up in the viewer
        shift = epochs.get(rank, base_epoch) - base_epoch
        for event in events:
            event = dict(event)
            if event.get("name") == "hvd_epoch":
                continue
            if "pid" in event:
                event["pid"] = event["pid"] + offset
            if "ts" in event:
                event["ts"] = event["ts"] + shift
            if event.get("name") == "process_name":
                args = dict(event.get("args") or {})
                args["name"] = f"rank {rank}: {args.get('name', '')}"
                event["args"] = args
            merged.append(event)
    with open(out_path, "w") as f:
        json.dump(merged, f)
