"""Leveled logging, the Python face of the core's logger.

Mirrors the reference's glog-style macros (``horovod/common/logging.h``):
levels TRACE/DEBUG/INFO/WARNING/ERROR/FATAL selected by ``HVD_LOG_LEVEL``,
timestamps suppressible with ``HVD_LOG_HIDE_TIME``.
"""

import logging
import sys

from horovod_tpu.utils import env as env_util

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_logger = None


def get_logger():
    global _logger
    if _logger is not None:
        return _logger
    logger = logging.getLogger("horovod_tpu")
    level_name = env_util.get_str(
        env_util.HVD_LOG_LEVEL, "warning").strip().lower()
    logger.setLevel(_LEVELS.get(level_name, logging.WARNING))
    handler = logging.StreamHandler(sys.stderr)
    if env_util.get_bool(env_util.HVD_LOG_HIDE_TIME):
        fmt = "[%(levelname)s] %(message)s"
    else:
        fmt = "%(asctime)s [%(levelname)s] %(message)s"
    handler.setFormatter(logging.Formatter(fmt))
    logger.addHandler(handler)
    logger.propagate = False
    _logger = logger
    return logger
