"""TPU-native input pipeline: sharded batch iteration + device prefetch.

The reference's data path is framework loaders feeding each rank its own
shard — torch ``DataLoader`` + ``DistributedSampler`` in the examples,
and Petastorm readers over per-rank Parquet row groups in the estimators
(``horovod/spark/keras/remote.py``: ``cur_shard=hvd.rank(),
shard_count=hvd.size()``).  The TPU equivalent below keeps the same
contract (disjoint per-rank shards, deterministic per-epoch shuffling)
and adds the piece TPU training actually needs: **device prefetch**.
An XLA training step dispatches asynchronously; if the NEXT batch's
host→device transfer only starts when the step returns, the HBM copy
sits on the critical path.  ``prefetch_to_device`` overlaps the copy
with compute via a background thread and a bounded queue, handing the
step loop batches that are already device-resident ``jax.Array``s.

Pieces:

- :class:`BatchIterator` — batches over in-memory arrays (the
  ``read_shard`` output), per-epoch seeded reshuffle.
- :class:`ParquetShardIterator` — streams THIS rank's Parquet row groups
  (``rg % shard_count == cur_shard``) one group at a time, so the shard
  never has to fit in host memory at once.
- :func:`prefetch_to_device` — background host→device staging; accepts a
  ``jax.sharding.Sharding`` for SPMD global batches or a ``Mesh`` (uses
  :func:`horovod_tpu.parallel.mesh.shard_global_batch` per batch).
"""

import queue
import threading

import numpy as np

__all__ = ["BatchIterator", "ParquetShardIterator", "prefetch_to_device",
           "lockstep_plan", "lockstep_shard_batches", "min_shard_rows",
           "require_sharded_store"]


def _tree_rows(data):
    """Leading-dim length of a {name: array} dict / tuple / array."""
    if isinstance(data, dict):
        arrays = list(data.values())
    elif isinstance(data, (tuple, list)):
        arrays = list(data)
    else:
        arrays = [data]
    if not arrays:
        raise ValueError("empty batch structure")
    rows = {int(np.shape(a)[0]) for a in arrays}
    if len(rows) != 1:
        raise ValueError(f"ragged leading dims: {sorted(rows)}")
    return rows.pop()


def _tree_take(data, idx):
    if isinstance(data, dict):
        return {k: v[idx] for k, v in data.items()}
    if isinstance(data, (tuple, list)):
        return type(data)(v[idx] for v in data)
    return data[idx]


class BatchIterator:
    """Deterministic batcher over in-memory per-rank shard data.

    ``data``: ``{name: array}`` dict (the ``ParquetStore.read_shard``
    output), tuple of arrays, or one array — batches keep the structure.
    ``shuffle``: reshuffles every epoch with ``seed + epoch`` so runs are
    reproducible and ranks (which hold disjoint shards) need no
    coordination — the reference gets the same property from
    ``DistributedSampler.set_epoch``.
    ``epochs=None`` iterates forever (the training-loop default: the
    step count, not the iterator, ends training).
    """

    def __init__(self, data, batch_size, *, shuffle=False, seed=0,
                 drop_remainder=True, epochs=1):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be > 0, got {batch_size}")
        self._data = data
        self._rows = _tree_rows(data)
        if self._rows == 0:
            raise ValueError("shard has zero rows")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epochs = epochs
        if drop_remainder and self._rows < batch_size:
            raise ValueError(
                f"shard rows ({self._rows}) < batch_size ({batch_size}) "
                f"with drop_remainder — every epoch would be empty")

    @property
    def batches_per_epoch(self):
        if self.drop_remainder:
            return self._rows // self.batch_size
        return -(-self._rows // self.batch_size)

    def __iter__(self):
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            if self.shuffle:
                order = np.random.default_rng(
                    self.seed + epoch).permutation(self._rows)
            else:
                order = np.arange(self._rows)
            stop = (self._rows - self._rows % self.batch_size
                    if self.drop_remainder else self._rows)
            for lo in range(0, stop, self.batch_size):
                yield _tree_take(self._data,
                                 order[lo:lo + self.batch_size])
            epoch += 1


class ParquetShardIterator:
    """Stream this rank's Parquet row groups into batches, one group in
    memory at a time.

    Matches ``ParquetStore.read_shard`` semantics (disjoint row groups
    ``rg % shard_count == cur_shard``, reference Petastorm wiring in
    ``horovod/spark/keras/remote.py``) without materializing the whole
    shard: rows left over when a group is exhausted carry into the next
    group's batches, so batch boundaries don't leak the row-group size.
    ``shuffle`` permutes the rank's row-group ORDER per epoch and the
    rows inside each group (window shuffle — the memory bound is one
    row group, same trade-off as Petastorm's shuffling buffer).
    """

    def __init__(self, store, cur_shard, shard_count, batch_size, *,
                 split="train", idx=None, columns=None, shuffle=False,
                 seed=0, drop_remainder=True, epochs=1):
        if not 0 <= cur_shard < shard_count:
            raise ValueError(
                f"cur_shard {cur_shard} outside [0, {shard_count})")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be > 0, got {batch_size}")
        self._store = store
        self._cur_shard = cur_shard
        self._shard_count = shard_count
        self._split = split
        self._idx = idx
        self._columns = columns
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epochs = epochs
        pf = store._open(split, idx)
        self._groups = [rg for rg in range(pf.metadata.num_row_groups)
                        if rg % shard_count == cur_shard]
        if not self._groups:
            raise ValueError(
                f"shard {cur_shard}/{shard_count} holds no row groups "
                f"({pf.metadata.num_row_groups} total) — rewrite with "
                f"smaller rows_per_row_group or fewer ranks")
        rows = sum(pf.metadata.row_group(rg).num_rows
                   for rg in self._groups)
        if drop_remainder and rows < batch_size:
            # same check BatchIterator does in __init__: an epoch that
            # yields nothing must fail loudly at construction, not run
            # zero training steps silently
            raise ValueError(
                f"shard {cur_shard}/{shard_count} rows ({rows}) < "
                f"batch_size ({batch_size}) with drop_remainder — "
                f"every epoch would be empty")

    def _read_group(self, pf, rg, schema_meta):
        table = pf.read_row_groups([rg], columns=self._columns)
        return self._store._to_numpy(table, schema_meta, table.num_rows)

    def __iter__(self):
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            rng = (np.random.default_rng(self.seed + epoch)
                   if self.shuffle else None)
            groups = list(self._groups)
            if rng is not None:
                rng.shuffle(groups)
            pf = self._store._open(self._split, self._idx)
            schema_meta = pf.schema_arrow.metadata
            pending = None  # carry-over rows smaller than batch_size
            for rg in groups:
                chunk = self._read_group(pf, rg, schema_meta)
                if rng is not None:
                    chunk = _tree_take(
                        chunk, rng.permutation(_tree_rows(chunk)))
                if pending is not None:
                    chunk = {k: np.concatenate([pending[k], v])
                             for k, v in chunk.items()}
                rows = _tree_rows(chunk)
                stop = rows - rows % self.batch_size
                for lo in range(0, stop, self.batch_size):
                    yield _tree_take(chunk,
                                     slice(lo, lo + self.batch_size))
                pending = (_tree_take(chunk, slice(stop, rows))
                           if stop < rows else None)
            if pending is not None and not self.drop_remainder:
                yield pending
            epoch += 1


def require_sharded_store(store):
    """Fail fast (before any I/O) when a store has no row-group layout
    to stream."""
    if not hasattr(store, "shard_row_counts"):
        raise ValueError(
            "streaming=True needs a sharded-dataset store "
            "(ParquetStore/FilesystemStore); this store has no "
            "row-group layout to stream")


def min_shard_rows(store, num_ranks):
    """Smallest shard's row count (footer metadata only), with the same
    clear empty-shard error ``read_shard`` raises — streaming must not
    degrade it to a ZeroDivisionError downstream."""
    counts = store.shard_row_counts(num_ranks)
    if min(counts) == 0:
        raise ValueError(
            f"shard {counts.index(0)} of {num_ranks} would be empty — "
            f"rewrite with smaller rows_per_row_group or fewer ranks")
    return min(counts)


def lockstep_plan(store, num_ranks, batch_size, epochs):
    """The lockstep trim: (clamped batch_size, steps_per_epoch, total
    steps) derived from the SMALLEST shard, identical on every rank —
    a rank running more per-batch collective rounds than its peers
    hangs the gang.  The streamed analog of ``read_shard``'s
    equal-shard trim; single source of truth for all three estimators'
    streaming paths."""
    rows = min_shard_rows(store, num_ranks)
    batch_size = min(batch_size, rows)
    steps_per_epoch = max(rows // batch_size, 1)
    return batch_size, steps_per_epoch, epochs * steps_per_epoch


def lockstep_shard_batches(store, rank, num_ranks, batch_size, epochs):
    """One rank's streamed batches under the :func:`lockstep_plan` cap
    (JAX and torch eager streaming paths)."""
    import itertools

    batch_size, _, steps = lockstep_plan(store, num_ranks, batch_size,
                                         epochs)
    return itertools.islice(
        iter(ParquetShardIterator(store, rank, num_ranks, batch_size,
                                  epochs=None)), steps)


def prefetch_to_device(iterator, size=2, *, sharding=None, mesh=None,
                       axis=None):
    """Stage batches onto device ahead of the training loop.

    A daemon thread pulls host batches from ``iterator``, moves them to
    device, and parks up to ``size`` device-resident batches in a
    bounded queue — the host→device copy of batch N+1 overlaps the
    compute of batch N instead of serializing after it.  ``size=2`` is
    the classic double buffer; more only helps when batch copy time is
    burstier than step time.

    Placement: default is ``jax.device_put`` to the default device
    (single-chip path); pass ``sharding`` (any ``jax.sharding.Sharding``)
    to lay the batch out for SPMD, or ``mesh`` (+ optional ``axis``) to
    build a multi-host GLOBAL batch from per-process local rows via
    :func:`horovod_tpu.parallel.mesh.shard_global_batch`.

    Source-iterator exceptions re-raise at the consuming ``next()`` —
    a data-path failure must fail the step loop, not silently end the
    epoch early.
    """
    import jax

    if size <= 0:
        raise ValueError(f"size must be > 0, got {size}")
    if sharding is not None and mesh is not None:
        raise ValueError("pass sharding OR mesh, not both")

    if mesh is not None:
        from horovod_tpu.parallel.mesh import MeshAxes, shard_global_batch

        axis = axis or MeshAxes.HVD

        def put(x):
            return shard_global_batch(np.asarray(x), mesh=mesh, axis=axis)
    elif sharding is not None:
        def put(x):
            return jax.device_put(x, sharding)
    else:
        put = jax.device_put

    q = queue.Queue(maxsize=size)
    sentinel = object()
    stop = threading.Event()

    def _put(item):
        # bounded put that gives up when the consumer has stopped — a
        # plain q.put would block this thread forever if the training
        # loop exits early, pinning device batches and the source
        # iterator until process exit
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in iterator:
                if stop.is_set() or \
                        not _put(jax.tree.map(put, batch)):
                    return
            _put(sentinel)
        except BaseException as exc:  # noqa: BLE001 — re-raised consumer-side
            _put((sentinel, exc))

    # start staging NOW (not at first next()): the whole point is the
    # first batch being on device before the loop asks for it
    producer_thread = threading.Thread(target=producer, daemon=True)
    producer_thread.start()

    def consume():
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    return
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] is sentinel:
                    raise item[1]
                yield item
        finally:
            # consumer done (exhausted, errored, or closed early):
            # release the producer and any queued device batches.  One
            # drain pass is not enough: a producer already inside q.put
            # when stop is set can land one more item after the drain,
            # pinning a device-resident batch until garbage collection.
            # _put re-checks stop before every attempt, so that window
            # closes within one put timeout (0.2s) — keep draining until
            # the producer exits or that window has passed; never block
            # on the SOURCE iterator, which may legally stall.
            import time as _time

            stop.set()
            deadline = _time.monotonic() + 1.0
            while True:
                try:
                    q.get_nowait()
                    continue
                except queue.Empty:
                    pass
                producer_thread.join(timeout=0.05)
                if not producer_thread.is_alive() \
                        or _time.monotonic() > deadline:
                    break
            while True:  # whatever landed during the final join
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    return consume()
