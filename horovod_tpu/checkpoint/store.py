"""Durable checkpoint file formats (docs/checkpoint.md).

On-disk layout inside ``HVD_TPU_CKPT_DIR``::

    s{step:012d}-e{epoch}-w{world}-r{rank}.shard       one per rank
    s{step:012d}-e{epoch}-w{world}-r{rank}.meta.json   sha256 + size
    manifest-s{step:012d}-e{epoch}-w{world}.json       rank 0, written last

Every file is written tmp + ``os.replace`` (atomic on POSIX), and the
meta sidecar lands AFTER its shard — so the digest only ever describes
a fully-renamed shard.  Completeness is a READ-time property: a
manifest is usable iff all ``world`` shards exist and every shard's
bytes hash to its recorded digest.  "Rank 0" means whoever holds rank
0 at write time — after a coordinator fail-over the elected root
authors the manifests (its stable worker id is recorded as
``root_wid``), and readers accept complete manifests from any author.  A job killed mid-write therefore
leaves a manifest that simply fails validation and the reader falls
back to the previous complete one; nothing needs fsync-ordered
bookkeeping beyond the rename barrier.
"""

import hashlib
import json
import os
import re

_SHARD_RE = re.compile(
    r"^s(\d{12})-e(\d+)-w(\d+)-r(\d+)\.shard$")
_MANIFEST_RE = re.compile(
    r"^manifest-s(\d{12})-e(\d+)-w(\d+)\.json$")

MANIFEST_FORMAT = 1


class CorruptShardError(RuntimeError):
    """A shard (or its meta sidecar) is missing, truncated, or fails
    its digest — the enclosing manifest is incomplete."""


def shard_name(step, epoch, world, rank) -> str:
    return f"s{step:012d}-e{epoch}-w{world}-r{rank}.shard"


def manifest_name(step, epoch, world) -> str:
    return f"manifest-s{step:012d}-e{epoch}-w{world}.json"


def _codec():
    """Payload codec: flax msgpack when present (the jax toolchain
    ships it), stdlib pickle otherwise.  Recorded per shard so a reader
    never guesses."""
    try:
        import flax.serialization  # noqa: F401
        return "msgpack"
    except ImportError:
        return "pickle"


def _dumps(obj, codec):
    if codec == "msgpack":
        from flax.serialization import msgpack_serialize
        return msgpack_serialize(obj)
    import pickle
    return pickle.dumps(obj)


def _loads(blob, codec):
    if codec == "msgpack":
        from flax.serialization import msgpack_restore
        return msgpack_restore(blob)
    import pickle
    # wire-safe: not wire input — a local checkpoint file this process
    # (or a prior incarnation of this job) wrote, sha256-verified
    # against its meta sidecar before reaching the unpickler
    return pickle.loads(blob)


def _atomic_write(path, data: bytes):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_shard(directory, step, epoch, world, rank, payload: dict):
    """Serialize ``payload`` into this rank's shard + meta sidecar."""
    codec = _codec()
    blob = _dumps(payload, codec)
    name = shard_name(step, epoch, world, rank)
    path = os.path.join(directory, name)
    _atomic_write(path, blob)
    meta = {"sha256": hashlib.sha256(blob).hexdigest(),
            "bytes": len(blob), "codec": codec}
    _atomic_write(f"{path}.meta.json",
                  json.dumps(meta).encode())
    return name


def read_shard(directory, step, epoch, world, rank) -> dict:
    """Load + digest-verify one shard; :class:`CorruptShardError` on
    any missing/torn/forged piece."""
    name = shard_name(step, epoch, world, rank)
    path = os.path.join(directory, name)
    try:
        with open(f"{path}.meta.json", "rb") as f:
            meta = json.loads(f.read().decode())
        with open(path, "rb") as f:
            blob = f.read()
        # the sidecar is corruption-shaped input like the shard itself:
        # a torn write can leave VALID json of the wrong shape (a
        # string, a list, "bytes" bound to a dict...), and every one of
        # those must read as a corrupt shard, not a TypeError escaping
        # the fallback walk
        if not isinstance(meta, dict):
            raise CorruptShardError(
                f"{name}: meta sidecar is {type(meta).__name__}, "
                f"expected object")
        recorded = int(meta.get("bytes", -1))
    except CorruptShardError:
        raise
    except (OSError, ValueError, TypeError) as exc:
        raise CorruptShardError(f"{name}: {exc}") from exc
    if len(blob) != recorded:
        raise CorruptShardError(
            f"{name}: {len(blob)} bytes on disk, meta records "
            f"{meta.get('bytes')}")
    if hashlib.sha256(blob).hexdigest() != meta.get("sha256"):
        raise CorruptShardError(f"{name}: sha256 mismatch")
    try:
        return _loads(blob, meta.get("codec", "msgpack"))
    except Exception as exc:  # noqa: BLE001 — a undecodable payload
        # with a VALID digest is a writer bug, but the reader's
        # contract is the same: fall back
        raise CorruptShardError(f"{name}: undecodable: {exc}") from exc


def write_manifest(directory, step, epoch, world, extra=None):
    body = {"format": MANIFEST_FORMAT, "step": int(step),
            "epoch": int(epoch), "world_size": int(world)}
    body.update(extra or {})
    _atomic_write(os.path.join(directory,
                               manifest_name(step, epoch, world)),
                  json.dumps(body).encode())


def read_manifest(directory, step, epoch, world) -> dict:
    """Load one manifest body; raises ``ValueError`` (which the restore
    fallback walk already treats as "try the previous manifest") when
    the bytes are torn json or json of the wrong shape — a manifest is
    corruption-shaped input exactly like a shard sidecar."""
    name = manifest_name(step, epoch, world)
    path = os.path.join(directory, name)
    with open(path, "rb") as f:
        try:
            body = json.loads(f.read().decode())
        except (ValueError, UnicodeDecodeError) as exc:
            raise ValueError(f"{name}: {exc}") from exc
    if not isinstance(body, dict):
        raise ValueError(
            f"{name}: manifest body is {type(body).__name__}, "
            f"expected object")
    return body


def list_manifests(directory):
    """All manifests, newest (step, epoch) first: ``[(step, epoch,
    world), ...]``."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            out.append((int(m.group(1)), int(m.group(2)),
                        int(m.group(3))))
    return sorted(out, reverse=True)


def list_own_shards(directory, rank):
    """This rank's shard keys, newest first: ``[(step, epoch, world)]``
    — pruning input."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        m = _SHARD_RE.match(name)
        if m and int(m.group(4)) == rank:
            out.append((int(m.group(1)), int(m.group(2)),
                        int(m.group(3))))
    return sorted(out, reverse=True)


def remove_shard(directory, step, epoch, world, rank):
    path = os.path.join(directory,
                        shard_name(step, epoch, world, rank))
    for p in (path, f"{path}.meta.json"):
        try:
            os.remove(p)
        except OSError:
            pass


def remove_manifest(directory, step, epoch, world):
    try:
        os.remove(os.path.join(directory,
                               manifest_name(step, epoch, world)))
    except OSError:
        pass
