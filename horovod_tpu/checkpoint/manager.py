"""Background sharded checkpoint writer + auto-resume (docs/checkpoint.md).

Layered on :class:`horovod_tpu.elastic.State`: ``State.commit()``
already produces a double-buffered snapshot (``_committed`` — deep
numpy copies, and the FULL allgathered optimizer state under eager
ZeRO).  ``maybe_save`` hands that snapshot to a dedicated writer
thread, so training overlaps checkpoint I/O; the queue is depth-1
latest-wins — under a slow disk, intermediate snapshots are skipped
rather than queued (durability wants the NEWEST state, not a backlog).

Per (step, epoch, world) checkpoint:

- every rank writes its block of the flat parameter vector (the eager
  ZeRO row partition — :func:`horovod_tpu.sharding.zero.flat_shard`)
  and, when the optimizer snapshot is in FULL form, its block of every
  length-``n_params`` optimizer leaf;
- rank 0 additionally writes the non-sharded leaves (step counters,
  replicated trees) and, last, the manifest.

Resume (:meth:`CheckpointManager.restore_latest`, rank 0 at
``elastic.run`` entry) walks manifests newest-first, digest-verifies
every shard, re-assembles at whatever world size the checkpoint was
written at, and installs the result as the State's committed snapshot
— ``State.restore()`` + the driver's first ``sync()`` then re-shard to
the CURRENT world size, so a 4-rank checkpoint resumes cleanly on 3
ranks.
"""

import threading
import time

import numpy as np

from horovod_tpu.checkpoint import store
from horovod_tpu.common import busy
from horovod_tpu.utils.logging import get_logger


def _flatten_params(params):
    """(flat float vector as numpy, n_params).  None -> (None, 0)."""
    if params is None:
        return None, 0
    from jax.flatten_util import ravel_pytree

    flat, _ = ravel_pytree(params)
    return np.asarray(flat), int(flat.size)


class CheckpointManager:
    """One per process; owns the writer thread and the resume logic."""

    def __init__(self, directory, interval_steps=1, keep=2,
                 io_delay=0.0):
        import os

        self._dir = directory
        self._interval = max(1, int(interval_steps))
        self._keep = max(0, int(keep))   # 0: keep everything
        # test hook (liveness-interplay regression): artificial per-
        # write disk latency, read at write time so tests can throttle
        self.io_delay = float(io_delay)
        os.makedirs(directory, exist_ok=True)
        self._log = get_logger()
        self._cond = threading.Condition()
        self._snapshot = None       # latest-wins slot; guarded by _cond
        self._stop = False          # guarded by _cond
        self._writing = False       # guarded by _cond
        self._last_step = None      # last step handed to the writer
        self._errors = 0            # failed writes (visible to tests)
        # joined in close(); daemon so a worker dying mid-write never
        # hangs process exit on a disk stall
        self._thread = threading.Thread(target=self._writer_loop,
                                        daemon=True,
                                        name="hvd-ckpt-writer")
        self._thread.start()

    # ------------------------------------------------------------- write side
    def maybe_save(self, state) -> bool:
        """Called from ``State.commit()``: enqueue a write every
        ``interval_steps`` committed steps."""
        if state.step % self._interval != 0:
            return False
        return self.save_now(state)

    def save_now(self, state) -> bool:
        """Unconditionally enqueue the state's committed snapshot."""
        if state._committed is None:
            return False
        params, opt, step, epoch = state._committed
        if step == self._last_step:
            return False   # commit() re-runs at a retried boundary
        rank, world, wid = self._topology()
        self._last_step = step
        snap = {"params": params, "opt": opt,
                "opt_full": bool(state._opt_full),
                "step": int(step), "epoch": int(epoch),
                "rank": rank, "world": world, "wid": wid}
        with self._cond:
            self._snapshot = snap   # latest wins
            self._cond.notify()
        return True

    @staticmethod
    def _topology():
        """Live (rank, world, worker_id) at save time — re-read on
        every snapshot, NOT cached at construction: manifest authorship
        follows whoever holds rank 0 NOW, so after a coordinator
        fail-over (docs/elastic.md#coordinator-fail-over) the new root
        writes the manifests without any re-keying step."""
        from horovod_tpu.common import basics

        if basics.is_initialized():
            return basics.rank(), basics.size(), basics.worker_id()
        return 0, 1, 0

    def wait(self, timeout=30.0) -> bool:
        """Block until the writer drained the queue (tests and drain
        teardown use this to make durability deterministic)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._snapshot is not None or self._writing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def close(self, flush=True):
        if flush:
            self.wait()
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout=30)

    def _writer_loop(self):
        while True:
            with self._cond:
                while self._snapshot is None and not self._stop:
                    self._cond.wait()
                if self._stop and self._snapshot is None:
                    return
                snap, self._snapshot = self._snapshot, None
                self._writing = True
            try:
                # busy window: a slow disk here must read as "slow, not
                # dead" to the coordinator's liveness tracker
                with busy.window():
                    if self.io_delay > 0:
                        time.sleep(self.io_delay)
                    self._write(snap)
            except Exception:  # noqa: BLE001 — a failed checkpoint
                # write must never kill training; the previous complete
                # manifest remains the recovery point
                self._errors += 1
                self._log.warning("checkpoint: write failed",
                                  exc_info=True)
            finally:
                with self._cond:
                    self._writing = False
                    self._cond.notify_all()

    def _write(self, snap):
        import jax

        from horovod_tpu.sharding.zero import flat_shard

        step, epoch = snap["step"], snap["epoch"]
        rank, world = snap["rank"], snap["world"]
        flat, n_params = _flatten_params(snap["params"])
        payload = {"params": (flat_shard(flat, world, rank)
                              if flat is not None else
                              np.zeros((0,), np.float32))}

        opt, opt_kind, opt_num = snap["opt"], "none", 0
        if opt is not None:
            leaves = jax.tree_util.tree_leaves(opt)
            opt_num = len(leaves)
            sharded, rest = {}, {}
            if snap["opt_full"]:
                opt_kind = "full"
                for i, leaf in enumerate(leaves):
                    arr = np.asarray(leaf)
                    if arr.ndim == 1 and arr.shape[0] == n_params:
                        sharded[str(i)] = flat_shard(arr, world, rank)
                    elif rank == 0:
                        rest[str(i)] = arr
            else:
                opt_kind = "replicated"
                if rank == 0:
                    rest = {str(i): np.asarray(leaf)
                            for i, leaf in enumerate(leaves)}
            payload["opt_sharded"] = sharded
            payload["opt_rest"] = rest

        store.write_shard(self._dir, step, epoch, world, rank, payload)
        if rank == 0:
            # manifest last: readers treat its presence as "worth
            # validating", and validation still demands all W shards.
            # root_wid records WHICH worker authored it — informational
            # (resume is authorship-agnostic by contract), but it makes
            # "did the post-fail-over root really take over?" a
            # greppable fact instead of a timestamp puzzle
            store.write_manifest(
                self._dir, step, epoch, world,
                extra={"n_params": n_params, "opt_kind": opt_kind,
                       "opt_num_leaves": opt_num,
                       "root_wid": snap.get("wid", 0)})
        self._prune(rank, keep_key=(step, epoch))

    def _prune(self, rank, keep_key):
        if self._keep <= 0:
            return
        own = [k for k in store.list_own_shards(self._dir, rank)]
        # group by (step, epoch) newest first; keep the newest N groups
        groups = sorted({(s, e) for s, e, _w in own}, reverse=True)
        dead = set(groups[self._keep:])
        for s, e, w in own:
            if (s, e) in dead:
                store.remove_shard(self._dir, s, e, w, rank)
                if rank == 0:
                    store.remove_manifest(self._dir, s, e, w)
                    # sweep the WHOLE dead group, not just this rank's
                    # shard: after an elastic shrink or a coordinator
                    # fail-over, shard indices beyond the current world
                    # (and the dead root's own shards) have no owner
                    # left to prune them — without this they accumulate
                    # for the life of the checkpoint directory
                    for r in range(w):
                        if r != rank:
                            store.remove_shard(self._dir, s, e, w, r)

    # ------------------------------------------------------------ resume side
    def restore_latest(self, state):
        """Install the newest COMPLETE checkpoint as ``state``'s
        committed snapshot and roll the live state onto it.  Walks past
        incomplete/corrupt manifests (truncated shard, bad digest,
        shape mismatch with the current model).  Returns ``(step,
        epoch)`` or None.  Call on ONE rank (the sync root) before the
        driver's first ``sync()`` — the sync broadcast distributes and
        re-shards for everyone else.

        Authorship-agnostic by contract: any COMPLETE manifest is a
        valid resume point no matter which root wrote it — the one the
        original rank 0 committed before dying, or the one the
        fail-over-elected root wrote after (the recorded ``root_wid``
        is informational)."""
        for step, epoch, world in store.list_manifests(self._dir):
            try:
                result = self._restore_one(state, step, epoch, world)
            except (store.CorruptShardError, OSError, ValueError,
                    KeyError, TypeError) as exc:
                # TypeError included deliberately: manifest/shard fields
                # are corruption-shaped input, and a torn-but-valid-JSON
                # body can bind any of them to the wrong type (int({})
                # and friends) — that must read as "manifest unusable,
                # walk back", never crash the resume
                self._log.warning(
                    "checkpoint: manifest step=%d epoch=%d world=%d "
                    "unusable (%s); trying previous", step, epoch,
                    world, exc)
                continue
            if result is not None:
                self._last_step = step
                self._log.warning(
                    "checkpoint: resumed from step %d (epoch %d, "
                    "written at world %d by root worker %s)", step,
                    epoch, world, result[2])
                return result[:2]
        return None

    def _restore_one(self, state, step, epoch, world):
        import jax

        manifest = store.read_manifest(self._dir, step, epoch, world)
        shards = [store.read_shard(self._dir, step, epoch, world, r)
                  for r in range(world)]

        flat = np.concatenate([np.asarray(s["params"]) for s in shards])
        n_params = int(manifest.get("n_params", flat.size))
        if flat.size != n_params:
            raise ValueError(
                f"assembled {flat.size} params, manifest records "
                f"{n_params}")
        if state.params is not None:
            from jax.flatten_util import ravel_pytree

            live_flat, unravel = ravel_pytree(state.params)
            if int(live_flat.size) != n_params:
                raise ValueError(
                    f"checkpoint holds {n_params} params but the live "
                    f"model has {int(live_flat.size)}")
            params = jax.tree_util.tree_map(
                np.asarray, unravel(flat.astype(live_flat.dtype)))
        elif n_params:
            raise ValueError(
                "checkpoint holds params but the live State has none")
        else:
            params = None

        opt_kind = manifest.get("opt_kind", "none")
        opt, opt_full = None, False
        if opt_kind != "none":
            if state.optimizer_state is None:
                raise ValueError(
                    "checkpoint holds optimizer state but the live "
                    "State has none")
            treedef = jax.tree_util.tree_structure(
                state.optimizer_state)
            num = int(manifest.get("opt_num_leaves",
                                   treedef.num_leaves))
            if num != treedef.num_leaves:
                raise ValueError(
                    f"checkpoint optimizer tree has {num} leaves, the "
                    f"live one {treedef.num_leaves}")
            leaves = []
            for i in range(num):
                key = str(i)
                if key in shards[0].get("opt_sharded", {}):
                    leaves.append(np.concatenate(
                        [np.asarray(s["opt_sharded"][key])
                         for s in shards]))
                elif key in shards[0].get("opt_rest", {}):
                    leaves.append(
                        np.asarray(shards[0]["opt_rest"][key]))
                else:
                    raise ValueError(
                        f"optimizer leaf {i} missing from checkpoint")
            opt = jax.tree_util.tree_unflatten(treedef, leaves)
            opt_full = opt_kind == "full"

        state._committed = (params, opt, int(step), int(epoch))
        state._opt_full = opt_full
        state.restore()
        return int(step), int(epoch), manifest.get("root_wid", 0)
