"""Durable sharded checkpointing with auto-resume (docs/checkpoint.md).

Surface::

    hvd.checkpoint.CheckpointManager(dir, interval_steps, keep)
    hvd.checkpoint.manager_from_env()   # None when HVD_TPU_CKPT_DIR unset

``elastic.run`` attaches a manager automatically when the checkpoint
directory is configured — most jobs never touch this package directly.
"""

from horovod_tpu.checkpoint import store
from horovod_tpu.checkpoint.manager import CheckpointManager

__all__ = ["CheckpointManager", "manager_from_env", "store"]


def manager_from_env():
    """The process's configured :class:`CheckpointManager`, or None when
    checkpointing is off (no ``HVD_TPU_CKPT_DIR`` / ``ckpt_dir``).
    Reads the live runtime config when initialized (so launcher/YAML
    overrides apply), the raw env otherwise."""
    from horovod_tpu.common import basics
    from horovod_tpu.utils import env as env_util

    if basics.is_initialized():
        config = basics._get_state().config
        directory = config.ckpt_dir
        interval = config.ckpt_interval_steps
        keep = config.ckpt_keep
    else:
        directory = env_util.get_str(env_util.HVD_TPU_CKPT_DIR)
        interval = max(1, env_util.get_int(
            env_util.HVD_TPU_CKPT_INTERVAL,
            env_util.DEFAULT_CKPT_INTERVAL_STEPS))
        keep = max(0, env_util.get_int(env_util.HVD_TPU_CKPT_KEEP,
                                       env_util.DEFAULT_CKPT_KEEP))
    if not directory:
        return None
    return CheckpointManager(directory, interval_steps=interval,
                             keep=keep)
