"""Keras binding (reference: ``horovod/keras/__init__.py`` +
``horovod/_keras/``): ``DistributedOptimizer`` and the callback family
over the Keras 3 callback API, backed by the same eager collectives as
the TF binding.

Per-symbol import guard: imports cleanly without TF/Keras; symbols raise
with guidance on first use.
"""

try:
    import keras as _keras
    _KERAS_ERROR = None
except ImportError as _exc:  # pragma: no cover — keras present in image
    _keras = None
    _KERAS_ERROR = _exc

from horovod_tpu.common import basics as _basics
from horovod_tpu.common.ops_enum import (  # noqa: F401
    Adasum, Average, Sum)

init = _basics.init
shutdown = _basics.shutdown
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size


def allreduce(value, name=None, average=True):
    """Allreduce a tensor-compatible value (reference:
    ``keras/__init__.py:74`` — the Keras-level value op; evaluates
    eagerly and returns the reduced tensor)."""
    from horovod_tpu import tensorflow as hvd_tf

    return hvd_tf.allreduce(value, name=name, average=average)


def allgather(value, name=None):
    """Allgather a tensor-compatible value along dim 0 (reference:
    ``keras/__init__.py:88``)."""
    from horovod_tpu import tensorflow as hvd_tf

    return hvd_tf.allgather(value, name=name)


def broadcast(value, root_rank, name=None):
    """Broadcast a tensor-compatible value from ``root_rank``
    (reference: ``keras/__init__.py:102``)."""
    from horovod_tpu import tensorflow as hvd_tf

    return hvd_tf.broadcast(value, root_rank, name=name)


def broadcast_object(obj, root_rank=0, name=None):
    """Pickle-based object broadcast (delegates to the TF binding)."""
    from horovod_tpu import tensorflow as hvd_tf

    return hvd_tf.broadcast_object(obj, root_rank=root_rank, name=name)


def _require_keras():
    if _keras is None:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.keras requires Keras/TensorFlow, which is not "
            "installed in this environment. Use the JAX-native API "
            "(horovod_tpu + flax) or horovod_tpu.torch instead."
        ) from _KERAS_ERROR


def DistributedOptimizer(optimizer, name=None, op=Average,
                         compression=None, backward_passes_per_step=1,
                         sparse_as_dense=False):
    """Keras flavor of the TF binding's optimizer wrapper (reference:
    ``keras/__init__.py`` delegating to ``_keras/__init__.py:48``)."""
    _require_keras()
    from horovod_tpu import tensorflow as hvd_tf

    return hvd_tf.DistributedOptimizer(
        optimizer, name=name, op=op, compression=compression,
        backward_passes_per_step=backward_passes_per_step,
        sparse_as_dense=sparse_as_dense)


def broadcast_global_variables(model_or_variables, root_rank=0):
    """Sync weights from ``root_rank`` (reference:
    ``keras/__init__.py`` broadcast_global_variables)."""
    if isinstance(model_or_variables, int):
        raise TypeError(
            "TF2 has no global-variable collection: pass the model (or "
            "its variables) explicitly, e.g. "
            "broadcast_global_variables(model, root_rank=0) — the "
            "reference's broadcast_global_variables(root_rank) signature "
            "is TF1-only")
    _require_keras()
    from horovod_tpu import tensorflow as hvd_tf

    variables = getattr(model_or_variables, "variables",
                        model_or_variables)
    hvd_tf.broadcast_variables(variables, root_rank)


def load_model(filepath, custom_objects=None, compression=None,
               sparse_as_dense=False):
    """Load a Keras model and wrap its optimizer (reference:
    ``keras/__init__.py:117`` load_model with optimizer rehydration).

    Models saved with a wrapped optimizer serialize the dynamic
    ``Distributed<Base>`` class name; wrappers for every standard keras
    optimizer are pre-registered here so such saves round-trip.  Like
    ``compression``, ``sparse_as_dense`` is not serialized — pass it
    again when reloading a model that trained with it."""
    _require_keras()
    from horovod_tpu.tensorflow import _make_distributed_class

    custom = dict(custom_objects or {})
    for attr in dir(_keras.optimizers):
        obj = getattr(_keras.optimizers, attr)
        if isinstance(obj, type) \
                and issubclass(obj, _keras.optimizers.Optimizer) \
                and obj is not _keras.optimizers.Optimizer:
            cls = _make_distributed_class(obj, compression=compression,
                                          sparse_as_dense=sparse_as_dense)
            custom.setdefault(cls.__name__, cls)
            # ALSO under the plain class name: a model saved with an
            # unwrapped optimizer then deserializes its slot variables
            # and iteration count directly INTO the wrapped class —
            # re-wrapping after the fact would reset that state
            custom.setdefault(obj.__name__, cls)
    model = _keras.models.load_model(filepath, custom_objects=custom)
    if getattr(model, "optimizer", None) is not None and not getattr(
            model.optimizer, "_hvd_wrapped", False):
        model.optimizer = DistributedOptimizer(
            model.optimizer, compression=compression,
            sparse_as_dense=sparse_as_dense)
    return model


# ------------------------------------------------------------- callbacks
if _keras is not None:
    class BroadcastGlobalVariablesCallback(_keras.callbacks.Callback):
        """Broadcast initial weights + optimizer state from root_rank at
        the start of training (reference: ``_keras/callbacks.py:22``)."""

        def __init__(self, root_rank=0):
            super().__init__()
            self.root_rank = root_rank
            self._done = False

        def on_batch_end(self, batch, logs=None):
            if self._done:
                return
            from horovod_tpu import tensorflow as hvd_tf

            hvd_tf.broadcast_variables(self.model.variables,
                                       self.root_rank)
            if getattr(self.model, "optimizer", None) is not None:
                hvd_tf.broadcast_variables(
                    self.model.optimizer.variables, self.root_rank)
            self._done = True

    class MetricAverageCallback(_keras.callbacks.Callback):
        """Average epoch metrics over ranks before other callbacks read
        them (reference: ``_keras/callbacks.py:48``)."""

        def on_epoch_end(self, epoch, logs=None):
            from horovod_tpu.callbacks import metric_average

            if logs:
                for key in list(logs):
                    try:
                        logs[key] = metric_average(
                            float(logs[key]), f"{key}.{epoch}")
                    except (TypeError, ValueError):
                        continue

    class LearningRateWarmupCallback(_keras.callbacks.Callback):
        """Reference warmup convention (``_keras/callbacks.py:172``):
        the COMPILED learning rate is the already-size-scaled target;
        warmup ramps from initial_lr/size up to initial_lr.  (Compile
        with ``lr = base_lr * hvd.size()`` per the horovod recipe.)"""

        def __init__(self, initial_lr=None, warmup_epochs=5,
                     momentum_correction=True, steps_per_epoch=None,
                     verbose=0):
            super().__init__()
            self.initial_lr = initial_lr
            self.warmup_epochs = warmup_epochs
            self.steps_per_epoch = steps_per_epoch
            self.verbose = verbose
            self._epoch = 0
            del momentum_correction  # keras 3 has no momentum var hook

        def _set_lr(self, value):
            self.model.optimizer.learning_rate.assign(value)

        def on_train_begin(self, logs=None):
            if self.initial_lr is None:
                self.initial_lr = float(
                    self.model.optimizer.learning_rate.numpy())

        def on_epoch_begin(self, epoch, logs=None):
            self._epoch = epoch

        def on_train_batch_begin(self, batch, logs=None):
            if self._epoch >= self.warmup_epochs:
                return
            if self.steps_per_epoch:
                progress = (self._epoch +
                            batch / self.steps_per_epoch) \
                    / self.warmup_epochs
            else:
                progress = (self._epoch + 1) / self.warmup_epochs
            size = _basics.size()
            scale = (1.0 + progress * (size - 1.0)) / size
            self._set_lr(self.initial_lr * scale)

        def on_epoch_end(self, epoch, logs=None):
            if epoch + 1 == self.warmup_epochs:
                self._set_lr(self.initial_lr)
                if self.verbose and _basics.rank() == 0:
                    print(f"Warmup complete: lr = {self.initial_lr}")

    class LearningRateScheduleCallback(_keras.callbacks.Callback):
        """Multiplier schedule vs the initial LR (reference:
        ``_keras/callbacks.py:89``)."""

        def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                     staircase=True, momentum_correction=True,
                     steps_per_epoch=None, initial_lr=None):
            super().__init__()
            self.multiplier = multiplier if callable(multiplier) \
                else (lambda epoch: multiplier)
            self.start_epoch = start_epoch
            self.end_epoch = end_epoch
            self.staircase = staircase
            self.steps_per_epoch = steps_per_epoch
            self.initial_lr = initial_lr
            self._epoch = 0
            del momentum_correction

        def on_train_begin(self, logs=None):
            if self.initial_lr is None:
                self.initial_lr = float(
                    self.model.optimizer.learning_rate.numpy())

        def _in_range(self, epoch):
            return (epoch >= self.start_epoch and
                    (self.end_epoch is None or epoch < self.end_epoch))

        def on_epoch_begin(self, epoch, logs=None):
            self._epoch = epoch
            if self.staircase and self._in_range(epoch):
                self.model.optimizer.learning_rate.assign(
                    self.initial_lr * self.multiplier(epoch))

        def on_train_batch_begin(self, batch, logs=None):
            if self.staircase or not self._in_range(self._epoch):
                return
            if self.steps_per_epoch:
                epoch = self._epoch + batch / self.steps_per_epoch
            else:
                epoch = self._epoch
            self.model.optimizer.learning_rate.assign(
                self.initial_lr * self.multiplier(epoch))
else:  # pragma: no cover — surface helpful errors without keras
    def _missing(*_args, **_kwargs):
        _require_keras()

    BroadcastGlobalVariablesCallback = _missing
    MetricAverageCallback = _missing
    LearningRateWarmupCallback = _missing
    LearningRateScheduleCallback = _missing


class callbacks:  # namespace parity: hvd.callbacks.MetricAverageCallback
    BroadcastGlobalVariablesCallback = None
    MetricAverageCallback = None
    LearningRateWarmupCallback = None
    LearningRateScheduleCallback = None


callbacks.BroadcastGlobalVariablesCallback = BroadcastGlobalVariablesCallback
callbacks.MetricAverageCallback = MetricAverageCallback
callbacks.LearningRateWarmupCallback = LearningRateWarmupCallback
callbacks.LearningRateScheduleCallback = LearningRateScheduleCallback
