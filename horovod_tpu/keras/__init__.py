"""Keras binding gate (reference: ``horovod/keras/__init__.py``).

Requires TensorFlow/Keras, not present in this image; see
``horovod_tpu.tensorflow``.
"""

try:
    import tensorflow  # noqa: F401
except ImportError as exc:  # pragma: no cover
    raise ImportError(
        "horovod_tpu.keras requires TensorFlow/Keras, which is not "
        "installed in this environment. Use the JAX-native API "
        "(horovod_tpu + flax) or horovod_tpu.torch instead."
    ) from exc
