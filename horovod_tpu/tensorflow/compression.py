"""Gradient compression for the TensorFlow binding (reference:
``horovod/tensorflow/compression.py``): fp16-on-the-wire with
decompression back to the source dtype.  On TPU the natural wire type is
bfloat16 (no precision cliff on the MXU), so ``fp16`` here maps to
bf16 — same redesign as the torch binding's compression."""

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class NoneCompressor(Compressor):
    pass


class FP16Compressor(Compressor):
    """Casts floating tensors to bfloat16 for transport."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating and tensor.dtype != tf.bfloat16:
            return tf.cast(tensor, tf.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tf.cast(tensor, ctx)
        return tensor


class Compression:
    """Namespace matching the reference API (``Compression.none`` /
    ``Compression.fp16``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
