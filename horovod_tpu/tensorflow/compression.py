"""Gradient compression for the TensorFlow binding (reference:
``horovod/tensorflow/compression.py``): fp16-on-the-wire with
decompression back to the source dtype, plus a TPU-native ``bf16``
compressor (no precision cliff on the MXU) matching the common and
torch compression surfaces."""

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class NoneCompressor(Compressor):
    pass


class _CastCompressor(Compressor):
    WIRE_DTYPE = None

    @classmethod
    def compress(cls, tensor):
        if tensor.dtype.is_floating and tensor.dtype != cls.WIRE_DTYPE:
            return tf.cast(tensor, cls.WIRE_DTYPE), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tf.cast(tensor, ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    WIRE_DTYPE = tf.float16


class BF16Compressor(_CastCompressor):
    WIRE_DTYPE = tf.bfloat16


class Compression:
    """Namespace matching the reference API (``Compression.none`` /
    ``Compression.fp16``) plus the TPU-native ``bf16``."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
