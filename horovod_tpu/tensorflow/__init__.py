"""TensorFlow 2 binding (reference: ``horovod/tensorflow/__init__.py``).

The TF surface — eager collectives, ``DistributedGradientTape``
(``__init__.py:515-535``), ``DistributedOptimizer`` (``:271-433``),
``broadcast_variables`` (``mpi_ops.py``), IndexedSlices sparse handling
(``mpi_ops.py:111-144``) — routed through the same controller + XLA/ring
data plane the torch binding uses, instead of per-framework C++ custom
ops.  TF tensors cross into the core as numpy (zero-copy on CPU eager);
results come back as ``tf.Tensor``.

Per-symbol import guard: this module imports cleanly without TensorFlow
(symbols raise with guidance on first use), and activates fully when TF
is present.
"""

try:
    import tensorflow as _tf
    _TF_ERROR = None
except ImportError as _exc:  # pragma: no cover — TF present in CI image
    _tf = None
    _TF_ERROR = _exc

import numpy as _np

from horovod_tpu.common import basics as _basics
from horovod_tpu.common.ops_enum import (  # noqa: F401
    Adasum, Average, ReduceOp, Sum)
from horovod_tpu.ops import eager as _eager

# re-exported process-model surface (reference: tensorflow/__init__.py
# re-exports basics through `hvd.`)
init = _basics.init
shutdown = _basics.shutdown
is_initialized = _basics.is_initialized
rank = _basics.rank
size = _basics.size
local_rank = _basics.local_rank
local_size = _basics.local_size
cross_rank = _basics.cross_rank
cross_size = _basics.cross_size
mpi_built = _basics.mpi_built
gloo_built = _basics.gloo_built
nccl_built = _basics.nccl_built
xla_built = _basics.xla_built
ccl_built = _basics.ccl_built
ddl_built = _basics.ddl_built
mpi_threads_supported = _basics.mpi_threads_supported
is_homogeneous = _basics.is_homogeneous


def _require_tf():
    if _tf is None:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.tensorflow requires TensorFlow, which is not "
            "installed in this environment. The JAX-native API "
            "(horovod_tpu) and the torch binding (horovod_tpu.torch) "
            "provide the same capabilities.") from _TF_ERROR


def _to_tf(result, dtype=None):
    out = _tf.constant(_np.asarray(result))
    if dtype is not None and out.dtype != dtype:
        out = _tf.cast(out, dtype)
    return out


# --------------------------------------------------------------- collectives
def _graph_bridge(fn, tensor, out_dtype, out_shape=None):
    """Run an eager collective inside a traced ``tf.function`` via
    ``tf.py_function`` (the reference uses registered custom ops for
    graph mode, ``tensorflow/mpi_ops.cc``; the py_function node plays
    that role here — it executes the eager data-plane call at step time
    with a trace-stable name).

    The py_function body runs on a TF executor thread, NOT the thread
    that traced it — so the tracing thread's rank context
    (``basics._tls``, set by ``run_parallel``) is captured here and
    re-entered around the eager call, or device-rank collectives would
    see no rank and fail (or all commit as rank 0)."""
    captured_rank = getattr(_basics._tls, "local_rank", None)

    def body(t):
        if captured_rank is None:
            return fn(t)
        previous = getattr(_basics._tls, "local_rank", None)
        _basics._tls.local_rank = captured_rank
        try:
            return fn(t)
        finally:
            _basics._tls.local_rank = previous

    out = _tf.py_function(body, [tensor], Tout=out_dtype)
    if out_shape is not None:
        out.set_shape(out_shape)
    return out


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              compression=None):
    """Allreduce a ``tf.Tensor`` (or ``tf.IndexedSlices``).

    IndexedSlices follow the reference's sparse path
    (``mpi_ops.py:111-144``): values/indices are allgathered instead of
    densified, and Average divides the gathered values by size.
    Differentiating THROUGH the sparse path is not supported (the dense
    path carries a custom gradient; sparse gradients normally arrive
    FROM the tape, not inside it — use ``sparse_as_dense=True`` if a
    connected tape through an IndexedSlices allreduce is required).

    Works in eager mode and inside ``tf.function`` (via a py_function
    bridge node).
    """
    _require_tf()
    if not _tf.executing_eagerly() and not isinstance(
            tensor, _tf.IndexedSlices):
        return _graph_bridge(
            lambda t: allreduce(t, average=average, name=name, op=op,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor,
                                compression=compression),
            tensor, tensor.dtype, tensor.shape)
    if isinstance(tensor, _tf.IndexedSlices):
        resolved = ReduceOp(op) if op is not None else (
            Sum if average is False else Average)
        if resolved == Adasum:
            raise NotImplementedError(
                "Adasum is not supported for tf.IndexedSlices")
        values = tensor.values
        if prescale_factor != 1.0:
            values = values * _tf.cast(prescale_factor, values.dtype)
        values = allgather(values,
                           name=f"{name}.values" if name else None)
        indices = allgather(tensor.indices,
                            name=f"{name}.indices" if name else None)
        if resolved == Average:
            values = values / size()
        if postscale_factor != 1.0:
            values = values * _tf.cast(postscale_factor, values.dtype)
        return _tf.IndexedSlices(values, indices,
                                 dense_shape=tensor.dense_shape)

    from horovod_tpu.tensorflow.compression import Compression
    comp = compression or Compression.none
    tensor = _tf.convert_to_tensor(tensor)

    # custom gradient so code differentiating THROUGH the allreduce
    # keeps a connected tape (the numpy round trip would sever it);
    # reference: tf.RegisterGradient("HorovodAllreduce") = allreduce of
    # the upstream gradient with the same op (mpi_ops.py:111)
    @_tf.custom_gradient
    def _allreduce_diff(t):
        compressed, ctx = comp.compress(t)
        # Resolve the auto name NOW, on the rank thread, with the same
        # per-thread counter the submission would use: the backward
        # below must reuse this exact name (+".grad") — minting a fresh
        # auto name at grad time would diverge across ranks whenever
        # gradient evaluation order differs (cross-rank hang).
        resolved = name or _eager._auto_name("allreduce")
        out = _eager.allreduce(
            compressed.numpy(), average=average, name=resolved, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
        out = comp.decompress(_to_tf(out, compressed.dtype), ctx)
        # the forward body runs WITH the rank context (directly on the
        # rank thread, or re-entered by _graph_bridge); the grad closure
        # fires later on whatever thread runs the backward — carry the
        # context along
        captured_rank = getattr(_basics._tls, "local_rank", None)

        def grad(dy):
            gname = f"{resolved}.grad"
            previous = getattr(_basics._tls, "local_rank", None)
            if captured_rank is not None:
                _basics._tls.local_rank = captured_rank
            try:
                g = _eager.allreduce(
                    dy.numpy(), average=average, name=gname, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor)
            finally:
                if captured_rank is not None:
                    _basics._tls.local_rank = previous
            return _to_tf(g, dy.dtype)

        return out, grad

    return _allreduce_diff(tensor)


def grouped_allreduce(tensors, average=None, name=None, op=None):
    _require_tf()
    base = name or "tf_grouped"
    tensors = [_tf.convert_to_tensor(t) for t in tensors]
    if not _tf.executing_eagerly():
        # same executor-thread context capture as _graph_bridge
        captured_rank = getattr(_basics._tls, "local_rank", None)

        def body(*ts):
            previous = getattr(_basics._tls, "local_rank", None)
            if captured_rank is not None:
                _basics._tls.local_rank = captured_rank
            try:
                return grouped_allreduce(list(ts), average=average,
                                         name=base, op=op)
            finally:
                if captured_rank is not None:
                    _basics._tls.local_rank = previous

        outs = _tf.py_function(
            body,
            tensors, Tout=[t.dtype for t in tensors])
        for out, t in zip(outs, tensors):
            out.set_shape(t.shape)
        return list(outs)
    arrays = [t.numpy() for t in tensors]
    outs = _eager.grouped_allreduce(arrays, average=average, name=base,
                                    op=op)
    return [_to_tf(o, t.dtype) for o, t in zip(outs, tensors)]


def allgather(tensor, name=None):
    _require_tf()
    tensor = _tf.convert_to_tensor(tensor)
    if not _tf.executing_eagerly():
        return _graph_bridge(
            lambda t: allgather(t, name=name), tensor, tensor.dtype,
            _tf.TensorShape([None]).concatenate(tensor.shape[1:]))
    out = _eager.allgather(tensor.numpy(), name=name)
    return _to_tf(out, tensor.dtype)


def broadcast(tensor, root_rank, name=None):
    _require_tf()
    tensor = _tf.convert_to_tensor(tensor)
    if not _tf.executing_eagerly():
        return _graph_bridge(
            lambda t: broadcast(t, root_rank, name=name), tensor,
            tensor.dtype, tensor.shape)
    out = _eager.broadcast(tensor.numpy(), root_rank, name=name)
    return _to_tf(out, tensor.dtype)


def alltoall(tensor, splits=None, name=None):
    _require_tf()
    tensor = _tf.convert_to_tensor(tensor)
    if not _tf.executing_eagerly():
        return _graph_bridge(
            lambda t: alltoall(t, splits=splits, name=name), tensor,
            tensor.dtype,
            _tf.TensorShape([None]).concatenate(tensor.shape[1:]))
    out = _eager.alltoall(tensor.numpy(), splits=splits, name=name)
    return _to_tf(out, tensor.dtype)


def join():
    return _eager.join()


# ---------------------------------------------------------------- variables
def broadcast_variables(variables, root_rank):
    """Assign every variable the root rank's value (reference:
    ``broadcast_variables`` / ``BroadcastGlobalVariablesHook``).  Names
    are positional so ranks pair up regardless of scope naming.  All
    broadcasts are submitted asynchronously and synchronized together,
    so a 500-variable model pays overlapping round-trips, not 500
    sequential ones."""
    _require_tf()
    variables = list(variables)
    handles = [
        _eager.broadcast_async(
            _tf.convert_to_tensor(var).numpy(), root_rank,
            name=f"bcast_var.{i}")
        for i, var in enumerate(variables)]
    for var, handle in zip(variables, handles):
        value = _to_tf(_eager.synchronize(handle))
        var.assign(_tf.cast(value, var.dtype))


def broadcast_object(obj, root_rank=0, name=None):
    """Pickle-based object broadcast (reference:
    ``tensorflow/functions.py`` broadcast_object)."""
    from horovod_tpu.common.objects import broadcast_object as _bo

    return _bo(obj, root_rank=root_rank, name=name or "tf_bcast_object")


# ------------------------------------------------------------ gradient tape
class _DistributedGradientTape:
    """Wraps a ``tf.GradientTape``; ``gradient()`` allreduces the result
    (reference: ``tensorflow/__init__.py:515`` _DistributedGradientTape)."""

    def __init__(self, tape, op=Average, compression=None,
                 prescale_factor=1.0, postscale_factor=1.0,
                 sparse_as_dense=False):
        self.__dict__["_tape"] = tape
        self.__dict__["_op"] = op
        self.__dict__["_compression"] = compression
        self.__dict__["_prescale"] = prescale_factor
        self.__dict__["_postscale"] = postscale_factor
        self.__dict__["_sparse_as_dense"] = sparse_as_dense


    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self.__dict__["_tape"], item)

    def gradient(self, target, sources, output_gradients=None):
        gradients = self._tape.gradient(target, sources, output_gradients)
        # a STABLE prefix: per-call counters freeze at trace time inside
        # tf.function, so ranks that retrace a different number of times
        # would submit mismatched names (hang or cross-step pairing);
        # collectives are synchronous, so steady-state name reuse is safe
        return _allreduce_grads(
            gradients, op=self._op, compression=self._compression,
            prescale_factor=self._prescale,
            postscale_factor=self._postscale,
            name_prefix="tape",
            sparse_as_dense=self._sparse_as_dense)


def DistributedGradientTape(gradtape, op=Average, compression=None,
                            prescale_factor=1.0, postscale_factor=1.0,
                            device_dense="", device_sparse="",
                            persistent=False, sparse_as_dense=False):
    """Factory matching the reference signature
    (``tensorflow/__init__.py:535``); device args accepted for API
    compatibility (placement is the data plane's concern here)."""
    _require_tf()
    del device_dense, device_sparse, persistent
    return _DistributedGradientTape(
        gradtape, op=op, compression=compression,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        sparse_as_dense=sparse_as_dense)


def _allreduce_grads(gradients, op=Average, compression=None,
                     prescale_factor=1.0, postscale_factor=1.0,
                     name_prefix="grad", sparse_as_dense=False):
    flat_is_list = isinstance(gradients, (list, tuple))
    grads = list(gradients) if flat_is_list else [gradients]
    out = []
    for i, grad in enumerate(grads):
        if grad is None:
            out.append(None)
        else:
            if sparse_as_dense and isinstance(grad, _tf.IndexedSlices):
                # reference: convert_to_tensor before the dense
                # allreduce (tensorflow/__init__.py:240)
                grad = _tf.convert_to_tensor(grad)
            out.append(allreduce(
                grad, op=op, name=f"{name_prefix}.{i}",
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                compression=compression))
    if flat_is_list:
        return tuple(out) if isinstance(gradients, tuple) else out
    return out[0]


# -------------------------------------------------------------- optimizer
def _make_distributed_class(base_cls, name=None, op=Average,
                            compression=None, backward_passes_per_step=1,
                            prescale_factor=1.0, postscale_factor=1.0,
                            sparse_as_dense=False):
    """Build the dynamic ``Distributed<Base>`` optimizer class.  Exposed
    separately so ``keras.load_model`` can reconstruct serialized
    instances (the class name lands in saved model configs)."""

    class _Distributed(base_cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            if backward_passes_per_step > 1 \
                    and not _tf.executing_eagerly():
                # the accumulation counter is Python state: inside a
                # traced tf.function it would freeze at trace time and
                # the compiled step would never apply updates
                raise RuntimeError(
                    "backward_passes_per_step > 1 requires eager "
                    "execution (model.compile(..., run_eagerly=True) "
                    "or an eager training loop)")
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            hvariables = [v for _, v in grads_and_vars]
            state = self.__dict__.setdefault(
                "_hvd_state", {"count": 0, "acc": None})
            if backward_passes_per_step > 1:
                dense = [
                    _tf.convert_to_tensor(g) if g is not None else None
                    for g in grads]
                if state["acc"] is None:
                    state["acc"] = dense
                else:
                    state["acc"] = [
                        a + g if (a is not None and g is not None)
                        else (a if g is None else g)
                        for a, g in zip(state["acc"], dense)]
                state["count"] += 1
                if state["count"] % backward_passes_per_step != 0:
                    return None
                grads, state["acc"] = state["acc"], None
                grads = [g / backward_passes_per_step
                         if g is not None else None for g in grads]
            # stable name prefix (no per-round counter): see the tape
            # wrapper — retrace-count skew across ranks must not shift
            # collective names
            reduced = _allreduce_grads(
                grads, op=op, compression=compression,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                name_prefix=f"opt.{name or 'grad'}",
                sparse_as_dense=sparse_as_dense)
            return super().apply_gradients(
                zip(reduced, hvariables), *args, **kwargs)

    _Distributed.__name__ = f"Distributed{base_cls.__name__}"
    return _Distributed


def DistributedOptimizer(optimizer, name=None, op=Average,
                         compression=None, backward_passes_per_step=1,
                         prescale_factor=1.0, postscale_factor=1.0,
                         device_dense="", device_sparse="",
                         sparse_as_dense=False):
    """Wrap a Keras optimizer so ``apply_gradients`` allreduces first
    (reference: ``tensorflow/__init__.py:271,433`` — the TF2/Keras
    flavor; the TF1 ``compute_gradients`` graph path has no analog on
    this stack).  ``backward_passes_per_step > 1`` accumulates locally
    and exchanges every N-th call (reference:
    ``gradient_aggregation_eager.py`` semantics)."""
    _require_tf()
    del device_dense, device_sparse
    cls = _make_distributed_class(
        optimizer.__class__, name=name, op=op, compression=compression,
        backward_passes_per_step=backward_passes_per_step,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        sparse_as_dense=sparse_as_dense)
    return cls.from_config(optimizer.get_config())


def broadcast_global_variables(root_rank):
    """Documented scope cut (reference API parity stub): TF2 eager has
    no global collections, and in a TF1 frozen graph the broadcast
    needs a session — which the reference's custom C++ op provides and
    the py_function bridge cannot.  Both real workflows are covered:
    ``broadcast_variables(model.variables, root_rank)`` on TF2, and
    :class:`BroadcastGlobalVariablesHook` for TF1 sessions."""
    _require_tf()
    raise NotImplementedError(
        "broadcast_global_variables needs TF1 global collections plus "
        "an in-graph op; use broadcast_variables(model.variables, "
        "root_rank) on TF2, or BroadcastGlobalVariablesHook inside a "
        "TF1 MonitoredTrainingSession")


class BroadcastGlobalVariablesHook(
        object if _tf is None else _tf.compat.v1.train.SessionRunHook):
    """TF1-era session hook (reference: ``tensorflow/__init__.py:210``):
    after session creation, every global variable takes rank
    ``root_rank``'s value — the MonitoredTrainingSession / Estimator
    workflow's initialization broadcast.

    The broadcast rides the eager numpy plane OUTSIDE the session graph
    (values read with ``session.run``, assigned back per variable), so
    it composes with frozen TF1 graphs the py_function bridge cannot
    live in."""

    def __init__(self, root_rank, device=""):
        _require_tf()
        super().__init__()
        self.root_rank = root_rank
        del device  # accepted for reference API parity; single plane

    def after_create_session(self, session, coord):
        del coord
        variables = _tf.compat.v1.global_variables()
        values = session.run(variables)
        handles = [
            _eager.broadcast_async(_np.asarray(value), self.root_rank,
                                   name=f"bcast_hook.{i}")
            for i, value in enumerate(values)]
        for var, handle in zip(variables, handles):
            var.load(_np.asarray(_eager.synchronize(handle))
                     .astype(var.dtype.as_numpy_dtype), session)
