"""TensorFlow binding (reference: ``horovod/tensorflow/__init__.py``).

TensorFlow is not part of this image's environment; the binding is gated and
raises a clear error on import.  The TF2 surface (DistributedGradientTape,
DistributedOptimizer, broadcast_variables) maps onto the same core
collectives the torch binding uses.
"""

try:
    import tensorflow  # noqa: F401
except ImportError as exc:  # pragma: no cover
    raise ImportError(
        "horovod_tpu.tensorflow requires TensorFlow, which is not installed "
        "in this environment. The JAX-native API (horovod_tpu) and the "
        "torch binding (horovod_tpu.torch) provide the same capabilities."
    ) from exc
