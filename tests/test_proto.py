"""Tier-1 gate for hvd-proto (docs/protocol_checking.md).

Three halves:

1. every protocol-invariant checker is proven to FIRE on its known-bad
   fixture under ``tests/proto_fixtures/`` and stay silent on the
   known-good twin;
2. the bounded model checker verifies the five real control-plane
   protocols CLEAN at every configured world size, catches each
   seeded-bug fixture model deterministically with file:line
   attribution into the fixture file, and its counterexample traces
   project to ``HVD_TPU_FAULT_SPEC`` schedules — one of which is
   replayed against the real 2-rank tcp runtime to show the real code
   upholds the property the broken model violates;
3. the full suite over ``horovod_tpu/`` reports zero non-baselined
   findings, the checked-in baseline stays small (<= 25) with a real
   justification on every entry, and the same seed + depth produce a
   byte-identical report.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from conftest import spawn_tcp_ranks
from horovod_tpu.tools.lint import findings as findings_mod
from horovod_tpu.tools.proto import mc
from horovod_tpu.tools.proto import protocols
from horovod_tpu.tools.proto.cli import (
    DEFAULT_BASELINE,
    REPO_ROOT,
    run_proto,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "proto_fixtures")


def _fixture(name):
    return os.path.join(FIXTURES, name)


def _fixture_config(filename):
    """Point every checker's protocol surface at the fixture module
    itself (matched by relpath suffix, like the project config's
    module paths); ``models`` stays empty so a full run over a fixture
    never drags the real protocol models in."""
    return {
        "msg_modules": [filename],
        "parity_surfaces": [
            {"plane": "a", "module": filename,
             "function": "sig_a", "subjects": ["msg"]},
            {"plane": "b", "module": filename,
             "function": "RequestB.signature", "subjects": ["self"]},
        ],
        "exhaustive_surfaces": [
            {"plane": "fixture", "module": filename,
             "enum": "RequestType"},
        ],
        "enum_module": filename,
        "divergence_modules": [filename],
        "models": [],
    }


def _proto_fixture(filename, checker):
    found = run_proto([_fixture(filename)],
                      config=_fixture_config(filename),
                      checkers=[checker])
    return [f for f in found
            if f.path.endswith(f"proto_fixtures/{filename}")]


CASES = [
    ("epoch-fencing", "epoch_fencing"),
    ("signature-parity", "signature_parity"),
    ("request-exhaustiveness", "request_exhaustiveness"),
    ("collective-divergence", "collective_divergence"),
]


@pytest.mark.parametrize("checker,stem", CASES, ids=[c[0] for c in CASES])
def test_checker_fires_on_bad_fixture(checker, stem):
    found = _proto_fixture(f"bad_{stem}.py", checker)
    assert found, f"{checker} did not fire on its known-bad fixture"


@pytest.mark.parametrize("checker,stem", CASES, ids=[c[0] for c in CASES])
def test_checker_silent_on_good_fixture(checker, stem):
    found = _proto_fixture(f"good_{stem}.py", checker)
    assert not found, (
        f"{checker} false-positived on its known-good fixture: "
        + "; ".join(f.render() for f in found))


def test_bad_fixture_details():
    """The bad fixtures trip the SPECIFIC protocol rules they encode."""
    fence = _proto_fixture("bad_epoch_fencing.py", "epoch-fencing")
    assert {(f.context, f.detail) for f in fence} == {
        ("NoEpochMsg", "missing-epoch"),
        ("DeadFenceMsg", "no-dispatch-check"),
        ("UnfencedMsg", "unfenced-dispatch"),
    }

    parity = _proto_fixture("bad_signature_parity.py", "signature-parity")
    details = {f.detail for f in parity}
    assert details == {"a:compression", "a:prescale", "b:shape"}, details

    exhaust = _proto_fixture("bad_request_exhaustiveness.py",
                             "request-exhaustiveness")
    details = {f.detail for f in exhaust}
    assert details == {"fixture:RequestType.BROADCAST",
                       "fixture:RequestType.JOIN"}, details

    div = _proto_fixture("bad_collective_divergence.py",
                         "collective-divergence")
    details = {f.detail for f in div}
    assert details == {"allreduce:if-arm", "broadcast:else-arm"}, details


# ------------------------------------------------------ the model checker
def _load_model(stem):
    """Import a fixture protocol model by file path (the fixtures are
    plain modules, not a package) and return its ``MODEL`` instance."""
    path = _fixture(f"{stem}.py")
    spec = importlib.util.spec_from_file_location(
        f"proto_fixture_{stem}", path)
    module = importlib.util.module_from_spec(spec)
    # registered so inspect can resolve the model class back to this
    # file — that resolution IS the finding's file:line attribution
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module.MODEL


def test_real_protocols_verify_clean():
    """The five documented control-plane protocols hold their safety
    and bounded-liveness properties at every configured world size."""
    found = mc.check(None, {"repo_root": REPO_ROOT})
    assert not found, "\n".join(f.render() for f in found)


SEEDED_BUGS = [
    ("bad_split_brain", "split-brain"),
    ("bad_missing_fence", "stale-epoch-apply"),
    ("bad_replay_gap", "non-exactly-once-delivery"),
    ("bad_lost_abort", "abort-not-delivered"),
]


@pytest.mark.parametrize("stem,prop", SEEDED_BUGS,
                         ids=[s[0] for s in SEEDED_BUGS])
def test_seeded_bug_fixture_is_caught(stem, prop):
    """Each fixture breaks ONE transition of a real protocol model; the
    checker finds the planted property violation and attributes it to
    the fixture file (file:line lands on the class encoding the bug)."""
    model = _load_model(stem)
    found = mc.check(None, {"models": [model], "repo_root": REPO_ROOT})
    assert len(found) == 1, [f.render() for f in found]
    finding = found[0]
    assert finding.checker == "model-check"
    assert finding.path == f"tests/proto_fixtures/{stem}.py"
    assert finding.line >= 1
    assert finding.context == model.name
    assert finding.detail.startswith(f"{prop}:n="), finding.detail
    assert "minimal counterexample" in finding.message


def test_seeded_catch_is_deterministic():
    """Same seed + depth -> the identical counterexample trace, across
    repeated runs; a different seed may pick a different equal-length
    trace but must still catch the same property at the same n."""
    model = _load_model("bad_split_brain")

    def catch(seed):
        for n in model.ns:
            violation = mc.check_model(model, n, seed=seed)
            if violation is not None:
                return violation
        raise AssertionError("seeded bug not caught")

    first, second = catch(seed=0), catch(seed=0)
    assert first.trace == second.trace
    assert (first.prop, first.n) == (second.prop, second.n)
    other = catch(seed=99)
    assert (other.prop, other.n) == (first.prop, first.n)
    assert len(other.trace) == len(first.trace)   # still minimal


def test_depth_bounds_exploration(monkeypatch):
    """--depth is a real bound: too shallow to reach the bug -> clean;
    and the HVD_TPU_PROTO_DEPTH env default feeds through."""
    model = _load_model("bad_split_brain")
    caught_n = next(n for n in model.ns
                    if mc.check_model(model, n) is not None)
    assert mc.check_model(model, caught_n, depth=1) is None
    monkeypatch.setenv("HVD_TPU_PROTO_DEPTH", "1")
    assert mc.check_model(model, caught_n) is None
    monkeypatch.setenv("HVD_TPU_PROTO_DEPTH", "10")
    assert mc.check_model(model, caught_n) is not None


def test_counterexample_projects_to_fault_spec():
    """The lost-abort counterexample's fault projection is a pure crash
    schedule in the HVD_TPU_FAULT_SPEC grammar."""
    from horovod_tpu.common import faults

    model = _load_model("bad_lost_abort")
    violation = next(v for v in (mc.check_model(model, n)
                                 for n in model.ns) if v is not None)
    spec = mc.to_fault_spec(violation.trace)
    assert spec == "rank1:allreduce:1:crash"
    parsed = faults.parse_fault_spec(spec)   # grammar-valid
    assert [(s.rank, s.point, s.step, s.action) for s in parsed] == [
        (1, "allreduce", 1, "crash")]


REPLAY_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
t = jnp.ones((8,)) * (r + 1)
try:
    hvd.allreduce(t, op=hvd.Sum, name="proto.replay")
    print(f"rank {r} COMPLETED", flush=True)
except hvd.HvdAbortedError as exc:
    print(f"rank {r} ABORTED origin={exc.origin_rank}", flush=True)
"""


def test_counterexample_replays_on_real_runtime():
    """Close the loop model -> runtime: the broken model hangs its
    survivors forever after the crash; driving the REAL 2-rank tcp
    runtime with the counterexample's fault schedule shows the real
    abort fan-out upholds the property — the survivor raises the typed
    abort naming the crashed rank instead of hanging."""
    model = _load_model("bad_lost_abort")
    violation = next(v for v in (mc.check_model(model, n)
                                 for n in model.ns) if v is not None)
    spec = mc.to_fault_spec(violation.trace)

    results = spawn_tcp_ranks(2, REPLAY_WORKER, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HVD_TPU_HEARTBEAT_INTERVAL": "0.25",
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_TPU_ABORT_TIMEOUT": "10",
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "20",
        "HVD_TCP_RING_THRESHOLD": "1024",
        "HVD_TPU_FAULT_SPEC": spec,
    })
    code0, out0, err0 = results[0]
    code1, out1, err1 = results[1]
    assert code1 == 1, f"crashed rank: {out1}\n{err1}"
    assert code0 == 0, f"survivor: {out0}\n{err0}"
    assert "rank 0 ABORTED origin=1" in out0, out0


# --------------------------------------------------------------- the gate
def test_full_suite_zero_nonbaselined_findings():
    findings = run_proto([os.path.join(REPO_ROOT, "horovod_tpu")])
    baseline = findings_mod.load_baseline(DEFAULT_BASELINE)
    active, _suppressed, _stale = findings_mod.split_baselined(
        findings, baseline)
    assert not active, (
        "hvd-proto found non-baselined violations:\n"
        + "\n".join(f.render() for f in active))


def test_baseline_is_small_and_justified():
    with open(DEFAULT_BASELINE) as f:
        data = json.load(f)
    entries = data.get("suppressions", [])
    assert len(entries) <= 25, (
        f"{len(entries)} baselined suppressions — the budget is 25; "
        f"fix findings instead of baselining them")
    for entry in entries:
        just = entry.get("justification", "")
        assert just and "TODO" not in just, (
            f"baseline entry {entry.get('key')!r} lacks a real "
            f"justification")


def test_baseline_suppression_roundtrip(tmp_path):
    """A finding whose key is baselined stops being active; unrelated
    baseline keys surface as stale — hvd-lint's machinery verbatim."""
    findings = run_proto([_fixture("bad_epoch_fencing.py")],
                         config=_fixture_config("bad_epoch_fencing.py"),
                         checkers=["epoch-fencing"])
    assert findings
    baseline = {findings[0].key: "fixture", "stale:key:x:y": "gone"}
    active, suppressed, stale = findings_mod.split_baselined(
        findings, baseline)
    assert findings[0].key not in {f.key for f in active}
    assert suppressed and stale == ["stale:key:x:y"]

    path = tmp_path / "base.json"
    findings_mod.write_baseline(str(path), findings, previous=baseline)
    reloaded = findings_mod.load_baseline(str(path))
    assert reloaded[findings[0].key] == "fixture"
    assert all("stale:" not in k for k in reloaded)


# ------------------------------------------------------------------ CLI
def test_cli_exit_codes_and_json(tmp_path):
    proto = os.path.join(REPO_ROOT, "bin", "hvd-proto")
    ok = subprocess.run(
        [sys.executable, proto, os.path.join(REPO_ROOT, "horovod_tpu")],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert ok.returncode == 0, ok.stdout + ok.stderr

    # a module whose relpath matches the project policy's message-module
    # scope, carrying an unfenced wire message -> exit 1 + JSON findings
    bad_dir = tmp_path / "ops"
    bad_dir.mkdir()
    (bad_dir / "tcp_controller.py").write_text(
        "class StrayMsg:\n"
        "    def __init__(self, name):\n"
        "        self.name = name\n")
    # a sibling module anchors the scan root at tmp_path so the bad
    # module's relpath keeps its scope-matching 'ops/' prefix
    (tmp_path / "conftest_anchor.py").write_text("")
    bad = subprocess.run(
        [sys.executable, proto, str(tmp_path),
         "--checkers", "epoch-fencing", "--no-baseline",
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    payload = json.loads(bad.stdout)
    assert payload["findings"]
    assert all({"checker", "path", "line", "key"} <= set(f)
               for f in payload["findings"])
    assert any(f["detail"] == "missing-epoch"
               for f in payload["findings"])

    unknown = subprocess.run(
        [sys.executable, proto, "--checkers", "no-such-checker", "."],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert unknown.returncode == 2


def test_same_seed_byte_identical_report():
    """The determinism contract: the same --seed and --depth produce a
    byte-identical report across independent processes."""
    proto = os.path.join(REPO_ROOT, "bin", "hvd-proto")
    cmd = [sys.executable, proto, "--checkers", "model-check",
           "--seed", "7", "--depth", "10", "--format", "json",
           "--no-baseline", os.path.join(REPO_ROOT, "horovod_tpu")]
    first = subprocess.run(cmd, capture_output=True, cwd=REPO_ROOT)
    second = subprocess.run(cmd, capture_output=True, cwd=REPO_ROOT)
    assert first.returncode == second.returncode == 0
    assert first.stdout == second.stdout

    # ...and with findings in the report: the rendered fixture catch is
    # identical run to run, counterexample trace included
    model_cfg = {"models": [_load_model("bad_replay_gap")],
                 "repo_root": REPO_ROOT, "proto_seed": 7}
    one = "\n".join(f.render() for f in mc.check(None, dict(model_cfg)))
    two = "\n".join(f.render() for f in mc.check(None, dict(model_cfg)))
    assert one and one == two
