"""Minimal MXNet stand-in for executing ``horovod_tpu.mxnet`` for real
(MXNet is EOL upstream and uninstallable in this image — no egress to
PyPI, and modern images lack its binary wheels).  Reproduces exactly
the API surface the binding touches:

- ``mx.nd.NDArray`` over numpy: ``asnumpy``, ``dtype``, ``context``,
  ``as_in_context``, in-place ``tensor[:] = ...``, arithmetic the
  examples use;
- ``mx.nd.array(data, dtype=)``;
- ``mx.optimizer.Optimizer`` base with ``rescale_grad`` + a concrete
  ``SGD`` whose ``update`` applies ``-lr * rescale_grad * grad``
  (the semantics the binding's sum+1/size trick relies on);
- ``mx.gluon.Trainer`` with ``_params`` / ``_scale`` /
  ``_allreduce_grads`` / ``step``, gluon ``Parameter`` with
  ``grad_req`` / ``list_grad()`` / ``data()``, and
  ``gluon.parameter.DeferredInitializationError``.

What it does NOT reproduce: the MXNet engine, symbolic graphs, GPUs.
The binding uses none of those (it routes through the framework's own
controller instead of ``MXEnginePushAsync``)."""

import numpy as np

__version__ = "0.0-shim"


class Context:
    def __init__(self, device_type="cpu", device_id=0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Context)
                and (self.device_type, self.device_id)
                == (other.device_type, other.device_id))


def cpu(device_id=0):
    return Context("cpu", device_id)


class _ND:
    """mx.nd namespace."""

    class NDArray:
        def __init__(self, data, ctx=None):
            self._data = np.asarray(data)
            self.context = ctx or cpu()

        # --- surface the binding touches -----------------------------
        def asnumpy(self):
            return np.array(self._data, copy=True)

        @property
        def dtype(self):
            return self._data.dtype

        @property
        def shape(self):
            return self._data.shape

        def as_in_context(self, ctx):
            self.context = ctx
            return self

        def __setitem__(self, key, value):
            if isinstance(value, _ND.NDArray):
                value = value._data
            self._data[key] = value

        def __getitem__(self, key):
            return _ND.NDArray(self._data[key], self.context)

        # --- conveniences for examples/tests -------------------------
        def __iadd__(self, other):
            self._data += (other._data if isinstance(other, _ND.NDArray)
                           else other)
            return self

        def __mul__(self, other):
            return _ND.NDArray(self._data * (
                other._data if isinstance(other, _ND.NDArray) else other),
                self.context)

        def __repr__(self):
            return f"NDArray({self._data!r})"

    @staticmethod
    def array(data, dtype=None, ctx=None):
        arr = np.asarray(data, dtype=dtype)
        return _ND.NDArray(arr, ctx)

    @staticmethod
    def zeros(shape, dtype=np.float32, ctx=None):
        return _ND.NDArray(np.zeros(shape, dtype), ctx)


nd = _ND


class _OptimizerModule:
    class Optimizer:
        def __init__(self, learning_rate=0.01, rescale_grad=1.0):
            self.lr = learning_rate
            self.rescale_grad = rescale_grad

        def create_state(self, index, weight):
            return None

        def create_state_multi_precision(self, index, weight):
            return self.create_state(index, weight)

        def update(self, index, weight, grad, state):
            raise NotImplementedError

        def update_multi_precision(self, index, weight, grad, state):
            self.update(index, weight, grad, state)

        def set_learning_rate(self, lr):
            self.lr = lr

        def set_lr_mult(self, args_lr_mult):
            self._lr_mult = args_lr_mult

        def set_wd_mult(self, args_wd_mult):
            self._wd_mult = args_wd_mult

    class SGD(Optimizer):
        def update(self, index, weight, grad, state):
            if isinstance(index, (tuple, list)):
                # real mx optimizers accept aggregated lists
                for idx, w, g, s in zip(index, weight, grad, state):
                    self.update(idx, w, g, s)
                return
            weight[:] = weight.asnumpy() - self.lr * (
                self.rescale_grad * grad.asnumpy())


optimizer = _OptimizerModule


class _ParameterModule:
    class DeferredInitializationError(RuntimeError):
        pass

    class Parameter:
        def __init__(self, name, data=None, grad_req="write"):
            self.name = name
            self.grad_req = grad_req
            self._data = data            # NDArray | None (deferred)
            self.grad = (nd.zeros(data.shape, data.dtype)
                         if data is not None else None)

        def data(self):
            if self._data is None:
                raise _ParameterModule.DeferredInitializationError(
                    f"parameter {self.name} not initialized")
            return self._data

        def list_grad(self):
            return [self.grad]

        # gluon's deferred-init protocol: initialize() routes through
        # _init_impl, which horovod's broadcast_parameters hooks
        def _init_impl(self, data):
            self._data = data
            self.grad = nd.zeros(data.shape, data.dtype)

        def initialize(self, data):
            self._init_impl(data)


class _GluonModule:
    parameter = _ParameterModule
    Parameter = _ParameterModule.Parameter

    class Trainer:
        """The gluon.Trainer subset DistributedTrainer extends: holds
        params + a (possibly kvstore-rescaled) optimizer, steps by
        allreducing grads then updating each parameter."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     kvstore="device", **kwargs):
            if hasattr(params, "values"):
                params = list(params.values())
            self._params = list(params)
            if isinstance(optimizer, str):
                optimizer = {"sgd": _OptimizerModule.SGD}[optimizer](
                    **(optimizer_params or {}))
            self._optimizer = optimizer
            self._scale = optimizer.rescale_grad
            # recorded so tests can assert horovod forces kvstore=None
            # (real gluon would otherwise route updates through a
            # 'device' KVStore that _allreduce_grads never feeds)
            self._kvstore = kvstore
            self._kwargs = kwargs

        def step(self, batch_size, ignore_stale_grad=False):
            del ignore_stale_grad
            self._allreduce_grads()
            self._optimizer.rescale_grad = self._scale / batch_size
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._optimizer.update(i, param.data(), param.grad,
                                           None)

        def _allreduce_grads(self):
            pass  # plain trainer: no exchange (single process)


gluon = _GluonModule
