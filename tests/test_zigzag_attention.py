"""Zigzag (load-balanced causal) ring attention vs the dense reference.

Same pattern as test_sequence_parallel.py: 8-device CPU mesh, random
tensors, exactness against ``reference_attention``, gradients via
autograd.  The zigzag layout is the balanced-causal design — see the
module docstring of ``parallel/zigzag_attention.py``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.parallel import (make_mesh, reference_attention,
                                  zigzag_ring_self_attention,
                                  zigzag_shard, zigzag_unshard)


def _rand(b=1, t=128, h=2, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("p_size", [1, 2, 4])
def test_zigzag_shard_roundtrip(p_size):
    x = jnp.arange(2 * 16 * 3).reshape(2, 16, 3).astype(jnp.float32)
    y = zigzag_unshard(zigzag_shard(x, p_size), p_size)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_zigzag_shard_layout():
    """Rank i's contiguous slice is chunk i then chunk 2P-1-i."""
    p = 4
    x = jnp.arange(2 * p * 2)[None, :, None]          # chunks of 2
    z = np.asarray(zigzag_shard(x, p))[0, :, 0]
    # rank 0: chunk 0 (0,1) + chunk 7 (14,15)
    np.testing.assert_array_equal(z[:4], [0, 1, 14, 15])
    # rank 3: chunk 3 (6,7) + chunk 4 (8,9)
    np.testing.assert_array_equal(z[12:], [6, 7, 8, 9])


@pytest.mark.parametrize("p_size", [2, 4, 8])
def test_zigzag_matches_dense_causal(p_size):
    mesh = make_mesh({"sp": p_size}, devices=jax.devices()[:p_size])
    q, k, v = _rand(t=128, seed=1)
    expected = reference_attention(q, k, v, causal=True)
    got = zigzag_ring_self_attention(q, k, v, mesh, use_flash=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_zigzag_single_rank_degenerate():
    mesh = make_mesh({"sp": 1}, devices=jax.devices()[:1])
    q, k, v = _rand(t=32, seed=2)
    expected = reference_attention(q, k, v, causal=True)
    got = zigzag_ring_self_attention(q, k, v, mesh, use_flash=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_zigzag_with_flash_blocks():
    """Flash kernel (interpret mode) computing each zigzag block.

    interpret-mode pallas inside strict-vma shard_map trips a jax
    hlo_interpreter limitation (same as the ring-attention test);
    real-TPU runs use check_vma=True fine — build the shard_map with
    check_vma=False here."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel._compat import shard_map
    from horovod_tpu.parallel.zigzag_attention import (
        zigzag_ring_attention)

    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    b, t, h, d = 1, 1024, 2, 16                    # C=128: packed lse
    q, k, v = _rand(b=b, t=t, h=h, d=d, seed=3)
    expected = reference_attention(q, k, v, causal=True)

    spec = P(None, "sp", None, None)
    fn = functools.partial(zigzag_ring_attention, axis_name="sp",
                           use_flash=True)
    try:
        sm = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:
        sm = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    sharding = NamedSharding(mesh, spec)
    args = [jax.device_put(zigzag_shard(x, 4), sharding)
            for x in (q, k, v)]
    got = zigzag_unshard(sm(*args), 4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_zigzag_gradients_match_dense():
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _rand(t=64, seed=4)

    def loss_z(q, k, v):
        return jnp.sum(
            zigzag_ring_self_attention(q, k, v, mesh,
                                       use_flash=False) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gz = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_zigzag_rejects_bad_length():
    with pytest.raises(ValueError, match="not divisible"):
        zigzag_shard(jnp.zeros((1, 30, 2, 4)), 4)


def test_zigzag_bf16():
    """bf16 inputs ride the same fp32 streaming-softmax accumulators."""
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    q, k, v = _rand(t=128, seed=6)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    expected = reference_attention(q, k, v, causal=True)
    got = zigzag_ring_self_attention(qb, kb, vb, mesh, use_flash=False)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(expected), rtol=0.1, atol=0.1)
