"""Unit tests for the shared controller helpers: SignatureCache LRU
semantics (common/response_cache.py) and the fusion bucket planner
(common/fusion.py) — the pieces every controller flavor now leans on."""

import pytest

from horovod_tpu.common.fusion import plan_buckets
from horovod_tpu.common.response_cache import SignatureCache


# ---------------------------------------------------------- SignatureCache
def test_cache_miss_then_hit_then_invalidate():
    cache = SignatureCache(capacity=4)
    assert not cache.check("t", ["sigA", "sigA"])  # MISS (empty)
    cache.store("t", ["sigA", "sigA"])
    assert cache.check("t", ["sigA", "sigA"])      # HIT
    assert cache.hits == 1
    assert not cache.check("t", ["sigB", "sigB"])  # signature changed
    cache.evict("t")
    assert not cache.check("t", ["sigA", "sigA"])  # INVALID -> miss


def test_cache_disagreeing_or_missing_signatures_never_hit_or_store():
    cache = SignatureCache(capacity=4)
    cache.store("t", ["sigA", "sigB"])   # ranks disagree: not stored
    assert len(cache) == 0
    cache.store("t", ["sigA", None])     # unavailable: not stored
    assert len(cache) == 0
    cache.store("t", ["sigA", "sigA"])
    assert not cache.check("t", ["sigA", None])
    assert not cache.check("t", ["sigA", "sigB"])


def test_cache_lru_eviction_order():
    cache = SignatureCache(capacity=2)
    cache.store("a", ["s"])
    cache.store("b", ["s"])
    assert cache.check("a", ["s"])  # refresh a
    cache.store("c", ["s"])         # evicts b (least recent)
    assert cache.check("a", ["s"])
    assert not cache.check("b", ["s"])
    assert cache.check("c", ["s"])
    assert len(cache) == 2


# ------------------------------------------------------------- plan_buckets
def _buckets(items, threshold=100):
    return list(plan_buckets(items, key_fn=lambda it: it[0],
                             nbytes_fn=lambda it: it[1],
                             threshold=threshold))


def test_buckets_split_on_key_change():
    items = [("k1", 10), ("k1", 10), ("k2", 10), ("k1", 10)]
    assert _buckets(items) == [
        [("k1", 10), ("k1", 10)], [("k2", 10)], [("k1", 10)]]


def test_buckets_split_on_threshold():
    items = [("k", 60), ("k", 60), ("k", 60)]
    assert _buckets(items, threshold=100) == [
        [("k", 60)], [("k", 60)], [("k", 60)]]
    items = [("k", 40), ("k", 40), ("k", 40)]
    assert _buckets(items, threshold=100) == [
        [("k", 40), ("k", 40)], [("k", 40)]]


def test_oversize_single_item_gets_own_bucket():
    items = [("k", 10), ("k", 500), ("k", 10)]
    assert _buckets(items, threshold=100) == [
        [("k", 10)], [("k", 500)], [("k", 10)]]


def test_empty_stream_yields_nothing():
    assert _buckets([]) == []


@pytest.mark.parametrize("n", [1, 7, 64])
def test_order_preserved_within_and_across_buckets(n):
    items = [("k", 30 + (i % 3)) for i in range(n)]
    flat = [it for bucket in _buckets(items) for it in bucket]
    assert flat == items
