"""Multi-host global-mesh end-to-end tests.

Two hvdrun processes, each with 4 virtual CPU devices, form ONE
8-device ``jax.distributed`` global mesh (reference analog:
``gloo_context.cc:56-73`` full-mesh rendezvous from launcher env).  The
data plane is compiled XLA collectives over the global mesh; the TCP
wire carries metadata only (``ops/global_controller.py``).

These are the pod-mode (``hvdrun --tpu``) tests the driver's real-TPU
runs can't cover on one chip.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HVDRUN = os.path.join(REPO, "bin", "hvdrun")

EAGER_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel

hvd.init()
pid = int(os.environ["HVD_RANK"])
assert hvd.size() == 8, hvd.size()
assert hvd.local_size() == 4, hvd.local_size()
assert hvd.cross_size() == 2
assert hvd.mesh().shape["hvd"] == 8

def per_rank(lr):
    r = hvd.rank()
    out = np.asarray(hvd.allreduce(jnp.full((4,), float(r)), op=hvd.Sum,
                                   name="ar"))
    np.testing.assert_allclose(out, np.full((4,), 28.0))

    out = np.asarray(hvd.allreduce(jnp.full((3,), float(r)), name="avg"))
    np.testing.assert_allclose(out, np.full((3,), 3.5))

    b = np.asarray(hvd.broadcast(jnp.full((3,), float(r)), root_rank=5,
                                 name="bc"))
    np.testing.assert_allclose(b, np.full((3,), 5.0))

    g = np.asarray(hvd.allgather(jnp.full((r % 2 + 1, 2), float(r)),
                                 name="ag"))
    expect = np.concatenate(
        [np.full((i % 2 + 1, 2), float(i)) for i in range(8)])
    np.testing.assert_allclose(g, expect)

    t = jnp.arange(8, dtype=jnp.float32) + 100 * r
    out = np.asarray(hvd.alltoall(t, name="a2a"))
    expect = np.array([float(src * 100 + r) for src in range(8)])
    np.testing.assert_allclose(out, expect)

    # variable splits alltoall: rank r sends (dst+1) rows to each dst
    rows = sum(d + 1 for d in range(8))
    t = jnp.full((rows, 2), float(r))
    splits = [d + 1 for d in range(8)]
    out = np.asarray(hvd.alltoall(t, splits=splits, name="a2av"))
    expect = np.concatenate(
        [np.full((r + 1, 2), float(src)) for src in range(8)])
    np.testing.assert_allclose(out, expect)
    return r

ranks = run_parallel(per_rank)
assert ranks == [pid * 4 + l for l in range(4)], ranks

# cross-process validation errors surface everywhere
from horovod_tpu.common.handles import HvdError
def bad(lr):
    r = hvd.rank()
    try:
        hvd.allreduce(jnp.ones((2 + r,)), op=hvd.Sum, name="bad")
        raise SystemExit("expected HvdError for mismatched shapes")
    except HvdError:
        return True
assert all(run_parallel(bad))

print(f"proc {pid} GMESH_EAGER_OK", flush=True)
hvd.shutdown()
"""

TRAIN_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import optax
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel
from horovod_tpu.parallel import shard_global_batch
from horovod_tpu.parallel._compat import shard_map
from jax.sharding import PartitionSpec as P

hvd.init()
pid = int(os.environ["HVD_RANK"])
mesh = hvd.mesh()

from horovod_tpu.models import MLP
model = MLP(features=(16, 4))
params = model.init(jax.random.PRNGKey(0), np.ones((1, 8), np.float32))
opt = hvd.DistributedOptimizer(optax.sgd(0.05), named_axes=("hvd",))
opt_state = opt.init(params)

def per_shard(params, opt_state, x, y):
    def loss_fn(p):
        return ((model.apply(p, x) - y) ** 2).mean()
    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = opt.update(grads, opt_state, params)
    return (optax.apply_updates(params, updates), opt_state,
            jax.lax.pmean(loss, "hvd"))

step = jax.jit(shard_map(per_shard, mesh=mesh,
    in_specs=(P(), P(), P("hvd"), P("hvd")), out_specs=(P(), P(), P())))

# per-host data loading: each process contributes its 8 local rows
rng = np.random.RandomState(pid)
xd = shard_global_batch(rng.randn(8, 8).astype(np.float32))
yd = shard_global_batch(rng.randn(8, 4).astype(np.float32))
losses = []
for _ in range(15):
    params, opt_state, loss = step(params, opt_state, xd, yd)
    losses.append(float(np.asarray(jax.device_get(loss))))
assert losses[-1] < losses[0] * 0.9, losses
print(f"proc {pid} SPMD_TRAIN_OK", flush=True)

def per_rank(lr):
    r = hvd.rank()
    # out-of-order async across the pod
    names = [f"n{i}" for i in range(8)]
    order = names if r % 2 == 0 else names[::-1]
    hs = {n: hvd.allreduce_async(jnp.ones((4,)) * (r + 1), op=hvd.Sum,
                                 name=n) for n in order}
    for n in names:
        np.testing.assert_allclose(np.asarray(hvd.synchronize(hs[n])),
                                   np.full((4,), 36.0))
    # Adasum across processes vs the numpy oracle
    from horovod_tpu.ops.adasum import adasum_reference
    data = [np.arange(1, 5, dtype=np.float32) * (i + 1) for i in range(8)]
    out = np.asarray(hvd.allreduce(jnp.asarray(data[r]), op=hvd.Adasum,
                                   name="ads"))
    np.testing.assert_allclose(out, adasum_reference(data), rtol=1e-4)
    # join with uneven work spanning both processes
    if r <= 2:
        extra = np.asarray(hvd.allreduce(jnp.ones((2,)) * 5, op=hvd.Sum,
                                         name="uneven"))
        np.testing.assert_allclose(extra, np.full((2,), 15.0))
    last = hvd.join()
    # ranks 0-2 joined only after their extra allreduce completed, so the
    # coordinator-serialized last joiner must be one of them
    assert last in (0, 1, 2), last
    return True

assert all(run_parallel(per_rank))
print(f"proc {pid} GMESH_TRAIN_OK", flush=True)
hvd.shutdown()
"""


def _run_gmesh(script, np_=2, devices_per_proc=4, timeout=600,
               extra_env=None):
    path = "/tmp/hvd_multihost_worker.py"
    with open(path, "w") as f:
        f.write(script)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("AXON_", "PALLAS_", "TPU_", "JAX_"))}
    env.update(extra_env or {})
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    from tests.conftest import readd_jax_cache
    readd_jax_cache(env)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}")
    cmd = [sys.executable, HVDRUN, "-np", str(np_), "--global-mesh",
           sys.executable, path]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_global_mesh_eager_collectives():
    result = _run_gmesh(EAGER_WORKER)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("GMESH_EAGER_OK") == 2


def test_global_mesh_spmd_training_and_join():
    result = _run_gmesh(TRAIN_WORKER)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("SPMD_TRAIN_OK") == 2
    assert result.stdout.count("GMESH_TRAIN_OK") == 2


MATRIX_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel

hvd.init()
pid = int(os.environ["HVD_RANK"])
n = hvd.size()

def per_rank(lr):
    r = hvd.rank()
    # dtype sweep over the compiled global-mesh plane
    for dtype in ("float32", "bfloat16", "int32", "uint8"):
        data = ((np.arange(6) % 3) + 1).astype(dtype)
        out = np.asarray(hvd.allreduce(jnp.asarray(data), op=hvd.Sum,
                                       name=f"gm.{dtype}"))
        expect = (((np.arange(6) % 3) + 1) * n).astype(np.float64)
        np.testing.assert_allclose(out.astype(np.float64), expect)

    # grouped fusion burst across processes
    handles = [hvd.allreduce_async(jnp.full((5,), float(r + 1)),
                                   op=hvd.Sum, name=f"gfuse.{i}")
               for i in range(12)]
    for h in handles:
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   np.full((5,), 36.0))

    # 0-d scalar over the compiled plane
    out = hvd.allreduce(jnp.float32(r), op=hvd.Sum, name="gm0d")
    assert np.asarray(out).ndim == 0
    assert float(np.asarray(out)) == sum(range(8))
    return True

assert all(run_parallel(per_rank))

# hierarchical allreduce over the (cross, local) = (process, chip) mesh
os.environ_backup = None
from horovod_tpu.common import basics
state = basics._get_state()
assert state.executor.hier_mesh is not None, "expected 2-proc hier mesh"
state.executor.hierarchical_allreduce = True

def per_rank_hier(lr):
    r = hvd.rank()
    out = np.asarray(hvd.allreduce(jnp.full((33,), float(r + 1)),
                                   op=hvd.Sum, name="gmhier"))
    np.testing.assert_allclose(out, np.full((33,), 36.0))
    return True

assert all(run_parallel(per_rank_hier))
print(f"proc {pid} GMESH_MATRIX_OK", flush=True)
hvd.shutdown()
"""


def test_global_mesh_dtype_matrix_and_hierarchical():
    result = _run_gmesh(MATRIX_WORKER)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("GMESH_MATRIX_OK") == 2


STALL_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel
from horovod_tpu.common.handles import HvdError

hvd.init()
pid = int(os.environ["HVD_RANK"])

def per_rank(lr):
    r = hvd.rank()
    # a healthy collective first: the stall must poison only the
    # stalled name, and only after the shutdown threshold
    out = np.asarray(hvd.allreduce(jnp.full((3,), float(r)), op=hvd.Sum,
                                   name="healthy"))
    np.testing.assert_allclose(out, np.full((3,), 28.0))

    if pid == 1:
        # process 1 never submits the stalled tensor
        return "skipped"
    try:
        hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="stalled")
        return "no-error"
    except HvdError as exc:
        assert "stall" in str(exc).lower(), exc
        return "raised"

results = run_parallel(per_rank)
expected = "raised" if pid == 0 else "skipped"
assert all(x == expected for x in results), (pid, results)
print(f"proc {pid} GMESH_STALL_OK", flush=True)
hvd.shutdown()
"""


def test_global_mesh_stall_shutdown():
    """A process that never submits a tensor trips the coordinator's
    stall shutdown; the waiting process gets a per-name HvdError while
    healthy collectives complete (reference: StallInspector +
    Response::ERROR semantics, on the pod control plane)."""
    result = _run_gmesh(STALL_WORKER, timeout=300, extra_env={
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "4",
    })
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("GMESH_STALL_OK") == 2


FOURPROC_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel

hvd.init()
pid = int(os.environ["HVD_RANK"])
assert hvd.size() == 8 and hvd.local_size() == 2 and hvd.cross_size() == 4

def per_rank(lr):
    r = hvd.rank()
    out = np.asarray(hvd.allreduce(jnp.full((5,), float(r + 1)),
                                   op=hvd.Sum, name="f.ar"))
    np.testing.assert_allclose(out, np.full((5,), 36.0))
    g = np.asarray(hvd.allgather(jnp.full((1, 2), float(r)), name="f.ag"))
    np.testing.assert_allclose(
        g, np.arange(8, dtype=np.float32)[:, None] * np.ones((1, 2)))
    return r

ranks = run_parallel(per_rank)
assert ranks == [pid * 2, pid * 2 + 1], ranks
print(f"proc {pid} GMESH_4P_OK", flush=True)
hvd.shutdown()
"""


def test_global_mesh_four_processes():
    """A different pod shape: 4 processes x 2 devices forming the same
    8-rank global mesh (the coordinator's per-process bookkeeping must
    not assume 2 hosts)."""
    result = _run_gmesh(FOURPROC_WORKER, np_=4, devices_per_proc=2,
                        timeout=600)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("GMESH_4P_OK") == 4


LOCAL_MISMATCH_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel
from horovod_tpu.common.handles import HvdError

hvd.init()
pid = int(os.environ["HVD_RANK"])

def per_rank(lr):
    r = hvd.rank()
    # ranks 0 and 1 live in process 0 and disagree on shape: the
    # coordinator only compares across processes, so the process must
    # catch this locally and the error must reach EVERY rank globally
    shape = (2, 3) if r != 1 else (3, 2)
    try:
        hvd.allreduce(jnp.ones(shape), op=hvd.Sum, name="local.bad")
        return "no-error"
    except HvdError as exc:
        assert "mismatched shapes" in str(exc), exc
        return "raised"

results = run_parallel(per_rank)
assert all(x == "raised" for x in results), (pid, results)

# and the job keeps working afterwards
def ok(lr):
    out = np.asarray(hvd.allreduce(jnp.ones((3,)), op=hvd.Sum,
                                   name="after.ok"))
    np.testing.assert_allclose(out, np.full((3,), 8.0))
    return True
assert all(run_parallel(ok))
print(f"proc {pid} GMESH_LOCAL_MISMATCH_OK", flush=True)
hvd.shutdown()
"""


def test_global_mesh_intra_process_mismatch_errors_globally():
    """Two ranks INSIDE one process disagreeing on a tensor's shape must
    error every rank in the job (regression: the coordinator only
    validated across processes, so the misalignment executed silently)."""
    result = _run_gmesh(LOCAL_MISMATCH_WORKER, timeout=300)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("GMESH_LOCAL_MISMATCH_OK") == 2


GROUPED_WORKER = r"""
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel

hvd.init()
pid = hvd.cross_rank()
n = hvd.size()

def per_rank(_local):
    r = hvd.rank()  # run_parallel passes the LOCAL thread index
    # mixed dtypes in one grouped submission: separate fusion buckets
    # on the coordinator (allreduce_bucket_key), all complete
    outs = hvd.grouped_allreduce(
        [jnp.ones(4, jnp.float32) * (r + 1),
         jnp.ones(4, jnp.bfloat16) * (r + 1),
         jnp.ones(4, jnp.float32) * 2 * (r + 1)],
        op=hvd.Sum, name="gg.mixed")
    total = float(sum(range(1, n + 1)))
    np.testing.assert_allclose(np.asarray(outs[0]), np.full(4, total))
    assert outs[1].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(outs[2]),
                               np.full(4, 2 * total))

    # scalar (0-d reshaped) + vector in one group
    outs = hvd.grouped_allreduce(
        [jnp.asarray([float(r)]), jnp.ones(3)],
        op=hvd.Sum, name="gg.scalar")
    assert float(outs[0][0]) == float(sum(range(n)))

    # a burst of small same-dtype tensors: fused into ordered buckets
    outs = hvd.grouped_allreduce(
        [jnp.full((8,), float(i + r)) for i in range(12)],
        op=hvd.Average, name="gg.burst")
    for i, out in enumerate(outs):
        expect = sum(i + rr for rr in range(n)) / n
        np.testing.assert_allclose(np.asarray(out), np.full(8, expect),
                                   rtol=1e-6)
    return True

assert all(run_parallel(per_rank))
print(f"proc {pid} GMESH_GROUPED_OK", flush=True)
"""


def test_global_mesh_grouped_fused_edges():
    """Grouped/fused edge cases under the gmesh controller (VERDICT r2
    item 8): mixed-dtype bucket splits, scalars, and a 12-tensor burst
    through the global sequence log."""
    result = _run_gmesh(GROUPED_WORKER, extra_env={
        "HVD_FUSION_THRESHOLD": "128",  # force multi-bucket fusion
    })
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr[-3000:]}"
    for p in range(2):
        assert f"proc {p} GMESH_GROUPED_OK" in result.stdout


ERROR_SWEEP_GMESH = r"""
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common.basics import run_parallel
from horovod_tpu.common.handles import HvdError

hvd.init()
pid = hvd.cross_rank()
n = hvd.size()

def per_rank(_local):
    r = hvd.rank()
    cases = [
        (lambda: hvd.allreduce(np.ones(2 + r % 2, np.float32),
                               op=hvd.Sum, name="ge.shape"), "shape"),
        (lambda: hvd.allreduce(
            np.ones(3, np.float32 if r % 2 == 0 else np.int32),
            op=hvd.Sum, name="ge.dtype"), "dtype"),
        (lambda: hvd.allreduce(
            np.ones(3, np.float32),
            op=hvd.Sum if r % 2 == 0 else hvd.Average,
            name="ge.op"), "op"),
        (lambda: hvd.broadcast(np.ones(3, np.float32), root_rank=r % 2,
                               name="ge.root"), "root"),
        (lambda: hvd.allgather(
            np.ones((2, 3 + r % 2), np.float32), name="ge.trail"),
         "trailing"),
    ]
    for submit, frag in cases:
        try:
            submit()
            raise AssertionError(f"expected HvdError for {frag}")
        except HvdError as exc:
            assert frag in str(exc).lower(), (frag, str(exc))
    # recovery: the names work again after the error rounds
    out = np.asarray(hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum,
                                   name="ge.shape"))
    np.testing.assert_allclose(out, np.full(3, float(n)))
    return True

assert all(run_parallel(per_rank))
print(f"proc {pid} GMESH_ERRORS_OK", flush=True)
"""


def test_global_mesh_error_sweep():
    """Per-op cross-rank mismatch sweep + recovery through the global
    sequence log (errors must surface on EVERY process identically)."""
    result = _run_gmesh(ERROR_SWEEP_GMESH)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr[-3000:]}"
    for p in range(2):
        assert f"proc {p} GMESH_ERRORS_OK" in result.stdout


POD81_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
pid = int(os.environ["HVD_RANK"])
r = hvd.rank()
assert hvd.size() == 8, hvd.size()
assert hvd.local_size() == 1, hvd.local_size()
assert hvd.cross_size() == 8, hvd.cross_size()
assert r == pid

# flat eager pass first
out = np.asarray(hvd.allreduce(jnp.full((5,), float(r)), op=hvd.Sum,
                               name="pod.ar"))
np.testing.assert_allclose(out, np.full((5,), 28.0))

# hierarchical allreduce over the (cross=2, local=4) split: SAME numbers
# as flat (communication-schedule choice only), exercised over a payload
# that needs padding to the local*64 alignment
from horovod_tpu.common import basics
st = basics._get_state()
assert st.executor.hier_mesh is not None, "hier mesh missing"
assert st.executor.hierarchical_allreduce, "hier allreduce not enabled"
x = jnp.arange(130, dtype=jnp.float32) + 1000.0 * r
out = np.asarray(hvd.allreduce(x, op=hvd.Sum, name="pod.har"))
expect = np.arange(130, dtype=np.float32) * 8 + 1000.0 * sum(range(8))
np.testing.assert_allclose(out, expect, rtol=1e-6)

# hierarchical average with prescale
out = np.asarray(hvd.allreduce(jnp.full((66,), float(r)),
                               prescale_factor=2.0, name="pod.havg"))
np.testing.assert_allclose(out, np.full((66,), 7.0))

# hierarchical allgather
assert st.executor.hierarchical_allgather
g = np.asarray(hvd.allgather(jnp.full((2, 3), float(r)), name="pod.hag"))
expect = np.concatenate([np.full((2, 3), float(i)) for i in range(8)])
np.testing.assert_allclose(g, expect)

# broadcast + alltoall ride the same 8x1 gang
b = np.asarray(hvd.broadcast(jnp.full((4,), float(r)), root_rank=6,
                             name="pod.bc"))
np.testing.assert_allclose(b, np.full((4,), 6.0))
t = jnp.arange(8, dtype=jnp.float32) + 100 * r
out = np.asarray(hvd.alltoall(t, name="pod.a2a"))
np.testing.assert_allclose(
    out, np.array([float(src * 100 + r) for src in range(8)]))

print(f"proc {pid} POD81_OK", flush=True)
hvd.shutdown()
"""


def test_global_mesh_8x1_hierarchical_gang():
    """VERDICT r3 item 7: the pod-realistic 8-process x 1-device shape
    with hierarchical allreduce/allgather over an explicit
    (cross=2, local=4) split, so the first real pod run has zero new
    code paths (reference: nccl_operations.cc:162-289 topology split)."""
    result = _run_gmesh(POD81_WORKER, np_=8, devices_per_proc=1,
                        extra_env={
                            "HVD_HIERARCHICAL_ALLREDUCE": "1",
                            "HVD_HIERARCHICAL_ALLGATHER": "1",
                            "HVD_HIER_LOCAL_SIZE": "4",
                        })
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("POD81_OK") == 8


ZIGZAG_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.parallel import (make_mesh, reference_attention,
                                  zigzag_ring_self_attention)

hvd.init()
mesh = make_mesh({"sp": len(jax.devices())})   # 8 devices over 2 procs

rng = np.random.RandomState(0)                 # same data on both hosts
b, t, h, d = 1, 128, 2, 16
q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
           for _ in range(3))
got = zigzag_ring_self_attention(q, k, v, mesh, use_flash=False)
exp = reference_attention(q, k, v, causal=True)
from jax.experimental import multihost_utils
got_np = np.asarray(multihost_utils.process_allgather(got, tiled=True))
np.testing.assert_allclose(got_np, np.asarray(exp),
                           rtol=2e-4, atol=2e-4)
print("GMESH_ZIGZAG_OK", flush=True)
hvd.shutdown()
"""


def test_global_mesh_zigzag_attention():
    """Zigzag (balanced causal) ring over the REAL 2-process x 4-device
    global mesh gang — the pod wiring — must be exact attention."""
    result = _run_gmesh(ZIGZAG_WORKER)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr[-3000:]}"
    assert result.stdout.count("GMESH_ZIGZAG_OK") == 2
