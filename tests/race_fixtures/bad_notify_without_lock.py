"""Known-bad hvd-race fixture: the publisher writes the condition's
predicate OUTSIDE the lock before notifying under it — the classic
lost-update shape.  The consumer's predicate read (holding the cv)
races the unlocked write: disjoint locksets, and no happens-before
edge connects them (the notify→wake edge orders only the accesses
AFTER the wakeup)."""

import threading
import time


class Box:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False   # guarded by self._cv
        self.value = None    # guarded by self._cv

    def consume(self):
        with self._cv:
            while not self.ready:
                self._cv.wait(timeout=5)
            return self.value

    def publish(self, value):
        # BUG: the predicate writes happen before the lock is taken
        self.value = value
        self.ready = True
        with self._cv:
            self._cv.notify_all()


def main():
    box = Box()
    consumer = threading.Thread(target=box.consume)
    consumer.start()
    time.sleep(0.2)   # let the consumer check the predicate first
    box.publish(42)
    consumer.join()


if __name__ == "__main__":
    main()
