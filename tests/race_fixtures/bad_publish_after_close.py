"""Known-bad hvd-race fixture: close() tears down the output buffer
while a publisher thread is still reading it — the shape of the real
close()-strands-_flush_sends race PR 3 fixed by hand in the ring data
plane.  The publisher's unlocked read of ``out`` races close()'s
unlocked teardown write: no common lock, no happens-before edge
(close never waits for the publisher)."""

import threading
import time


class Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self.out = []      # guarded by self._lock
        self.closing = False

    def publish_loop(self):
        for _ in range(100):
            buf = self.out          # BUG: read without the lock
            if buf is None:
                return
            buf.append(1)
            time.sleep(0.002)

    def close(self):
        # BUG: tears down state the publisher still reads, without
        # taking the lock or waiting for the publisher to exit
        self.out = None


def main():
    sink = Sink()
    publisher = threading.Thread(target=sink.publish_loop)
    publisher.start()
    time.sleep(0.05)
    sink.close()
    publisher.join()


if __name__ == "__main__":
    main()
