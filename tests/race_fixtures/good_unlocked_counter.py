"""Good twin of ``bad_unlocked_counter``: the same increment loop with
the counter's lock held — both threads' locksets share ``_lock``, so
no report."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0   # guarded by self._lock

    def bump(self):
        for _ in range(200):
            with self._lock:
                self.count += 1


def main():
    counter = Counter()
    workers = [threading.Thread(target=counter.bump) for _ in range(2)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    return counter.count


if __name__ == "__main__":
    main()
