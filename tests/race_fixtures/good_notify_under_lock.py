"""Good twin of ``bad_notify_without_lock``: predicate and payload are
written under the condition's lock, exactly as the annotation
declares — consumer and publisher locksets share the cv."""

import threading
import time


class Box:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False   # guarded by self._cv
        self.value = None    # guarded by self._cv

    def consume(self):
        with self._cv:
            while not self.ready:
                self._cv.wait(timeout=5)
            return self.value

    def publish(self, value):
        with self._cv:
            self.value = value
            self.ready = True
            self._cv.notify_all()


def main():
    box = Box()
    consumer = threading.Thread(target=box.consume)
    consumer.start()
    time.sleep(0.2)
    box.publish(42)
    consumer.join()


if __name__ == "__main__":
    main()
