"""Known-bad hvd-race fixture: a shared counter incremented by two
threads with no lock at all — the canonical Eraser write-write (and
read-write: ``+=`` is a read then a write) race.  Caught regardless of
interleaving: the accesses have empty locksets and no happens-before
path connects sibling threads that were both started before either
join."""

import threading


class Counter:
    def __init__(self):
        self.count = 0

    def bump(self):
        for _ in range(200):
            self.count += 1


def main():
    counter = Counter()
    workers = [threading.Thread(target=counter.bump) for _ in range(2)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    return counter.count


if __name__ == "__main__":
    main()
