"""Good twin of ``bad_publish_after_close``: close() signals the
publisher to stop (event set→wait edge) and JOINS it before tearing
the buffer down — the child-exit→joiner happens-before edge orders
every publisher read before close()'s write, so the same unlocked
teardown is race-free."""

import threading
import time


class Sink:
    def __init__(self):
        self._stop = threading.Event()
        self.out = []
        self._publisher = None

    def publish_loop(self):
        while not self._stop.is_set():
            self.out.append(1)
            time.sleep(0.002)

    def start(self):
        self._publisher = threading.Thread(target=self.publish_loop)
        self._publisher.start()

    def close(self):
        self._stop.set()
        self._publisher.join()
        # ordered after every publisher access by the join edge
        self.out = None


def main():
    sink = Sink()
    sink.start()
    time.sleep(0.05)
    sink.close()


if __name__ == "__main__":
    main()
