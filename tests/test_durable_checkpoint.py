"""Graceful drain + durable sharded checkpointing (docs/checkpoint.md).

Unit layer: the shard/manifest store (digest verification, atomicity
contract, newest-first listing), the CheckpointManager (interval
gating, retention pruning, fallback past corrupt or incomplete
manifests, cross-world shard re-assembly), the drain protocol pieces
(preempt fault action, drain-marked directives, coordinator busy/
draining liveness interplay, culprit attribution, the launcher grace
window), and the dead-epoch rendezvous scope purge primitive.

Integration layer, against real worker processes on the tcp plane:

- the preempt matrix cell — rank 2 of 4 is SIGTERM'd mid-training,
  drains with ZERO ``HvdAbortedError`` anywhere, exits 0, and the
  survivors converge bitwise to an uninterrupted 3-rank run;
- the acceptance scenario — the drained job checkpoints durably, the
  whole job is then killed mid-step, and a fresh 3-rank job
  auto-resumes from the newest complete manifest to finish
  digest-identical to an uninterrupted run;
- cross-world resume — a checkpoint written at world 4 resumes on 3;
- the throttled-writer liveness regression (busy-flagged heartbeats);
- the checkpoint writer thread is clean under the hvd-race shim.
"""

import glob
import importlib.machinery
import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from conftest import spawn_tcp_ranks
from horovod_tpu.checkpoint import CheckpointManager, store
from horovod_tpu.common.handles import (HvdAbortedError, HvdDrainedError,
                                        HvdError, HvdReconfigureError,
                                        is_drain_reason, make_abort_error)
from horovod_tpu.elastic.state import State

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _standalone_runtime(monkeypatch):
    """In-process suites that ran earlier may leave the threaded runtime
    initialized (size N) in this interpreter; these units model a
    standalone pre-init process, where ``CheckpointManager`` falls back
    to the (rank 0, world 1) topology.  Subprocess tests are unaffected."""
    from horovod_tpu.common import basics
    monkeypatch.setattr(basics, "is_initialized", lambda: False)


# ------------------------------------------------------------ store ---------
def test_shard_roundtrip_and_digest_verification(tmp_path):
    payload = {"params": np.arange(16, dtype=np.float32),
               "opt_sharded": {"0": np.ones(4, np.float32)},
               "opt_rest": {}}
    store.write_shard(str(tmp_path), 7, 1, 2, 0, payload)
    got = store.read_shard(str(tmp_path), 7, 1, 2, 0)
    assert np.array_equal(np.asarray(got["params"]), payload["params"])
    assert np.array_equal(np.asarray(got["opt_sharded"]["0"]),
                          payload["opt_sharded"]["0"])
    # no torn .tmp files survive the atomic rename
    assert not glob.glob(str(tmp_path / "*.tmp.*"))


def test_corrupt_or_missing_shard_raises_typed_error(tmp_path):
    store.write_shard(str(tmp_path), 3, 0, 1, 0,
                      {"params": np.arange(8, dtype=np.float32)})
    path = tmp_path / store.shard_name(3, 0, 1, 0)

    blob = bytearray(path.read_bytes())
    blob[len(blob) // 2] ^= 0xFF          # flip one payload byte
    path.write_bytes(bytes(blob))
    with pytest.raises(store.CorruptShardError):
        store.read_shard(str(tmp_path), 3, 0, 1, 0)

    # truncation trips the byte-count check before the digest
    path.write_bytes(bytes(blob[:-4]))
    with pytest.raises(store.CorruptShardError):
        store.read_shard(str(tmp_path), 3, 0, 1, 0)

    os.remove(f"{path}.meta.json")        # missing sidecar
    with pytest.raises(store.CorruptShardError):
        store.read_shard(str(tmp_path), 3, 0, 1, 0)
    with pytest.raises(store.CorruptShardError):
        store.read_shard(str(tmp_path), 99, 0, 1, 0)   # never written


def test_list_manifests_newest_first(tmp_path):
    for step, epoch, world in [(5, 0, 4), (10, 0, 3), (10, 1, 3)]:
        store.write_manifest(str(tmp_path), step, epoch, world)
    assert store.list_manifests(str(tmp_path)) == [
        (10, 1, 3), (10, 0, 3), (5, 0, 4)]
    assert store.list_manifests(str(tmp_path / "nonexistent")) == []


# ---------------------------------------------------------- manager ---------
def _commit_steps(state, manager, steps):
    """Drive commits one at a time, draining the writer between them so
    the latest-wins slot cannot coalesce snapshots under test."""
    for _ in range(steps):
        state.params["w"] = state.params["w"] + 1.0
        state.step += 1
        state.commit()
        assert manager.wait(timeout=30)


def test_interval_gates_and_keep_prunes(tmp_path):
    state = State(params={"w": np.zeros(8, np.float32)})
    m = CheckpointManager(str(tmp_path), interval_steps=3, keep=0)
    state.attach_checkpoint(m)
    try:
        _commit_steps(state, m, 7)
    finally:
        m.close()
    assert store.list_manifests(str(tmp_path)) == [(6, 0, 1), (3, 0, 1)]

    pruned = tmp_path / "pruned"
    state2 = State(params={"w": np.zeros(8, np.float32)})
    m2 = CheckpointManager(str(pruned), interval_steps=1, keep=1)
    state2.attach_checkpoint(m2)
    try:
        _commit_steps(state2, m2, 3)
    finally:
        m2.close()
    assert store.list_manifests(str(pruned)) == [(3, 0, 1)]
    assert store.list_own_shards(str(pruned), 0) == [(3, 0, 1)]


def test_restore_round_trips_params_and_optimizer(tmp_path):
    state = State(params={"w": np.zeros(8, np.float32)},
                  optimizer_state={"m": np.full(8, 2.0, np.float32),
                                   "count": np.float32(5)})
    m = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    state.attach_checkpoint(m)
    try:
        _commit_steps(state, m, 4)
    finally:
        m.close()

    fresh = State(params={"w": np.zeros(8, np.float32)},
                  optimizer_state={"m": np.zeros(8, np.float32),
                                   "count": np.float32(0)})
    m2 = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    try:
        assert m2.restore_latest(fresh) == (4, 0)
    finally:
        m2.close()
    assert fresh.step == 4
    assert np.array_equal(fresh.params["w"], np.full(8, 4.0))
    assert np.array_equal(fresh.optimizer_state["m"], np.full(8, 2.0))
    assert float(fresh.optimizer_state["count"]) == 5.0
    # restore installed the snapshot as the committed rollback point
    fresh.params["w"] += 99.0
    fresh.restore()
    assert np.array_equal(fresh.params["w"], np.full(8, 4.0))


def test_corrupt_newest_falls_back_to_previous_complete(tmp_path):
    state = State(params={"w": np.zeros(8, np.float32)})
    m = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    state.attach_checkpoint(m)
    try:
        _commit_steps(state, m, 2)
    finally:
        m.close()

    shard = tmp_path / store.shard_name(2, 0, 1, 0)
    blob = bytearray(shard.read_bytes())
    blob[0] ^= 0xFF
    shard.write_bytes(bytes(blob))

    fresh = State(params={"w": np.zeros(8, np.float32)})
    m2 = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    try:
        assert m2.restore_latest(fresh) == (1, 0)
    finally:
        m2.close()
    assert np.array_equal(fresh.params["w"], np.full(8, 1.0))


def test_incomplete_manifest_missing_world_shard_is_skipped(tmp_path):
    # a complete world-1 checkpoint at step 3 ...
    state = State(params={"w": np.zeros(8, np.float32)})
    m = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    state.attach_checkpoint(m)
    try:
        _commit_steps(state, m, 3)
    finally:
        m.close()
    # ... then a NEWER world-2 checkpoint with only rank 0's shard on
    # disk (rank 1 died pre-write): manifest present, validation fails
    m2 = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    try:
        m2._write({"params": {"w": np.full(8, 9.0, np.float32)},
                   "opt": None, "opt_full": False,
                   "step": 5, "epoch": 0, "rank": 0, "world": 2})
    finally:
        m2.close()
    assert store.list_manifests(str(tmp_path))[0] == (5, 0, 2)

    fresh = State(params={"w": np.zeros(8, np.float32)})
    m3 = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    try:
        assert m3.restore_latest(fresh) == (3, 0)
    finally:
        m3.close()
    assert np.array_equal(fresh.params["w"], np.full(8, 3.0))


def test_shape_mismatched_checkpoint_is_not_resumed(tmp_path):
    state = State(params={"w": np.zeros(8, np.float32)})
    m = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    state.attach_checkpoint(m)
    try:
        _commit_steps(state, m, 1)
    finally:
        m.close()
    grown = State(params={"w": np.zeros(12, np.float32)})
    m2 = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    try:
        assert m2.restore_latest(grown) is None
    finally:
        m2.close()
    assert np.array_equal(grown.params["w"], np.zeros(12))


def test_cross_world_restore_reassembles_four_shards(tmp_path,
                                                    monkeypatch):
    """Shards written by 4 ranks (params + FULL-form optimizer) must
    re-assemble into the exact original vectors on restore — the
    byte-level contract behind resuming a w4 checkpoint at any world."""
    n = 10
    params = {"w": np.arange(n, dtype=np.float32)}
    opt = {"count": np.float32(7.0),
           "m": np.arange(n, dtype=np.float32) * 2.0}
    m = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    try:
        for rank in range(4):
            m._write({"params": params, "opt": opt, "opt_full": True,
                      "step": 40, "epoch": 1, "rank": rank, "world": 4})
    finally:
        m.close()
    manifest = store.read_manifest(str(tmp_path), 40, 1, 4)
    assert manifest["n_params"] == n
    assert manifest["opt_kind"] == "full"
    # each rank's shard holds only ITS block of the row partition
    assert len(store.read_shard(str(tmp_path), 40, 1, 4, 0)["params"]) == 3
    assert len(store.read_shard(str(tmp_path), 40, 1, 4, 3)["params"]) == 2

    # restore at world 1 (reshard is a passthrough there): the restored
    # live state must equal the original full vectors bit-for-bit
    from horovod_tpu.sharding import zero as zero_mod
    monkeypatch.setattr(zero_mod, "_topology_of",
                        lambda basics, group=None: (0, 1))
    fresh = State(params={"w": np.zeros(n, np.float32)},
                  optimizer_state={"count": np.float32(0),
                                   "m": np.zeros(n, np.float32)},
                  zero_n_params=n)
    m2 = CheckpointManager(str(tmp_path), interval_steps=1, keep=0)
    try:
        assert m2.restore_latest(fresh) == (40, 1)
    finally:
        m2.close()
    assert fresh.step == 40 and fresh.epoch == 1
    assert np.array_equal(fresh.params["w"], params["w"])
    assert np.array_equal(np.asarray(fresh.optimizer_state["m"]),
                          opt["m"])
    assert float(fresh.optimizer_state["count"]) == 7.0
    assert fresh._opt_full is True


def test_manager_from_env_reads_env_contract(tmp_path, monkeypatch):
    import horovod_tpu.checkpoint as ckpt
    from horovod_tpu.common import basics

    # force the env path even when another test initialized the runtime
    monkeypatch.setattr(basics, "is_initialized", lambda: False)
    monkeypatch.delenv("HVD_TPU_CKPT_DIR", raising=False)
    assert ckpt.manager_from_env() is None
    monkeypatch.setenv("HVD_TPU_CKPT_DIR", str(tmp_path / "ck"))
    monkeypatch.setenv("HVD_TPU_CKPT_INTERVAL", "7")
    monkeypatch.setenv("HVD_TPU_CKPT_KEEP", "3")
    m = ckpt.manager_from_env()
    try:
        assert (m._dir, m._interval, m._keep) == (
            str(tmp_path / "ck"), 7, 3)
    finally:
        m.close()


# ------------------------------------------------------ drain protocol ------
def test_fault_grammar_accepts_preempt():
    from horovod_tpu.common.faults import parse_fault_spec

    (spec,) = parse_fault_spec("rank2:allreduce:3:preempt")
    assert (spec.rank, spec.point, spec.step, spec.action) == (
        2, "allreduce", 3, "preempt")
    with pytest.raises(ValueError):
        parse_fault_spec("rank2:allreduce:3:sigterm")


def test_preempt_action_delivers_sigterm_to_self():
    from horovod_tpu.common import faults

    got = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    try:
        faults.configure("rank0:unit_point:1:preempt", rank=0)
        # the operation itself proceeds (not a drop) ...
        assert faults.check("unit_point") is False
        # ... and the preemption notice lands on this process
        for _ in range(200):
            if got:
                break
            time.sleep(0.005)
        assert got == [signal.SIGTERM]
        assert faults.check("unit_point") is False   # fires exactly once
        assert got == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)
        faults.configure(None)


def _load_chaos():
    loader = importlib.machinery.SourceFileLoader(
        "hvd_chaos_under_test", os.path.join(REPO, "bin", "hvd-chaos"))
    spec = importlib.util.spec_from_loader(loader.name, loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def test_chaos_preempt_cells_are_elastic_only_and_deterministic():
    from horovod_tpu.common.faults import parse_fault_spec

    chaos = _load_chaos()
    for seed in range(40):
        plain = chaos.generate_spec(seed, 4, 3)
        assert plain == chaos.generate_spec(seed, 4, 3)  # reproducible
        assert "preempt" not in plain    # non-elastic pool unchanged
        parse_fault_spec(plain)
        elastic = chaos.generate_spec(seed, 4, 3, elastic=True)
        assert elastic == chaos.generate_spec(seed, 4, 3, elastic=True)
        parse_fault_spec(elastic)
    assert any("preempt" in chaos.generate_spec(s, 4, 3, elastic=True)
               for s in range(40))


def test_pick_culprit_never_blames_a_clean_exit():
    from horovod_tpu.run.launch import pick_culprit

    # the drained rank exited 0 FIRST; the real failure exited 9 later
    failures = [(2, 0, False, 1.0), (1, 9, False, 2.0)]
    assert pick_culprit(failures) == (1, 9)
    # even when the fault spec armed the drained rank with the preempt
    assert pick_culprit(failures, crash_ranks=frozenset({2})) == (1, 9)


def test_termination_grace_window_env(monkeypatch):
    from horovod_tpu.run import safe_shell_exec

    monkeypatch.delenv("HVD_TPU_TERM_GRACE", raising=False)
    assert safe_shell_exec.termination_grace_seconds() == 5.0
    monkeypatch.setenv("HVD_TPU_TERM_GRACE", "9.5")
    assert safe_shell_exec.termination_grace_seconds() == 9.5


def test_drained_sentinel_and_error_taxonomy():
    import horovod_tpu as hvd

    assert not hvd.elastic.DRAINED             # falsy ...
    assert hvd.elastic.DRAINED is not None     # ... but not None
    assert repr(hvd.elastic.DRAINED) == "hvd.elastic.DRAINED"
    exc = HvdDrainedError(3)
    assert isinstance(exc, HvdError)
    assert not isinstance(exc, HvdAbortedError)   # a drain is a success
    assert exc.worker_id == 3 and hvd.HvdDrainedError is HvdDrainedError


def test_drain_marked_directive_roundtrip():
    from horovod_tpu.common.handles import encode_reconfig_reason

    reason = encode_reconfig_reason(2, [0, 1, 3], [2], "drained",
                                    drain=True)
    assert is_drain_reason(reason)
    exc = make_abort_error(2, reason)
    assert isinstance(exc, HvdReconfigureError) and exc.drain
    plain = encode_reconfig_reason(2, [0, 1, 3], [2], "died")
    assert not is_drain_reason(plain)
    assert not make_abort_error(2, plain).drain
    assert not is_drain_reason("rank 2 died")


def test_plan_drain_marks_directive_and_respects_refusals():
    from horovod_tpu.elastic.membership import ElasticContext

    ctx = ElasticContext(members=[0, 1, 2, 3], epoch=0)
    exc = make_abort_error(2, ctx.plan_drain(2))
    assert exc.drain and exc.epoch == 1
    assert exc.members == [0, 1, 3] and exc.dead == [2]
    # a drain racing an already-decided plan is refused
    assert ctx.plan_drain(3) is None
    # coordinator rank and min-ranks refusals
    assert ElasticContext(members=[0, 1], epoch=0).plan_drain(0) is None
    assert ElasticContext(members=[0, 1], epoch=0,
                          min_ranks=2).plan_drain(1) is None


def test_coordinator_grants_drain_and_publishes_pull_only_directive():
    from horovod_tpu.elastic.membership import ElasticContext
    from horovod_tpu.ops.tcp_controller import (CoordinatorService,
                                                DrainAck, DrainMsg)
    from horovod_tpu.run.service import secret

    ctx = ElasticContext(members=[0, 1, 2, 3], epoch=0)
    svc = CoordinatorService(4, secret.make_secret_key(), elastic=ctx)
    try:
        ack = svc._handle(DrainMsg(2), None)
        assert isinstance(ack, DrainAck) and ack.ok
        with svc._cv:
            assert 2 in svc._draining
        origin, reason = svc._abort
        assert origin == 2 and is_drain_reason(reason)
        exc = make_abort_error(origin, reason)
        assert exc.members == [0, 1, 3] and exc.drain
    finally:
        svc.shutdown()


def test_coordinator_refuses_drain_without_elastic_context():
    from horovod_tpu.ops.tcp_controller import (CoordinatorService,
                                                DrainAck, DrainMsg)
    from horovod_tpu.run.service import secret

    svc = CoordinatorService(4, secret.make_secret_key())
    try:
        ack = svc._handle(DrainMsg(2), None)
        assert isinstance(ack, DrainAck) and not ack.ok
        assert svc._abort is None         # nothing aborted
        with svc._cv:                     # liveness blame restored
            assert 2 not in svc._draining
    finally:
        svc.shutdown()


def test_inprocess_controllers_refuse_drain():
    from horovod_tpu.ops.global_controller import GlobalMeshController
    from horovod_tpu.ops.python_controller import PythonController

    assert PythonController.request_drain(
        object.__new__(PythonController)) is False
    assert GlobalMeshController.request_drain(
        object.__new__(GlobalMeshController)) is False


# ------------------------------------------- busy / liveness interplay ------
def test_busy_window_nests_and_reports():
    from horovod_tpu.common import busy

    assert not busy.active()
    with busy.window():
        assert busy.active()
        with busy.window():
            assert busy.active()
        assert busy.active()
    assert not busy.active()


def _liveness_svc():
    from horovod_tpu.ops.tcp_controller import CoordinatorService
    from horovod_tpu.run.service import secret

    return CoordinatorService(2, secret.make_secret_key(),
                              liveness_timeout_sec=10.0)


def test_busy_rank_gets_doubled_liveness_window():
    from horovod_tpu.run.service import network

    svc = _liveness_svc()
    try:
        svc._handle(network.HeartbeatMsg(1, busy=True), None)
        with svc._cv:
            svc._last_seen[0] = time.monotonic()
            svc._last_seen[1] = time.monotonic() - 15.0   # 1.5x window
            svc._last_liveness_scan = 0.0   # open the scan time-gate
        svc._check_liveness()
        assert svc._abort is None        # busy: the deadline doubled
        with svc._cv:
            svc._last_seen[1] = time.monotonic() - 25.0   # past 2x
            svc._last_liveness_scan = 0.0
        svc._check_liveness()
        assert svc._abort is not None and svc._abort[0] == 1
    finally:
        svc.shutdown()


def test_non_busy_rank_keeps_plain_window():
    from horovod_tpu.run.service import network

    svc = _liveness_svc()
    try:
        svc._handle(network.HeartbeatMsg(1, busy=False), None)
        with svc._cv:
            svc._last_seen[0] = time.monotonic()
            svc._last_seen[1] = time.monotonic() - 15.0
            svc._last_liveness_scan = 0.0   # open the scan time-gate
        svc._check_liveness()
        assert svc._abort is not None and svc._abort[0] == 1
    finally:
        svc.shutdown()


def test_draining_rank_is_exempt_from_liveness_blame():
    svc = _liveness_svc()
    try:
        with svc._cv:
            svc._draining.add(1)
            svc._last_seen[0] = time.monotonic()
            svc._last_seen[1] = time.monotonic() - 100.0
        svc._check_liveness()
        assert svc._abort is None        # its silence is the departure
    finally:
        svc.shutdown()


# ------------------------------------------------- rendezvous scope purge ---
def test_delete_scope_purges_dead_epoch_keys_only():
    from horovod_tpu.run import http_client
    from horovod_tpu.run.http_server import RendezvousServer

    server = RendezvousServer()
    port = server.start()
    try:
        http_client.put("127.0.0.1", port, "controller.e1", "addr", b"x")
        http_client.put("127.0.0.1", port, "peers.e1", "r0", b"y")
        http_client.put("127.0.0.1", port, "controller.e2", "addr", b"z")
        for scope in ("controller.e1", "peers.e1"):
            http_client.delete_scope("127.0.0.1", port, scope)
            assert http_client.list_keys("127.0.0.1", port, scope) == []
        with pytest.raises(KeyError):
            http_client.get("127.0.0.1", port, "controller.e1", "addr",
                            timeout=0.2)
        # the live epoch's scope is untouched
        assert http_client.get("127.0.0.1", port, "controller.e2",
                               "addr", timeout=2) == b"z"
    finally:
        server.stop()


# ------------------------------------------------------------ integration ---
CKPT_WORKER = r"""
import hashlib, os, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

wid = int(os.environ["HVD_RANK"])
steps = int(os.environ.get("EL_STEPS", "6"))
die_at = int(os.environ.get("EL_DIE_AT", "-1"))

hvd.init()

state = hvd.elastic.State(
    params={"w": jnp.zeros((1000,), dtype=jnp.float32)}, step=0)

def train(state):
    while state.step < steps:
        if state.step == die_at:
            # deterministic whole-job kill: give the background writer
            # time to drain the committed snapshot, then die hard
            time.sleep(1.0)
            os._exit(1)
        # integer-valued and identical on every rank: the allreduce
        # average is EXACT for any world size, so the final params are
        # bitwise-independent of membership (and resume) history
        grad = jnp.full((1000,), float(state.step + 1),
                        dtype=jnp.float32)
        avg = hvd.allreduce(grad, op=hvd.Average,
                            name=f"elastic.grad.{state.step}")
        state.params = {"w": state.params["w"] - avg}
        state.step += 1
        state.commit()

try:
    result = hvd.elastic.run(train, state)
except hvd.HvdAbortedError as exc:
    print(f"wid {wid} ABORTED origin={exc.origin_rank}", flush=True)
    raise SystemExit(0)
if result is hvd.elastic.DRAINED:
    print(f"wid {wid} DRAINED", flush=True)
    raise SystemExit(0)
digest = hashlib.sha1(
    np.asarray(state.params["w"]).tobytes()).hexdigest()
print(f"rank {hvd.rank()} wid {wid} DIGEST={digest} "
      f"size={hvd.size()} steps={state.step}", flush=True)
hvd.shutdown()
print(f"wid {wid} DONE", flush=True)
"""

_EL_ENV = {
    "JAX_PLATFORMS": "cpu",
    "HVD_TPU_HEARTBEAT_INTERVAL": "0.25",
    "HVD_TPU_ABORT_TIMEOUT": "10",
    "HVD_TPU_LIVENESS_TIMEOUT": "2",
    "HVD_TPU_RECONFIG_TIMEOUT": "60",
    "HVD_STALL_CHECK_TIME_SECONDS": "1",
    "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
    "HVD_TCP_RING_THRESHOLD": "1024",
}


def _digests(results, ranks):
    out = {}
    for r in ranks:
        code, stdout, stderr = results[r]
        assert code == 0, f"rank {r}: {stdout}\n{stderr}"
        line = next(l for l in stdout.splitlines() if "DIGEST=" in l)
        fields = dict(kv.split("=") for kv in line.split() if "=" in kv)
        out[r] = (fields["DIGEST"], int(fields["size"]),
                  int(fields["steps"]))
    return out


def _assert_zero_aborts(results, ranks):
    for r in ranks:
        assert "ABORTED" not in results[r][1], \
            f"rank {r}: {results[r][1]}\n{results[r][2]}"
        assert "HvdAbortedError" not in results[r][2], \
            f"rank {r} stderr: {results[r][2]}"


_REFERENCE_DIGESTS = {}


def _reference_digest(world, steps):
    """Rank-0 digest of an uninterrupted ``world``-rank, ``steps``-step
    run — memoized, several tests compare against the same baseline."""
    key = (world, steps)
    if key not in _REFERENCE_DIGESTS:
        results = spawn_tcp_ranks(world, CKPT_WORKER, timeout=150,
                                  extra_env={**_EL_ENV,
                                             "EL_STEPS": str(steps)})
        _REFERENCE_DIGESTS[key] = _digests(
            results, ranks=list(range(world)))[0][0]
    return _REFERENCE_DIGESTS[key]


# The five scenario tests below spawn real multi-rank TCP jobs (tens of
# seconds each).  They carry the `slow` marker to stay out of the
# wall-clock-capped tier-1 sweep — the dedicated `checkpoint` CI job
# (bin/gen-ci) runs this file unfiltered, so they remain enforced.
@pytest.mark.slow
def test_preempt_drains_rank_and_survivors_converge_bitwise():
    """The preempt matrix cell: rank 2 of 4 receives SIGTERM at its
    third allreduce.  It must drain (exit 0, DRAINED marker), every
    survivor must reconfigure with ZERO ``HvdAbortedError``, and the
    survivors' final params must be bitwise-identical to an
    uninterrupted 3-rank run."""
    results = spawn_tcp_ranks(4, CKPT_WORKER, timeout=150, extra_env={
        **_EL_ENV,
        "HVD_TPU_ELASTIC": "1",
        "HVD_TPU_FAULT_SPEC": "rank2:allreduce:3:preempt",
    })
    code2, out2, err2 = results[2]
    assert code2 == 0, f"drained rank exited {code2}: {out2}\n{err2}"
    assert "wid 2 DRAINED" in out2, out2
    _assert_zero_aborts(results, ranks=[0, 1, 2, 3])
    got = _digests(results, ranks=[0, 1, 3])
    for r, (digest, size, steps) in got.items():
        assert size == 3, f"rank {r} finished at world size {size}"
        assert steps == 6
    assert len({d for d, _, _ in got.values()}) == 1, got

    assert got[0][0] == _reference_digest(3, 6), got


@pytest.mark.slow
def test_drain_then_whole_job_kill_auto_resumes_digest_identical(
        tmp_path):
    """The acceptance scenario (ISSUE: preemption-aware drain + durable
    checkpointing).  Phase 1: a 4-rank job checkpointing every commit
    loses rank 2 to a preemption drain at step 3, reconfigures to 3
    ranks, then the WHOLE job is killed at step 9.  Phase 2: a fresh
    3-rank job pointed at the same directory auto-resumes from the
    newest complete manifest and finishes digest-identical to an
    uninterrupted 3-rank run."""
    ckpt_dir = str(tmp_path / "ckpt")
    phase1 = spawn_tcp_ranks(4, CKPT_WORKER, timeout=180, extra_env={
        **_EL_ENV,
        "HVD_TPU_ELASTIC": "1",
        "EL_STEPS": "10",
        "HVD_TPU_CKPT_DIR": ckpt_dir,
        "HVD_TPU_CKPT_INTERVAL": "1",
        "HVD_TPU_FAULT_SPEC": (
            "rank2:allreduce:3:preempt,rank0:allreduce:9:crash,"
            "rank1:allreduce:9:crash,rank3:allreduce:9:crash"),
    })
    assert phase1[2][0] == 0, f"drained rank: {phase1[2][1]}"
    assert "wid 2 DRAINED" in phase1[2][1]
    for r in (0, 1, 3):
        # the whole-job kill landed: each survivor either died by its
        # own crash fault or caught the abort from a ring neighbor that
        # crashed mid-overlap — but nobody finished training
        assert phase1[r][0] != 0 or "ABORTED" in phase1[r][1], \
            f"rank {r}: {phase1[r][1]}\n{phase1[r][2]}"
        assert "DIGEST=" not in phase1[r][1], phase1[r][1]
    # durable evidence survived the kill: at least one manifest at w3
    assert any(w == 3 for _s, _e, w in store.list_manifests(ckpt_dir))

    phase2 = spawn_tcp_ranks(3, CKPT_WORKER, timeout=180, extra_env={
        **_EL_ENV,
        "HVD_TPU_ELASTIC": "1",
        "EL_STEPS": "10",
        "HVD_TPU_CKPT_DIR": ckpt_dir,
        "HVD_TPU_CKPT_INTERVAL": "1",
    })
    got = _digests(phase2, ranks=[0, 1, 2])
    assert "resumed from step" in phase2[0][2], phase2[0][2]
    for r, (digest, size, steps) in got.items():
        assert size == 3 and steps == 10
    assert len({d for d, _, _ in got.values()}) == 1, got

    assert got[0][0] == _reference_digest(3, 10), got


@pytest.mark.slow
def test_checkpoint_written_at_world4_resumes_on_3_ranks(tmp_path):
    """Cross-world resume: every rank of a 4-rank job dies at step 3
    (after the writer drained), so the ONLY checkpoints on disk are
    world-4 shards.  A 3-rank job must re-assemble them, re-shard to
    its own world, and finish digest-identical to an uninterrupted
    3-rank run."""
    ckpt_dir = str(tmp_path / "ckpt")
    phase1 = spawn_tcp_ranks(4, CKPT_WORKER, timeout=150, extra_env={
        **_EL_ENV,
        "HVD_TPU_ELASTIC": "1",
        "EL_STEPS": "6",
        "EL_DIE_AT": "3",
        "HVD_TPU_CKPT_DIR": ckpt_dir,
        "HVD_TPU_CKPT_INTERVAL": "1",
    })
    for r in range(4):
        assert phase1[r][0] == 1, f"rank {r}: {phase1[r][1]}"
    manifests = store.list_manifests(ckpt_dir)
    assert manifests and all(w == 4 for _s, _e, w in manifests)

    phase2 = spawn_tcp_ranks(3, CKPT_WORKER, timeout=150, extra_env={
        **_EL_ENV,
        "HVD_TPU_ELASTIC": "1",
        "EL_STEPS": "6",
        "HVD_TPU_CKPT_DIR": ckpt_dir,
        "HVD_TPU_CKPT_INTERVAL": "1",
    })
    assert "resumed from step 3" in phase2[0][2], phase2[0][2]
    got = _digests(phase2, ranks=[0, 1, 2])
    for r, (digest, size, steps) in got.items():
        assert size == 3 and steps == 6
    assert got[0][0] == _reference_digest(3, 6), got


BUSY_WORKER = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.checkpoint import CheckpointManager

hvd.init()
state = hvd.elastic.State(
    params={"w": jnp.zeros((1000,), dtype=jnp.float32)}, step=0)
m = CheckpointManager(os.environ["CKPT_DIR"], interval_steps=1, keep=0,
                      io_delay=float(os.environ["CKPT_IO_DELAY"]))
state.attach_checkpoint(m)
try:
    for _ in range(2):
        g = jnp.ones((1000,), dtype=jnp.float32)
        avg = hvd.allreduce(g, op=hvd.Average,
                            name=f"busy.{state.step}")
        state.params = {"w": state.params["w"] - avg}
        state.step += 1
        state.commit()
        assert m.wait(timeout=60)   # sit inside the throttled write
    # a collective AFTER the slow writes: the job must still be alive
    hvd.allreduce(jnp.ones((1000,), dtype=jnp.float32),
                  name="busy.final")
    assert m._errors == 0
    print(f"rank {hvd.rank()} BUSY_OK", flush=True)
finally:
    state.attach_checkpoint(None)
    m.close()
hvd.shutdown()
"""


@pytest.mark.slow
def test_throttled_writer_does_not_trip_liveness(tmp_path):
    """Liveness-interplay regression: each write sleeps 3 s inside the
    busy window while the liveness window is 2 s.  The busy-flagged
    heartbeats must keep every rank alive — no abort, no drain, both
    ranks finish clean."""
    results = spawn_tcp_ranks(2, BUSY_WORKER, timeout=120, extra_env={
        **_EL_ENV,
        "CKPT_DIR": str(tmp_path / "ckpt"),
        "CKPT_IO_DELAY": "3.0",
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
    })
    for r in (0, 1):
        code, out, err = results[r]
        assert code == 0, f"rank {r}: {out}\n{err}"
        assert "BUSY_OK" in out, f"rank {r}: {out}"
        assert "ABORTED" not in out


# ----------------------------------------------------------- race shim ------
RACE_CKPT_BODY = r"""
import os
import numpy as np
import horovod_tpu  # installs the race shim under HVD_TPU_RACE=1
from horovod_tpu.checkpoint import CheckpointManager
from horovod_tpu.elastic.state import State

state = State(params={"w": np.zeros((256,), np.float32)},
              optimizer_state={"m": np.zeros((256,), np.float32)})
m = CheckpointManager(os.environ["CKPT_DIR"], interval_steps=1, keep=2)
state.attach_checkpoint(m)
for _ in range(5):
    state.params["w"] = state.params["w"] + 1.0
    state.step += 1
    state.commit()       # racing the writer thread on purpose
assert m.wait(timeout=60)
fresh = State(params={"w": np.zeros((256,), np.float32)},
              optimizer_state={"m": np.zeros((256,), np.float32)})
assert m.restore_latest(fresh) is not None
m.close()
assert m._errors == 0
print("RACE_CKPT_OK", flush=True)
"""


@pytest.mark.slow
def test_checkpoint_writer_clean_under_race_shim(tmp_path):
    """The commit-path/writer-thread handoff (latest-wins slot, busy
    window, close/flush join) under the hvd-race shim with a fixed
    seed: zero non-baselined race reports."""
    from horovod_tpu.tools.lint import findings as findings_mod

    script = tmp_path / "race_ckpt_worker.py"
    script.write_text(RACE_CKPT_BODY)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({
        "JAX_PLATFORMS": "cpu",
        "HVD_TPU_RACE": "1",
        "HVD_TPU_RACE_SEED": "3",
        "HVD_TPU_RACE_REPORT": str(tmp_path / "ckpt"),
        "CKPT_DIR": str(tmp_path / "store"),
    })
    out = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, text=True, timeout=240,
                         cwd=REPO)
    assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
    assert "RACE_CKPT_OK" in out.stdout

    baseline = findings_mod.load_baseline(
        os.path.join(REPO, ".hvd-race-baseline.json"))
    active = []
    for path in sorted(glob.glob(str(tmp_path / "ckpt.*.json"))):
        with open(path) as f:
            data = json.load(f)
        active.extend(f for f in data["findings"]
                      if f["key"] not in baseline)
    assert not active, "\n".join(f["message"] for f in active)
