"""Config tri-surface precedence + compression round-trips (reference:
the env/CLI/YAML tri-surface kept in sync manually, ``runner.py:285-459``
+ ``config_parser.py``; compression: ``torch/compression.py:45``)."""

import numpy as np
import pytest

from horovod_tpu.run import config_parser
from horovod_tpu.run.runner import make_parser
from horovod_tpu.utils import env as env_util


def _parse(argv):
    return make_parser().parse_args(argv + ["python", "x.py"])


def test_cli_flag_maps_to_env():
    args = _parse(["-np", "2", "--fusion-threshold-mb", "16",
                   "--cycle-time-ms", "2.5", "--cache-capacity", "99"])
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_FUSION_THRESHOLD] == str(16 * 1024 * 1024)
    assert env[env_util.HVD_CYCLE_TIME] == "2.5"
    assert env[env_util.HVD_CACHE_CAPACITY] == "99"


def test_yaml_fills_unset_cli_flags(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "params:\n"
        "  fusion_threshold_mb: 8\n"
        "  cycle_time_ms: 7.0\n"
        "autotune:\n"
        "  enabled: true\n"
        "timeline:\n"
        "  filename: /tmp/t.json\n")
    args = _parse(["-np", "2", "--cycle-time-ms", "1.5"])
    config_parser.apply_config_to_args(
        args, config_parser.load_config_file(str(cfg)))
    env = config_parser.env_from_args(args)
    # CLI wins over YAML; YAML fills the rest
    assert env[env_util.HVD_CYCLE_TIME] == "1.5"
    assert env[env_util.HVD_FUSION_THRESHOLD] == str(8 * 1024 * 1024)
    assert env[env_util.HVD_AUTOTUNE] == "1"
    assert env[env_util.HVD_TIMELINE] == "/tmp/t.json"


def test_stall_and_log_flags_map():
    args = _parse(["-np", "2", "--no-stall-check",
                   "--stall-check-warning-time-seconds", "11",
                   "--stall-check-shutdown-time-seconds", "22",
                   "--log-level", "debug"])
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_STALL_CHECK_DISABLE] == "1"
    assert env[env_util.HVD_STALL_CHECK_TIME_SECONDS] == "11.0"
    assert env[env_util.HVD_STALL_SHUTDOWN_TIME_SECONDS] == "22.0"
    assert env[env_util.HVD_LOG_LEVEL] == "debug"


def test_config_from_env_roundtrip(monkeypatch):
    from horovod_tpu.common.config import Config

    monkeypatch.setenv(env_util.HVD_FUSION_THRESHOLD, "1048576")
    monkeypatch.setenv(env_util.HVD_CYCLE_TIME, "3.0")
    monkeypatch.setenv(env_util.HVD_STALL_CHECK_TIME_SECONDS, "9")
    cfg = Config.from_env()
    assert cfg.fusion_threshold_bytes == 1048576
    assert cfg.cycle_time_ms == 3.0
    assert cfg.stall_warning_seconds == 9


# ------------------------------------------------------------- compression --
def test_jax_compression_roundtrip(hvd):
    from horovod_tpu.common.compression import Compression

    import jax.numpy as jnp

    x = jnp.linspace(-3, 3, 64, dtype=jnp.float32)
    comp, ctx = Compression.fp16.compress(x)
    assert str(np.asarray(comp).dtype) == "float16"
    out = Compression.fp16.decompress(comp, ctx)
    assert str(np.asarray(out).dtype) == "float32"
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=0.05)

    comp, ctx = Compression.bf16.compress(x)
    assert str(np.asarray(comp).dtype) == "bfloat16"
    out = Compression.bf16.decompress(comp, ctx)
    assert str(np.asarray(out).dtype) == "float32"

    comp, ctx = Compression.none.compress(x)
    np.testing.assert_array_equal(
        np.asarray(Compression.none.decompress(comp, ctx)), x)


def test_torch_compression_roundtrip():
    torch = pytest.importorskip("torch")
    from horovod_tpu.torch.compression import Compression

    x = torch.linspace(-3, 3, 64)
    comp, ctx = Compression.fp16.compress(x)
    assert comp.dtype == torch.float16
    out = Compression.fp16.decompress(comp, ctx)
    assert out.dtype == torch.float32
    assert torch.allclose(out, x, atol=0.05)
    comp, ctx = Compression.bf16.compress(x)
    assert comp.dtype == torch.bfloat16


def test_tf_compression_roundtrip():
    tf = pytest.importorskip("tensorflow")
    from horovod_tpu.tensorflow.compression import Compression

    x = tf.linspace(-3.0, 3.0, 64)
    comp, ctx = Compression.fp16.compress(x)
    assert comp.dtype == tf.float16
    out = Compression.fp16.decompress(comp, ctx)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=0.05)
    comp, ctx = Compression.bf16.compress(x)
    assert comp.dtype == tf.bfloat16


def test_int_tensors_pass_compression_untouched():
    from horovod_tpu.common.compression import Compression

    import jax.numpy as jnp

    x = jnp.arange(10, dtype=jnp.int32)
    comp, ctx = Compression.fp16.compress(x)
    out = Compression.fp16.decompress(comp, ctx)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert np.asarray(out).dtype == np.int32
