"""Config tri-surface precedence + compression round-trips (reference:
the env/CLI/YAML tri-surface kept in sync manually, ``runner.py:285-459``
+ ``config_parser.py``; compression: ``torch/compression.py:45``)."""

import numpy as np
import pytest

from horovod_tpu.run import config_parser
from horovod_tpu.run.runner import make_parser
from horovod_tpu.utils import env as env_util


def _parse(argv):
    return make_parser().parse_args(argv + ["python", "x.py"])


def test_cli_flag_maps_to_env():
    args = _parse(["-np", "2", "--fusion-threshold-mb", "16",
                   "--cycle-time-ms", "2.5", "--cache-capacity", "99"])
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_FUSION_THRESHOLD] == str(16 * 1024 * 1024)
    assert env[env_util.HVD_CYCLE_TIME] == "2.5"
    assert env[env_util.HVD_CACHE_CAPACITY] == "99"


def test_yaml_fills_unset_cli_flags(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "params:\n"
        "  fusion_threshold_mb: 8\n"
        "  cycle_time_ms: 7.0\n"
        "autotune:\n"
        "  enabled: true\n"
        "timeline:\n"
        "  filename: /tmp/t.json\n")
    args = _parse(["-np", "2", "--cycle-time-ms", "1.5"])
    config_parser.apply_config_to_args(
        args, config_parser.load_config_file(str(cfg)))
    env = config_parser.env_from_args(args)
    # CLI wins over YAML; YAML fills the rest
    assert env[env_util.HVD_CYCLE_TIME] == "1.5"
    assert env[env_util.HVD_FUSION_THRESHOLD] == str(8 * 1024 * 1024)
    assert env[env_util.HVD_AUTOTUNE] == "1"
    assert env[env_util.HVD_TIMELINE] == "/tmp/t.json"


def test_stall_and_log_flags_map():
    args = _parse(["-np", "2", "--no-stall-check",
                   "--stall-check-warning-time-seconds", "11",
                   "--stall-check-shutdown-time-seconds", "22",
                   "--log-level", "debug"])
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_STALL_CHECK_DISABLE] == "1"
    assert env[env_util.HVD_STALL_CHECK_TIME_SECONDS] == "11.0"
    assert env[env_util.HVD_STALL_SHUTDOWN_TIME_SECONDS] == "22.0"
    assert env[env_util.HVD_LOG_LEVEL] == "debug"


def test_config_from_env_roundtrip(monkeypatch):
    from horovod_tpu.common.config import Config

    monkeypatch.setenv(env_util.HVD_FUSION_THRESHOLD, "1048576")
    monkeypatch.setenv(env_util.HVD_CYCLE_TIME, "3.0")
    monkeypatch.setenv(env_util.HVD_STALL_CHECK_TIME_SECONDS, "9")
    cfg = Config.from_env()
    assert cfg.fusion_threshold_bytes == 1048576
    assert cfg.cycle_time_ms == 3.0
    assert cfg.stall_warning_seconds == 9


# ------------------------------------------------------------- compression --
def test_jax_compression_roundtrip(hvd):
    from horovod_tpu.common.compression import Compression

    import jax.numpy as jnp

    x = jnp.linspace(-3, 3, 64, dtype=jnp.float32)
    comp, ctx = Compression.fp16.compress(x)
    assert str(np.asarray(comp).dtype) == "float16"
    out = Compression.fp16.decompress(comp, ctx)
    assert str(np.asarray(out).dtype) == "float32"
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=0.05)

    comp, ctx = Compression.bf16.compress(x)
    assert str(np.asarray(comp).dtype) == "bfloat16"
    out = Compression.bf16.decompress(comp, ctx)
    assert str(np.asarray(out).dtype) == "float32"

    comp, ctx = Compression.none.compress(x)
    np.testing.assert_array_equal(
        np.asarray(Compression.none.decompress(comp, ctx)), x)


def test_torch_compression_roundtrip():
    torch = pytest.importorskip("torch")
    from horovod_tpu.torch.compression import Compression

    x = torch.linspace(-3, 3, 64)
    comp, ctx = Compression.fp16.compress(x)
    assert comp.dtype == torch.float16
    out = Compression.fp16.decompress(comp, ctx)
    assert out.dtype == torch.float32
    assert torch.allclose(out, x, atol=0.05)
    comp, ctx = Compression.bf16.compress(x)
    assert comp.dtype == torch.bfloat16


def test_tf_compression_roundtrip():
    tf = pytest.importorskip("tensorflow")
    from horovod_tpu.tensorflow.compression import Compression

    x = tf.linspace(-3.0, 3.0, 64)
    comp, ctx = Compression.fp16.compress(x)
    assert comp.dtype == tf.float16
    out = Compression.fp16.decompress(comp, ctx)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=0.05)
    comp, ctx = Compression.bf16.compress(x)
    assert comp.dtype == tf.bfloat16


def test_int_tensors_pass_compression_untouched():
    from horovod_tpu.common.compression import Compression

    import jax.numpy as jnp

    x = jnp.arange(10, dtype=jnp.int32)
    comp, ctx = Compression.fp16.compress(x)
    out = Compression.fp16.decompress(comp, ctx)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert np.asarray(out).dtype == np.int32


def test_round4_flag_additions_map():
    """--start-timeout / --network-interface / --disable-cache and the
    negation flags (reference runner.py surface) land in the worker
    env contract."""
    args = _parse(["-np", "2", "--start-timeout", "45",
                   "--network-interface", "eth7", "--disable-cache",
                   "--no-autotune", "--no-hierarchical-allreduce",
                   "--no-hierarchical-allgather", "--stall-check"])
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_START_TIMEOUT] == "45.0"
    assert env[env_util.HVD_IFACE] == "eth7"
    assert env[env_util.HVD_CACHE_CAPACITY] == "0"
    assert env[env_util.HVD_AUTOTUNE] == "0"
    assert env[env_util.HVD_HIERARCHICAL_ALLREDUCE] == "0"
    assert env[env_util.HVD_HIERARCHICAL_ALLGATHER] == "0"
    assert env[env_util.HVD_STALL_CHECK_DISABLE] == "0"
    # negation wins over the positive flag: explicit off
    both = _parse(["-np", "2", "--autotune", "--no-autotune"])
    assert config_parser.env_from_args(both)[env_util.HVD_AUTOTUNE] == "0"


def test_output_filename_per_rank_logs(tmp_path):
    """--output-filename writes <dir>/rank.N/stdout|stderr (reference:
    horovodrun --output-filename layout)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "w.py"
    script.write_text(
        "import os, sys\n"
        "print('OUT rank', os.environ['HVD_RANK'])\n"
        "print('ERR rank', os.environ['HVD_RANK'], file=sys.stderr)\n")
    out_dir = tmp_path / "logs"
    env = dict(os.environ)
    env["PYTHONPATH"] = repo
    result = subprocess.run(
        [sys.executable, os.path.join(repo, "bin", "hvdrun"),
         "-np", "2", "--output-filename", str(out_dir),
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert result.returncode == 0, result.stderr[-2000:]
    for r in (0, 1):
        out = (out_dir / f"rank.{r}" / "stdout").read_text()
        err = (out_dir / f"rank.{r}" / "stderr").read_text()
        assert f"OUT rank {r}" in out, out
        assert f"ERR rank {r}" in err, err
        # reference MultiFile semantics: files capture AND the console
        # still sees every rank's output
        assert f"OUT rank {r}" in result.stdout, result.stdout[-2000:]
        assert f"ERR rank {r}" in result.stderr, result.stderr[-2000:]


def test_output_filename_zero_pads_rank_dirs(tmp_path):
    """Rank dirs are zero-padded to the width of num_proc-1 (reference
    layout: rank.00..rank.10 for an 11-rank job)."""
    import sys

    from horovod_tpu.run import allocate as allocate_mod
    from horovod_tpu.run import launch as launch_mod

    slots = allocate_mod.allocate(
        [allocate_mod.HostInfo("localhost", 11)], 11)
    rc = launch_mod.launch_job(
        slots, f"{sys.executable} -c \"print('hi')\"",
        "127.0.0.1", 0, output_filename=str(tmp_path / "logs"))
    assert rc == 0
    dirs = sorted(p.name for p in (tmp_path / "logs").iterdir())
    assert dirs == [f"rank.{r:02d}" for r in range(11)], dirs


def test_start_timeout_bounds_gang_start(tmp_path, monkeypatch):
    """HVD_START_TIMEOUT must reach the worker's rendezvous waits: the
    tcp controller's peer resolution passes it as the KV-poll timeout
    (reference: horovodrun --start-timeout gang semantics)."""
    import types

    from horovod_tpu.ops import tcp_controller as tc
    from horovod_tpu.run import http_client

    seen = {}

    def fake_get(addr, port, scope, key, timeout=None):
        seen["timeout"] = timeout
        return b"lo=127.0.0.1:1"

    monkeypatch.setattr(http_client, "get", fake_get)
    monkeypatch.setenv(env_util.HVD_RENDEZVOUS_ADDR, "127.0.0.1")
    monkeypatch.setenv(env_util.HVD_RENDEZVOUS_PORT, "1")
    monkeypatch.setenv(env_util.HVD_START_TIMEOUT, "7.5")
    from horovod_tpu.run.service import network

    class _NoClient:
        def __init__(self, *a, **k):
            pass

    monkeypatch.setattr(network, "MuxClient", _NoClient)
    stub = types.SimpleNamespace(
        _key=b"k", _epoch=0, _filter_ifaces=lambda tagged: tagged)
    stub._peer_addrs = types.MethodType(
        tc.TcpController._peer_addrs, stub)
    stub._scope = types.MethodType(tc.TcpController._scope, stub)
    tc.TcpController._resolve_peer(stub, 1)
    assert seen["timeout"] == 7.5
    # and the default is the documented 120 s
    monkeypatch.delenv(env_util.HVD_START_TIMEOUT)
    tc.TcpController._resolve_peer(stub, 1)
    assert seen["timeout"] == 120.0


def test_mpi_args_flag_splits():
    args = _parse(["-np", "2", "--launcher", "mpirun",
                   "--mpi-args=--mca btl_tcp_if_include eth0"])
    import shlex
    assert shlex.split(args.mpi_args) == [
        "--mca", "btl_tcp_if_include", "eth0"]


def test_compression_tri_surface(monkeypatch, tmp_path):
    """--compression / params.compression / HVD_TPU_COMPRESSION all land
    on Config.compression, CLI winning over YAML."""
    from horovod_tpu.common.config import Config

    args = _parse(["-np", "2", "--compression", "int8"])
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_TPU_COMPRESSION] == "int8"

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("params:\n  compression: bf16\n")
    args = _parse(["-np", "2"])
    config_parser.apply_config_to_args(
        args, config_parser.load_config_file(str(cfg)))
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_TPU_COMPRESSION] == "bf16"

    monkeypatch.setenv(env_util.HVD_TPU_COMPRESSION, "fp16")
    assert Config.from_env().compression == "fp16"


def test_session_flag_additions_map(tmp_path):
    """--reconnect-budget / --replay-buffer-bytes (the self-healing
    transport knobs, docs/fault_tolerance.md "connection blips vs dead
    peers") land in the worker env contract; YAML fills unset flags."""
    args = _parse(["-np", "2", "--reconnect-budget", "20",
                   "--replay-buffer-bytes", "1048576"])
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_TPU_RECONNECT_BUDGET] == "20.0"
    assert env[env_util.HVD_TPU_REPLAY_BUFFER_BYTES] == "1048576"
    # unset: the knobs stay out of the env (workers use the defaults —
    # budget 0 keeps the wire byte-identical to the pre-session layer)
    bare = config_parser.env_from_args(_parse(["-np", "2"]))
    assert env_util.HVD_TPU_RECONNECT_BUDGET not in bare
    assert env_util.HVD_TPU_REPLAY_BUFFER_BYTES not in bare
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "fault_tolerance:\n"
        "  reconnect_budget: 15\n"
        "  replay_buffer_bytes: 2097152\n")
    args = _parse(["-np", "2", "--reconnect-budget", "20"])
    config_parser.apply_config_to_args(
        args, config_parser.load_config_file(str(cfg)))
    env = config_parser.env_from_args(args)
    assert env[env_util.HVD_TPU_RECONNECT_BUDGET] == "20.0"   # CLI wins
    assert env[env_util.HVD_TPU_REPLAY_BUFFER_BYTES] == "2097152"
