"""Tier-1 gate for hvd-race (docs/race_detection.md).

Four halves:

1. every known-bad fixture under ``tests/race_fixtures/`` is caught
   DETERMINISTICALLY under a fixed seed (the same seed twice yields the
   byte-identical report) and every good twin stays silent;
2. the concurrency-heavy suite paths — the loopback ring data plane
   (the tcp-matrix harness), the fault-injection worker harness
   (including the mid-ring crash), and the python-controller
   stall-inspector path — run under the shim with ZERO non-baselined
   reports;
3. shim neutrality: with ``HVD_TPU_RACE`` unset the shim is provably
   not installed (stock identities, module absent, stock lock
   throughput); with it set the shim is provably installed;
4. the baseline stays small (<= 10) and justified.
"""

import glob
import json
import os
import subprocess
import sys

import pytest

from conftest import spawn_tcp_ranks
from horovod_tpu.tools.lint import findings as findings_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "race_fixtures")
HVD_RACE = os.path.join(REPO, "bin", "hvd-race")
BASELINE = os.path.join(REPO, ".hvd-race-baseline.json")


def _run_hvd_race(fixture, seed=7, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, HVD_RACE, "--seed", str(seed), "--no-baseline",
         "--format", "json", *extra, os.path.join(FIXTURES, fixture)],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO)
    payload = json.loads(out.stdout) if out.stdout.strip() else {}
    return out.returncode, payload, out.stderr


BAD_CASES = [
    ("bad_unlocked_counter.py", "Counter", "count"),
    ("bad_notify_without_lock.py", "Box", "ready"),
    ("bad_publish_after_close.py", "Sink", "out"),
]


@pytest.mark.parametrize("fixture,cls,attr", BAD_CASES,
                         ids=[c[0] for c in BAD_CASES])
def test_bad_fixture_is_caught(fixture, cls, attr):
    code, payload, err = _run_hvd_race(fixture)
    assert code == 1, f"{fixture}: expected findings, got rc={code}\n{err}"
    found = payload["findings"]
    assert any(f["context"] == cls and f["detail"].startswith(attr + ":")
               for f in found), found


@pytest.mark.parametrize("fixture", [
    "good_unlocked_counter.py",
    "good_notify_under_lock.py",
    "good_publish_join_before_close.py",
])
def test_good_twin_is_silent(fixture):
    code, payload, err = _run_hvd_race(fixture)
    assert code == 0, (f"{fixture}: false positive(s): "
                       f"{payload.get('findings')}\n{err}")
    assert payload["findings"] == []


def test_same_seed_reproduces_identical_report():
    """The HVD_TPU_RACE_SEED determinism contract: the fuzzer's
    preemption decisions — and therefore the report, down to the racing
    sites, thread names and message text — are a pure function of the
    seed."""
    _, first, _ = _run_hvd_race("bad_unlocked_counter.py", seed=7)
    _, second, _ = _run_hvd_race("bad_unlocked_counter.py", seed=7)
    assert first["findings"], "fixture produced no findings"
    assert first == second


def test_report_attributes_both_stacks_and_annotation():
    """A report names both racing sites with thread names, the
    ownership history, and the '# guarded by' declaration it
    contradicts."""
    _, payload, _ = _run_hvd_race("bad_notify_without_lock.py")
    (finding,) = [f for f in payload["findings"]
                  if f["detail"].startswith("ready:")]
    msg = finding["message"]
    assert "consume" in msg and "publish" in msg      # both sites
    assert "MainThread" in msg                        # thread names
    assert "first write by" in msg                    # ownership history
    assert "contradicts declared '# guarded by self._cv'" in msg


# ------------------------------------------------------- shim neutrality --
NEUTRALITY_PROBE = r"""
import sys, time
import horovod_tpu  # the install gate runs (or not) here
import threading, queue, _thread

race_on = __RACE_ON__
if race_on:
    assert "horovod_tpu.tools.race.shim" in sys.modules, \
        "HVD_TPU_RACE=1 did not install the shim"
    from horovod_tpu.tools.race import shim
    assert shim.is_installed()
    assert threading.Lock is shim.TracedLock
    assert threading.Event is shim.TracedEvent
else:
    assert "horovod_tpu.tools.race.shim" not in sys.modules, \
        "shim module imported with HVD_TPU_RACE unset"
    assert threading.Lock is _thread.allocate_lock, threading.Lock
    assert threading.Thread.start.__module__ == "threading"
    assert threading.Thread.join.__module__ == "threading"
    assert queue.Queue.put.__module__ == "queue"
    assert queue.Queue.get.__module__ == "queue"
    # micro-benchmark: stock lock throughput (instrumentation would
    # cost an order of magnitude; the floor is generous so machine
    # load cannot flake it)
    lock = threading.Lock()
    n = 200_000
    start = time.perf_counter()
    for _ in range(n):
        lock.acquire()
        lock.release()
    elapsed = time.perf_counter() - start
    assert elapsed < 2.0, f"{n} stock lock cycles took {elapsed:.2f}s"
print("NEUTRAL-OK")
"""


def _run_probe(race_on):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("HVD_TPU_RACE", None)
    if race_on:
        env["HVD_TPU_RACE"] = "1"
    script = NEUTRALITY_PROBE.replace("__RACE_ON__", str(race_on))
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr
    assert "NEUTRAL-OK" in out.stdout


def test_shim_absent_when_off():
    _run_probe(race_on=False)


def test_shim_installed_when_on():
    _run_probe(race_on=True)


# ------------------------------------------- suites under the shim --------
def _nonbaselined(report_glob):
    baseline = findings_mod.load_baseline(BASELINE)
    active = []
    for path in sorted(glob.glob(report_glob)):
        with open(path) as f:
            data = json.load(f)
        for finding in data["findings"]:
            if finding["key"] not in baseline:
                active.append(finding)
    return active


def _run_inline_under_shim(body, report_prefix, tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({
        "JAX_PLATFORMS": "cpu",
        "HVD_TPU_RACE": "1",
        "HVD_TPU_RACE_SEED": "3",
        "HVD_TPU_RACE_REPORT": str(tmp_path / report_prefix),
    })
    out = subprocess.run([sys.executable, "-c", body], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=REPO)
    assert out.returncode == 0, f"{out.stdout}\n{out.stderr}"
    return _nonbaselined(str(tmp_path / (report_prefix + ".*.json")))


RING_HARNESS = r"""
import numpy as np
import threading
import horovod_tpu  # installs the shim
import bench

services, planes = bench._ring_harness(2, 1024, 2)
def run_all(fn):
    errs = []
    def run(r):
        try:
            fn(r)
        except BaseException as e:
            errs.append(e)
    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts: t.start()
    for t in ts: t.join()
    assert not errs, errs

arrs = [np.arange(4000, dtype=np.float32) * (r + 1) for r in range(2)]
out = [None, None]
def ar(r):
    out[r] = planes[r].allreduce(1, arrs[r], [0, 1],
                                 op_average=False, world_size=2)
run_all(ar)
assert np.array_equal(out[0], out[1])
def ar8(r):
    out[r] = planes[r].allreduce(2, arrs[r], [0, 1], op_average=False,
                                 world_size=2, compression="int8")
run_all(ar8)
def bc(r):
    out[r] = planes[r].broadcast(3, arrs[0] if r == 0 else None,
                                 [0, 1], 0, shape=arrs[0].shape,
                                 dtype="float32")
run_all(bc)
# abort waking a blocked stripe recv, then teardown
caught = []
def blocked():
    try:
        planes[1].recv_chunk((99, "rs", 0), 0, 3 * 1024, timeout=30)
    except BaseException as e:
        caught.append(e)
t = threading.Thread(target=blocked); t.start()
import time; time.sleep(0.3)
services[1].abort(0, "race-gate abort")
t.join(5)
assert caught
for p in planes: p.close()
for s in services: s.shutdown()
print("RING-OK")
"""


def test_ring_dataplane_clean_under_shim(tmp_path):
    """The tcp-matrix harness path: exact + int8 + broadcast rounds and
    an abort wakeup over the real loopback transport, shim on — every
    report is baselined or nonexistent."""
    active = _run_inline_under_shim(RING_HARNESS, "ring", tmp_path)
    assert not active, "\n".join(f["message"] for f in active)


STALL_HARNESS = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common import basics

hvd.init()
def per_rank():
    hvd.allreduce(jnp.ones((64,)), op=hvd.Sum, name="race.stall")
    hvd.allgather(jnp.ones((8,)), name="race.gather")
basics.run_parallel(per_rank)
import time; time.sleep(1.5)   # let the stall inspector run cycles
basics.run_parallel(per_rank)
hvd.shutdown()
print("STALL-OK")
"""


def test_stall_path_clean_under_shim(tmp_path):
    """The test_stall harness path: the python controller's cycle loop
    + stall inspector under the shim."""
    env_body = (
        "import os\n"
        "os.environ['HVD_CONTROLLER'] = 'python'\n"
        "os.environ['HVD_STALL_CHECK_TIME_SECONDS'] = '1'\n"
        + STALL_HARNESS)
    active = _run_inline_under_shim(env_body, "stall", tmp_path)
    assert not active, "\n".join(f["message"] for f in active)


GROUPS_HARNESS = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu import groups as groups_mod
from horovod_tpu.common import basics

hvd.init()
n = hvd.size()
g0 = hvd.new_group(list(range(n // 2)), name="race.g0")
g1 = hvd.new_group(list(range(n // 2, n)), name="race.g1")

def per_rank(r):
    grp = g0 if r in g0 else g1
    for i in range(3):
        hvd.allreduce(jnp.ones((64,)) * (r + 1), op=hvd.Sum,
                      name=f"race.grp.{i}", group=grp)
        hvd.allgather(jnp.ones((4,)) * r, name=f"race.gath.{i}",
                      group=grp)
    hvd.barrier(name="race.join")

basics.run_parallel(per_rank)
assert groups_mod.stats()["max_concurrent_groups"] >= 2, \
    groups_mod.stats()
basics.run_parallel(per_rank)
hvd.shutdown()
print("GROUPS-OK")
"""


def test_concurrent_groups_clean_under_shim(tmp_path):
    """ISSUE 14: two process groups' collectives concurrently in
    flight from worker threads — per-group negotiation tables, caches
    and ring namespaces racing each other and the world barrier, shim
    on: zero non-baselined reports (and the in-flight gauge proves the
    two groups really did overlap under the shim's preemption)."""
    env_body = (
        "import os\n"
        "os.environ['HVD_CONTROLLER'] = 'python'\n"
        + GROUPS_HARNESS)
    active = _run_inline_under_shim(env_body, "groups", tmp_path)
    assert not active, "\n".join(f["message"] for f in active)


FT_WORKER = r"""
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
t = jnp.ones((70000,)) * (r + 1)
start = time.monotonic()
try:
    hvd.allreduce(t, op=hvd.Sum, name="race.ft")
    print(f"rank {r} COMPLETED", flush=True)
except hvd.HvdAbortedError as exc:
    print(f"rank {r} ABORTED origin={exc.origin_rank}", flush=True)
"""


def test_fault_harness_clean_under_shim_and_origin_deterministic(
        tmp_path):
    """The fault-injection harness path under the shim: a mid-ring
    crash at rank 1.  Two assertions ride one spawn: (1) zero
    non-baselined race reports from the surviving rank (the crashed
    rank os._exit()s, so it writes none, by design); (2) the abort
    origin is ALWAYS the dead rank — liveness detection and the
    survivor's own failed sends now name the same origin
    (RingSendError carries the proven-dead peer), so culprit naming
    no longer depends on which detector fires first under load."""
    results = spawn_tcp_ranks(2, FT_WORKER, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HVD_TPU_RACE": "1",
        "HVD_TPU_RACE_SEED": "3",
        "HVD_TPU_RACE_REPORT": str(tmp_path / "ft"),
        "HVD_TPU_HEARTBEAT_INTERVAL": "0.25",
        "HVD_TPU_ABORT_TIMEOUT": "10",
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TCP_RING_THRESHOLD": "1024",
        "HVD_TPU_FAULT_SPEC": "rank1:ring:1:crash",
    }, timeout=240)
    code0, out0, err0 = results[0]
    code1, out1, _ = results[1]
    assert code1 == 1, f"crashed rank: {out1}"
    assert code0 == 0, f"survivor: {out0}\n{err0}"
    assert "rank 0 ABORTED origin=1" in out0, out0
    active = _nonbaselined(str(tmp_path / "ft.*.json"))
    assert not active, "\n".join(f["message"] for f in active)


ELASTIC_WORKER = r"""
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
state = hvd.elastic.State(
    params={"w": jnp.zeros((2000,), dtype=jnp.float32)}, step=0)

def train(state):
    while state.step < 3:
        g = jnp.full((2000,), float(state.step + 1), dtype=jnp.float32)
        avg = hvd.allreduce(g, op=hvd.Average,
                            name=f"race.el.{state.step}")
        state.params = {"w": state.params["w"] - avg}
        state.step += 1
        state.commit()

hvd.elastic.run(train, state)
print(f"rank {hvd.rank()} RECONFIGURED size={hvd.size()} "
      f"steps={state.step}", flush=True)
hvd.shutdown()
"""


def test_elastic_reconfig_path_clean_under_shim(tmp_path):
    """The elastic reconfiguration path under the shim: membership
    planning racing the abort fan-out, the controller-generation
    teardown racing in-flight ring traffic, and the epoch-scoped
    gang restart — zero non-baselined race reports on any survivor."""
    results = spawn_tcp_ranks(3, ELASTIC_WORKER, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HVD_TPU_RACE": "1",
        "HVD_TPU_RACE_SEED": "3",
        "HVD_TPU_RACE_REPORT": str(tmp_path / "el"),
        "HVD_TPU_ELASTIC": "1",
        "HVD_TPU_HEARTBEAT_INTERVAL": "0.25",
        "HVD_TPU_ABORT_TIMEOUT": "10",
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_TPU_RECONFIG_TIMEOUT": "60",
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TCP_RING_THRESHOLD": "1024",
        "HVD_TPU_FAULT_SPEC": "rank2:allreduce:2:crash",
    }, timeout=240)
    assert results[2][0] == 1, f"crashed rank: {results[2][1]}"
    for r in (0, 1):
        code, out, err = results[r]
        assert code == 0, f"rank {r}: {out}\n{err}"
        assert "RECONFIGURED size=2 steps=3" in out, f"rank {r}: {out}"
    active = _nonbaselined(str(tmp_path / "el.*.json"))
    assert not active, "\n".join(f["message"] for f in active)


def test_coord_failover_path_clean_under_shim(tmp_path):
    """The coordinator fail-over path under the shim: rank 0 dies
    mid-collective, the survivors' CAS election races their heartbeat
    monitors and the abort fan-out, the new rank 0 starts a fresh
    CoordinatorService while the old epoch's teardown is still in
    flight — zero non-baselined race reports on any survivor."""
    results = spawn_tcp_ranks(4, ELASTIC_WORKER, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HVD_TPU_RACE": "1",
        "HVD_TPU_RACE_SEED": "3",
        "HVD_TPU_RACE_REPORT": str(tmp_path / "cf"),
        "HVD_TPU_ELASTIC": "1",
        "HVD_TPU_COORD_FAILOVER": "1",
        "HVD_TPU_HEARTBEAT_INTERVAL": "0.25",
        "HVD_TPU_ABORT_TIMEOUT": "10",
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_TPU_RECONFIG_TIMEOUT": "60",
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TCP_RING_THRESHOLD": "1024",
        "HVD_TPU_FAULT_SPEC": "rank0:allreduce:2:crash",
    }, timeout=240)
    assert results[0][0] == 1, f"crashed rank 0: {results[0][1]}"
    for r in (1, 2, 3):
        code, out, err = results[r]
        assert code == 0, f"rank {r}: {out}\n{err}"
        assert "RECONFIGURED size=3 steps=3" in out, f"rank {r}: {out}"
    active = _nonbaselined(str(tmp_path / "cf.*.json"))
    assert not active, "\n".join(f["message"] for f in active)


# ------------------------------------------------------------- baseline --
def test_baseline_is_small_and_justified():
    with open(BASELINE) as f:
        data = json.load(f)
    entries = data.get("suppressions", [])
    assert len(entries) <= 10, (
        f"{len(entries)} baselined race suppressions — the budget is "
        f"10; fix races (or annotate deliberate lock-free reads at the "
        f"site) instead of baselining them")
    for entry in entries:
        just = entry.get("justification", "")
        assert just and "TODO" not in just, (
            f"baseline entry {entry.get('key')!r} lacks a real "
            f"justification")


def test_write_baseline_roundtrip(tmp_path):
    """hvd-race shares hvd-lint's baseline machinery: --write-baseline
    captures this run's findings and preserves prior justifications."""
    base = tmp_path / "race-base.json"
    base.write_text(json.dumps({"suppressions": [
        {"key": "race:tests/race_fixtures/bad_unlocked_counter.py:"
                "Counter:count:write-write",
         "justification": "fixture"}]}))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, HVD_RACE, "--seed", "7", "--baseline",
         str(base), "--write-baseline",
         os.path.join(FIXTURES, "bad_unlocked_counter.py")],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO)
    assert out.returncode == 0, out.stderr
    reloaded = findings_mod.load_baseline(str(base))
    key = ("race:tests/race_fixtures/bad_unlocked_counter.py:"
           "Counter:count:write-write")
    assert reloaded[key] == "fixture"           # justification survives
    assert any(k != key for k in reloaded)      # new finding captured


def test_write_baseline_refuses_partial_run(tmp_path):
    """A target that crashes observed only a prefix of the findings:
    regenerating the baseline from it would silently prune every
    justified suppression the crash prevented re-observing — the CLI
    must refuse (exit 3) and leave the baseline untouched."""
    target = tmp_path / "crasher.py"
    target.write_text("def main():\n    raise RuntimeError('boom')\n")
    base = tmp_path / "race-base.json"
    original = json.dumps({"suppressions": [
        {"key": "race:x.py:C:attr:write-write",
         "justification": "justified elsewhere"}]})
    base.write_text(original)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, HVD_RACE, "--baseline", str(base),
         "--write-baseline", str(target)],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO)
    assert out.returncode == 3, out.stdout + out.stderr
    assert "baseline NOT rewritten" in out.stderr
    assert base.read_text() == original


ADAPTIVE_COORD_HARNESS = r"""
import threading
import time
import horovod_tpu  # installs the shim
from horovod_tpu.common import rtt
from horovod_tpu.ops.tcp_controller import CoordinatorService
from horovod_tpu.run.service import network, secret

svc = CoordinatorService(4, secret.make_secret_key(),
                         liveness_timeout_sec=30.0,
                         straggler_factor=4.0, straggler_windows=2)
errs = []
def worker(rank):
    # the production shape: per-connection handler threads feed
    # heartbeats (busy flags + RTT reports) while the liveness scan,
    # the straggler scan and verdict reads run concurrently
    try:
        tr = rtt.RttTracker(alpha=0.5)
        for i in range(40):
            tr.sample(rtt.COORD_KEY, 0.01 * rank + 0.001 * i)
            svc._handle(network.HeartbeatMsg(
                rank, busy=(i % 3 == 0), rtt=tr.worst() or None), None)
            svc.straggler_verdicts()
    except BaseException as e:
        errs.append(e)
ts = [threading.Thread(target=worker, args=(r,)) for r in range(1, 4)]
for t in ts: t.start()
for t in ts: t.join()
assert not errs, errs
svc.shutdown()
print("ADAPTIVE-OK")
"""


def test_adaptive_coordinator_path_clean_under_shim(tmp_path):
    """The soak rig's coordinator hot path (docs/soak.md): concurrent
    heartbeats carrying busy flags + RTT reports through the adaptive
    liveness deadline, the straggler scan and verdict reads, with
    RttTracker EWMAs updating alongside — shim on, zero non-baselined
    findings."""
    active = _run_inline_under_shim(ADAPTIVE_COORD_HARNESS, "adaptive",
                                    tmp_path)
    assert not active, "\n".join(f["message"] for f in active)


HIER_HARNESS = r"""
import numpy as np
import threading
import horovod_tpu  # installs the shim
import bench

services, planes = bench._ring_harness(4, 4096, 2)
def run_all(fn):
    errs = []
    def run(r):
        try:
            fn(r)
        except BaseException as e:
            errs.append(e)
    ts = [threading.Thread(target=run, args=(r,)) for r in range(4)]
    for t in ts: t.start()
    for t in ts: t.join()
    assert not errs, errs

arrs = [np.arange(5000, dtype=np.float32) * (r + 1) for r in range(4)]
groups = [[0, 1], [2, 3]]
out = [None] * 4
def hier(r):
    out[r] = planes[r].allreduce_hierarchical(
        1, arrs[r], [0, 1, 2, 3], groups, op_average=False,
        world_size=4)
run_all(hier)
assert all(np.array_equal(o, out[0]) for o in out[1:])
def hier8(r):
    out[r] = planes[r].allreduce_hierarchical(
        2, arrs[r], [0, 1, 2, 3], groups, op_average=False,
        world_size=4, compression="int8")
run_all(hier8)
assert all(np.array_equal(o, out[0]) for o in out[1:])
def rhd(r):
    out[r] = planes[r].allreduce_rhd(3, arrs[r], [0, 1, 2, 3],
                                     op_average=False, world_size=4)
run_all(rhd)
assert all(np.array_equal(o, out[0]) for o in out[1:])
for p in planes: p.close()
for s in services: s.shutdown()
print("HIER-OK")
"""


def test_hierarchical_schedule_clean_under_shim(tmp_path):
    """ISSUE 12: the hierarchical and rhd data-plane phases — owner-
    targeted intra-group scatter, delegate gather/ring/broadcast (exact
    and int8 wire), pairwise recursive doubling — across 4 rank threads
    on the real loopback transport, shim on: every report is baselined
    or nonexistent."""
    active = _run_inline_under_shim(HIER_HARNESS, "hier", tmp_path)
    assert not active, "\n".join(f["message"] for f in active)


SESSION_HARNESS = r"""
import socket
import threading
import horovod_tpu  # installs the shim
from horovod_tpu.run.service import network, secret

key = secret.make_secret_key()


class Echo(network.MuxService):
    def _handle(self, req, client_address):
        return ("echo", req)


class Hdr:
    def __init__(self, tag):
        self.tag = tag
        self.payload = None


svc = Echo("race session", key)
client = network.MuxClient([("127.0.0.1", svc.port)], key, timeout=10,
                           peer=1, reconnect_budget=30, retry_for=10)
stripe = network.StripeClient([("127.0.0.1", svc.port)], key,
                              timeout=10, peer=1, reconnect_budget=30,
                              retry_for=10)
# concurrent senders racing the heal: the reader thread, the send
# retry loops and the sever all contend for the session state
errs = []
def pump(i):
    try:
        for j in range(12):
            client.post(("post", i, j))
            assert client.send(("ask", i, j)) == ("echo", ("ask", i, j))
            stripe.post_bulk(Hdr((i, j)), b"\x5a" * 2048)
    except BaseException as e:  # noqa: BLE001
        errs.append(e)
ts = [threading.Thread(target=pump, args=(i,)) for i in range(3)]
for t in ts: t.start()
import time
time.sleep(0.1)
for _ in range(2):           # sever both transports mid-traffic
    with client._state_lock:
        if client._sock is not None:
            client._sock.shutdown(socket.SHUT_RDWR)
    with stripe._lock:
        if stripe._sock is not None:
            stripe._sock.shutdown(socket.SHUT_RDWR)
    time.sleep(0.2)
for t in ts: t.join()
assert not errs, errs
stripe.close()
client.close()
svc.shutdown()
print("SESSION-OK")
"""


def test_session_heal_clean_under_shim(tmp_path):
    """ISSUE 17 gate: the self-healing session layer — concurrent
    send/post/bulk pumps racing two mid-stream severs and the heals
    they trigger — produces zero non-baselined findings under the
    interleaving shim."""
    active = _run_inline_under_shim(SESSION_HARNESS, "session", tmp_path)
    assert not active, "\n".join(f["message"] for f in active)
