"""Fused Pallas softmax cross-entropy vs the XLA oracle (interpret mode
on CPU; compiled Pallas on TPU — see KERNEL_VALIDATION.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.ops.pallas import softmax_xent, softmax_xent_reference


def _data(shape, v, seed=0, scale=3.0):
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.randn(*shape, v).astype(np.float32) * scale)
    labels = jnp.asarray(rng.randint(0, v, shape))
    return logits, labels


@pytest.mark.parametrize("shape,v", [((4, 16), 512), ((3, 7), 1000),
                                     ((24,), 4096)])
def test_forward_matches_oracle_and_optax(shape, v):
    logits, labels = _data(shape, v)
    out = softmax_xent(logits, labels, True)
    ref = softmax_xent_reference(logits, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    ox = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ox),
                               rtol=1e-5, atol=1e-5)


def test_backward_matches_oracle_multi_grid():
    # n=24 rows -> block 8, grid 3: exercises cross-step independence
    logits, labels = _data((3, 8), 1024, seed=1)

    gp = jax.grad(lambda x: jnp.mean(softmax_xent(x, labels, True)))(logits)
    gr = jax.grad(
        lambda x: jnp.mean(softmax_xent_reference(x, labels)))(logits)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


def test_extreme_logits_stable():
    """Online logsumexp must not overflow for large-magnitude logits."""
    logits, labels = _data((16,), 512, seed=2, scale=200.0)
    out = softmax_xent(logits, labels, True)
    ref = softmax_xent_reference(logits, labels)
    assert np.all(np.isfinite(np.asarray(out)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_bf16_logits_fp32_loss():
    logits, labels = _data((4, 8), 512, seed=3)
    lb = logits.astype(jnp.bfloat16)
    out = softmax_xent(lb, labels, True)
    assert out.dtype == jnp.float32
    ref = softmax_xent_reference(lb, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-3, atol=1e-3)
    g = jax.grad(lambda x: jnp.mean(softmax_xent(x, labels, True)))(lb)
    assert g.dtype == jnp.bfloat16


def test_odd_row_count_pads_and_slices():
    logits, labels = _data((5,), 768, seed=4)  # 5 rows -> pad to 8
    out = softmax_xent(logits, labels, True)
    ref = softmax_xent_reference(logits, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    gp = jax.grad(lambda x: jnp.sum(softmax_xent(x, labels, True)))(logits)
    gr = jax.grad(
        lambda x: jnp.sum(softmax_xent_reference(x, labels)))(logits)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


def test_large_vocab_fwd_bwd():
    """vocab=32768 (production LM scale) through the VMEM-chunked
    streaming path, forward + backward vs optax."""
    logits, labels = _data((16,), 32768, seed=9, scale=1.0)

    got = softmax_xent(logits, labels, True)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    gf = jax.grad(lambda l: jnp.mean(softmax_xent(l, labels, True)))(
        logits)
    gr = jax.grad(lambda l: jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(l, labels)))(
        logits)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-5, atol=1e-7)
