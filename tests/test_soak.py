"""Soak-rig tests (docs/soak.md).

Unit layer (tier-1): the chaos-schedule determinism pins — same seed,
byte-identical spec, including the cross-version contract that old
seeds keep producing the EXACT specs they produced before the
degraded-network cells existed.

Slow layer: bin/hvd-soak itself — the 16-rank chaos soak with every
regression gate, and the 64-rank collect-only scale leg.
"""

import importlib.machinery
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_soak():
    loader = importlib.machinery.SourceFileLoader(
        "hvd_soak_under_test", os.path.join(REPO, "bin", "hvd-soak"))
    spec = importlib.util.spec_from_loader(loader.name, loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


# ---------------------------------------------------- determinism pins ------
def test_generate_spec_old_seed_is_byte_identical():
    """The replay contract across versions: a seed that produced a
    given spec BEFORE the degraded-network cells existed produces the
    byte-identical spec today (degrade cells draw strictly after every
    pre-existing draw)."""
    from horovod_tpu.run.chaos import generate_spec

    # literal pinned from the pre-degrade generator output; a reordered
    # RNG draw (the bug class this guards against) changes these bytes
    want = ("rank0:allgather:1:preempt,rank0:send:5:preempt,"
            "rank3:broadcast:1:preempt")
    assert generate_spec(7, 4, 3, elastic=True) == want
    assert generate_spec(7, 4, 3, elastic=True, degrade=0) == want
    # degrade cells append AFTER the unchanged binary prefix
    with_degrade = generate_spec(7, 4, 3, elastic=True, degrade=2)
    assert with_degrade.startswith(want + ",")
    assert with_degrade == generate_spec(7, 4, 3, elastic=True,
                                         degrade=2)
    # the group-collective cell (ISSUE 14) draws strictly after every
    # pre-existing cell: without --groups the spec is byte-identical
    # to older trees, with it the cell appends after the same prefix
    assert generate_spec(7, 4, 3, elastic=True, groups=False) == want
    with_groups = generate_spec(7, 4, 3, elastic=True, groups=True)
    assert with_groups == want + ",rank3:allreduce:5:crash"
    stacked = generate_spec(7, 4, 3, elastic=True, coord_failover=True,
                            groups=True)
    no_groups = generate_spec(7, 4, 3, elastic=True,
                              coord_failover=True)
    assert stacked.startswith(no_groups + ",")
    # the mid-stream break cells (ISSUE 17) draw strictly after every
    # pre-existing cell: without --blips the spec is byte-identical to
    # older trees, with it the cells append after the same prefix
    assert generate_spec(7, 4, 3, elastic=True, blips=0) == want
    with_blips = generate_spec(7, 4, 3, elastic=True, blips=2)
    assert with_blips.startswith(want + ",")
    assert with_blips == generate_spec(7, 4, 3, elastic=True, blips=2)
    full_stack = generate_spec(7, 4, 3, elastic=True,
                               coord_failover=True, groups=True,
                               blips=1)
    assert full_stack.startswith(stacked + ",")


def test_generate_spec_blip_cells_parse_and_spare_rank0():
    """The mid-stream break cells must land on the link point with a
    reset/blip action on a non-coordinator rank (cutting the
    coordinator's links turns a heal soak into a liveness test)."""
    from horovod_tpu.common import faults
    from horovod_tpu.run.chaos import generate_spec

    for seed in range(8):
        base = generate_spec(seed, 8, 2)
        spec = generate_spec(seed, 8, 2, blips=3)
        assert spec.startswith(base + ",")
        cells = faults.parse_fault_spec(spec[len(base) + 1:])
        assert len(cells) == 3
        for cell in cells:
            assert cell.point == "link"
            assert cell.action in ("reset", "blip")
            assert cell.rank != 0
            if cell.action == "reset":
                assert 0.0 < float(cell.param) <= 1.0
                assert cell.duration is not None and cell.duration > 0


def test_generate_spec_group_cell_parses_and_spares_rank0():
    """The group cell must land on a collective/ring point with a
    crash/drop action on a non-coordinator rank (killing rank 0 turns
    the group-abort cell into a coordinator fail-over test)."""
    from horovod_tpu.common import faults
    from horovod_tpu.run.chaos import generate_spec

    for seed in range(8):
        base = generate_spec(seed, 8, 2)
        spec = generate_spec(seed, 8, 2, groups=True)
        assert spec.startswith(base + ",")
        (cell,) = faults.parse_fault_spec(spec[len(base) + 1:])
        assert cell.point in ("allreduce", "ring")
        assert cell.action in ("crash", "drop")
        assert cell.rank != 0
        assert cell.step >= 2


def test_generate_spec_degrade_cells_parse_and_target_the_link():
    from horovod_tpu.common import faults
    from horovod_tpu.run.chaos import generate_spec

    for seed in range(8):
        specs = faults.parse_fault_spec(
            generate_spec(seed, 8, 2, degrade=3))
        degrade = [s for s in specs if s.point == "link"]
        assert len(degrade) == 3
        for s in degrade:
            assert s.action in ("delay", "jitter", "throttle", "flaky")
            assert s.duration is not None and s.duration > 0


def test_soak_chaos_schedule_is_deterministic_and_rank0_safe():
    soak = _load_soak()
    spec1, cast1 = soak.chaos_spec(11, 16)
    spec2, cast2 = soak.chaos_spec(11, 16)
    assert spec1 == spec2 and cast1 == cast2
    for seed in range(16):
        spec, cast = soak.chaos_spec(seed, 16)
        # rank 0 hosts the coordinator: afflicting it turns the soak's
        # "no false positives" criterion into a guaranteed real abort
        assert 0 not in cast.values()
        # the four base casualties stay distinct; the reset victim must
        # SURVIVE the soak (a healed link on a rank that later dies
        # proves nothing), so it may not be the crash/preempt rank
        base = {cast[k] for k in ("crash", "preempt", "delay", "flaky")}
        assert len(base) == 4
        assert cast["reset"] not in {cast["crash"], cast["preempt"]}
        from horovod_tpu.common import faults
        parsed = faults.parse_fault_spec(spec)
        assert {s.action for s in parsed} == {
            "crash", "preempt", "delay", "flaky", "reset"}


def test_hvd_chaos_cli_exposes_degrade_flag():
    # spec generation itself is pinned above; here only the CLI surface
    # (launching a job from a unit test is the slow tests' business)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    chaos = os.path.join(REPO, "bin", "hvd-chaos")
    out = subprocess.run(
        [sys.executable, chaos, "--help"], env=env,
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "--degrade" in out.stdout
    assert "--blips" in out.stdout


# ----------------------------------------------------------- slow legs ------
def _run_soak(args, timeout):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hvd-soak")] + args,
        env=env, capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
def test_soak_16_ranks_all_gates_pass(tmp_path):
    """The acceptance soak: 16 oversubscribed ranks, >=1 crash, >=1
    preemption drain, >=1 delayed link, >=1 flaky link — zero
    false-positive aborts, every reconfiguration within the bound, the
    drained rank exits 0, survivors digest-identical to a chaos-free
    run at the same final membership."""
    proc = _run_soak(["--ranks", "16", "--steps", "8",
                      "--report", str(tmp_path)], timeout=560)
    report_path = tmp_path / "SOAK_r16.json"
    assert report_path.exists(), f"{proc.stdout}\n{proc.stderr}"
    report = json.loads(report_path.read_text())
    assert proc.returncode == 0, (proc.stdout, proc.stderr, report)
    assert report["pass"] is True, report
    assert all(report["gates"].values()), report["gates"]
    assert report["final_size"] == 14, report


@pytest.mark.slow
def test_soak_64_ranks_collect_only_completes(tmp_path):
    """The scale leg: a 64-rank gang forms (rendezvous, secret
    exchange, liveness registration) and tears down clean on one
    oversubscribed host — the O(N) control-plane proof."""
    proc = _run_soak(["--ranks", "64", "--collect-only",
                      "--report", str(tmp_path)], timeout=560)
    report_path = tmp_path / "SOAK_r64.json"
    assert report_path.exists(), f"{proc.stdout}\n{proc.stderr}"
    report = json.loads(report_path.read_text())
    assert proc.returncode == 0, (proc.stdout, proc.stderr, report)
    assert report["pass"] is True, report
    assert report["gates"]["all_ranks_reported"], report
