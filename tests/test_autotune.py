"""Autotuner math and ParameterManager behavior (reference test model:
the reference validates Adasum against a Python oracle in
``test_adasum_pytorch.py``; the same oracle pattern is applied here to the
GP / expected-improvement math of ``horovod/common/optim/*`` and the tuning
walk of ``horovod/common/parameter_manager.cc``)."""

import math

import numpy as np
import pytest

from horovod_tpu.common import autotune


# ---------------------------------------------------------------- numpy oracles

def gp_oracle(x_train, y_train, x_query, length_scale, signal_var, noise_var):
    """Textbook GP posterior with the documented RBF kernel."""
    x_train = np.atleast_2d(np.asarray(x_train, float))
    x_query = np.asarray(x_query, float).ravel()

    def k(a, b):
        return signal_var * math.exp(
            -float(np.sum((a - b) ** 2)) / (2.0 * length_scale ** 2))

    n = x_train.shape[0]
    big_k = np.array([[k(x_train[i], x_train[j]) for j in range(n)]
                      for i in range(n)]) + noise_var * np.eye(n)
    ks = np.array([k(x_train[i], x_query) for i in range(n)])
    inv = np.linalg.inv(big_k)
    mean = ks @ inv @ np.asarray(y_train, float)
    var = k(x_query, x_query) - ks @ inv @ ks
    return mean, max(var, 0.0)


def ei_oracle(mean, stddev, best, xi=0.01):
    imp = mean - best - xi
    if stddev <= 0:
        return max(imp, 0.0)
    z = imp / stddev
    phi = math.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1 + math.erf(z / math.sqrt(2)))
    return imp * cdf + stddev * phi


# ----------------------------------------------------------------------- tests

@pytest.mark.parametrize("length_scale,signal_var,noise_var", [
    (1.0, 1.0, 1e-6),
    (0.5, 2.0, 1e-3),
    (2.0, 0.7, 0.1),
])
def test_gp_matches_numpy_oracle(length_scale, signal_var, noise_var):
    rng = np.random.RandomState(42)
    x = rng.uniform(-2, 2, size=(12, 3))
    y = np.sin(x[:, 0]) + 0.3 * x[:, 1] - 0.5 * x[:, 2] ** 2

    gp = autotune.GaussianProcess(length_scale, signal_var, noise_var)
    gp.fit(x, y)

    for q in rng.uniform(-2, 2, size=(8, 3)):
        mean, var = gp.predict(q)
        em, ev = gp_oracle(x, y, q, length_scale, signal_var, noise_var)
        assert mean == pytest.approx(em, rel=1e-8, abs=1e-10)
        assert var == pytest.approx(ev, rel=1e-6, abs=1e-9)


def test_gp_interpolates_training_points_with_tiny_noise():
    x = np.array([[0.0], [1.0], [2.0]])
    y = np.array([1.0, -1.0, 0.5])
    gp = autotune.GaussianProcess(1.0, 1.0, 1e-10).fit(x, y)
    for xi_, yi in zip(x, y):
        mean, var = gp.predict(xi_)
        assert mean == pytest.approx(yi, abs=1e-6)
        assert var < 1e-6


def test_expected_improvement_matches_oracle():
    cases = [(1.0, 0.5, 0.8), (0.0, 1.0, 2.0), (3.0, 0.0, 1.0),
             (-1.0, 0.2, -0.5), (2.0, 0.0, 3.0)]
    for mean, sd, best in cases:
        assert autotune.expected_improvement(mean, sd, best) == pytest.approx(
            ei_oracle(mean, sd, best), rel=1e-12, abs=1e-15)


def test_ei_zero_when_no_improvement_possible():
    assert autotune.expected_improvement(0.0, 0.0, 1.0) == 0.0
    # Positive stddev always gives some exploration value.
    assert autotune.expected_improvement(0.0, 1.0, 5.0) > 0.0


def test_bayes_opt_converges_near_optimum():
    """Maximize a smooth 1-d function; after a budget of samples the best
    observed point should be close to the true argmax."""
    def f(x):
        return -(x - 3.2) ** 2  # max at 3.2

    bo = autotune.BayesianOptimizer(low=[0.0], high=[8.0], gp_noise=1e-4)
    best_x = None
    for _ in range(25):
        x = bo.suggest()
        y = f(x[0])
        bo.add_sample(x, y)
        if best_x is None or y >= bo.best_y:
            best_x = x[0]
    assert bo.best_y > -0.5          # i.e. |x*-3.2| < ~0.7
    assert abs(best_x - 3.2) < 0.7


def test_bayes_opt_suggestions_stay_in_bounds():
    bo = autotune.BayesianOptimizer(low=[1.0, 2.0], high=[3.0, 10.0])
    for i in range(10):
        x = bo.suggest()
        assert 1.0 <= x[0] <= 3.0
        assert 2.0 <= x[1] <= 10.0
        bo.add_sample(x, float(i))


def test_parameter_manager_walks_and_pins_best(tmp_path):
    """Drive the PM with a synthetic workload whose bytes/sec peaks at a
    32 MB fusion threshold; after the tuning walk finishes the pinned values
    must reproduce the best-scoring configuration and the CSV log must have
    one row per observation."""
    log = tmp_path / "autotune.csv"
    pm = autotune.ParameterManager(
        warmup_samples=1, steady_state_samples=3, bayes_opt_max_samples=5,
        gp_noise=0.1, log_path=str(log))

    def score(fusion_bytes):
        mb = fusion_bytes / (1024 * 1024)
        return 1e9 * math.exp(-((math.log2(max(mb, 1e-9)) - 5.0) ** 2) / 8.0)

    t = 0.0
    seen_best = 0.0
    for _ in range(5000):
        if not pm.tuning:
            break
        t += 0.01
        # bytes proportional to the synthetic throughput for this window
        pm.record(int(score(pm.fusion_threshold_bytes) * 0.01))
        pm.update(t)
        seen_best = max(seen_best, pm.best_score)
    assert not pm.tuning, "tuning walk should finish within the budget"

    # Pinned fusion threshold near the synthetic optimum (32 MB), within the
    # resolution of a 5-sample-per-categorical BO walk.
    pinned_mb = pm.fusion_threshold_bytes / (1024 * 1024)
    assert 4 <= pinned_mb <= 256
    assert pm.best_score == pytest.approx(seen_best)
    assert pm.best_score > 0.5e9

    rows = log.read_text().strip().splitlines()
    assert rows[0].startswith("score_bytes_per_sec,")
    assert len(rows) > 5  # header + one per observation


def test_parameter_manager_warmup_windows_discarded():
    pm = autotune.ParameterManager(warmup_samples=2, steady_state_samples=2,
                                   bayes_opt_max_samples=3)
    # First update only opens the window; two warmup windows discarded; the
    # two windows after that form the first observation.
    t = 0.0
    observations = 0
    for i in range(5):
        t += 1.0
        pm.record(1000)
        if pm.update(t):
            observations += 1
    assert observations == 1  # exactly one tuning step after 5 windows


def test_native_core_exposes_tuned_params():
    """The embedded core publishes live tuned values through the controller
    (reference: SynchronizeParameters makes tuned values visible)."""
    import horovod_tpu as hvd

    hvd.init()
    try:
        from horovod_tpu.common import basics
        controller = basics._state.controller
        if not hasattr(controller, "tuned_params"):
            pytest.skip("controller without native core")
        params = controller.tuned_params()
        assert params["fusion_threshold_bytes"] > 0
        assert params["cycle_time_ms"] > 0
        assert params["cache_enabled"] in (True, False)
        assert params["tuning"] is False  # autotune off by default
    finally:
        hvd.shutdown()


def test_parameter_manager_converges_on_synthetic_bandwidth():
    """Drive the tuner against a synthetic bandwidth model (throughput a
    bell curve over log2(fusion threshold), peaked away from the default)
    and check the pinned parameters beat the default configuration —
    the oracle VERDICT r1 asked the bandwidth microbench to provide."""
    import math as m

    peak_log2 = m.log2(8 * 1024 * 1024)   # best threshold ~8MB
    default_bytes = 64 * 1024 * 1024

    def rate(threshold_bytes, cycle_ms):
        # bytes/sec: bell over threshold, mild penalty for long cycles
        t = m.log2(max(threshold_bytes, 1))
        bell = m.exp(-((t - peak_log2) ** 2) / 8.0)
        return 2e9 * bell / (1.0 + cycle_ms / 50.0)

    pm = autotune.ParameterManager(
        warmup_samples=1, steady_state_samples=3,
        bayes_opt_max_samples=8, gp_noise=0.3,
        fusion_threshold_bytes=default_bytes, cycle_time_ms=5.0)

    now = 0.0
    work_bytes = 256 * 1024 * 1024
    for _ in range(8000):
        r = rate(pm.fusion_threshold_bytes, pm.cycle_time_ms)
        now += work_bytes / r
        pm.record(work_bytes)
        pm.update(now)
        if not pm.tuning:
            break

    assert not pm.tuning, "tuner never converged"
    tuned = rate(pm.fusion_threshold_bytes, pm.cycle_time_ms)
    base = rate(default_bytes, 5.0)
    assert tuned >= base, (tuned, base, pm.fusion_threshold_bytes,
                           pm.cycle_time_ms)
    assert pm.best_score > 0


AUTOTUNE_E2E_SCRIPT = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common import basics

hvd.init()
controller = basics._get_state().controller
assert controller.tuned_params()["tuning"] is True

# enough steady-state named traffic to close several sample windows
def fn(r):
    for s in range(40):
        for i in range(4):
            hvd.allreduce(jnp.full((256,), float(r + s)), op=hvd.Sum,
                          name=f"tune.{i}")
basics.run_parallel(fn)

params = controller.tuned_params()
assert params["fusion_threshold_bytes"] > 0
assert params["cycle_time_ms"] > 0
hvd.shutdown()
print("AUTOTUNE-E2E OK", params["fusion_threshold_bytes"],
      params["cycle_time_ms"])
"""


def test_autotune_end_to_end_through_collectives(tmp_path):
    """Drive the embedded Bayesian tuner through real eager collectives
    (reference: ParameterManager scores bytes/sec windows during
    training and logs to HOROVOD_AUTOTUNE_LOG): the tuner must be live,
    produce positive tuned values, and write its CSV log."""
    import os
    import subprocess
    import sys

    log = tmp_path / "autotune.csv"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_WARMUP_SAMPLES": "1",
        "HVD_AUTOTUNE_STEADY_STATE_SAMPLES": "2",
        "HVD_CYCLE_TIME": "1",
    })
    result = subprocess.run(
        [sys.executable, "-c", AUTOTUNE_E2E_SCRIPT], env=env,
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(__file__)))
    assert result.returncode == 0, result.stderr[-3000:]
    assert "AUTOTUNE-E2E OK" in result.stdout
    # the tuner logged its parameter walk
    assert log.exists(), "autotune log not written"
    lines = log.read_text().strip().splitlines()
    assert len(lines) >= 2, lines  # header + at least one sample row
    header = lines[0].lower()
    assert "fusion" in header and "cycle" in header, header
    # sample rows parse: numeric fusion threshold + cycle time + score
    row = lines[1].split(",")
    assert float(row[header.split(",").index("score_bytes_per_sec")]) >= 0


TCP_AUTOTUNE_SCRIPT = r"""
import hashlib
import json
import os

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common import basics

hvd.init()
r, n = hvd.rank(), hvd.size()

# steady-state named traffic: every completed entry feeds the rank-0
# tuner; tuned values ride back on the result messages
for s in range(80):
    out = np.asarray(hvd.allreduce(np.ones(256, np.float32), op=hvd.Sum,
                                   name=f"tune.{s % 4}"))
    assert out[0] == n

# one final collective so every rank applies the stamp of the SAME
# (globally last) entry
np.asarray(hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum,
                         name="tune.final"))

controller = basics._get_state().controller
params = controller.tuned_params()
assert params["fusion_threshold_bytes"] > 0
assert params["cycle_time_ms"] > 0

# publication happened and the knobs CHANGED at least once beyond the
# initial values (seq >= 2: maybe_update only returns on value change)
assert controller._tuned is not None, "no tuned params ever applied"
assert controller._tuned[0] >= 2, controller._tuned

# cross-rank identity: digest of the applied params must agree
digest = hashlib.sha256(
    json.dumps(params, sort_keys=True).encode()).digest()
gathered = np.asarray(hvd.allgather(
    np.frombuffer(digest, np.uint8).reshape(1, -1), name="tune.digest"))
for row in gathered:
    assert bytes(row) == digest, "tuned params differ across ranks"

hvd.shutdown()
print(f"rank {r} TCP_AUTOTUNE_OK", flush=True)
"""


def test_tcp_autotune_synchronized_across_ranks(tmp_path):
    """VERDICT r2 item 5: HVD_AUTOTUNE=1 in a 4-proc hvdrun tcp job
    measurably changes knobs, values identical across ranks, CSV log
    written by rank 0 (reference: controller.cc:33
    SynchronizeParameters + parameter_manager.cc logging)."""
    import os
    import subprocess
    import sys

    path = "/tmp/hvd_autotune_tcp_worker.py"
    with open(path, "w") as f:
        f.write(TCP_AUTOTUNE_SCRIPT)
    log = tmp_path / "autotune_tcp.csv"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_WARMUP_SAMPLES": "1",
        "HVD_AUTOTUNE_STEADY_STATE_SAMPLES": "1",
    })
    hvdrun = os.path.join(repo, "bin", "hvdrun")
    result = subprocess.run(
        [sys.executable, hvdrun, "-np", "4", sys.executable, path],
        env=env, capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, \
        result.stdout[-2000:] + result.stderr[-3000:]
    for r in range(4):
        assert f"rank {r} TCP_AUTOTUNE_OK" in result.stdout
    assert log.exists(), "rank-0 autotune CSV log not written"
    assert len(log.read_text().strip().splitlines()) >= 2


GMESH_AUTOTUNE_SCRIPT = r"""
import hashlib
import json

import jax
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.basics import run_parallel

hvd.init()
pid = hvd.cross_rank()

def per_rank(r):
    for s in range(60):
        out = np.asarray(hvd.allreduce(
            np.ones(128, np.float32), op=hvd.Sum, name=f"tune.{s % 4}"))
        assert out[0] == hvd.size()
    np.asarray(hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                             name="tune.final"))
    return True

assert all(run_parallel(per_rank))

controller = basics._get_state().controller
params = controller.tuned_params()
assert params["fusion_threshold_bytes"] > 0
assert controller._tuned is not None, "no params entry ever applied"

def per_rank_digest(r):
    digest = hashlib.sha256(
        json.dumps(params, sort_keys=True).encode()).digest()
    gathered = np.asarray(hvd.allgather(
        np.frombuffer(digest, np.uint8).reshape(1, -1),
        name=f"tune.digest"))
    return all(bytes(row) == digest for row in gathered)

assert all(run_parallel(per_rank_digest))
hvd.shutdown()
print(f"proc {pid} GMESH_AUTOTUNE_OK", flush=True)
"""


def test_gmesh_autotune_synchronized(tmp_path):
    """Autotune in global-mesh mode: the pid-0 metadata coordinator
    tunes; 'params' entries in the global sequence log apply the same
    values on every process at the same point of the response stream."""
    import os
    import subprocess
    import sys

    path = "/tmp/hvd_autotune_gmesh_worker.py"
    with open(path, "w") as f:
        f.write(GMESH_AUTOTUNE_SCRIPT)
    log = tmp_path / "autotune_gmesh.csv"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("AXON_", "PALLAS_", "TPU_", "JAX_"))}
    env["PYTHONPATH"] = repo
    env["JAX_PLATFORMS"] = "cpu"
    from tests.conftest import readd_jax_cache
    readd_jax_cache(env)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.update({
        "HVD_AUTOTUNE": "1",
        "HVD_AUTOTUNE_LOG": str(log),
        "HVD_AUTOTUNE_WARMUP_SAMPLES": "1",
        "HVD_AUTOTUNE_STEADY_STATE_SAMPLES": "1",
    })
    hvdrun = os.path.join(repo, "bin", "hvdrun")
    result = subprocess.run(
        [sys.executable, hvdrun, "-np", "2", "--global-mesh",
         sys.executable, path],
        env=env, capture_output=True, text=True, timeout=600)
    assert result.returncode == 0, \
        result.stdout[-2000:] + result.stderr[-3000:]
    for p in range(2):
        assert f"proc {p} GMESH_AUTOTUNE_OK" in result.stdout
    assert log.exists(), "pid-0 autotune CSV log not written"


# ------------------------------------------------- configured-value seeding

def test_parameter_manager_seeds_hierarchical_from_config():
    """ADVICE r3 (medium): the standalone PM must start from — and on a
    no-improvement walk converge back to — the operator's explicit
    hierarchical/cache choices (reference seeds SetHierarchicalAllreduce
    etc. before tuning begins)."""
    pm = autotune.ParameterManager(hierarchical_allreduce=True,
                                   hierarchical_allgather=True,
                                   cache_enabled=False)
    assert pm.hierarchical_allreduce is True
    assert pm.hierarchical_allgather is True
    assert pm.cache_enabled is False
    # default ctor keeps the old defaults
    pm2 = autotune.ParameterManager()
    assert pm2.hierarchical_allreduce is False
    assert pm2.cache_enabled is True


def test_autotune_manager_first_publication_respects_hierarchical():
    """With HVD_HIERARCHICAL_ALLREDUCE=1 + HVD_AUTOTUNE=1 the FIRST
    published knob set must not silently flip the hierarchical paths
    off (the bug: hvd_pm_create never passed the seeds, so Options
    defaulted false and _apply_tuned overrode the operator's choice)."""
    import types

    from horovod_tpu.ops.autotune import AutotuneManager

    config = types.SimpleNamespace(
        autotune=True, autotune_warmup_samples=1,
        autotune_steady_state_samples=2, autotune_log="",
        fusion_threshold_bytes=64 * 1024 * 1024, cycle_time_ms=1.0,
        hierarchical_allreduce=True, hierarchical_allgather=True)
    mgr = AutotuneManager(config)
    try:
        upd = mgr.maybe_update()  # first call always publishes
        assert upd is not None
        _, params = upd
        assert params["hierarchical_allreduce"] is True
        assert params["hierarchical_allgather"] is True
    finally:
        mgr.close()
