"""ZeRO-sharded weight update, first-class reduce-scatter, and the
NamedSharding MeshExecutor (docs/sharding.md).

Covers the full subsystem contract:

- ``zero_shard_layout`` / ``shard_chunk_size`` units against the
  ``np.array_split`` partition they promise;
- ``make_mesh`` input hardening and fsdp-axis meshes;
- eager ``hvd.reduce_scatter`` parity against numpy oracles on the
  8-rank in-process mesh (odd sizes, ``dim0 < world``, 2-D row blocks,
  Sum/Average, pre/postscale, bf16/int8 wire compression, Adasum and
  0-d rejection) plus ``grouped_allgather``;
- ``ZeroDistributedOptimizer`` numerics parity with the replicated
  update (exact-quantizing int8 leg included), the 1/N state-footprint
  guarantee, the deterministic ``min_size`` fallback, and the
  ``gather_zero_state`` / ``reshard_zero_state`` roundtrip;
- never-fuse: sharded and replicated collectives under the SAME tensor
  name must not satisfy each other's caches in any controller (native
  behavioral, tcp signature unit, python-controller subprocess);
- ``MeshExecutor`` selection via ``HVD_TPU_EXECUTOR=mesh`` in a
  subprocess: dp-axis mesh, ``named_sharding``, and the same collective
  + ZeRO numerics as the psum executor.
"""

import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from horovod_tpu.common import basics
from horovod_tpu.common.config import _validated_executor
from horovod_tpu.common.handles import HvdError
from horovod_tpu.common.ops_enum import (INT8_BLOCK, RequestType, Sum,
                                         reduce_scatter_split_sizes)
from horovod_tpu.parallel.mesh import MeshAxes, make_mesh
from horovod_tpu.sharding.zero import shard_chunk_size, zero_shard_layout

N = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _per_rank(fn):
    return basics.run_parallel(fn)


# ================================================================ units ====
@pytest.mark.parametrize("n_params,world", [
    (0, 4), (1, 4), (3, 4), (8, 4), (13, 4), (1000, 3), (7, 8), (5, 1),
])
def test_zero_shard_layout_matches_array_split(n_params, world):
    oracle = [len(c) for c in np.array_split(np.arange(n_params), world)]
    offset = 0
    for rank in range(world):
        counts, off, cnt = zero_shard_layout(n_params, world, rank)
        assert list(counts) == oracle
        assert cnt == oracle[rank]
        assert off == offset
        offset += cnt
    assert offset == n_params
    assert list(counts) == list(reduce_scatter_split_sizes(n_params, world))


def test_shard_chunk_size_is_ceil_div():
    assert shard_chunk_size(8, 4) == 2
    assert shard_chunk_size(9, 4) == 3
    assert shard_chunk_size(1, 4) == 1
    assert shard_chunk_size(0, 4) == 0
    assert shard_chunk_size(5, 1) == 5


@pytest.mark.parametrize("bad", ["psums", "MESH", "", "gspmd"])
def test_validated_executor_rejects_typos(bad):
    with pytest.raises(ValueError, match="HVD_TPU_EXECUTOR"):
        _validated_executor(bad)
    assert _validated_executor("psum") == "psum"
    assert _validated_executor("mesh") == "mesh"


# ==================================================== make_mesh hardening ====
def test_make_mesh_rejects_non_int_sizes():
    devs = jax.devices()[:4]
    with pytest.raises(ValueError, match="must be an int"):
        make_mesh({MeshAxes.DP: 2.0, MeshAxes.FSDP: 2}, devices=devs)
    with pytest.raises(ValueError, match="must be an int"):
        make_mesh({MeshAxes.DP: True, MeshAxes.FSDP: 4}, devices=devs)


def test_make_mesh_rejects_zero_and_negative_sizes():
    devs = jax.devices()[:4]
    with pytest.raises(ValueError, match="must be a positive int"):
        make_mesh({MeshAxes.DP: 0, MeshAxes.FSDP: -1}, devices=devs)
    with pytest.raises(ValueError, match="must be a positive int"):
        make_mesh({MeshAxes.DP: -2}, devices=devs)
    with pytest.raises(ValueError, match="at most one axis may be -1"):
        make_mesh({MeshAxes.DP: -1, MeshAxes.FSDP: -1}, devices=devs)


def test_make_mesh_rejects_non_divisible_absorption():
    devs = jax.devices()[:8]
    with pytest.raises(ValueError, match="not divisible"):
        make_mesh({MeshAxes.DP: 3, MeshAxes.FSDP: -1}, devices=devs)


def test_make_mesh_builds_fsdp_meshes():
    devs = jax.devices()[:8]
    m = make_mesh({MeshAxes.DP: 2, MeshAxes.FSDP: 4}, devices=devs)
    assert m.axis_names == (MeshAxes.DP, MeshAxes.FSDP)
    assert m.devices.shape == (2, 4)
    m = make_mesh({MeshAxes.DP: 2, MeshAxes.FSDP: -1}, devices=devs)
    assert m.shape[MeshAxes.FSDP] == 4
    # default: flat dp mesh over everything
    m = make_mesh(devices=devs)
    assert m.axis_names == (MeshAxes.DP,) and m.devices.shape == (8,)


# ============================================== eager reduce_scatter =======
@pytest.mark.parametrize("dim0", [1, 7, 8, 13, 29])
def test_reduce_scatter_sum_odd_sizes(hvd, dim0):
    data = [np.random.RandomState(100 + r).randn(dim0).astype(np.float32)
            for r in range(N)]
    full = np.stack(data).astype(np.float64).sum(0)
    blocks = np.array_split(full, N)

    def fn(r):
        return np.asarray(hvd.reduce_scatter(
            jnp.asarray(data[r]), op=hvd.Sum, name=f"rs.sum.{dim0}"))

    outs = _per_rank(fn)
    for r, out in enumerate(outs):
        assert out.shape == blocks[r].shape
        np.testing.assert_allclose(out.astype(np.float64), blocks[r],
                                   rtol=1e-5, atol=1e-5)


def test_reduce_scatter_2d_row_blocks(hvd):
    data = [np.full((11, 3), float(r + 1), np.float32) for r in range(N)]
    total = float(sum(range(1, N + 1)))
    counts = reduce_scatter_split_sizes(11, N)

    def fn(r):
        return np.asarray(hvd.reduce_scatter(
            jnp.asarray(data[r]), op=hvd.Sum, name="rs.2d"))

    for r, out in enumerate(_per_rank(fn)):
        assert out.shape == (counts[r], 3)
        np.testing.assert_allclose(out, np.full((counts[r], 3), total))


def test_reduce_scatter_average_with_scaling(hvd):
    data = [np.arange(9, dtype=np.float32) * (r + 1) for r in range(N)]
    full = np.stack(data).mean(0) * 0.5 * 2.0
    blocks = np.array_split(full, N)

    def fn(r):
        return np.asarray(hvd.reduce_scatter(
            jnp.asarray(data[r]), op=hvd.Average, prescale_factor=0.5,
            postscale_factor=2.0, name="rs.avg.scaled"))

    for r, out in enumerate(_per_rank(fn)):
        np.testing.assert_allclose(out, blocks[r], rtol=1e-5)


def test_reduce_scatter_bf16_wire(hvd):
    # small integers are exact in bf16, so the compressed wire must
    # reproduce the exact oracle
    data = [np.arange(17, dtype=np.float32) * (r + 1) for r in range(N)]
    full = np.stack(data).sum(0)
    blocks = np.array_split(full, N)

    def fn(r):
        return np.asarray(hvd.reduce_scatter(
            jnp.asarray(data[r]), op=hvd.Sum, compression="bf16",
            name="rs.bf16"))

    for r, out in enumerate(_per_rank(fn)):
        np.testing.assert_allclose(out, blocks[r])


def test_reduce_scatter_int8_wire_block_constant_exact(hvd):
    # block-constant data quantizes exactly (one scale per block)
    nblocks = 2 * N
    base = np.repeat(np.arange(nblocks, dtype=np.float32) + 1.0, INT8_BLOCK)
    data = [base * (r + 1) for r in range(N)]
    full = base * sum(range(1, N + 1))
    blocks = np.array_split(full, N)

    def fn(r):
        return np.asarray(hvd.reduce_scatter(
            jnp.asarray(data[r]), op=hvd.Sum, compression="int8",
            name="rs.int8"))

    for r, out in enumerate(_per_rank(fn)):
        np.testing.assert_allclose(out, blocks[r], rtol=1e-6)


def test_reduce_scatter_rejects_adasum(hvd):
    with pytest.raises(ValueError, match="Adasum"):
        hvd.reduce_scatter(jnp.ones((4,)), op=hvd.Adasum, name="rs.adasum")


def test_reduce_scatter_rejects_0d(hvd):
    def fn(r):
        try:
            hvd.reduce_scatter(jnp.asarray(1.0), op=hvd.Sum, name="rs.0d")
        except (HvdError, ValueError) as exc:
            return type(exc).__name__
        return None

    assert all(_per_rank(fn))


def test_grouped_allgather_variable_dim0(hvd):
    def fn(r):
        tensors = [jnp.full((r + 1,), float(r), jnp.float32),
                   jnp.full((2, 3), float(r + 10), jnp.float32)]
        return [np.asarray(t) for t in
                hvd.grouped_allgather(tensors, name="ga.group")]

    outs = _per_rank(fn)
    exp_a = np.concatenate([np.full((i + 1,), float(i), np.float32)
                            for i in range(N)])
    exp_b = np.concatenate([np.full((2, 3), float(i + 10), np.float32)
                            for i in range(N)])
    for a, b in outs:
        np.testing.assert_allclose(a, exp_a)
        np.testing.assert_allclose(b, exp_b)


# ======================================================= ZeRO optimizer ====
_LR = 0.05


def _oracle_adam(params, mean_grads_per_step):
    """The replicated update every rank would compute locally."""
    opt = optax.adam(_LR)
    st = opt.init(params)
    p = params
    for g in mean_grads_per_step:
        u, st = opt.update(g, st, p)
        p = optax.apply_updates(p, u)
    return p


def _shard_leaf_lengths(state, n_params):
    return sorted(int(l.shape[0]) for l in jax.tree_util.tree_leaves(state)
                  if getattr(l, "ndim", 0) == 1)


def test_zero_optimizer_matches_replicated_update(hvd):
    params = {"w": jnp.asarray(np.random.RandomState(0)
                               .randn(33).astype(np.float32)),
              "b": jnp.asarray(np.random.RandomState(1)
                               .randn(5, 3).astype(np.float32))}
    n_params = 33 + 15
    steps = 3
    rank_grads = [[jax.tree_util.tree_map(
        lambda p, r=r, s=s: jnp.asarray(
            np.random.RandomState(7 * r + s).randn(*p.shape)
            .astype(np.float32)), params) for s in range(steps)]
        for r in range(N)]
    mean_grads = [jax.tree_util.tree_map(
        lambda *gs: sum(gs) / N, *[rank_grads[r][s] for r in range(N)])
        for s in range(steps)]
    oracle = _oracle_adam(params, mean_grads)
    counts, _, _ = zero_shard_layout(n_params, N, 0)

    def fn(r):
        opt = hvd.ZeroDistributedOptimizer(optax.adam(_LR), min_size=1)
        st = opt.init(params)
        lens = _shard_leaf_lengths(st, n_params)
        p = params
        for g in rank_grads[r]:
            u, st = opt.update(g, st, p)
            p = optax.apply_updates(p, u)
        # gather -> reshard must be the identity on the live shard
        full = hvd.gather_zero_state(st, n_params)
        back = hvd.reshard_zero_state(full, n_params)
        same = all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(jax.tree_util.tree_leaves(st),
                                   jax.tree_util.tree_leaves(back)))
        full_lens = _shard_leaf_lengths(full, n_params)
        return p, lens, same, full_lens

    for r, (p, lens, roundtrip_ok, full_lens) in enumerate(_per_rank(fn)):
        # numerics: identical to the replicated update
        for k in params:
            np.testing.assert_allclose(np.asarray(p[k]),
                                       np.asarray(oracle[k]),
                                       rtol=0, atol=1e-6)
        # footprint: every 1-D state leaf is this rank's 1/N shard
        assert lens and set(lens) == {counts[r]}, (r, lens)
        assert counts[r] < n_params
        # gathered state is full-size, and resharding it returns the
        # exact live shard
        assert full_lens and set(full_lens) == {n_params}
        assert roundtrip_ok


def test_zero_optimizer_int8_wire_matches_replicated(hvd):
    # block-constant gradients quantize exactly, so the int8-compressed
    # sharded update must match the uncompressed replicated oracle
    n_params = N * INT8_BLOCK  # alignment: each rank's shard = 1 block
    params = jnp.zeros((n_params,), jnp.float32)
    steps = 2
    rank_grads = [[jnp.asarray(np.repeat(
        np.arange(N, dtype=np.float32) + 1 + r + 3 * s, INT8_BLOCK))
        for s in range(steps)] for r in range(N)]
    mean_grads = [sum(rank_grads[r][s] for r in range(N)) / N
                  for s in range(steps)]
    oracle = _oracle_adam(params, mean_grads)

    def fn(r):
        opt = hvd.ZeroDistributedOptimizer(optax.adam(_LR),
                                           compression="int8", min_size=1)
        st = opt.init(params)
        p = params
        for g in rank_grads[r]:
            u, st = opt.update(g, st, p)
            p = optax.apply_updates(p, u)
        return np.asarray(p)

    for out in _per_rank(fn):
        np.testing.assert_allclose(out, np.asarray(oracle),
                                   rtol=0, atol=1e-6)


def test_zero_min_size_falls_back_to_replicated_state(hvd):
    # below the threshold the update must keep FULL state on every rank
    # (and still match the oracle) -- the fallback is a pure function of
    # (n_params, world, min_size) so all ranks agree
    params = jnp.asarray(np.random.RandomState(3)
                         .randn(12).astype(np.float32))
    grads = [jnp.asarray(np.random.RandomState(50 + r)
                         .randn(12).astype(np.float32)) for r in range(N)]
    oracle = _oracle_adam(params, [sum(grads) / N])

    def fn(r):
        opt = hvd.ZeroDistributedOptimizer(optax.adam(_LR), min_size=10_000)
        st = opt.init(params)
        lens = _shard_leaf_lengths(st, 12)
        u, st = opt.update(grads[r], st, params)
        return np.asarray(optax.apply_updates(params, u)), lens

    for out, lens in _per_rank(fn):
        np.testing.assert_allclose(out, np.asarray(oracle),
                                   rtol=0, atol=1e-6)
        assert lens and set(lens) == {12}


# ============================================================ never-fuse ====
def test_same_name_allreduce_and_reduce_scatter_never_share_cache(hvd):
    # two rounds: the second hits the native response cache + the
    # executor's memoized programs, where a shared signature would
    # hand a reduce_scatter the cached allreduce (or vice versa)
    data = [np.arange(24, dtype=np.float32) * (r + 1) for r in range(N)]
    full = np.stack(data).sum(0)
    blocks = np.array_split(full, N)
    for _ in range(2):
        ar = _per_rank(lambda r: np.asarray(hvd.allreduce(
            jnp.asarray(data[r]), op=hvd.Sum, name="cachesep")))
        for out in ar:
            np.testing.assert_allclose(out, full, rtol=1e-5)
        rs = _per_rank(lambda r: np.asarray(hvd.reduce_scatter(
            jnp.asarray(data[r]), op=hvd.Sum, name="cachesep")))
        for r, out in enumerate(rs):
            assert out.shape == blocks[r].shape
            np.testing.assert_allclose(out, blocks[r], rtol=1e-5)


def test_tcp_signature_separates_request_types():
    # the tcp response cache keys on _signature: identical tensors that
    # differ ONLY in request type must never collide
    from horovod_tpu.ops.tcp_controller import CollectiveMsg, _signature

    ar = CollectiveMsg("t", 0, RequestType.ALLREDUCE, Sum, b"", (8,),
                       "float32")
    rs = CollectiveMsg("t", 0, RequestType.REDUCE_SCATTER, Sum, b"", (8,),
                       "float32")
    assert _signature(ar) != _signature(rs)
    ring = CollectiveMsg("t", 0, RequestType.REDUCE_SCATTER, Sum, b"", (8,),
                         "float32", ring=True)
    assert _signature(rs) != _signature(ring)


# ================================================== subprocess matrices ====
def _run_cpu_script(script, extra_env=None, timeout=300, devices=4):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env.update(extra_env or {})
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=timeout,
                          cwd=REPO)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr[-4000:]}")
    return proc.stdout


MESH_EXECUTOR_SCRIPT = r"""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.parallel.mesh import MeshAxes

hvd.init()
n = hvd.size()
assert n == 4, n

ex = basics._state.executor
assert type(ex).__name__ == "MeshExecutor", type(ex).__name__
assert tuple(ex.mesh.axis_names) == (MeshAxes.DP,), ex.mesh.axis_names
assert ex.axis == MeshAxes.DP

ns = ex.named_sharding(MeshAxes.DP)
from jax.sharding import NamedSharding, PartitionSpec
assert isinstance(ns, NamedSharding)
assert ns.spec == PartitionSpec(MeshAxes.DP), ns.spec

# collective parity on the dp-axis mesh
data = [np.arange(13, dtype=np.float32) * (r + 1) for r in range(n)]
full = np.stack(data).sum(0)

out = basics.run_parallel(lambda r: np.asarray(
    hvd.allreduce(jnp.asarray(data[r]), op=hvd.Sum, name="mesh.ar")))
for o in out:
    np.testing.assert_allclose(o, full, rtol=1e-5)

blocks = np.array_split(full, n)
out = basics.run_parallel(lambda r: np.asarray(
    hvd.reduce_scatter(jnp.asarray(data[r]), op=hvd.Sum, name="mesh.rs")))
for r, o in enumerate(out):
    assert o.shape == blocks[r].shape
    np.testing.assert_allclose(o, blocks[r], rtol=1e-5)

# ZeRO step numerics on the mesh executor == local replicated oracle
params = jnp.asarray(np.random.RandomState(0).randn(21).astype(np.float32))
grads = [jnp.asarray(np.random.RandomState(10 + r)
                     .randn(21).astype(np.float32)) for r in range(n)]
opt = optax.adam(0.05)
st0 = opt.init(params)
u, _ = opt.update(sum(grads) / n, st0, params)
oracle = np.asarray(optax.apply_updates(params, u))

def step(r):
    zopt = hvd.ZeroDistributedOptimizer(optax.adam(0.05), min_size=1)
    st = zopt.init(params)
    u, st = zopt.update(grads[r], st, params)
    return np.asarray(optax.apply_updates(params, u))

for o in basics.run_parallel(step):
    np.testing.assert_allclose(o, oracle, rtol=0, atol=1e-6)

hvd.shutdown()
print("MESH_OK", flush=True)
"""


def test_mesh_executor_selected_by_env_and_matches_psum():
    out = _run_cpu_script(MESH_EXECUTOR_SCRIPT,
                          extra_env={"HVD_TPU_EXECUTOR": "mesh"})
    assert "MESH_OK" in out


PYTHON_CONTROLLER_SCRIPT = r"""
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common import basics

hvd.init()
n = hvd.size()
assert n == 4, n
assert type(basics._state.controller).__name__ == "PythonController"

data = [np.arange(10, dtype=np.float32) * (r + 1) for r in range(n)]
full = np.stack(data).sum(0)
blocks = np.array_split(full, n)

# interleave allreduce and reduce_scatter under ONE name, twice --
# never-fuse + per-request-type dispatch in the python controller
for _ in range(2):
    out = basics.run_parallel(lambda r: np.asarray(
        hvd.allreduce(jnp.asarray(data[r]), op=hvd.Sum, name="pync")))
    for o in out:
        np.testing.assert_allclose(o, full, rtol=1e-5)
    out = basics.run_parallel(lambda r: np.asarray(
        hvd.reduce_scatter(jnp.asarray(data[r]), op=hvd.Sum, name="pync")))
    for r, o in enumerate(out):
        assert o.shape == blocks[r].shape
        np.testing.assert_allclose(o, blocks[r], rtol=1e-5)

# grouped_allgather through the python controller
out = basics.run_parallel(lambda r: [np.asarray(t) for t in
    hvd.grouped_allgather([jnp.full((r + 1,), float(r), jnp.float32)],
                          name="py.ga")])
exp = np.concatenate([np.full((i + 1,), float(i), np.float32)
                      for i in range(n)])
for (o,) in out:
    np.testing.assert_allclose(o, exp)

hvd.shutdown()
print("PY_OK", flush=True)
"""


def test_python_controller_reduce_scatter_and_never_fuse():
    out = _run_cpu_script(PYTHON_CONTROLLER_SCRIPT,
                          extra_env={"HVD_CONTROLLER": "python"})
    assert "PY_OK" in out
