"""Process-mode (tcp) dtype matrix + stall/fusion/join combination tests
(reference: the dtype x device sweep of ``test/test_torch.py`` run under
``horovodrun --gloo``, and ``test_stall.py`` driven purely by env vars).

The numpy data plane keeps 64-bit types exact here (the device-rank
matrix in ``test_dtype_matrix.py`` covers the XLA-native types).

The in-process half is the ISSUE 3 parity matrix: the pipelined
multi-stream ring (native wire dtypes, segment overlap, socket
striping) against the seed-era serial f64-wire ring, across dtypes x
sizes x compression x stripes, plus the wire-byte accounting the
acceptance criterion names."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from conftest import spawn_tcp_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HVDRUN = os.path.join(REPO, "bin", "hvdrun")


def _run_hvdrun(np_, script, extra_env=None, timeout=600):
    path = "/tmp/hvd_tcp_matrix_worker.py"
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, HVDRUN, "-np", str(np_), sys.executable, path]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


DTYPE_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r, n = hvd.rank(), hvd.size()

ALL = ["float16", "bfloat16", "float32", "float64",
       "int8", "int16", "int32", "int64", "uint8", "uint16",
       "uint32", "uint64"]

# -- allreduce sum, every dtype, star plane (exact accumulation) ---------
for dtype in ALL:
    # cast AFTER scaling: numpy promotes bf16*int to float32
    data = ((np.arange(6) + 1) * (r + 1)).astype(dtype)
    out = np.asarray(hvd.allreduce(data, op=hvd.Sum,
                                   name=f"sum.{dtype}"))
    assert str(out.dtype) == dtype, (out.dtype, dtype)
    expect = (np.arange(6) + 1).astype(np.float64) * sum(
        range(1, n + 1))
    np.testing.assert_allclose(out.astype(np.float64), expect,
                               rtol=2e-2 if "16" in dtype else 1e-9)

# int64 exactness beyond float64's 2**53 (the star plane accumulates
# integers in int64, never through floats)
big = np.array([2**60 + r], dtype=np.int64)
out = np.asarray(hvd.allreduce(big, op=hvd.Sum, name="i64exact"))
assert int(out[0]) == sum(2**60 + i for i in range(n)), int(out[0])

# -- broadcast every dtype ------------------------------------------------
for dtype in ALL:
    data = (np.arange(4) * (r + 2)).astype(dtype)
    out = np.asarray(hvd.broadcast(data, root_rank=1,
                                   name=f"bc.{dtype}"))
    np.testing.assert_allclose(
        out.astype(np.float64),
        (np.arange(4) * 3).astype(dtype).astype(np.float64))

# -- allgather with variable dims, 64-bit types ---------------------------
for dtype in ["float64", "int64", "uint32"]:
    data = np.full((r + 1, 2), r + 1).astype(dtype)
    out = np.asarray(hvd.allgather(data, name=f"ag.{dtype}"))
    expect = np.concatenate(
        [np.full((i + 1, 2), i + 1) for i in range(n)]).astype(np.float64)
    np.testing.assert_allclose(out.astype(np.float64), expect)

# -- alltoall int64 -------------------------------------------------------
t = (np.arange(2 * n) + 100 * r).astype(np.int64)
out = np.asarray(hvd.alltoall(t, name="a2a.i64"))
expect = np.concatenate(
    [np.arange(2 * r, 2 * r + 2) + 100 * src for src in range(n)])
np.testing.assert_allclose(out, expect)

# -- ring plane sweep (threshold forced to 1KB) ---------------------------
for dtype in ["float32", "float64", "int64"]:
    data = np.full((70001,), 3).astype(dtype) * (r + 1)
    out = np.asarray(hvd.allreduce(data, op=hvd.Sum,
                                   name=f"ring.{dtype}"))
    assert str(out.dtype) == dtype
    np.testing.assert_allclose(
        out.astype(np.float64),
        np.full((70001,), 3 * sum(range(1, n + 1)), np.float64))

# -- 0-d scalars over the wire -------------------------------------------
out = hvd.allreduce(np.float64(1.5), op=hvd.Sum, name="sc64")
assert np.asarray(out).ndim == 0
assert float(np.asarray(out)) == 1.5 * n

print(f"rank {r} TCP_DTYPES_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_dtype_matrix_2proc():
    result = _run_hvdrun(2, DTYPE_WORKER,
                         extra_env={"HVD_TCP_RING_THRESHOLD": "1024"})
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("TCP_DTYPES_OK") == 2


STALL_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.handles import HvdError

hvd.init()
r, n = hvd.rank(), hvd.size()
assert n == 4

# fusion-heavy traffic while rank 3 goes silent (neither submitting nor
# joining — a join would legitimately complete the collective with zero
# stand-ins): healthy collectives complete first, then the stalled name
# trips the stall inspector, which PROMOTES the stall into a coordinated
# abort (sticky — the job is over): every rank, the silent culprit
# included, must fail its next operation with the typed error naming the
# stalled tensor, not hang (reference: StallInspector shutdown promoted
# into the PR-2 abort protocol).
import time
handles = {}
for i in range(6):
    handles[i] = hvd.allreduce_async(jnp.ones((8,)) * (r + 1),
                                     op=hvd.Sum, name=f"ok{i}")
for i, h in handles.items():
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               np.full((8,), 10.0))

if r != 3:
    try:
        hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="stalled")
        raise SystemExit("expected stall shutdown abort")
    except HvdError as exc:
        assert "stalled" in str(exc), str(exc)
else:
    time.sleep(8)  # silent through the 4s stall-shutdown window

try:
    hvd.join()
    raise SystemExit("expected the abort to poison the join barrier")
except HvdError as exc:
    assert "stalled" in str(exc), str(exc)
print(f"rank {r} STALL_ABORT_OK", flush=True)
try:
    hvd.shutdown()
except Exception:
    pass  # rank 0's exit may take the coordinator with it first
"""


def test_tcp_stall_shutdown_with_fusion_and_join_4proc():
    result = _run_hvdrun(4, STALL_WORKER, extra_env={
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "4",
    }, timeout=420)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("STALL_ABORT_OK") == 4
    assert "Stalled tensor" in (result.stdout + result.stderr)


GROUPED_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r, n = hvd.rank(), hvd.size()

# grouped allreduce with mixed dtypes and mixed planes (some above the
# 1KB ring threshold, some below)
tensors = [
    jnp.ones((4,), jnp.float32) * (r + 1),
    jnp.ones((70000,), jnp.float32) * (r + 1),
    jnp.ones((8,), jnp.int32) * (r + 1),
    jnp.ones((70000,), jnp.float64) * (r + 1),
]
outs = hvd.grouped_allreduce(tensors, op=hvd.Sum, name="grp")
for t, out in zip(tensors, outs):
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float64),
        np.full(t.shape, float(sum(range(1, n + 1)))))

print(f"rank {r} GROUPED_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_grouped_mixed_planes_4proc():
    result = _run_hvdrun(4, GROUPED_WORKER,
                         extra_env={"HVD_TCP_RING_THRESHOLD": "1024"})
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("GROUPED_OK") == 4


JOINED_RANK_WORKER = r"""
import time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
assert hvd.size() == 3

if r == 0:
    # submit, then join while rank 2 hasn't contributed yet: the
    # collective must WAIT for rank 2, not complete without it
    h = hvd.allreduce_async(jnp.full((4,), 1.0), op=hvd.Sum, name="t")
    last = hvd.join()
    out = np.asarray(hvd.synchronize(h))
elif r == 1:
    out = np.asarray(hvd.allreduce(jnp.full((4,), 2.0), op=hvd.Sum,
                                   name="t"))
    last = hvd.join()
else:
    time.sleep(1.5)  # rank 0 has joined well before this submission
    out = np.asarray(hvd.allreduce(jnp.full((4,), 4.0), op=hvd.Sum,
                                   name="t"))
    last = hvd.join()

# every contribution must be in the sum, including the joined rank 0's
np.testing.assert_allclose(out, np.full((4,), 7.0), err_msg=str(out))
print(f"rank {r} JOINED_COUNT_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_joined_rank_does_not_satisfy_live_rank():
    """Regression: the coordinator counted a since-joined rank's request
    toward completion, finishing a collective without a live rank's
    contribution (silent wrong sum)."""
    result = _run_hvdrun(3, JOINED_RANK_WORKER, timeout=300)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("JOINED_COUNT_OK") == 3


ERROR_SWEEP_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common.handles import HvdError

hvd.init()
r, n = hvd.rank(), hvd.size()

# per-op cross-rank mismatch sweep over the tcp coordinator
# (reference: the error-path coverage test_torch.py runs per backend)
cases = [
    # (submit, error fragment)
    (lambda: hvd.allreduce(np.ones(2 + r % 2, np.float32), op=hvd.Sum,
                           name="e.shape"), "shape"),
    (lambda: hvd.allreduce(
        np.ones(3, np.float32 if r % 2 == 0 else np.int32), op=hvd.Sum,
        name="e.dtype"), "dtype"),
    (lambda: hvd.allreduce(np.ones(3, np.float32),
                           op=hvd.Sum if r % 2 == 0 else hvd.Average,
                           name="e.op"), "op"),
    (lambda: (hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum,
                            name="e.type") if r % 2 == 0 else
              hvd.broadcast(np.ones(3, np.float32), root_rank=0,
                            name="e.type")), "type"),
    (lambda: hvd.broadcast(np.ones(3, np.float32), root_rank=r % 2,
                           name="e.root"), "root"),
    (lambda: hvd.allgather(
        np.ones((2, 3 + r % 2), np.float32), name="e.trail"),
     "trailing"),
    (lambda: hvd.alltoall(np.ones((4, 2), np.float32),
                          splits=[2] * n, name="e.split"), "split"),
]
for submit, frag in cases:
    try:
        submit()
        raise SystemExit(f"expected HvdError for {frag}")
    except HvdError as exc:
        assert frag in str(exc).lower(), (frag, str(exc))

# every poisoned name recovers (error responses clear the entry)
out = np.asarray(hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum,
                               name="e.shape"))
np.testing.assert_allclose(out, np.full(3, float(n)))

# torch binding over the SAME tcp plane (reference: horovodrun --gloo
# pytest test_torch.py)
import torch
import horovod_tpu.torch as hvd_t
h = hvd_t.grouped_allreduce_async(
    [torch.ones(4) * (r + 1), torch.ones(2) * 10 * (r + 1)],
    op=hvd_t.Sum, name="e.tg")
outs = hvd_t.synchronize(h)
total = float(sum(range(1, n + 1)))
assert torch.allclose(outs[0], torch.full((4,), total))
assert torch.allclose(outs[1], torch.full((2,), 10 * total))
try:
    hvd_t.allreduce(torch.ones(2 + r % 2), op=hvd_t.Sum, name="e.tshape")
    raise SystemExit("expected HvdError (torch over tcp)")
except HvdError as exc:
    assert "shape" in str(exc).lower()

print(f"rank {r} TCP_ERRORS_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_error_sweep_and_torch_binding_4proc():
    """Cross-rank mismatch sweep per op over the tcp coordinator, error
    recovery, and the torch binding (incl. the grouped one-handle
    contract) riding the same process-mode plane."""
    result = _run_hvdrun(4, ERROR_SWEEP_WORKER, timeout=420)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr[-3000:]}"
    assert result.stdout.count("TCP_ERRORS_OK") == 4


REDUCE_SCATTER_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r, n = hvd.rank(), hvd.size()

# star plane (small payloads): several dtypes x odd sizes; the numpy
# data plane keeps 64-bit types exact
for dtype in ["float32", "float64", "int64", "int32"]:
    for size in [7, 10]:
        data = ((np.arange(size) + 1) * (r + 1)).astype(dtype)
        out = np.asarray(hvd.reduce_scatter(data, op=hvd.Sum,
                                            name=f"rs.{dtype}.{size}"))
        assert str(out.dtype) == dtype, (out.dtype, dtype)
        full = (np.arange(size) + 1).astype(np.float64) * sum(range(1, n + 1))
        expect = np.array_split(full, n)[r]
        np.testing.assert_allclose(out.astype(np.float64), expect)

# average + prescale through the coordinator star
data = np.full(9, 2.0 * (r + 1), np.float32)
out = np.asarray(hvd.reduce_scatter(data, op=hvd.Average,
                                    prescale_factor=0.5, name="rs.avg"))
full = np.full(9, 0.5 * 2.0 * sum(range(1, n + 1)) / n)
np.testing.assert_allclose(out, np.array_split(full, n)[r], rtol=1e-6)

# ring plane (above the 1KB threshold): the share-reduce half of the
# ring allreduce, exact against a float64 oracle
for size in [70001, 20001]:
    data = np.random.RandomState(size + r).randn(size).astype(np.float32)
    out = np.asarray(hvd.reduce_scatter(data, op=hvd.Sum,
                                        name=f"rs.ring.{size}"))
    allv = np.stack([np.random.RandomState(size + i).randn(size)
                     for i in range(n)]).astype(np.float32)
    expect = np.array_split(allv.astype(np.float64).sum(0), n)[r]
    np.testing.assert_allclose(out.astype(np.float64), expect,
                               rtol=1e-4, atol=1e-4)

# ring + int8 wire compression (block-constant data quantizes exactly,
# tolerance covers the per-hop requantization)
blocks = np.repeat(np.arange(140, dtype=np.float32) + 1, 512)[:70001]
data = blocks * (r + 1)
out = np.asarray(hvd.reduce_scatter(data, op=hvd.Sum, compression="int8",
                                    name="rs.ring.int8"))
full = blocks.astype(np.float64) * sum(range(1, n + 1))
np.testing.assert_allclose(out.astype(np.float64),
                           np.array_split(full, n)[r], rtol=2e-2, atol=0.6)

# 2-D: row-block split along dim 0
data = np.full((10, 3), float(r + 1), np.float32)
out = np.asarray(hvd.reduce_scatter(data, op=hvd.Sum, name="rs.2d"))
counts = [10 // n + (1 if i < 10 % n else 0) for i in range(n)]
assert out.shape == (counts[r], 3), out.shape
np.testing.assert_allclose(
    out, np.full((counts[r], 3), float(sum(range(1, n + 1)))))

# grouped_allgather re-assembles variable-dim0 blocks (the ZeRO second
# half) through the same controller
outs = hvd.grouped_allgather([np.full((r + 1,), float(r), np.float32)],
                             name="rs.ga")
expect = np.concatenate([np.full((i + 1,), float(i), np.float32)
                         for i in range(n)])
np.testing.assert_allclose(np.asarray(outs[0]), expect)

print(f"rank {r} RS_TCP_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_reduce_scatter_both_planes_4proc():
    """First-class reduce_scatter through the tcp controller: coordinator
    star for small payloads, worker ring (share-reduce half, shifted
    schedule) above the threshold, dtype fidelity, int8 wire, and the
    allgather inverse (docs/sharding.md)."""
    result = _run_hvdrun(4, REDUCE_SCATTER_WORKER,
                         extra_env={"HVD_TCP_RING_THRESHOLD": "1024"})
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr[-3000:]}"
    assert result.stdout.count("RS_TCP_OK") == 4


# ===================================================================
# ISSUE 3 parity matrix: pipelined multi-stream ring vs the seed ring
# (in-process, real loopback TCP — the exact transport of tcp mode).
# ===================================================================
class _PipelinedHarness:
    """One PeerService mailbox + RingPlane per rank with bulk stripes
    (the transport rig is ``bench._ring_harness`` — one definition for
    the bench sweep, this matrix, and the fault tests)."""

    def __init__(self, p, segment_bytes, stripes):
        import bench

        self.p = p
        self.services, self.planes = bench._ring_harness(
            p, segment_bytes, stripes)
        self._ring_id = 0

    def run_all(self, fn):
        outs = [None] * self.p
        errs = []

        def run(r):
            try:
                outs[r] = fn(r)
            except Exception as exc:  # noqa: BLE001 — surface in test
                errs.append(exc)

        threads = [threading.Thread(target=run, args=(r,))
                   for r in range(self.p)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errs, errs
        return outs

    def allreduce(self, data, seed=False, op_average=False, **kw):
        self._ring_id += 1
        rid = self._ring_id
        ranks = list(range(self.p))
        if seed:
            return self.run_all(lambda r: self.planes[r].allreduce_seed(
                rid, data[r], ranks, world_size=self.p, timeout=60,
                op_average=op_average, **kw))
        return self.run_all(lambda r: self.planes[r].allreduce(
            rid, data[r], ranks, world_size=self.p, timeout=60,
            op_average=op_average, **kw))

    def close(self):
        for plane in self.planes:
            plane.close()
        for svc in self.services:
            svc.shutdown()


# sub-segment, multi-segment, and odd-remainder sizes against an 8 KB
# segment (chunks of ~size/3 elements -> 1, ~10 and ~30 segments)
_PARITY_SIZES = [500, 20001, 70001]


def _assert_rank_consistent(outs):
    for out in outs[1:]:
        assert np.array_equal(np.asarray(out), np.asarray(outs[0])), \
            "ring result differs across ranks"


@pytest.mark.parametrize("stripes", [1, 2, 4])
def test_pipelined_ring_parity_matrix(stripes):
    """dtypes (fp32/bf16/fp16/int32) x sizes (sub-segment,
    multi-segment, odd remainder) x compression (none/int8/bf16):
    the pipelined ring must match the seed ring (exact legs) or the
    float64 oracle within the codec bound (compressed legs), and be
    bit-identical across ranks in every cell."""
    import ml_dtypes

    harness = _PipelinedHarness(3, segment_bytes=8192, stripes=stripes)
    try:
        for size in _PARITY_SIZES:
            fdata = [np.random.RandomState(17 * size + r).randn(size)
                     for r in range(harness.p)]
            exact = np.sum(np.stack(fdata), 0)

            # ---- exact legs: parity against the seed ring ------------
            for dtype, rtol, atol in [
                    (np.float32, 1e-4, 1e-4),
                    (ml_dtypes.bfloat16, 1e-1, 0.25),
                    (np.float16, 2e-2, 0.1)]:
                data = [d.astype(dtype) for d in fdata]
                outs = harness.allreduce(data)
                ref = harness.allreduce(data, seed=True)
                _assert_rank_consistent(outs)
                assert outs[0].dtype == np.dtype(dtype)
                np.testing.assert_allclose(
                    np.asarray(outs[0], np.float64),
                    np.asarray(ref[0], np.float64),
                    rtol=rtol, atol=atol,
                    err_msg=f"{np.dtype(dtype).name} size={size}")

            # int32: modular wire arithmetic must stay EXACT vs seed
            idata = [(np.arange(size) * (r + 1) - size // 2).astype(
                np.int32) for r in range(harness.p)]
            outs = harness.allreduce(idata)
            ref = harness.allreduce(idata, seed=True)
            _assert_rank_consistent(outs)
            assert np.array_equal(outs[0], ref[0]), f"int32 size={size}"

            # ---- compressed legs (fp32 input) ------------------------
            data = [d.astype(np.float32) for d in fdata]
            for comp, atol in [("int8", 0.5), ("bf16", None)]:
                outs = harness.allreduce(data, compression=comp)
                _assert_rank_consistent(outs)
                if atol is not None:
                    assert np.abs(
                        np.asarray(outs[0], np.float64) - exact
                    ).max() < atol, f"{comp} size={size}"
                else:
                    np.testing.assert_allclose(
                        np.asarray(outs[0], np.float64), exact,
                        rtol=3e-2, atol=0.1,
                        err_msg=f"{comp} size={size}")
    finally:
        harness.close()


def test_pipelined_ring_int_average_survives_intermediate_overflow():
    """Regression: an int32 AVERAGE whose intermediate sum exceeds
    int32's range must read the true wide total before dividing — the
    modular native wire is only exact for a pure sum, so averaged or
    postscaled integer rings widen to int64 on the wire like the seed."""
    harness = _PipelinedHarness(3, segment_bytes=4096, stripes=2)
    try:
        data = [np.full(5000, 2 ** 30, np.int32)
                for _ in range(harness.p)]
        outs = harness.allreduce(data, op_average=True)
        ref = harness.allreduce(data, seed=True, op_average=True)
        _assert_rank_consistent(outs)
        assert np.array_equal(outs[0], ref[0])
        # the true average of 3 x 2^30 is 2^30 — NOT the wrapped value
        assert outs[0][0] == 2 ** 30, outs[0][0]

        # postscale on the sum path widens too
        outs = harness.allreduce(data, postscale=0.25)
        ref = harness.allreduce(data, seed=True, postscale=0.25)
        _assert_rank_consistent(outs)
        assert np.array_equal(outs[0], ref[0])
    finally:
        harness.close()


def test_pipelined_ring_wire_bytes_half_of_seed():
    """Acceptance: the exact-path fp32 ring ships <= 0.51x the seed
    ring's wire bytes per rank, measured at the framing layer (every
    control post and bulk stripe frame counts, headers included)."""
    harness = _PipelinedHarness(4, segment_bytes=1 << 18, stripes=2)
    try:
        data = [np.random.RandomState(r).randn(1 << 18).astype(np.float32)
                for r in range(harness.p)]  # 1 MB per rank
        harness.allreduce(data)
        pipelined = [plane.bytes_sent() for plane in harness.planes]
        harness.allreduce(data, seed=True)
        seed = [plane.bytes_sent() - b
                for plane, b in zip(harness.planes, pipelined)]
        for pp, ss in zip(pipelined, seed):
            assert pp <= 0.51 * ss, (pipelined, seed)
    finally:
        harness.close()


def test_pipelined_ring_broadcast_allgather_native_dtype_bytes():
    """Satellite: broadcast and allgather ship the array's own dtype —
    wire bytes for an N-element fp32 tensor stay ~4N per hop, nowhere
    near the 8N an f64-wire plane would move."""
    harness = _PipelinedHarness(3, segment_bytes=8192, stripes=2)
    try:
        n = 50000
        arr = np.random.RandomState(3).randn(n).astype(np.float32)
        base = [plane.bytes_sent() for plane in harness.planes]
        outs = harness.run_all(lambda r: harness.planes[r].broadcast(
            7001, arr if r == 0 else None, [0, 1, 2], 0,
            shape=arr.shape, dtype="float32", timeout=60))
        for out in outs:
            assert np.array_equal(out, arr)
        sent = [plane.bytes_sent() - b
                for plane, b in zip(harness.planes, base)]
        # root + one forwarder each upload the tensor once (~4N bytes
        # + framing); the last rank sends nothing
        for moved in sent[:2]:
            assert moved < 1.15 * arr.nbytes, sent

        blocks = [np.full((r + 2, 5), r, np.float32)
                  for r in range(harness.p)]
        nb = [b.nbytes for b in blocks]
        base = [plane.bytes_sent() for plane in harness.planes]
        outs = harness.run_all(lambda r: harness.planes[r].allgather(
            7002, blocks[r], [0, 1, 2], block_nbytes=nb, timeout=60))
        for out in outs:
            for i, blob in enumerate(out):
                assert np.array_equal(
                    np.frombuffer(blob, np.float32),
                    blocks[i].reshape(-1))
        sent = [plane.bytes_sent() - b
                for plane, b in zip(harness.planes, base)]
        total_payload = sum(nb)
        for moved in sent:
            # each rank forwards every block except the one that ends
            # its rotation: < total payload + framing
            assert moved < total_payload + 2048, (sent, total_payload)
    finally:
        harness.close()


def test_pipelined_ring_adasum_native_wire_matches_oracle():
    """Satellite: adasum wires the native dtype (fp32 halves on the
    exchange + gather legs) yet still matches the numpy VHDD oracle,
    rank-consistently."""
    from horovod_tpu.ops.adasum import adasum_reference

    harness = _PipelinedHarness(4, segment_bytes=4096, stripes=2)
    try:
        data = [np.random.RandomState(40 + r).randn(3333).astype(
            np.float32) for r in range(harness.p)]
        base = [plane.bytes_sent() for plane in harness.planes]
        outs = harness.run_all(lambda r: harness.planes[r].adasum(
            7003, data[r], list(range(harness.p)), timeout=60))
        _assert_rank_consistent(outs)
        oracle = adasum_reference(data)
        np.testing.assert_allclose(
            np.asarray(outs[0], np.float64),
            np.asarray(oracle, np.float64), rtol=5e-3, atol=5e-3)
        sent = [plane.bytes_sent() - b
                for plane, b in zip(harness.planes, base)]
        # halves + gather in fp32: ~2x the vector's 4N bytes per rank
        # plus scalar rounds — an f64-wire plane would move ~2x more
        for moved in sent:
            assert moved < 3.0 * data[0].nbytes, sent
    finally:
        harness.close()


# ===================================================================
# ISSUE 12 schedule matrix: the hierarchical and rhd schedules on the
# same transport rig — parity vs the seed ring / float64 oracle,
# bitwise rank consistency, odd worlds and mixed groups, the
# mid-collective fault cell, and digest-identical elastic re-planning.
# ===================================================================
def _sched_allreduce(harness, schedule, data, groups=None, **kw):
    """One allreduce round through the named data-plane schedule."""
    harness._ring_id += 1
    rid = harness._ring_id
    ranks = list(range(harness.p))
    kw.setdefault("op_average", False)
    if schedule == "hierarchical":
        return harness.run_all(
            lambda r: harness.planes[r].allreduce_hierarchical(
                rid, data[r], ranks, groups, world_size=harness.p,
                timeout=60, **kw))
    assert schedule == "rhd"
    return harness.run_all(lambda r: harness.planes[r].allreduce_rhd(
        rid, data[r], ranks, world_size=harness.p, timeout=60, **kw))


@pytest.mark.parametrize("schedule", ["hierarchical", "rhd"])
def test_schedule_dtype_compression_parity_matrix(schedule):
    """schedule x dtype x compression cells in a non-power-of-two world
    (p=5, mixed groups [3, 2]): exact legs must match the seed ring
    within the dtype's wire tolerance (int32 exactly), compressed legs
    the float64 oracle within the codec bound, and every cell must be
    bitwise identical across ranks — the invariant that makes a
    schedule safe to swap under a running model."""
    import ml_dtypes

    harness = _PipelinedHarness(5, segment_bytes=8192, stripes=2)
    groups = [[0, 1, 2], [3, 4]]
    try:
        for size in (500, 20001):
            fdata = [np.random.RandomState(31 * size + r).randn(size)
                     for r in range(harness.p)]
            exact = np.sum(np.stack(fdata), 0)

            # ---- exact legs: parity against the seed ring ------------
            for dtype, rtol, atol in [
                    (np.float32, 1e-4, 1e-3),
                    (ml_dtypes.bfloat16, 1e-1, 0.5),
                    (np.float16, 3e-2, 0.2)]:
                data = [d.astype(dtype) for d in fdata]
                outs = _sched_allreduce(harness, schedule, data,
                                        groups=groups)
                ref = harness.allreduce(data, seed=True)
                _assert_rank_consistent(outs)
                assert outs[0].dtype == np.dtype(dtype)
                np.testing.assert_allclose(
                    np.asarray(outs[0], np.float64),
                    np.asarray(ref[0], np.float64),
                    rtol=rtol, atol=atol,
                    err_msg=f"{schedule} {np.dtype(dtype).name} "
                            f"size={size}")

            # int32: modular wire arithmetic stays EXACT vs seed
            idata = [(np.arange(size) * (r + 1) - size // 2).astype(
                np.int32) for r in range(harness.p)]
            outs = _sched_allreduce(harness, schedule, idata,
                                    groups=groups)
            ref = harness.allreduce(idata, seed=True)
            _assert_rank_consistent(outs)
            assert np.array_equal(outs[0], ref[0]), \
                f"{schedule} int32 size={size}"

            # ---- compressed legs (fp32 input) ------------------------
            # rhd accepts the knob but wires native fp32 (latency
            # regime), so its "compressed" cells are exact; the
            # hierarchical cells compose the codec across all 4 phases.
            data = [d.astype(np.float32) for d in fdata]
            for comp in ("int8", "bf16"):
                outs = _sched_allreduce(harness, schedule, data,
                                        groups=groups, compression=comp)
                _assert_rank_consistent(outs)
                tol = 0.8 if comp == "int8" else 0.4
                if schedule == "rhd":
                    tol = 1e-3
                assert np.abs(
                    np.asarray(outs[0], np.float64) - exact
                ).max() < tol, f"{schedule} {comp} size={size}"
    finally:
        harness.close()


@pytest.mark.parametrize("p,groups", [
    (3, [[0, 1], [2]]),               # odd world, singleton group
    (5, [[0, 1], [2, 3], [4]]),       # odd world, HIER_LOCAL_SIZE=2 tail
    (6, [[0, 1, 2], [3, 4, 5]]),      # even split of a non-power-of-two
    (6, [[0, 1, 2, 3], [4, 5]]),      # mixed 4+2 grouping
])
def test_schedule_odd_worlds_match_seed(p, groups):
    """Non-power-of-two worlds and odd/mixed group shapes: both new
    schedules (hierarchical over the given groups, rhd with its
    fold-in extras) must match the seed ring and stay rank-consistent
    — the shapes an elastic reconfiguration leaves behind."""
    harness = _PipelinedHarness(p, segment_bytes=8192, stripes=2)
    try:
        for size in (997, 20001):
            data = [np.random.RandomState(7 * size + r).randn(size)
                    .astype(np.float32) for r in range(p)]
            ref = harness.allreduce(data, seed=True)
            for schedule in ("hierarchical", "rhd"):
                outs = _sched_allreduce(harness, schedule, data,
                                        groups=groups)
                _assert_rank_consistent(outs)
                np.testing.assert_allclose(
                    np.asarray(outs[0], np.float64),
                    np.asarray(ref[0], np.float64),
                    rtol=1e-4, atol=1e-3,
                    err_msg=f"{schedule} p={p} size={size}")
    finally:
        harness.close()


def test_hierarchical_average_prescale_postscale():
    """The op/scale surface composes with the two-level plan: average
    divides the wide total once, pre/postscale apply at the ends, all
    rank-consistently (the widened-wire rule the flat ring follows)."""
    harness = _PipelinedHarness(4, segment_bytes=4096, stripes=2)
    groups = [[0, 1], [2, 3]]
    try:
        data = [np.random.RandomState(60 + r).randn(4001).astype(
            np.float32) for r in range(4)]
        exact = np.sum(np.stack([d.astype(np.float64) for d in data]), 0)
        outs = _sched_allreduce(harness, "hierarchical", data,
                                groups=groups, op_average=True)
        _assert_rank_consistent(outs)
        np.testing.assert_allclose(np.asarray(outs[0], np.float64),
                                   exact / 4, rtol=1e-4, atol=1e-4)
        outs = _sched_allreduce(harness, "hierarchical", data,
                                groups=groups, prescale=0.5,
                                postscale=2.0)
        _assert_rank_consistent(outs)
        np.testing.assert_allclose(np.asarray(outs[0], np.float64),
                                   exact, rtol=1e-4, atol=1e-4)
        outs = _sched_allreduce(harness, "rhd", data, op_average=True)
        _assert_rank_consistent(outs)
        np.testing.assert_allclose(np.asarray(outs[0], np.float64),
                                   exact / 4, rtol=1e-4, atol=1e-4)
    finally:
        harness.close()


def test_replan_groups_digest_identical_across_reconfig(monkeypatch):
    """Elastic acceptance: group planning is a pure function of the
    live membership (+ env override) — repeated plans and plans from
    differently-ordered membership produce digest-identical groupings,
    so every survivor of a reconfiguration executes the same plan the
    coordinator stamped."""
    import hashlib
    import json as _json

    from horovod_tpu.ops import tcp_controller

    co = object.__new__(tcp_controller.CoordinatorService)
    co._host_of = {r: f"host{r // 4}" for r in range(8)}

    def digest(groups):
        return hashlib.sha256(
            _json.dumps(groups, sort_keys=True).encode()).hexdigest()

    monkeypatch.delenv("HVD_HIER_LOCAL_SIZE", raising=False)
    full = [co._plan_groups(range(8)) for _ in range(3)]
    assert full[0] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert len({digest(g) for g in full}) == 1

    # rank 5 lost: the re-plan from surviving membership is itself
    # deterministic and keeps the host partition
    survivors = [r for r in range(8) if r != 5]
    replans = [co._plan_groups(survivors) for _ in range(3)]
    assert replans[0] == [[0, 1, 2, 3], [4, 6, 7]]
    assert len({digest(g) for g in replans}) == 1
    # membership order must not matter
    assert co._plan_groups(reversed(survivors)) == replans[0]

    # the explicit local-size override chunks the sorted membership,
    # same determinism contract
    monkeypatch.setenv("HVD_HIER_LOCAL_SIZE", "3")
    chunked = [co._plan_groups(survivors) for _ in range(3)]
    assert chunked[0] == [[0, 1, 2], [3, 4, 6], [7]]
    assert len({digest(g) for g in chunked}) == 1

    # degenerate topologies yield no two-level plan (stay flat)
    monkeypatch.delenv("HVD_HIER_LOCAL_SIZE", raising=False)
    co._host_of = {r: f"h{r}" for r in range(4)}   # one rank per host
    assert co._plan_groups(range(4)) is None
    co._host_of = {r: "h0" for r in range(4)}      # all one host
    assert co._plan_groups(range(4)) is None
    co._host_of = {}                               # unknown topology
    assert co._plan_groups(range(4)) is None


HIER_FAULT_WORKER = r"""
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
t = jnp.ones((70000,)) * (r + 1)
start = time.monotonic()
try:
    hvd.allreduce(t, op=hvd.Sum, name="hier.ft")
    print(f"rank {r} COMPLETED", flush=True)
except hvd.HvdAbortedError as exc:
    elapsed = time.monotonic() - start
    from horovod_tpu.common import basics
    svc = basics._get_state().controller._peer_service
    leaked = len(svc._mailbox) if svc is not None else 0
    print(f"rank {r} ABORTED origin={exc.origin_rank} "
          f"elapsed={elapsed:.1f} leaked={leaked}", flush=True)
print(f"rank {r} DONE", flush=True)
"""


def test_hierarchical_crash_mid_collective_aborts_all_ranks():
    """ISSUE 12 fault cell: rank 2 dies AFTER the coordinator stamped a
    hierarchical ring_go — peers in BOTH groups are committed (blocked
    in phase recvs / on the delegate ring).  Liveness converts the
    silence into one coordinated abort: every survivor wakes with the
    typed error naming origin=2, well inside the deadline, mailbox
    clean."""
    results = spawn_tcp_ranks(4, HIER_FAULT_WORKER, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HVD_TPU_SCHEDULE": "hierarchical",
        "HVD_HIER_LOCAL_SIZE": "2",
        "HVD_TCP_RING_THRESHOLD": "1024",
        "HVD_TPU_HEARTBEAT_INTERVAL": "0.25",
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_TPU_ABORT_TIMEOUT": "10",
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        # keep the ring recv timeout far beyond liveness so the typed
        # abort, not a local TimeoutError, wakes the blocked phases
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TPU_FAULT_SPEC": "rank2:ring:1:crash",
    })
    assert results[2][0] == 1, f"crashed rank: {results[2][1]}"
    for r in (0, 1, 3):
        code, out, err = results[r]
        assert code == 0, f"rank {r}: {out}\n{err[-2000:]}"
        line = next(l for l in out.splitlines()
                    if l.startswith(f"rank {r} ABORTED"))
        fields = dict(kv.split("=") for kv in line.split()[3:])
        assert fields["origin"] == "2", line
        assert float(fields["elapsed"]) < 10.0, line
        assert fields["leaked"] == "0", line


def test_resolve_schedule_bands_and_fallbacks(monkeypatch):
    """The coordinator's auto resolution: rhd owns the [8KB, 256KB]
    latency band (below it the star's single fused round-trip wins),
    hierarchical needs a viable grouping, disagreeing requests fall
    back to auto instead of fusing, and forced-but-infeasible choices
    degrade to the flat ring."""
    from types import SimpleNamespace

    from horovod_tpu.ops import tcp_controller
    from horovod_tpu.ops.tcp_dataplane import (DEFAULT_RHD_MAX_BYTES,
                                               DEFAULT_RHD_MIN_BYTES)

    monkeypatch.delenv("HVD_HIER_LOCAL_SIZE", raising=False)
    co = object.__new__(tcp_controller.CoordinatorService)
    co._published = None
    co._host_of = {r: f"h{r // 2}" for r in range(4)}

    def resolve(nbytes, scheds=("auto",) * 4):
        reqs = {i: SimpleNamespace(schedule=s)
                for i, s in enumerate(scheds)}
        return co._resolve_schedule(reqs, list(range(4)), nbytes)

    # auto: the rhd band has a floor AND a ceiling (both inclusive)
    assert resolve(DEFAULT_RHD_MIN_BYTES)[0] == "rhd"
    assert resolve(DEFAULT_RHD_MAX_BYTES)[0] == "rhd"
    sched, groups = resolve(DEFAULT_RHD_MIN_BYTES - 1)
    assert (sched, groups) == ("hierarchical", [[0, 1], [2, 3]])
    assert resolve(DEFAULT_RHD_MAX_BYTES + 1)[0] == "hierarchical"
    # rhd carries no groups
    assert resolve(DEFAULT_RHD_MIN_BYTES)[1] is None
    # forced hierarchical keeps its groups whatever the size
    sched, groups = resolve(1 << 10, scheds=("hierarchical",) * 4)
    assert (sched, groups) == ("hierarchical", [[0, 1], [2, 3]])
    # disagreeing requests fall back to auto resolution, never fuse a
    # mixed plan (here: large payload + topology -> hierarchical)
    assert resolve(1 << 20, scheds=("rhd", "flat_ring", "auto", "auto")
                   )[0] == "hierarchical"

    # no topology: everything outside the band is the flat ring, and a
    # forced hierarchical degrades to it
    co._host_of = {}
    assert resolve(DEFAULT_RHD_MAX_BYTES + 1)[0] == "flat_ring"
    assert resolve(1 << 10)[0] == "flat_ring"
    assert resolve(1 << 20, scheds=("hierarchical",) * 4
                   )[0] == "flat_ring"
    # "star" reaching a ring round (tuned-value propagation race) runs
    # the flat ring rather than desyncing
    assert resolve(1 << 20, scheds=("star",) * 4)[0] == "flat_ring"
