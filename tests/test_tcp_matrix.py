"""Process-mode (tcp) dtype matrix + stall/fusion/join combination tests
(reference: the dtype x device sweep of ``test/test_torch.py`` run under
``horovodrun --gloo``, and ``test_stall.py`` driven purely by env vars).

The numpy data plane keeps 64-bit types exact here (the device-rank
matrix in ``test_dtype_matrix.py`` covers the XLA-native types)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HVDRUN = os.path.join(REPO, "bin", "hvdrun")


def _run_hvdrun(np_, script, extra_env=None, timeout=600):
    path = "/tmp/hvd_tcp_matrix_worker.py"
    with open(path, "w") as f:
        f.write(script)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, HVDRUN, "-np", str(np_), sys.executable, path]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


DTYPE_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r, n = hvd.rank(), hvd.size()

ALL = ["float16", "bfloat16", "float32", "float64",
       "int8", "int16", "int32", "int64", "uint8", "uint16",
       "uint32", "uint64"]

# -- allreduce sum, every dtype, star plane (exact accumulation) ---------
for dtype in ALL:
    # cast AFTER scaling: numpy promotes bf16*int to float32
    data = ((np.arange(6) + 1) * (r + 1)).astype(dtype)
    out = np.asarray(hvd.allreduce(data, op=hvd.Sum,
                                   name=f"sum.{dtype}"))
    assert str(out.dtype) == dtype, (out.dtype, dtype)
    expect = (np.arange(6) + 1).astype(np.float64) * sum(
        range(1, n + 1))
    np.testing.assert_allclose(out.astype(np.float64), expect,
                               rtol=2e-2 if "16" in dtype else 1e-9)

# int64 exactness beyond float64's 2**53 (the star plane accumulates
# integers in int64, never through floats)
big = np.array([2**60 + r], dtype=np.int64)
out = np.asarray(hvd.allreduce(big, op=hvd.Sum, name="i64exact"))
assert int(out[0]) == sum(2**60 + i for i in range(n)), int(out[0])

# -- broadcast every dtype ------------------------------------------------
for dtype in ALL:
    data = (np.arange(4) * (r + 2)).astype(dtype)
    out = np.asarray(hvd.broadcast(data, root_rank=1,
                                   name=f"bc.{dtype}"))
    np.testing.assert_allclose(
        out.astype(np.float64),
        (np.arange(4) * 3).astype(dtype).astype(np.float64))

# -- allgather with variable dims, 64-bit types ---------------------------
for dtype in ["float64", "int64", "uint32"]:
    data = np.full((r + 1, 2), r + 1).astype(dtype)
    out = np.asarray(hvd.allgather(data, name=f"ag.{dtype}"))
    expect = np.concatenate(
        [np.full((i + 1, 2), i + 1) for i in range(n)]).astype(np.float64)
    np.testing.assert_allclose(out.astype(np.float64), expect)

# -- alltoall int64 -------------------------------------------------------
t = (np.arange(2 * n) + 100 * r).astype(np.int64)
out = np.asarray(hvd.alltoall(t, name="a2a.i64"))
expect = np.concatenate(
    [np.arange(2 * r, 2 * r + 2) + 100 * src for src in range(n)])
np.testing.assert_allclose(out, expect)

# -- ring plane sweep (threshold forced to 1KB) ---------------------------
for dtype in ["float32", "float64", "int64"]:
    data = np.full((70001,), 3).astype(dtype) * (r + 1)
    out = np.asarray(hvd.allreduce(data, op=hvd.Sum,
                                   name=f"ring.{dtype}"))
    assert str(out.dtype) == dtype
    np.testing.assert_allclose(
        out.astype(np.float64),
        np.full((70001,), 3 * sum(range(1, n + 1)), np.float64))

# -- 0-d scalars over the wire -------------------------------------------
out = hvd.allreduce(np.float64(1.5), op=hvd.Sum, name="sc64")
assert np.asarray(out).ndim == 0
assert float(np.asarray(out)) == 1.5 * n

print(f"rank {r} TCP_DTYPES_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_dtype_matrix_2proc():
    result = _run_hvdrun(2, DTYPE_WORKER,
                         extra_env={"HVD_TCP_RING_THRESHOLD": "1024"})
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("TCP_DTYPES_OK") == 2


STALL_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.handles import HvdError

hvd.init()
r, n = hvd.rank(), hvd.size()
assert n == 4

# fusion-heavy traffic while rank 3 goes silent (neither submitting nor
# joining — a join would legitimately complete the collective with zero
# stand-ins): the stalled name must fail via stall shutdown WITHOUT
# poisoning the healthy collectives or the later join barrier
# (reference: StallInspector shutdown + Join interplay).
import time
handles = {}
for i in range(6):
    handles[i] = hvd.allreduce_async(jnp.ones((8,)) * (r + 1),
                                     op=hvd.Sum, name=f"ok{i}")
for i, h in handles.items():
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               np.full((8,), 10.0))

if r != 3:
    try:
        hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="stalled")
        raise SystemExit("expected stall shutdown error")
    except HvdError as exc:
        assert "stalled" in str(exc), str(exc)
else:
    time.sleep(8)  # silent through the 4s stall-shutdown window

last = hvd.join()
assert last in range(4)
print(f"rank {r} STALL_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_stall_shutdown_with_fusion_and_join_4proc():
    result = _run_hvdrun(4, STALL_WORKER, extra_env={
        "HVD_STALL_CHECK_TIME_SECONDS": "1",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "4",
    }, timeout=420)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("STALL_OK") == 4
    assert "Stalled tensor" in (result.stdout + result.stderr)


GROUPED_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r, n = hvd.rank(), hvd.size()

# grouped allreduce with mixed dtypes and mixed planes (some above the
# 1KB ring threshold, some below)
tensors = [
    jnp.ones((4,), jnp.float32) * (r + 1),
    jnp.ones((70000,), jnp.float32) * (r + 1),
    jnp.ones((8,), jnp.int32) * (r + 1),
    jnp.ones((70000,), jnp.float64) * (r + 1),
]
outs = hvd.grouped_allreduce(tensors, op=hvd.Sum, name="grp")
for t, out in zip(tensors, outs):
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float64),
        np.full(t.shape, float(sum(range(1, n + 1)))))

print(f"rank {r} GROUPED_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_grouped_mixed_planes_4proc():
    result = _run_hvdrun(4, GROUPED_WORKER,
                         extra_env={"HVD_TCP_RING_THRESHOLD": "1024"})
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("GROUPED_OK") == 4


JOINED_RANK_WORKER = r"""
import time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
assert hvd.size() == 3

if r == 0:
    # submit, then join while rank 2 hasn't contributed yet: the
    # collective must WAIT for rank 2, not complete without it
    h = hvd.allreduce_async(jnp.full((4,), 1.0), op=hvd.Sum, name="t")
    last = hvd.join()
    out = np.asarray(hvd.synchronize(h))
elif r == 1:
    out = np.asarray(hvd.allreduce(jnp.full((4,), 2.0), op=hvd.Sum,
                                   name="t"))
    last = hvd.join()
else:
    time.sleep(1.5)  # rank 0 has joined well before this submission
    out = np.asarray(hvd.allreduce(jnp.full((4,), 4.0), op=hvd.Sum,
                                   name="t"))
    last = hvd.join()

# every contribution must be in the sum, including the joined rank 0's
np.testing.assert_allclose(out, np.full((4,), 7.0), err_msg=str(out))
print(f"rank {r} JOINED_COUNT_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_joined_rank_does_not_satisfy_live_rank():
    """Regression: the coordinator counted a since-joined rank's request
    toward completion, finishing a collective without a live rank's
    contribution (silent wrong sum)."""
    result = _run_hvdrun(3, JOINED_RANK_WORKER, timeout=300)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr}"
    assert result.stdout.count("JOINED_COUNT_OK") == 3


ERROR_SWEEP_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common.handles import HvdError

hvd.init()
r, n = hvd.rank(), hvd.size()

# per-op cross-rank mismatch sweep over the tcp coordinator
# (reference: the error-path coverage test_torch.py runs per backend)
cases = [
    # (submit, error fragment)
    (lambda: hvd.allreduce(np.ones(2 + r % 2, np.float32), op=hvd.Sum,
                           name="e.shape"), "shape"),
    (lambda: hvd.allreduce(
        np.ones(3, np.float32 if r % 2 == 0 else np.int32), op=hvd.Sum,
        name="e.dtype"), "dtype"),
    (lambda: hvd.allreduce(np.ones(3, np.float32),
                           op=hvd.Sum if r % 2 == 0 else hvd.Average,
                           name="e.op"), "op"),
    (lambda: (hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum,
                            name="e.type") if r % 2 == 0 else
              hvd.broadcast(np.ones(3, np.float32), root_rank=0,
                            name="e.type")), "type"),
    (lambda: hvd.broadcast(np.ones(3, np.float32), root_rank=r % 2,
                           name="e.root"), "root"),
    (lambda: hvd.allgather(
        np.ones((2, 3 + r % 2), np.float32), name="e.trail"),
     "trailing"),
    (lambda: hvd.alltoall(np.ones((4, 2), np.float32),
                          splits=[2] * n, name="e.split"), "split"),
]
for submit, frag in cases:
    try:
        submit()
        raise SystemExit(f"expected HvdError for {frag}")
    except HvdError as exc:
        assert frag in str(exc).lower(), (frag, str(exc))

# every poisoned name recovers (error responses clear the entry)
out = np.asarray(hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum,
                               name="e.shape"))
np.testing.assert_allclose(out, np.full(3, float(n)))

# torch binding over the SAME tcp plane (reference: horovodrun --gloo
# pytest test_torch.py)
import torch
import horovod_tpu.torch as hvd_t
h = hvd_t.grouped_allreduce_async(
    [torch.ones(4) * (r + 1), torch.ones(2) * 10 * (r + 1)],
    op=hvd_t.Sum, name="e.tg")
outs = hvd_t.synchronize(h)
total = float(sum(range(1, n + 1)))
assert torch.allclose(outs[0], torch.full((4,), total))
assert torch.allclose(outs[1], torch.full((2,), 10 * total))
try:
    hvd_t.allreduce(torch.ones(2 + r % 2), op=hvd_t.Sum, name="e.tshape")
    raise SystemExit("expected HvdError (torch over tcp)")
except HvdError as exc:
    assert "shape" in str(exc).lower()

print(f"rank {r} TCP_ERRORS_OK", flush=True)
hvd.shutdown()
"""


def test_tcp_error_sweep_and_torch_binding_4proc():
    """Cross-rank mismatch sweep per op over the tcp coordinator, error
    recovery, and the torch binding (incl. the grouped one-handle
    contract) riding the same process-mode plane."""
    result = _run_hvdrun(4, ERROR_SWEEP_WORKER, timeout=420)
    assert result.returncode == 0, \
        f"stdout:\n{result.stdout}\nstderr:\n{result.stderr[-3000:]}"
    assert result.stdout.count("TCP_ERRORS_OK") == 4
