"""Process groups: concurrent sub-communicators (docs/groups.md).

Covers the handle/grid API, cross-group isolation (same tensor name in
two groups and the world never fuses or cache-collides), verified
cross-group concurrency via the ``max_concurrent_groups`` high-water
mark, elastic re-forming as a pure function of (spec, members), and the
acceptance scenario: a two-stage Megatron-style model trained with
ZeRO-DP x TP x PP composed entirely from ``hvd.grid()`` groups, checked
against a replicated numpy oracle.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.common import basics
from horovod_tpu.common.handles import HvdError
from horovod_tpu import groups as groups_mod
from horovod_tpu.groups import GroupUnsatisfiableError

N = 8


def _per_rank(fn):
    return basics.run_parallel(fn)


# ================================================================ API ====
def test_group_handle_api(hvd):
    g = hvd.new_group([1, 3, 5], name="odd3")
    assert g.ranks == [1, 3, 5]
    assert g.size == 3
    assert g.rank(3) == 1 and g.rank(5) == 2
    assert g.rank(0) == -1
    assert 3 in g and 0 not in g
    assert "odd3" in repr(g)

    # identical spec => identical gid, on any thread (no communication)
    out = {}

    def mk():
        out[threading.get_ident()] = hvd.new_group([1, 3, 5], name="odd3")

    t = threading.Thread(target=mk)
    t.start()
    t.join()
    (peer,) = out.values()
    assert peer.gid == g.gid

    with pytest.raises(HvdError):
        hvd.new_group([])
    with pytest.raises(HvdError):
        hvd.new_group([0, N])            # out of range
    with pytest.raises(HvdError):
        groups_mod.resolve("not-a-group")


def test_grid_planning(hvd):
    g = hvd.grid(dp=2, tp=2, pp=2)
    assert g.axes == ("dp", "tp", "pp")
    assert g.mesh_axes() == {"dp": 2, "tp": 2, "pp": 2}
    # C-order: rank = dp*4 + tp*2 + pp, same layout as make_mesh
    for r in range(N):
        dp, tp, pp = np.unravel_index(r, (2, 2, 2))
        assert g.coords(r) == (dp, tp, pp)
        assert g.group("dp", r).ranks == [tp * 2 + pp, 4 + tp * 2 + pp]
        assert g.group("tp", r).ranks == [dp * 4 + pp, dp * 4 + 2 + pp]
        assert g.group("pp", r).ranks == [dp * 4 + tp * 2,
                                          dp * 4 + tp * 2 + 1]
    # every axis partitions the world
    for axis in g.axes:
        seen = sorted(r2 for r in range(N)
                      for r2 in g.group(axis, r).ranks)
        assert sorted(set(seen)) == list(range(N))

    with pytest.raises(HvdError):
        hvd.grid(dp=3, tp=2)             # 6 != world size 8
    with pytest.raises(HvdError):
        hvd.grid()


def test_group_max_cap(hvd, monkeypatch):
    from horovod_tpu.utils import env as env_util

    hvd.new_group([0, 1], name="cap.preexisting")
    monkeypatch.setenv(env_util.HVD_TPU_GROUP_MAX,
                       str(len(groups_mod._specs)))
    # a registered spec is returned, never re-counted against the cap
    assert hvd.new_group([0, 1], name="cap.preexisting").size == 2
    with pytest.raises(HvdError, match="HVD_TPU_GROUP_MAX"):
        hvd.new_group([0, 1], name="cap.one-too-many")


# ==================================================== isolation + flight ====
def test_disjoint_groups_isolated_and_concurrently_in_flight(hvd):
    """Two groups + the world, SAME tensor name everywhere: each scope
    reduces over exactly its members, and the coordinator's high-water
    mark proves both groups had negotiation entries open at once."""
    lo = hvd.new_group([0, 1, 2, 3], name="iso.lo")
    hi = hvd.new_group([4, 5, 6, 7], name="iso.hi")

    def fn(r):
        mine, base = (lo, 0) if r < 4 else (hi, 4)
        outs = []
        for round_ in range(3):   # round >=1 exercises the cached path
            g = np.asarray(hvd.allreduce(
                jnp.full((5,), float(r + 1)), op=hvd.Sum,
                name=f"iso.{round_}", group=mine))
            w = np.asarray(hvd.allreduce(
                jnp.full((5,), float(r + 1)), op=hvd.Sum,
                name=f"iso.{round_}"))
            outs.append((g, w))
        return outs

    for r, outs in enumerate(_per_rank(fn)):
        base = 0 if r < 4 else 4
        expect = float(sum(range(base + 1, base + 5)))
        for g, w in outs:
            np.testing.assert_allclose(g, np.full((5,), expect))
            np.testing.assert_allclose(w, np.full((5,), 36.0))

    # asserted, not assumed: two DISTINCT sub-groups in flight at once
    assert groups_mod.stats()["max_concurrent_groups"] >= 2


def test_group_collectives_all_types(hvd):
    ga = hvd.new_group([0, 2, 4, 6], name="even4")

    def fn(r):
        if r % 2:
            return None
        i = r // 2   # group-local rank
        out = {}
        out["avg"] = np.asarray(hvd.allreduce(
            jnp.full((4,), float(r)), name="g.avg", group=ga))
        out["bc"] = np.asarray(hvd.broadcast(
            jnp.full((3,), float(r)), root_rank=6, name="g.bc", group=ga))
        out["ag"] = np.asarray(hvd.allgather(
            jnp.full((i + 1, 2), float(r)), name="g.ag", group=ga))
        out["ga"] = [np.asarray(t) for t in hvd.grouped_allgather(
            [jnp.full((2,), float(r)), jnp.full((1, 3), float(-r))],
            name="g.gag", group=ga)]
        t = jnp.arange(4, dtype=jnp.float32) + 100 * r
        out["a2a"] = np.asarray(hvd.alltoall(t, name="g.a2a", group=ga))
        out["rs"] = np.asarray(hvd.reduce_scatter(
            jnp.arange(8, dtype=jnp.float32) * (i + 1), op=hvd.Sum,
            name="g.rs", group=ga))
        hvd.barrier(group=ga, name="g.bar")
        return out

    members = [0, 2, 4, 6]
    for r, out in enumerate(_per_rank(fn)):
        if r % 2:
            assert out is None
            continue
        i = r // 2
        np.testing.assert_allclose(out["avg"], np.full((4,), 3.0))
        np.testing.assert_allclose(out["bc"], np.full((3,), 6.0))
        np.testing.assert_allclose(out["ag"], np.concatenate(
            [np.full((j + 1, 2), float(m))
             for j, m in enumerate(members)]))
        np.testing.assert_allclose(out["ga"][0], np.concatenate(
            [np.full((2,), float(m)) for m in members]))
        np.testing.assert_allclose(out["ga"][1], np.concatenate(
            [np.full((1, 3), float(-m)) for m in members]))
        np.testing.assert_allclose(out["a2a"], np.concatenate(
            [np.arange(1, dtype=np.float32) + i + 100 * m
             for m in members]))
        full = np.arange(8, dtype=np.float32) * sum(
            j + 1 for j in range(4))
        np.testing.assert_allclose(out["rs"], np.array_split(full, 4)[i])


def test_group_joins_fusion_bucket_key(hvd):
    """Never-fuse rule: the group id is part of the fusion bucket key,
    so two groups' (or a group's and the world's) small allreduces can
    never land in one fused buffer."""
    from horovod_tpu.ops.python_controller import PythonController

    base = dict(dtype="float32", op=1, prescale=1.0, postscale=1.0)
    world = PythonController.allreduce_bucket_key(**base)
    ga = PythonController.allreduce_bucket_key(**base, group="aaaa")
    gb = PythonController.allreduce_bucket_key(**base, group="bbbb")
    assert len({world, ga, gb}) == 3


# ============================================================== elastic ====
def test_reform_is_a_pure_function_of_members(hvd):
    """reform(members): explicit groups re-map their recorded worker
    ids (missing => sticky typed error), grids re-plan the same shape —
    and re-forming with the original membership restores everything."""
    exp = hvd.new_group([1, 2], name="reform.explicit")
    grd = hvd.grid(dp=4, tp=2)
    tp0 = grd.group("tp", 0)
    orig = basics.members()
    assert exp.ranks == [1, 2]

    try:
        # worker 0 departs; 7 survivors (grid 4x2 no longer fits)
        survivors = [w for w in orig if w != orig[0]]
        groups_mod.reform(survivors)
        assert exp.ranks == [0, 1]   # same workers, re-mapped ranks
        with pytest.raises(GroupUnsatisfiableError):
            tp0.ranks

        # worker 1 departs instead: the explicit group dies typed...
        groups_mod.reform([w for w in orig if w != orig[1]])
        with pytest.raises(GroupUnsatisfiableError) as ei:
            exp.ranks
        assert ei.value.missing == (orig[1],)
        with pytest.raises(GroupUnsatisfiableError):
            groups_mod.resolve(exp)

        # ...and an 8-member membership in a NEW order re-plans the grid
        rotated = orig[1:] + orig[:1]
        groups_mod.reform(rotated)
        assert tp0.size == 2
    finally:
        groups_mod.reform(orig)
    assert exp.ranks == [1, 2]
    assert tp0.ranks == [0, 1]


# =================================================== 3D acceptance run ====
_LR = 0.1
_D = 8      # model width (== hidden, so stages chain)
_B = 4      # per-replica batch
_STEPS = 3


def _block_params(stage, tp):
    """Stage ``stage``'s weights, column/row-split for tp shard ``tp``
    (Megatron style): A (D, D/2) column shard, B (D/2, D) row shard.
    Seeded by (stage, tp) only, so dp replicas start identical."""
    rs = np.random.RandomState(17 + 5 * stage + tp)
    return {
        "A": jnp.asarray(rs.randn(_D, _D // 2).astype(np.float32) * 0.3),
        "B": jnp.asarray(rs.randn(_D // 2, _D).astype(np.float32) * 0.3),
    }


def _batch(dp, step):
    rs = np.random.RandomState(101 + 10 * dp + step)
    return (rs.randn(_B, _D).astype(np.float32),
            rs.randn(_B, _D).astype(np.float32))


def _oracle_3d():
    """Replicated numpy reference: full (unsharded) two-stage model,
    gradients averaged over the dp replicas, plain SGD."""
    full = []
    for s in range(2):
        shards = [_block_params(s, t) for t in range(2)]
        full.append({
            "A": np.concatenate([np.asarray(p["A"]) for p in shards], 1),
            "B": np.concatenate([np.asarray(p["B"]) for p in shards], 0),
        })
    losses = []
    for step in range(_STEPS):
        grads = [{"A": 0.0, "B": 0.0} for _ in range(2)]
        step_losses = []
        for dp in range(2):
            x, target = _batch(dp, step)
            h0 = np.tanh(x @ full[0]["A"])
            y0 = h0 @ full[0]["B"]
            h1 = np.tanh(y0 @ full[1]["A"])
            y1 = h1 @ full[1]["B"]
            step_losses.append(float(np.mean((y1 - target) ** 2)))
            dy1 = 2.0 * (y1 - target) / y1.size
            grads[1]["B"] += h1.T @ dy1
            dpre1 = (dy1 @ full[1]["B"].T) * (1 - h1 ** 2)
            grads[1]["A"] += y0.T @ dpre1
            dy0 = dpre1 @ full[1]["A"].T
            grads[0]["B"] += h0.T @ dy0
            dpre0 = (dy0 @ full[0]["B"].T) * (1 - h0 ** 2)
            grads[0]["A"] += x.T @ dpre0
        for s in range(2):
            for k in ("A", "B"):
                full[s][k] = full[s][k] - _LR * grads[s][k] / 2.0
        losses.append(step_losses)
    return full, losses


def test_zero_dp_tp_pp_transformer_blocks_train(hvd):
    """The ISSUE's acceptance scenario: ZeRO-DP x TP x PP composed from
    one ``hvd.grid(dp=2, tp=2, pp=2)``.  Each rank owns ONE pipeline
    stage's ONE tensor shard; tp partial sums allreduce in the tp
    group, activations/grad-activations cross stages by pp-group
    broadcast, and ZeRO shards optimizer state over the dp group.  The
    result must match the replicated full-model oracle, and the
    controller must have had >= 2 distinct groups in flight at once."""
    grd = hvd.grid(dp=2, tp=2, pp=2)
    oracle, oracle_losses = _oracle_3d()

    def fn(r):
        dp, tp, pp = grd.coords(r)
        dp_g = grd.group("dp")
        tp_g = grd.group("tp")
        pp_g = grd.group("pp")
        assert dp_g.rank() == dp and tp_g.rank() == tp \
            and pp_g.rank() == pp
        peer = {m for m in pp_g.ranks if m != r}.pop()

        params = _block_params(pp, tp)
        opt = hvd.ZeroDistributedOptimizer(optax.sgd(_LR), min_size=1,
                                           group=dp_g)
        st = opt.init(params)
        losses = []
        for step in range(_STEPS):
            x, target = _batch(dp, step)
            tag = f"p3d.{step}"
            if pp == 0:
                h = jnp.tanh(jnp.asarray(x) @ params["A"])
                y0 = np.asarray(hvd.allreduce(
                    h @ params["B"], op=hvd.Sum, name=f"{tag}.fwd",
                    group=tp_g))
                # hand y0 to the stage-1 peer
                hvd.broadcast(jnp.asarray(y0), root_rank=r,
                              name=f"{tag}.act", group=pp_g)
                dy = np.asarray(hvd.broadcast(
                    jnp.zeros((_B, _D), jnp.float32), root_rank=peer,
                    name=f"{tag}.gact", group=pp_g))
                x_in = jnp.asarray(x)
            else:
                y0 = np.asarray(hvd.broadcast(
                    jnp.zeros((_B, _D), jnp.float32), root_rank=peer,
                    name=f"{tag}.act", group=pp_g))
                x_in = jnp.asarray(y0)
                h = jnp.tanh(x_in @ params["A"])
                y1 = np.asarray(hvd.allreduce(
                    h @ params["B"], op=hvd.Sum, name=f"{tag}.fwd",
                    group=tp_g))
                losses.append(float(np.mean((y1 - target) ** 2)))
                dy = 2.0 * (y1 - target) / y1.size

            # local backward for this stage's shard; dx needs the tp sum
            dy = jnp.asarray(dy)
            gB = h.T @ dy
            dpre = (dy @ params["B"].T) * (1 - h ** 2)
            gA = x_in.T @ dpre
            if pp == 1:
                dx = np.asarray(hvd.allreduce(
                    dpre @ params["A"].T, op=hvd.Sum, name=f"{tag}.bwd",
                    group=tp_g))
                hvd.broadcast(jnp.asarray(dx), root_rank=r,
                              name=f"{tag}.gact", group=pp_g)

            # ZeRO over the dp group: reduce_scatter(Average) + shard
            # update + allgather — exactly the oracle's sum/2 step
            grads = {"A": gA, "B": gB}
            u, st = opt.update(grads, st, params)
            params = optax.apply_updates(params, u)
        return {"dp": dp, "tp": tp, "pp": pp, "losses": losses,
                "A": np.asarray(params["A"]),
                "B": np.asarray(params["B"])}

    results = _per_rank(fn)
    for out in results:
        s, t = out["pp"], out["tp"]
        np.testing.assert_allclose(
            out["A"], oracle[s]["A"][:, t * 4:(t + 1) * 4],
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            out["B"], oracle[s]["B"][t * 4:(t + 1) * 4, :],
            rtol=1e-5, atol=1e-6)
        if out["pp"] == 1:
            np.testing.assert_allclose(
                out["losses"],
                [ls[out["dp"]] for ls in oracle_losses], rtol=1e-5)
            # training moved: replicated oracle loss strictly improves
            mean0 = np.mean(oracle_losses[0])
            meanN = np.mean(oracle_losses[-1])
            assert meanN < mean0
    # dp replicas of the same (tp, pp) cell ended bitwise identical
    by_cell = {}
    for out in results:
        by_cell.setdefault((out["tp"], out["pp"]), []).append(out)
    for cell, outs in by_cell.items():
        assert len(outs) == 2
        assert outs[0]["A"].tobytes() == outs[1]["A"].tobytes(), cell
        assert outs[0]["B"].tobytes() == outs[1]["B"].tobytes(), cell

    # collectives from >= 2 distinct groups verifiably in flight at once
    assert groups_mod.stats()["max_concurrent_groups"] >= 2
