"""Compression numerics matrix (ISSUE 1): int8 round-trip error bounds
vs block size, XLA-fused vs TCP-ring parity on the same payloads,
bucket-key separation (compressed and uncompressed requests must not
fuse), non-float passthrough, and the SPMD optimizer paths.

Error-bound convention ("block-scaled bound"): a block-scaled int8
allreduce of p contributions passes each element through at most p + 1
quantizations (p contribution encodes + 1 result encode), each bounded
by blockmax/254, so the max absolute error is checked against 1e-2 of
the exact result's max magnitude.
"""

import threading

import numpy as np
import jax.numpy as jnp
import pytest

from horovod_tpu.common import basics
from horovod_tpu.common.compression import (Compression, INT8_BLOCK,
                                            dequantize_int8_blocks,
                                            quantize_int8_blocks,
                                            resolve_compression)

N = 8


def _per_rank(fn):
    return basics.run_parallel(fn)


def _assert_block_bound(approx, exact, rel=1e-2):
    scale = np.abs(exact).max()
    err = np.abs(np.asarray(approx, np.float64)
                 - np.asarray(exact, np.float64)).max()
    assert err <= rel * scale, f"max err {err} > {rel} * max|exact| {scale}"


# ---------------------------------------------------------------- round trip
@pytest.mark.parametrize("block", [64, 256, 1024])
def test_int8_roundtrip_error_bound_vs_block_size(block):
    x = jnp.asarray(np.random.RandomState(0).randn(4 * 1024)
                    .astype(np.float32))
    q, s = quantize_int8_blocks(x, block)
    back = dequantize_int8_blocks(q, s, block)
    # per-element bound: half a quantization step of the element's block
    step = np.repeat(np.asarray(s), block)
    assert np.all(np.abs(np.asarray(back) - np.asarray(x)) <= step / 2 + 1e-7)
    # scales: one fp32 per block, max-abs derived
    assert np.asarray(s).shape == (x.size // block,)


def test_int8_roundtrip_exact_on_zeros_and_uniform_blocks():
    x = jnp.zeros((INT8_BLOCK * 2,), jnp.float32)
    q, s = quantize_int8_blocks(x)
    assert np.array_equal(np.asarray(dequantize_int8_blocks(q, s)),
                          np.zeros(x.shape, np.float32))
    # a block of +/-127-step-aligned values round-trips exactly
    y = jnp.asarray(np.tile([127.0, -127.0], INT8_BLOCK)[:INT8_BLOCK * 2]
                    .astype(np.float32))
    q, s = quantize_int8_blocks(y)
    np.testing.assert_allclose(np.asarray(dequantize_int8_blocks(q, s)),
                               np.asarray(y), rtol=1e-6)


def test_resolve_compression_surface():
    assert resolve_compression(None, default="int8") == "int8"
    assert resolve_compression("BF16") == "bf16"
    assert resolve_compression(Compression.int8) == "int8"
    assert resolve_compression(Compression.none) == "none"
    with pytest.raises(ValueError):
        resolve_compression("zstd")


# ------------------------------------------------------------ XLA fused plane
def test_int8_allreduce_xla_fused_sum_and_average(hvd):
    size = 1 << 14
    data = [np.random.RandomState(r).randn(size).astype(np.float32)
            for r in range(N)]
    exact = np.sum(np.stack(data, 0), 0)

    def fn(r):
        s = hvd.allreduce(jnp.asarray(data[r]), op=hvd.Sum,
                          name="int8.sum", compression="int8")
        a = hvd.allreduce(jnp.asarray(data[r]), op=hvd.Average,
                          name="int8.avg", compression=Compression.int8)
        return np.asarray(s), np.asarray(a)

    for s, a in _per_rank(fn):
        _assert_block_bound(s, exact)
        _assert_block_bound(a, exact / N)


def test_int8_allreduce_prescale_postscale(hvd):
    data = [np.random.RandomState(100 + r).randn(4096).astype(np.float32)
            for r in range(N)]
    exact = np.sum(np.stack(data, 0) * 0.5, 0) * 2.0

    def fn(r):
        return np.asarray(hvd.allreduce(
            jnp.asarray(data[r]), op=hvd.Sum, name="int8.scaled",
            prescale_factor=0.5, postscale_factor=2.0, compression="int8"))

    for out in _per_rank(fn):
        _assert_block_bound(out, exact)


def test_bf16_allreduce_xla_fused(hvd):
    data = [np.random.RandomState(10 + r).randn(4096).astype(np.float32)
            for r in range(N)]
    exact = np.sum(np.stack(data, 0), 0)

    def fn(r):
        return np.asarray(hvd.allreduce(
            jnp.asarray(data[r]), op=hvd.Sum, name="bf16.sum",
            compression="bf16"))

    for out in _per_rank(fn):
        # bf16 keeps ~8 mantissa bits: 2% of max is a generous envelope
        _assert_block_bound(out, exact, rel=2e-2)


def test_non_float_passthrough_exact(hvd):
    data = [(np.arange(512) * (r + 1)).astype(np.int32) for r in range(N)]
    exact = np.sum(np.stack(data, 0), 0)

    def fn(r):
        return np.asarray(hvd.allreduce(
            jnp.asarray(data[r]), op=hvd.Sum, name="int8.intpass",
            compression="int8"))

    for out in _per_rank(fn):
        assert np.array_equal(out, exact)


def test_tiny_tensor_passthrough_exact(hvd):
    # below one scale block the quantized path is skipped entirely
    data = [np.full((8,), r + 0.25, np.float32) for r in range(N)]
    exact = np.sum(np.stack(data, 0), 0)

    def fn(r):
        return np.asarray(hvd.allreduce(
            jnp.asarray(data[r]), op=hvd.Sum, name="int8.tiny",
            compression="int8"))

    for out in _per_rank(fn):
        np.testing.assert_allclose(out, exact, rtol=1e-6)


# -------------------------------------------------------- bucket separation
def test_bucket_key_separates_compression():
    from horovod_tpu.ops.python_controller import PythonController

    base = PythonController.allreduce_bucket_key(
        np.float32, 1, 1.0, 1.0, "none")
    comp = PythonController.allreduce_bucket_key(
        np.float32, 1, 1.0, 1.0, "int8")
    assert base != comp
    # while everything else identical still fuses
    assert base == PythonController.allreduce_bucket_key(
        np.float32, 1, 1.0, 1.0, "none")


def test_compression_resolution_unanimous_and_mixed():
    from horovod_tpu.ops.python_controller import PythonController

    assert PythonController.resolve_group_compression(
        ["int8", "int8"]) == "int8"
    # disagreement (e.g. autotune mid-publication) resolves exact
    assert PythonController.resolve_group_compression(
        ["int8", "none"]) == "none"


def test_mixed_compression_same_cycle_both_correct(hvd):
    """A compressed and an uncompressed allreduce negotiated in the same
    cycles must not fuse (different wire formats) — both complete with
    their own numerics."""
    size = 2048
    data = [np.random.RandomState(30 + r).randn(size).astype(np.float32)
            for r in range(N)]
    exact = np.sum(np.stack(data, 0), 0)

    def fn(r):
        h1 = hvd.allreduce_async(jnp.asarray(data[r]), op=hvd.Sum,
                                 name="mix.q", compression="int8")
        h2 = hvd.allreduce_async(jnp.asarray(data[r]), op=hvd.Sum,
                                 name="mix.exact", compression="none")
        return np.asarray(hvd.synchronize(h1)), \
            np.asarray(hvd.synchronize(h2))

    for q, e in _per_rank(fn):
        _assert_block_bound(q, exact)
        np.testing.assert_allclose(e, exact, rtol=1e-5)


def test_signature_includes_compression():
    from horovod_tpu.common.ops_enum import RequestType
    from horovod_tpu.ops.python_controller import EagerRequest

    t = jnp.zeros((4,), jnp.float32)
    a = EagerRequest(rank=0, req_type=RequestType.ALLREDUCE, name="x",
                     tensor=t, handle=None, compression="none")
    b = EagerRequest(rank=0, req_type=RequestType.ALLREDUCE, name="x",
                     tensor=t, handle=None, compression="int8")
    assert a.signature() != b.signature()


# --------------------------------------------------------------- TCP ring
class _RingHarness:
    """In-process worker ring over real loopback TCP: one PeerService
    mailbox + RingPlane per rank, resolve_peer via MuxClient."""

    def __init__(self, p):
        from horovod_tpu.ops.tcp_dataplane import PeerService, RingPlane
        from horovod_tpu.run.service import network

        self.p = p
        key = b"0" * 32
        self.services = [PeerService(key) for _ in range(p)]

        def resolver(rank):
            return network.MuxClient(
                [("127.0.0.1", self.services[rank].port)], key, timeout=30)

        self.planes = [RingPlane(r, self.services[r], resolver)
                       for r in range(p)]

    def allreduce(self, ring_id, data, **kw):
        outs = [None] * self.p
        errs = []

        def run(r):
            try:
                outs[r] = self.planes[r].allreduce(
                    ring_id, data[r], list(range(self.p)),
                    world_size=self.p, timeout=60, **kw)
            except Exception as exc:  # noqa: BLE001 — surface in the test
                errs.append(exc)

        threads = [threading.Thread(target=run, args=(r,))
                   for r in range(self.p)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errs, errs
        return outs

    def close(self):
        for plane in self.planes:
            plane.close()
        for svc in self.services:
            svc.shutdown()


@pytest.fixture(scope="module")
def ring4():
    harness = _RingHarness(4)
    yield harness
    harness.close()


def test_ring_int8_allreduce_numerics(ring4):
    p = ring4.p
    data = [np.random.RandomState(r).randn(1 << 14).astype(np.float32)
            for r in range(p)]
    exact = np.sum(np.stack(data, 0), 0)
    outs = ring4.allreduce(1001, data, op_average=False, compression="int8")
    for out in outs:
        assert out.dtype == np.float32
        _assert_block_bound(out, exact)
    # rank-consistency: every rank decodes the same blobs
    for out in outs[1:]:
        assert np.array_equal(out, outs[0])


def test_ring_bf16_allreduce_numerics(ring4):
    p = ring4.p
    data = [np.random.RandomState(50 + r).randn(8192).astype(np.float32)
            for r in range(p)]
    exact = np.sum(np.stack(data, 0), 0)
    outs = ring4.allreduce(1002, data, op_average=True, compression="bf16")
    for out in outs:
        _assert_block_bound(out, exact / p, rel=2e-2)


def test_ring_int8_int_dtype_stays_exact(ring4):
    p = ring4.p
    data = [(np.arange(4096) * (r + 1)).astype(np.int64) for r in range(p)]
    exact = np.sum(np.stack(data, 0), 0)
    outs = ring4.allreduce(1003, data, op_average=False, compression="int8")
    for out in outs:
        assert np.array_equal(out, exact)


def test_ring_int8_wire_bytes_quarter(ring4):
    """Bytes-on-wire accounting at the framing layer: the int8 ring
    must ship ~1/4 of the exact ring's wire bytes (1 byte/elem + ~1.6%
    fp32 scales vs the exact path's NATIVE fp32 4 bytes/elem — the
    exact ring wires the input dtype since the pipelined data plane,
    so the fp32-equivalent convention is the measured value itself)."""
    p = ring4.p

    def measured(ring_id, compression):
        base = [plane.bytes_sent() for plane in ring4.planes]
        ring4.allreduce(ring_id, data, op_average=False,
                        compression=compression)
        return sum(plane.bytes_sent() - b
                   for plane, b in zip(ring4.planes, base))

    data = [np.random.RandomState(r).randn(1 << 14).astype(np.float32)
            for r in range(p)]
    none_bytes = measured(1004, "none")
    int8_bytes = measured(1005, "int8")
    assert int8_bytes <= 0.30 * none_bytes, (int8_bytes, none_bytes)


def test_ring_vs_xla_fused_parity_same_payload(hvd, ring4):
    """Both data planes within the block-scaled bound of the same exact
    sum, and within 2x the bound of each other (they quantize with the
    same block size but accumulate fp32 vs fp64)."""
    p = ring4.p
    size = 1 << 14
    data = [np.random.RandomState(70 + r).randn(size).astype(np.float32)
            for r in range(p)]
    padded = data + [np.zeros(size, np.float32)] * (N - p)
    exact = np.sum(np.stack(data, 0), 0)

    ring_out = ring4.allreduce(1006, data, op_average=False,
                               compression="int8")[0]

    def fn(r):
        return np.asarray(hvd.allreduce(
            jnp.asarray(padded[r]), op=hvd.Sum, name="parity.int8",
            compression="int8"))

    xla_out = _per_rank(fn)[0]
    _assert_block_bound(ring_out, exact)
    _assert_block_bound(xla_out, exact)
    _assert_block_bound(ring_out, xla_out, rel=2e-2)


# ------------------------------------------------------------- SPMD wrappers
def test_distributed_optimizer_int8_reduces_gradients(hvd):
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel._compat import shard_map

    mesh = hvd.mesh()
    n = mesh.devices.size
    grads = np.random.RandomState(3).randn(n, 2048).astype(np.float32)
    expected = grads.mean(0)

    opt = hvd.DistributedOptimizer(optax.sgd(1.0), named_axes=("hvd",),
                                   compression=Compression.int8)

    def per_shard(g):
        state = opt.init({"w": g[0]})
        updates, _ = opt.update({"w": g[0]}, state)
        return updates["w"][None]

    out = jax.jit(shard_map(per_shard, mesh=mesh, in_specs=P("hvd"),
                            out_specs=P("hvd")))(jnp.asarray(grads))
    # sgd(1.0) updates are -mean(grad)
    _assert_block_bound(-np.asarray(out)[0], expected)


def test_sharded_optimizer_int8_reduce_scatter(hvd):
    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel._compat import shard_map_unchecked

    mesh = hvd.mesh()
    n = mesh.devices.size
    grads = np.random.RandomState(5).randn(n, 4096).astype(np.float32)
    expected = grads.mean(0)

    opt = hvd.ShardedDistributedOptimizer(optax.sgd(1.0),
                                          compression=Compression.int8)

    def per_shard(g):
        params = {"w": g[0]}
        state = opt.init(params)
        updates, _ = opt.update({"w": g[0]}, state, params)
        return updates["w"][None]

    out = jax.jit(shard_map_unchecked(
        per_shard, mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd")))(
            jnp.asarray(grads))
    _assert_block_bound(-np.asarray(out)[0], expected)


def test_allreduce_gradients_int8_multi_axis_rejected():
    from horovod_tpu.jax_api import _single_axis

    assert _single_axis(("hvd",), "x") == "hvd"
    assert _single_axis("hvd", "x") == "hvd"
    with pytest.raises(ValueError):
        _single_axis(("a", "b"), "x")


# ------------------------------------------------------------ config surface
def test_hvd_tpu_compression_env(monkeypatch):
    from horovod_tpu.common.config import Config

    monkeypatch.setenv("HVD_TPU_COMPRESSION", "int8")
    assert Config.from_env().compression == "int8"
    monkeypatch.setenv("HVD_TPU_COMPRESSION", "bogus")
    with pytest.raises(ValueError):
        Config.from_env()
    monkeypatch.delenv("HVD_TPU_COMPRESSION")
    assert Config.from_env().compression == "none"


def test_default_params_include_compression():
    from horovod_tpu.common.config import Config
    from horovod_tpu.ops.autotune import default_params

    cfg = Config()
    cfg.compression = "int8"
    assert default_params(cfg)["compression"] == "int8"


def test_parameter_manager_compression_knob():
    from horovod_tpu.common import autotune

    pm = autotune.ParameterManager(compression=True,
                                   compression_available=True)
    assert pm.compression_enabled is True
    pm_off = autotune.ParameterManager()
    assert pm_off.compression_enabled is False


# ----------------------------------------------------- hierarchical schedule
def test_int8_and_bf16_hierarchical_allreduce():
    """Compressed fused allreduce on the two-level (cross, local) mesh:
    quantized legs over the fast local axis, fp32 chunk across the
    cross axis (requantize only before the allgather leg)."""
    import jax

    from horovod_tpu.common.ops_enum import ReduceOp
    from horovod_tpu.ops.xla_executor import XlaExecutor

    class _Handle:
        def set_result(self, value):
            self.res = value

        def set_error(self, message):
            raise AssertionError(message)

    class _Entry:
        pass

    ex = XlaExecutor(jax.devices(), hier_local_size=4)
    ex.hierarchical_allreduce = True
    assert ex.hier_mesh is not None
    data = [np.random.RandomState(r).randn(10000).astype(np.float32)
            for r in range(N)]
    exact = np.sum(np.stack(data, 0), 0)
    for comp, rel in (("int8", 1e-2), ("bf16", 2e-2)):
        entry = _Entry()
        entry.shape = (10000,)
        entry.dtype = np.dtype(np.float32)
        entry.tensors = {r: jnp.asarray(data[r]) for r in range(N)}
        entry.handles = {r: _Handle() for r in range(N)}
        ex.allreduce_fused([entry], op=ReduceOp.SUM, prescale_factor=1.0,
                           postscale_factor=1.0, compression=comp)
        for rank in (0, 5):
            _assert_block_bound(np.asarray(entry.handles[rank].res),
                                exact, rel=rel)
