"""Fault-tolerant collective runtime tests (docs/fault_tolerance.md).

Unit layer: fault-spec grammar, the purge LRU bound, abort waking a
blocked mailbox recv, connect retry with backoff.

Integration layer: the crash / drop / refuse x allreduce / broadcast /
allgather matrix against real worker processes on the tcp plane — each
cell is driven by a deterministic ``HVD_TPU_FAULT_SPEC`` so the failure
fires at an exact step, and the assertion is the acceptance criterion:
every surviving rank raises ``HvdAbortedError`` naming the origin rank
within the abort deadline, no hangs, no leaked mailbox chunks.
"""

import threading
import time

import pytest

from conftest import spawn_tcp_ranks
from horovod_tpu.common import faults
from horovod_tpu.common.handles import HvdAbortedError


# ------------------------------------------------------------ spec grammar --
def test_fault_spec_grammar():
    specs = faults.parse_fault_spec(
        "rank1:allreduce:2:crash, rank0:send:5:drop ,*:connect:1:refuse")
    assert [(s.rank, s.point, s.step, s.action) for s in specs] == [
        (1, "allreduce", 2, "crash"),
        (0, "send", 5, "drop"),
        (None, "connect", 1, "refuse"),
    ]
    assert faults.parse_fault_spec("") == []
    assert faults.parse_fault_spec(None) == []


@pytest.mark.parametrize("bad", [
    "rank1:allreduce:crash",          # missing field
    "node1:allreduce:1:crash",        # bad target
    "rank1:allreduce:0:crash",        # step is 1-based
    "rank1:allreduce:x:crash",        # non-integer step
    "rank1:allreduce:1:explode",      # unknown action
    "rank1::1:crash",                 # empty point
])
def test_fault_spec_rejects_bad_grammar(bad):
    with pytest.raises(ValueError):
        faults.parse_fault_spec(bad)


def test_injector_fires_at_exact_step_for_matching_rank():
    inj = faults.FaultInjector(
        faults.parse_fault_spec("rank1:send:3:drop,*:recv:2:refuse"),
        rank=1)
    assert [inj.fire("send") for _ in range(4)] == [
        None, None, "drop", None]
    assert [inj.fire("recv") for _ in range(3)] == [None, "refuse", None]
    # rank mismatch: counter still advances, fault never fires
    other = faults.FaultInjector(
        faults.parse_fault_spec("rank1:send:1:drop"), rank=0)
    assert [other.fire("send") for _ in range(3)] == [None, None, None]


def test_config_validates_fault_spec_at_init(monkeypatch):
    from horovod_tpu.common.config import Config

    monkeypatch.setenv("HVD_TPU_FAULT_SPEC", "rank1:allreduce:1:explode")
    with pytest.raises(ValueError, match="action"):
        Config.from_env()


# --------------------------------------------------------- peer mailbox -----
def _peer_service():
    from horovod_tpu.ops.tcp_dataplane import PeerService
    from horovod_tpu.run.service import secret

    return PeerService(secret.make_secret_key())


def _push_chunk(svc, ring_id, src=1, payload=b"x"):
    from horovod_tpu.ops.tcp_dataplane import ChunkMsg

    svc._handle(ChunkMsg(((ring_id, "rs", 0)), src, payload), None)


def test_purged_ring_ids_are_a_bounded_lru():
    svc = _peer_service()
    try:
        for ring_id in range(1000):
            svc.purge(ring_id)
        assert len(svc._purged) == svc._PURGED_KEEP
        # late chunk of a recently purged round is dropped
        _push_chunk(svc, 999)
        assert svc._mailbox == {}
        # re-purging a hot id refreshes its LRU slot instead of letting
        # a newer purge evict it
        svc.purge(1000 - svc._PURGED_KEEP)  # oldest retained id
        svc.purge(2000)  # evicts the NEXT-oldest, not the refreshed one
        assert (1000 - svc._PURGED_KEEP) in svc._purged
        assert (1001 - svc._PURGED_KEEP) not in svc._purged
        # an id evicted from the LRU is forgotten: its chunks land again
        _push_chunk(svc, 0)
        assert len(svc._mailbox) == 1
    finally:
        svc.shutdown()


def test_abort_wakes_blocked_recv_and_purges_mailbox():
    svc = _peer_service()
    try:
        _push_chunk(svc, 7, src=2)
        assert len(svc._mailbox) == 1
        caught = []

        def blocked_recv():
            try:
                svc.recv(((99, "rs", 0)), 3, timeout=30)
            except BaseException as exc:  # noqa: BLE001
                caught.append(exc)

        t = threading.Thread(target=blocked_recv, daemon=True)
        t.start()
        time.sleep(0.2)
        start = time.monotonic()
        svc.abort(5, "injected test abort")
        t.join(timeout=5)
        assert not t.is_alive(), "abort did not wake the blocked recv"
        assert time.monotonic() - start < 2.0
        assert isinstance(caught[0], HvdAbortedError)
        assert caught[0].origin_rank == 5
        # no leaked chunks: buffer purged, late arrivals refused
        assert svc._mailbox == {}
        _push_chunk(svc, 8)
        assert svc._mailbox == {}
        # sticky: the next recv fails immediately too
        with pytest.raises(HvdAbortedError):
            svc.recv(((100, "rs", 0)), 1, timeout=5)
    finally:
        svc.shutdown()


# ------------------------------------------------------- transport retry ----
def test_basic_client_retries_refused_connects_with_backoff():
    from horovod_tpu.run.service import network, secret

    key = secret.make_secret_key()
    svc = network.BasicService("retry target", key)
    try:
        faults.configure("*:connect:1:refuse,*:connect:2:refuse", rank=0)
        client = network.BasicClient([("127.0.0.1", svc.port)], key,
                                     retry_for=20)
        resp = client.send(network.PingRequest())
        assert isinstance(resp, network.PingResponse)
    finally:
        faults.configure(None)
        svc.shutdown()


def test_basic_client_retry_budget_zero_fails_fast():
    from horovod_tpu.run.service import network, secret

    client = network.BasicClient([("127.0.0.1", 1)],
                                 secret.make_secret_key(),
                                 timeout=1, retry_for=0)
    start = time.monotonic()
    with pytest.raises(ConnectionError):
        client.send(network.PingRequest())
    assert time.monotonic() - start < 5.0


def test_mux_client_retries_refused_connects():
    from horovod_tpu.run.service import network, secret

    key = secret.make_secret_key()
    svc = network.MuxService("mux retry target", key)
    try:
        faults.configure("*:connect:1:refuse", rank=0)
        client = network.MuxClient([("127.0.0.1", svc.port)], key,
                                   retry_for=20)
        resp = client.send((network.PingRequest()), timeout=10)
        assert isinstance(resp, network.PingResponse)
        client.close()
    finally:
        faults.configure(None)
        svc.shutdown()


def test_http_client_all_verbs_with_retry():
    from horovod_tpu.run import http_client
    from horovod_tpu.run.http_server import RendezvousServer

    server = RendezvousServer()
    port = server.start()
    try:
        http_client.put("127.0.0.1", port, "s", "k", b"v")
        assert http_client.get("127.0.0.1", port, "s", "k") == b"v"
        http_client.delete("127.0.0.1", port, "s", "k")
        with pytest.raises(KeyError):
            http_client.get("127.0.0.1", port, "s", "k", timeout=0.2)
    finally:
        server.stop()
    # dead endpoint: the bounded retry gives up within its budget
    start = time.monotonic()
    with pytest.raises(OSError):
        http_client.get("127.0.0.1", port, "s", "k", retry_for=0.5)
    assert time.monotonic() - start < 10.0


# ----------------------------------------------- launcher culprit naming ----
def test_safe_shell_exec_reports_event_termination():
    import sys

    from horovod_tpu.run import safe_shell_exec

    # natural failure: no event involvement recorded; the exit
    # timestamp is recorded for the launcher's death-order attribution
    info = {}
    code = safe_shell_exec.execute([sys.executable, "-c", "exit(3)"],
                                   info=info)
    assert code == 3
    assert not info.get("terminated_by_event")
    assert info.get("exit_ts") is not None

    # event-driven kill: the victim is marked so the launcher does not
    # blame it for the job failure
    event = threading.Event()
    info = {}
    threading.Timer(0.3, event.set).start()
    code = safe_shell_exec.execute(
        [sys.executable, "-c", "import time; time.sleep(30)"],
        events=[event], info=info)
    assert code != 0
    assert info.get("terminated_by_event") is True


def test_culprit_attribution_survives_reap_order_skew():
    """Deflake regression (the load-sensitive culprit flake): reap
    order is NOT death order — stream-forwarder drains and thread
    scheduling sit between a child dying and its failure being
    recorded, so under machine load a survivor that exits nonzero
    because of the coordinated abort can be reaped BEFORE the rank
    whose death caused it.  Attribution must rank by exit timestamp
    and by the fault spec's own crash ranks, never by arrival."""
    from horovod_tpu.run.launch import fault_crash_ranks, pick_culprit
    from horovod_tpu.utils import env as env_util

    # induced reap-order skew: the survivor (abort exit, ts 105) was
    # recorded first; the true culprit (died at ts 100) second
    failures = [(0, 1, False, 105.0), (1, 7, False, 100.0)]
    assert pick_culprit(failures) == (1, 7)

    # a victim of the kill fan-out never steals the blame, even with
    # the earliest timestamp
    failures = [(2, -15, True, 99.0), (1, 7, False, 100.0)]
    assert pick_culprit(failures) == (1, 7)

    # all-victims (launcher interrupt edge case): fall back to the
    # earliest observed death
    failures = [(2, -15, True, 99.0), (0, -15, True, 98.0)]
    assert pick_culprit(failures) == (0, -15)

    # an injected-crash rank is the culprit by construction — timing
    # evidence cannot outvote the fault spec
    failures = [(0, 1, False, 100.0), (1, 1, False, 101.0)]
    assert pick_culprit(failures, frozenset({1})) == (1, 1)

    # a missing timestamp (launch-phase failure) sorts last
    failures = [(0, 1, False, None), (1, 7, False, 100.0)]
    assert pick_culprit(failures) == (1, 7)

    # crash-rank extraction from the worker env contract
    assert fault_crash_ranks(
        {env_util.HVD_TPU_FAULT_SPEC:
         "rank1:ring:1:crash,rank0:send:2:drop,*:connect:1:refuse"}) \
        == frozenset({1})
    assert fault_crash_ranks({}) == frozenset()
    assert fault_crash_ranks(
        {env_util.HVD_TPU_FAULT_SPEC: "garbage"}) == frozenset()


# ------------------------------------------------------ injected matrix -----
# Worker for the crash/drop x op matrix: runs one collective; on a
# coordinated abort it reports the origin rank, the elapsed time and
# the mailbox residue so the test can assert the acceptance criterion.
MATRIX_WORKER = r"""
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
op = os.environ["FT_OP"]
n_elems = int(os.environ.get("FT_SIZE", "70000"))
t = jnp.ones((n_elems,)) * (r + 1)
start = time.monotonic()
try:
    if op == "allreduce":
        hvd.allreduce(t, op=hvd.Sum, name="ft.tensor")
    elif op == "broadcast":
        hvd.broadcast(t, root_rank=0, name="ft.tensor")
    else:
        hvd.allgather(t, name="ft.tensor")
    print(f"rank {r} COMPLETED", flush=True)
except hvd.HvdAbortedError as exc:
    elapsed = time.monotonic() - start
    from horovod_tpu.common import basics
    svc = basics._get_state().controller._peer_service
    leaked = len(svc._mailbox) if svc is not None else 0
    print(f"rank {r} ABORTED origin={exc.origin_rank} "
          f"elapsed={elapsed:.1f} leaked={leaked}", flush=True)
print(f"rank {r} DONE", flush=True)
"""

# tight failure-detection windows so each cell stays tier-1 fast; the
# abort deadline stays well above them so elapsed < deadline is a real
# bound, not a tautology
_FT_ENV = {
    "JAX_PLATFORMS": "cpu",
    "HVD_TPU_HEARTBEAT_INTERVAL": "0.25",
    "HVD_TPU_ABORT_TIMEOUT": "10",
    "HVD_STALL_CHECK_TIME_SECONDS": "1",
    "HVD_TCP_RING_THRESHOLD": "1024",
}


def _assert_aborted(out, rank, origin, deadline=10.0):
    line = next(l for l in out.splitlines()
                if l.startswith(f"rank {rank} ABORTED"))
    fields = dict(kv.split("=") for kv in line.split()[3:])
    allowed = origin if isinstance(origin, tuple) else (origin,)
    assert fields["origin"] in {str(o) for o in allowed}, line
    assert float(fields["elapsed"]) < deadline, line
    assert fields["leaked"] == "0", line


@pytest.mark.parametrize("op", ["allreduce", "broadcast", "allgather"])
def test_injected_crash_aborts_survivor(op):
    """Rank 1 hard-exits at its first <op> submit (pre-negotiation, so
    this exercises the coordinator-star side): the liveness monitor
    notices the silence and rank 0 raises HvdAbortedError(origin=1)."""
    results = spawn_tcp_ranks(2, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": op,
        "FT_SIZE": "8",  # below the ring threshold: star path
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "20",
        "HVD_TPU_FAULT_SPEC": f"rank1:{op}:1:crash",
    })
    code0, out0, err0 = results[0]
    code1, out1, err1 = results[1]
    assert code1 == 1, f"crashed rank: {out1}\n{err1}"
    assert code0 == 0, f"survivor: {out0}\n{err0}"
    _assert_aborted(out0, rank=0, origin=1)


def test_injected_crash_mid_ring_allreduce():
    """The acceptance scenario: rank 1 dies AFTER the coordinator's
    ring go-ahead, with rank 0 already blocked on its chunks — the ring
    path's worst case.  Liveness converts the silence into an abort and
    the blocked recv wakes with the typed error, mailbox clean.

    origin=1 is deterministic whichever detector fires first under
    machine load: liveness names the silent rank, and the survivor's
    own hard failure evidence (RingSendError — the transport write to
    the dead peer broke) now carries the peer rank into the abort
    origin instead of blaming the rank that noticed.  (A recv timeout
    deliberately still names the noticing rank: in a 3+-rank ring the
    silent predecessor is usually an innocent rank blocked behind the
    real casualty — and its 30s bound can never beat the 2s liveness
    window here anyway.)"""
    results = spawn_tcp_ranks(2, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": "allreduce",
        "FT_SIZE": "70000",  # above the ring threshold: ring path
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        # keep the ring recv timeout far beyond liveness so the typed
        # abort (origin=the dead rank), not a local TimeoutError, is
        # what wakes the survivor
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TPU_FAULT_SPEC": "rank1:ring:1:crash",
    })
    code0, out0, err0 = results[0]
    code1, out1, _ = results[1]
    assert code1 == 1, f"crashed rank: {out1}"
    assert code0 == 0, f"survivor: {out0}\n{err0}"
    _assert_aborted(out0, rank=0, origin=1)


@pytest.mark.parametrize("op", ["allreduce", "broadcast", "allgather"])
def test_injected_drop_promotes_stall_into_abort(op):
    """Rank 1 silently drops its contribution (the rank is alive and
    heartbeating — liveness can't see it): the stall inspector promotes
    the stalled tensor into a coordinated abort naming rank 1, and BOTH
    ranks — including the dropper, whose handle would otherwise wait
    forever — raise the same typed error."""
    results = spawn_tcp_ranks(2, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": op,
        "FT_SIZE": "8",
        "HVD_TPU_LIVENESS_TIMEOUT": "30",  # must NOT fire: rank 1 lives
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "2",
        "HVD_TPU_FAULT_SPEC": f"rank1:{op}:1:drop",
    })
    for rank, (code, out, err) in enumerate(results):
        assert code == 0, f"rank {rank}: {out}\n{err}"
        _assert_aborted(out, rank=rank, origin=1)


def test_injected_send_drop_bounded_without_stall_shutdown():
    """A chunk silently dropped on the wire AFTER negotiation is the
    failure neither liveness (the sender is alive and heartbeating) nor
    the stall inspector (negotiation completed) can see: the ring-recv
    backstop (4x the abort deadline) must convert it into a coordinated
    abort even with the stall shutdown off — the default config."""
    results = spawn_tcp_ranks(2, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": "allreduce",
        "FT_SIZE": "70000",  # ring path
        "HVD_TPU_ABORT_TIMEOUT": "1",  # recv backstop = 4s
        "HVD_TPU_LIVENESS_TIMEOUT": "30",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "0",
        "HVD_TPU_FAULT_SPEC": "rank0:send:1:drop",
    })
    for rank, (code, out, err) in enumerate(results):
        assert code == 0, f"rank {rank}: {out}\n{err}"
        # whichever blocked rank's backstop fires first names itself
        _assert_aborted(out, rank=rank, origin=(0, 1))


@pytest.mark.parametrize("op", ["allreduce", "broadcast", "allgather"])
def test_injected_connect_refusals_are_retried(op):
    """Both ranks' first two connection attempts are refused: the
    backoff retry carries rendezvous/negotiation through and the
    collective completes exactly — a transport blip is not a failure."""
    results = spawn_tcp_ranks(2, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": op,
        "FT_SIZE": "70000",  # ring path: peer connects retried too
        "HVD_TPU_LIVENESS_TIMEOUT": "30",
        "HVD_TPU_FAULT_SPEC": "*:connect:1:refuse,*:connect:2:refuse",
        "HVD_TPU_CONNECT_RETRY_SECONDS": "20",
    })
    for rank, (code, out, err) in enumerate(results):
        assert code == 0, f"rank {rank}: {out}\n{err}"
        assert f"rank {rank} COMPLETED" in out, f"{out}\n{err}"


def test_user_abort_reaches_blocked_peer():
    """hvd.abort() from one rank fails a peer blocked in negotiation
    with the typed error naming the aborting rank."""
    script = r"""
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
start = time.monotonic()
try:
    if r == 1:
        time.sleep(1.0)  # let rank 0 block in negotiation first
        hvd.abort("operator says no")
        # the sticky abort fails this rank's own next submit too
        try:
            hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="after")
            print(f"rank {r} UNEXPECTED-OK", flush=True)
        except hvd.HvdAbortedError:
            print(f"rank {r} STICKY-OK", flush=True)
    else:
        hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="ua.tensor")
        print(f"rank {r} COMPLETED", flush=True)
except hvd.HvdAbortedError as exc:
    print(f"rank {r} ABORTED origin={exc.origin_rank} "
          f"elapsed={time.monotonic() - start:.1f} leaked=0", flush=True)
print(f"rank {r} DONE", flush=True)
"""
    results = spawn_tcp_ranks(2, script, extra_env={
        **_FT_ENV,
        "HVD_TPU_LIVENESS_TIMEOUT": "30",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
    })
    code0, out0, err0 = results[0]
    code1, out1, err1 = results[1]
    assert code0 == 0, f"{out0}\n{err0}"
    assert code1 == 0, f"{out1}\n{err1}"
    _assert_aborted(out0, rank=0, origin=1)
    assert "rank 1 STICKY-OK" in out1, out1


def test_launcher_names_culprit_rank():
    """End-to-end through hvdrun: a rank that dies on its own is named
    as the culprit — the SIGTERMed victims can no longer steal the
    blame with their -15 (satellite: exit-code/rank propagation)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = "/tmp/hvd_culprit_worker.py"
    with open(path, "w") as f:
        f.write(r"""
import os, sys, time
rank = int(os.environ["HVD_RANK"])
if rank == 1:
    time.sleep(0.5)
    sys.exit(7)
time.sleep(30)
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    result = subprocess.run(
        [sys.executable, os.path.join(repo, "bin", "hvdrun"), "-np", "2",
         sys.executable, path],
        env=env, capture_output=True, text=True, timeout=120)
    assert result.returncode == 7, result.stderr
    assert "rank 1 failed first (exit code 7)" in result.stderr, \
        result.stderr


def test_hvd_chaos_prints_reproducible_spec():
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    chaos = os.path.join(repo, "bin", "hvd-chaos")

    def spec_for(seed):
        out = subprocess.run(
            [sys.executable, chaos, "--seed", str(seed), "--faults", "2",
             "--", "-np", "2", "--version"],
            env=env, capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        line = next(l for l in out.stdout.splitlines()
                    if "HVD_TPU_FAULT_SPEC=" in l)
        spec = line.split("HVD_TPU_FAULT_SPEC=")[1].strip("'\"")
        faults.parse_fault_spec(spec)  # valid grammar
        return spec

    assert spec_for(7) == spec_for(7)       # same seed -> same spec
    assert spec_for(7) != spec_for(8)       # different seed -> different


# ------------------------------------ sub-group collectives (groups.md) -----
# Two 2-rank process groups; the failure is injected INSIDE one group's
# collective.  Group-scoped abort semantics: the whole job dies typed
# with the true origin — including the OTHER group's members, who were
# busy with their own healthy collective — and no per-group ring state
# leaks (PeerService purge is group-aware).
GROUP_MATRIX_WORKER = r"""
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
lo = hvd.new_group([0, 1], name="ft.lo")
hi = hvd.new_group([2, 3], name="ft.hi")
mine = lo if r < 2 else hi
n_elems = int(os.environ.get("FT_SIZE", "8"))
t = jnp.ones((n_elems,)) * (r + 1)
start = time.monotonic()
try:
    hvd.allreduce(t, op=hvd.Sum, name="ft.group", group=mine)
    # the healthy group reaches the world barrier and must ALSO die
    hvd.barrier(name="ft.join")
    print(f"rank {r} COMPLETED", flush=True)
except hvd.HvdAbortedError as exc:
    elapsed = time.monotonic() - start
    from horovod_tpu.common import basics
    svc = basics._get_state().controller._peer_service
    leaked = len(svc._mailbox) if svc is not None else 0
    print(f"rank {r} ABORTED origin={exc.origin_rank} "
          f"elapsed={elapsed:.1f} leaked={leaked}", flush=True)
print(f"rank {r} DONE", flush=True)
"""


def test_injected_crash_inside_subgroup_aborts_whole_job():
    """Rank 1 hard-exits at its group's allreduce submit: every
    survivor — group peer AND both members of the other, healthy group
    — raises HvdAbortedError naming rank 1."""
    results = spawn_tcp_ranks(4, GROUP_MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_SIZE": "8",  # star path
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "20",
        "HVD_TPU_FAULT_SPEC": "rank1:allreduce:1:crash",
    })
    assert results[1][0] == 1, f"crashed rank: {results[1][1]}"
    for rank in (0, 2, 3):
        code, out, err = results[rank]
        assert code == 0, f"rank {rank}: {out}\n{err}"
        _assert_aborted(out, rank=rank, origin=1)


def test_injected_crash_mid_subgroup_ring_no_leaked_state():
    """Rank 1 dies after its GROUP ring's go-ahead with rank 0 blocked
    on chunks in the group-qualified ring namespace: the abort wakes
    the blocked recv typed and the group-aware purge leaves zero
    mailbox residue on every survivor."""
    results = spawn_tcp_ranks(4, GROUP_MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_SIZE": "70000",  # above the ring threshold: group rings
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TPU_FAULT_SPEC": "rank1:ring:1:crash",
    })
    assert results[1][0] == 1, f"crashed rank: {results[1][1]}"
    for rank in (0, 2, 3):
        code, out, err = results[rank]
        assert code == 0, f"rank {rank}: {out}\n{err}"
        _assert_aborted(out, rank=rank, origin=1)


def test_injected_drop_inside_subgroup_promotes_stall():
    """Rank 1 silently skips its group contribution while heartbeating:
    the stall inspector sees the half-reported GROUP entry, promotes it
    into a coordinated abort naming rank 1, and all four ranks — the
    dropper included — fail typed."""
    results = spawn_tcp_ranks(4, GROUP_MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_SIZE": "8",
        "HVD_TPU_LIVENESS_TIMEOUT": "30",  # must NOT fire: rank 1 lives
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "2",
        "HVD_TPU_FAULT_SPEC": "rank1:allreduce:1:drop",
    })
    for rank, (code, out, err) in enumerate(results):
        assert code == 0, f"rank {rank}: {out}\n{err}"
        _assert_aborted(out, rank=rank, origin=1)


# ----------------------------------------- pipelined stripe data plane ------
def _stripe_planes(p=2, segment_bytes=1024, stripes=2):
    """Loopback ring rig — one definition in ``bench._ring_harness``."""
    import bench

    return bench._ring_harness(p, segment_bytes, stripes)


def test_abort_wakes_blocked_stripe_recv_mid_pipeline():
    """A recv blocked on the MISSING segments of a partially-delivered
    chunk (some stripes delivered, one wedged) must wake with the typed
    error when the abort lands — stripe sockets are covered by the same
    mailbox condition the abort signals."""
    services, planes = _stripe_planes(p=2, segment_bytes=1024, stripes=2)
    try:
        # rank 0 delivers only the FIRST segment of a 3-segment chunk
        # (simulating a wedged stripe): enqueue segment 0 directly
        planes[0]._enqueue_segment(1, 0, (42, "rs", 0, 0), b"x" * 1024)
        planes[0]._flush_sends(5)
        caught = []

        def blocked():
            try:
                planes[1].recv_chunk((42, "rs", 0), 0, 3 * 1024,
                                     timeout=30)
            except BaseException as exc:  # noqa: BLE001
                caught.append(exc)

        t = threading.Thread(target=blocked, daemon=True)
        t.start()
        time.sleep(0.3)
        assert t.is_alive(), "recv should be blocked on segment 1"
        start = time.monotonic()
        services[1].abort(0, "injected stripe abort")
        t.join(timeout=5)
        assert not t.is_alive(), "abort did not wake the stripe recv"
        assert time.monotonic() - start < 2.0
        assert isinstance(caught[0], HvdAbortedError)
        # the already-delivered segment did not leak
        assert services[1]._mailbox == {}
        assert services[1]._by_ring == {}
    finally:
        for plane in planes:
            plane.close()
        for svc in services:
            svc.shutdown()


def test_purge_drops_stale_segments_mid_pipeline_and_is_ring_indexed():
    """Purging an aborted round drops exactly that ring's buffered
    segments (O(chunks of the ring) via the ring-id index), refuses its
    late-arriving stripe segments, and leaves other rounds' chunks
    untouched."""
    services, planes = _stripe_planes(p=2, segment_bytes=1024, stripes=2)
    try:
        svc = services[1]
        # segments of two interleaved rounds, delivered over stripes
        for seg in range(3):
            planes[0]._enqueue_segment(1, seg, (7, "rs", 0, seg),
                                       b"a" * 100)
        planes[0]._enqueue_segment(1, 0, (8, "ag", 0, 0), b"b" * 100)
        planes[0]._flush_sends(5)
        deadline = time.monotonic() + 5
        while len(svc._mailbox) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(svc._mailbox) == 4
        assert set(svc._by_ring) == {7, 8}

        svc.purge(7)
        assert len(svc._mailbox) == 1, svc._mailbox
        assert set(svc._by_ring) == {8}
        # a straggler segment of the purged round is refused...
        planes[0]._enqueue_segment(1, 1, (7, "rs", 0, 3), b"late")
        planes[0]._flush_sends(5)
        time.sleep(0.2)
        assert len(svc._mailbox) == 1
        # ...while the live round's chunk is still collectable
        got = planes[1].recv_chunk((8, "ag", 0), 0, 100, timeout=5)
        assert bytes(got) == b"b" * 100
        assert svc._by_ring == {}
    finally:
        for plane in planes:
            plane.close()
        for svc in services:
            svc.shutdown()


def test_sender_thread_failure_fails_the_round_fast():
    """A bulk send that fails (dead stripe peer) surfaces on the
    compute thread as a ConnectionError instead of a silent stall."""
    from horovod_tpu.ops.tcp_dataplane import PeerService, RingPlane
    from horovod_tpu.run.service import network, secret

    key = secret.make_secret_key()
    svc = PeerService(key)
    try:
        def resolver(rank):
            return network.MuxClient([("127.0.0.1", svc.port)], key,
                                     timeout=10)

        def resolve_bulk(rank):
            # dead endpoint, no retry budget: post_bulk fails fast
            return network.StripeClient([("127.0.0.1", 1)], key,
                                        timeout=1, retry_for=0)

        plane = RingPlane(0, svc, resolver, resolve_bulk,
                          segment_bytes=64, stripes=1)
        plane.send_chunk(1, (9, "rs", 0), b"x" * 256)
        with pytest.raises((ConnectionError, TimeoutError)):
            plane._flush_sends(10)
        plane.close()
    finally:
        svc.shutdown()


def test_send_failure_wakes_blocked_recv():
    """A recv already blocked on the mailbox must wake with the send
    failure as soon as the sender thread records it — not after the
    full recv timeout (the peer can never send the segments this rank's
    broken sends were the prerequisite for)."""
    from horovod_tpu.ops.tcp_dataplane import PeerService, RingPlane
    from horovod_tpu.run.service import network, secret

    key = secret.make_secret_key()
    svc = PeerService(key)
    try:
        def resolver(rank):
            return network.MuxClient([("127.0.0.1", svc.port)], key,
                                     timeout=10)

        def resolve_bulk(rank):
            return network.StripeClient([("127.0.0.1", 1)], key,
                                        timeout=1, retry_for=0)

        plane = RingPlane(0, svc, resolver, resolve_bulk,
                          segment_bytes=64, stripes=1)
        caught = []

        def blocked():
            try:
                plane.recv_chunk((9, "rs", 0), 1, 64, timeout=30)
            except BaseException as exc:  # noqa: BLE001
                caught.append(exc)

        t = threading.Thread(target=blocked, daemon=True)
        t.start()
        time.sleep(0.2)
        assert t.is_alive(), "recv should be blocked"
        start = time.monotonic()
        plane.send_chunk(1, (9, "x", 0), b"y" * 64)  # sender will fail
        t.join(timeout=10)
        assert not t.is_alive(), "send failure did not wake the recv"
        assert time.monotonic() - start < 5.0
        assert isinstance(caught[0], ConnectionError), caught
        plane.close()
    finally:
        svc.shutdown()


# ------------------------------------------- degraded-network tolerance -----
# docs/fault_tolerance.md "degraded networks": duration-scoped link
# degradations (delay/jitter/throttle/flaky/partition), the adaptive
# liveness deadline that tells slow from dead, and the k x median
# straggler verdict.
def test_fault_spec_degrade_grammar_round_trip():
    specs = faults.parse_fault_spec(
        "rank1:link:2:delay:40:6, rank0:link:1:flaky:0.2 ,"
        "*:link:3:throttle:16:2,rank2:link:1:jitter:5:1,"
        "rank0:link:1:partition:2-5:4")
    got = [(s.rank, s.point, s.step, s.action, s.param, s.duration)
           for s in specs]
    assert got == [
        (1, "link", 2, "delay", 40.0, 6.0),
        (0, "link", 1, "flaky", 0.2, None),   # no duration: forever
        (None, "link", 3, "throttle", 16.0, 2.0),
        (2, "link", 1, "jitter", 5.0, 1.0),
        (0, "link", 1, "partition", (2, 5), 4.0),
    ]


@pytest.mark.parametrize("bad", [
    "rank1:allreduce:1:crash:5",       # binary actions take no param
    "rank1:allreduce:1:crash:5:2",     # ... nor a duration
    "rank1:link:1:delay",              # degrade action needs a param
    "rank1:link:1:delay:-1",           # negative delay
    "rank1:link:1:flaky:2",            # probability > 1
    "rank1:link:1:throttle:0",         # zero rate
    "rank1:link:1:partition:5",        # not a range
    "rank1:link:1:partition:5-2",      # inverted range
    "rank1:link:1:delay:10:0",         # zero duration
    "rank1:link:1:degrade:1",          # unknown degrade action
])
def test_fault_spec_rejects_bad_degrade_grammar(bad):
    with pytest.raises(ValueError):
        faults.parse_fault_spec(bad)


def test_link_state_aggregation_and_partition_cut_rule():
    # two delay cells: the worst one wins; partition cuts a link iff
    # exactly one endpoint is inside the range
    inj = faults.FaultInjector(faults.parse_fault_spec(
        "rank0:link:1:delay:10,rank0:link:1:delay:30,"
        "rank0:link:1:partition:2-5"), rank=0)
    state = inj.link(peer=3)
    assert state is not None
    assert state.delay_s == pytest.approx(0.030)
    assert state.partitioned        # rank 0 outside, peer 3 inside
    assert not inj.link(peer=1).partitioned  # both outside: no cut
    # rendezvous-style traffic has no peer identity: never partitioned
    assert not inj.link(peer=None).partitioned


def test_link_faults_are_deterministic_under_the_seed_contract():
    spec = "rank0:link:1:flaky:0.5,rank0:link:1:jitter:50"
    def rolls(rank):
        inj = faults.FaultInjector(faults.parse_fault_spec(spec),
                                   rank=rank, seed_text=spec)
        out = []
        for _ in range(32):
            s = inj.link(peer=1)
            out.append((s.drop, round(s.delay_s, 6)))
        return out
    assert rolls(0) == rolls(0)          # same rank: same stream
    # per-rank decorrelation: rank 1's cells target rank 0 only, so
    # build a rank-1 injector with its own cell to compare streams
    spec1 = spec.replace("rank0", "rank1")
    inj1 = faults.FaultInjector(faults.parse_fault_spec(spec1),
                                rank=1, seed_text=spec1)
    rolls1 = [(s.drop, round(s.delay_s, 6))
              for s in (inj1.link(peer=0) for _ in range(32))]
    assert rolls1 != rolls(0)


def test_degrade_cells_arm_at_step_and_expire_after_duration():
    inj = faults.FaultInjector(faults.parse_fault_spec(
        "rank0:link:3:delay:20:0.15"), rank=0)
    assert inj.link(peer=1) is None      # hit 1: not armed yet
    assert inj.link(peer=1) is None      # hit 2
    state = inj.link(peer=1)             # hit 3: armed
    assert state is not None and state.delay_s == pytest.approx(0.020)
    time.sleep(0.2)                      # past the 0.15s duration
    assert inj.link(peer=1) is None      # expired


# ------------------------- slow vs dead: the adaptive liveness deadline -----
def _coordinator(**kwargs):
    from horovod_tpu.ops.tcp_controller import CoordinatorService
    from horovod_tpu.run.service import secret

    return CoordinatorService(3, secret.make_secret_key(), **kwargs)


def test_adaptive_deadline_composes_busy_and_rtt_without_double_double():
    svc = _coordinator(liveness_timeout_sec=10.0, straggler_factor=4.0)
    try:
        with svc._cv:
            base = svc._deadline_for_locked(1)
            svc._busy_ranks.add(1)
            busy = svc._deadline_for_locked(1)
            svc._peer_rtt[1] = 0.5
            both = svc._deadline_for_locked(1)
            svc._busy_ranks.discard(1)
            rtt_only = svc._deadline_for_locked(1)
        assert base == pytest.approx(10.0)
        assert busy == pytest.approx(20.0)       # busy MULTIPLIES
        assert rtt_only == pytest.approx(12.0)   # rtt ADDS (0.5 * 4)
        # composed: busy doubles the base, rtt adds on top — the rtt
        # slack itself is NOT doubled by the busy flag
        assert both == pytest.approx(22.0)
        # pathological report: slack capped at factor x base window
        with svc._cv:
            svc._peer_rtt[1] = 1e9
            capped = svc._deadline_for_locked(1)
        assert capped == pytest.approx(10.0 + 40.0)
    finally:
        svc.shutdown()


def test_slow_rank_outlives_fixed_window_dead_rank_does_not():
    """The discrimination the whole feature exists for: with identical
    silence, the rank that REPORTED a slow link survives a scan that
    declares the non-reporting rank dead."""
    svc = _coordinator(liveness_timeout_sec=0.4, straggler_factor=4.0)
    try:
        now = time.monotonic()
        with svc._cv:
            # both silent for ~2 base windows; rank 1 reported a 0.5s
            # RTT beforehand (slack 2.0s), rank 2 reported nothing
            svc._last_seen[1] = now - 0.8
            svc._last_seen[2] = now - 0.8
            svc._peer_rtt[1] = 0.5
            svc._last_liveness_scan = 0.0
        svc._check_liveness()
        assert svc._abort is not None
        origin, reason = svc._abort
        assert origin == 2 and "presumed dead" in reason
    finally:
        svc.shutdown()


def test_liveness_scan_is_time_gated_not_per_heartbeat():
    svc = _coordinator(liveness_timeout_sec=30.0)
    try:
        with svc._cv:
            svc._last_seen[1] = time.monotonic() - 1e6  # long dead
            svc._last_liveness_scan = time.monotonic()  # just scanned
        svc._check_liveness()   # gated: no scan, no abort
        assert svc._abort is None
        with svc._cv:
            svc._last_liveness_scan = 0.0
        svc._check_liveness()   # gate open: the dead rank is found
        assert svc._abort is not None
    finally:
        svc.shutdown()


def test_straggler_verdict_needs_consecutive_windows_and_is_sticky():
    svc = _coordinator(liveness_timeout_sec=30.0, straggler_factor=4.0,
                       straggler_windows=2)
    try:
        with svc._cv:
            svc._peer_rtt.update({0: 0.01, 1: 0.01, 2: 0.5})
            assert svc._straggler_scan_locked() is None  # 1st window
            assert svc._straggler_scan_locked() is None  # exclusion off
        verdicts = svc.straggler_verdicts()
        assert list(verdicts) == [2]
        assert verdicts[2]["factor"] == 4.0
        with svc._cv:
            # a recovered rank resets its streak before a verdict
            svc._straggler_hits[1] = 1
            svc._peer_rtt[1] = 0.01
            svc._straggler_scan_locked()
            assert 1 not in svc._straggler_hits
        # verdict is sticky: recorded once, not re-logged every scan
        assert list(svc.straggler_verdicts()) == [2]
    finally:
        svc.shutdown()


def test_straggler_scan_requires_three_reporters():
    svc = _coordinator(liveness_timeout_sec=30.0, straggler_factor=2.0)
    try:
        with svc._cv:
            svc._peer_rtt.update({1: 0.01, 2: 5.0})
            for _ in range(10):
                assert svc._straggler_scan_locked() is None
        assert svc.straggler_verdicts() == {}
    finally:
        svc.shutdown()


def test_rtt_tracker_ewma_and_worst():
    from horovod_tpu.common import rtt

    t = rtt.RttTracker(alpha=0.5)
    assert t.worst() == 0.0
    t.sample(rtt.COORD_KEY, 0.1)
    t.sample(("peer", 3), 0.4)
    t.sample(("peer", 3), 0.2)          # ewma: 0.3
    assert t.get(("peer", 3)) == pytest.approx(0.3)
    assert t.worst() == pytest.approx(0.3)
    t.clear()
    assert t.worst() == 0.0 and t.snapshot() == {}
    assert rtt.median([3.0, 1.0, 2.0]) == 2.0
    assert rtt.median([4.0, 1.0, 2.0, 3.0]) == 2.5


# ------------------------ degradation x collective integration matrix -------
@pytest.mark.parametrize("op", ["allreduce", "broadcast", "allgather"])
def test_delayed_link_completes_without_abort(op):
    """A 60ms injected delay on every frame rank 1 writes makes it
    measurably slow — but slow is not dead: the collective completes
    exactly and nobody aborts (the no-false-positive criterion)."""
    results = spawn_tcp_ranks(2, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": op,
        "FT_SIZE": "70000",  # ring path: bulk stripes feel it too
        "HVD_TPU_LIVENESS_TIMEOUT": "15",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TPU_FAULT_SPEC": "rank1:link:1:delay:60",
    })
    for rank, (code, out, err) in enumerate(results):
        assert code == 0, f"rank {rank}: {out}\n{err}"
        assert f"rank {rank} COMPLETED" in out, f"{out}\n{err}"
        assert "ABORTED" not in out, out


def test_flaky_link_is_transparent_to_the_collective():
    """30% frame loss toward rank 1's peers: the link layer re-rolls
    the lost writes in place (the TCP-retransmit analog), the
    collective completes exactly, and the once-per-peer marker proves
    the chaos actually engaged."""
    results = spawn_tcp_ranks(2, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": "allreduce",
        "FT_SIZE": "70000",
        "HVD_TPU_LIVENESS_TIMEOUT": "15",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
        "HVD_TPU_FAULT_SPEC": "rank1:link:1:flaky:0.3",
    })
    for rank, (code, out, err) in enumerate(results):
        assert code == 0, f"rank {rank}: {out}\n{err}"
        assert f"rank {rank} COMPLETED" in out, f"{out}\n{err}"
    assert "[hvd-fault] flaky link" in (results[1][1] + results[1][2])


def test_partitioned_link_is_a_real_failure_with_the_right_origin():
    """The discrimination's other half: a permanent partition isolating
    rank 2 is NOT a slow link — its control-plane writes fail outright,
    the loss is converted into a coordinated abort, and the typed error
    every survivor sees names rank 2 as the origin (so an operator
    replaces the right host)."""
    results = spawn_tcp_ranks(3, MATRIX_WORKER, extra_env={
        **_FT_ENV,
        "FT_OP": "allreduce",
        "FT_SIZE": "8",  # star path: the cut hits rank 2's
        "HVD_TPU_LIVENESS_TIMEOUT": "3",  # control-plane heartbeats
        "HVD_TPU_CONNECT_RETRY_SECONDS": "5",  # fail the cut link fast
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "12",
        "HVD_TPU_FAULT_SPEC": "rank2:link:1:partition:2-2",
    })
    code2, out2, err2 = results[2]
    assert code2 != 0 or "ABORTED" in out2, \
        f"partitioned rank survived: {out2}\n{err2}"
    for rank in (0, 1):
        code, out, err = results[rank]
        assert code == 0, f"rank {rank}: {out}\n{err}"
        _assert_aborted(out, rank, origin=2, deadline=45.0)


# ------------------- mid-stream break grammar + the self-healing matrix -----
def test_fault_spec_midstream_grammar_round_trip():
    specs = faults.parse_fault_spec(
        "rank2:link:*:reset:0.3, rank1:link:2:reset:0.2:6 ,"
        "rank1:link:5:blip:30000")
    got = [(s.rank, s.point, s.step, s.action, s.param, s.duration)
           for s in specs]
    assert got == [
        # '*' step: armed from the first write; no duration: permanent
        (2, "link", None, "reset", 0.3, None),
        (1, "link", 2, "reset", 0.2, 6.0),
        (1, "link", 5, "blip", 30000.0, None),
    ]


@pytest.mark.parametrize("bad", [
    "rank1:allreduce:*:crash",        # '*' step is midstream-only
    "rank1:link:*:delay:40",          # ... degrade cells too
    "rank1:link:1:reset",             # reset wants a probability
    "rank1:link:1:reset:1.5",         # probability > 1
    "rank1:link:1:reset:often",       # non-numeric probability
    "rank1:link:1:blip:3000:5",       # blip takes no duration
    "rank1:link:1:blip:-5",           # negative window
    "rank1:link:1:blip",              # blip wants a window
])
def test_fault_spec_rejects_bad_midstream_grammar(bad):
    with pytest.raises(ValueError):
        faults.parse_fault_spec(bad)


# Worker for the self-healing matrix (docs/fault_tolerance.md
# "connection blips vs dead peers"): several steps of allreduce +
# broadcast folded into one digest, so "completed" also means
# "bit-identical to the fault-free run" — a heal that corrupted or
# double-delivered a frame would change the bytes.
SESSION_WORKER = r"""
import hashlib, os
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd

hvd.init()
r = hvd.rank()
steps = int(os.environ.get("FT_STEPS", "4"))
n_elems = int(os.environ.get("FT_SIZE", "20000"))
digest = hashlib.sha256()
try:
    for step in range(steps):
        t = jnp.arange(n_elems, dtype=jnp.float32) * (r + 1) + step
        out = hvd.allreduce(t, op=hvd.Sum, name=f"sess.ar.{step}")
        digest.update(np.asarray(out).tobytes())
        b = hvd.broadcast(t, root_rank=0, name=f"sess.bc.{step}")
        digest.update(np.asarray(b).tobytes())
    print(f"rank {r} COMPLETED digest={digest.hexdigest()}", flush=True)
except hvd.HvdAbortedError as exc:
    print(f"rank {r} ABORTED origin={exc.origin_rank}", flush=True)
print(f"rank {r} DONE", flush=True)
"""

# wide liveness/stall windows: these cells assert the HEAL path, so no
# detector may convert the engineered blip into a verdict first
_SESSION_ENV = {
    **_FT_ENV,
    "FT_STEPS": "4",
    "FT_SIZE": "20000",   # above the ring threshold: bulk stripes too
    "HVD_TPU_LIVENESS_TIMEOUT": "15",
    "HVD_STALL_SHUTDOWN_TIME_SECONDS": "30",
}


def _session_digests(results):
    out_digests = []
    for rank, (code, out, err) in enumerate(results):
        assert code == 0, f"rank {rank}: {out}\n{err[-2000:]}"
        assert "ABORTED" not in out, f"rank {rank}: {out}"
        line = next(l for l in out.splitlines()
                    if l.startswith(f"rank {rank} COMPLETED"))
        out_digests.append(line.split("digest=")[1])
    return out_digests


def _healed_count(results):
    return sum(err.count("[hvd-session] reconnect healed")
               for _code, _out, err in results)


def test_midstream_reset_heals_and_completes_bitwise_identical():
    """THE acceptance scenario (ISSUE 17): every frame rank 2 writes
    has a 30% chance of tearing the connection mid-ring — and the job
    completes with digests bitwise-identical to a fault-free run, zero
    aborts, the breaks healed by session resume + replay instead of
    costing a reconfiguration."""
    clean = spawn_tcp_ranks(4, SESSION_WORKER, extra_env=_SESSION_ENV,
                            timeout=180)
    chaos = spawn_tcp_ranks(4, SESSION_WORKER, extra_env={
        **_SESSION_ENV,
        "HVD_TPU_RECONNECT_BUDGET": "30",
        "HVD_TPU_FAULT_SPEC": "rank2:link:*:reset:0.3",
    }, timeout=180)
    want = _session_digests(clean)
    assert len(set(want)) == 1, want     # all ranks agree with each other
    got = _session_digests(chaos)
    assert got == want, (got, want)      # ... and chaos run is bit-equal
    assert _healed_count(chaos) >= 1, \
        "no [hvd-session] heal marker: the chaos never engaged"
    assert any("[hvd-fault] mid-stream reset" in err
               for _c, _o, err in chaos), "reset fault never armed"


def test_midstream_reset_with_zero_budget_reproduces_typed_abort():
    """The feature-off pin, both ways: with the default budget (0) a
    mid-stream reset is exactly today's failure — the typed abort, no
    heal attempts — and the SAME spec with a budget completes with a
    heal.  One knob flips between the two worlds."""
    spec = "rank1:link:1:reset:1.0:4"
    broken = spawn_tcp_ranks(2, SESSION_WORKER, extra_env={
        **_SESSION_ENV,
        "HVD_TPU_LIVENESS_TIMEOUT": "3",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "12",
        "HVD_TPU_FAULT_SPEC": spec,
    }, timeout=180)
    assert _healed_count(broken) == 0, "budget 0 must never heal"
    outs = "\n".join(out for _c, out, _e in broken)
    assert "COMPLETED" not in outs, outs
    assert ("ABORTED" in outs
            or any(code != 0 for code, _o, _e in broken)), broken
    healed = spawn_tcp_ranks(2, SESSION_WORKER, extra_env={
        **_SESSION_ENV,
        "HVD_TPU_RECONNECT_BUDGET": "30",
        "HVD_TPU_FAULT_SPEC": spec,
    }, timeout=180)
    _session_digests(healed)
    assert _healed_count(healed) >= 1


def test_blip_outlasting_the_budget_escalates():
    """A 30s link flap against a 2s budget is a dead peer as far as
    the job can tell: the heal loop exhausts its window (connects are
    refused while the flap is down), the ORIGINAL error escalates, and
    the typed abort fires — no infinite retry, no hang."""
    results = spawn_tcp_ranks(2, SESSION_WORKER, extra_env={
        **_SESSION_ENV,
        "HVD_TPU_LIVENESS_TIMEOUT": "5",
        "HVD_STALL_SHUTDOWN_TIME_SECONDS": "12",
        "HVD_TPU_RECONNECT_BUDGET": "2",
        "HVD_TPU_FAULT_SPEC": "rank1:link:5:blip:30000",
    }, timeout=180)
    outs = "\n".join(out for _c, out, _e in results)
    assert "COMPLETED" not in outs, outs
    assert ("ABORTED" in outs
            or any(code != 0 for code, _o, _e in results)), results
    assert _healed_count(results) == 0, \
        "a connect during an open blip window must be refused"


def test_healing_rank_is_exempt_from_straggler_verdicts():
    """The reconnect/liveness interplay: a rank mid-heal heartbeats as
    busy + reconnecting, so a tight liveness window and the straggler
    detector both stand down while the session resumes — the blip never
    becomes an exclusion."""
    results = spawn_tcp_ranks(2, SESSION_WORKER, extra_env={
        **_SESSION_ENV,
        "FT_STEPS": "6",
        "HVD_TPU_LIVENESS_TIMEOUT": "2",
        "HVD_TPU_RECONNECT_BUDGET": "30",
        "HVD_TPU_FAULT_SPEC": "rank1:link:1:reset:0.3:5",
    }, timeout=180)
    _session_digests(results)
    assert _healed_count(results) >= 1
    assert not any("straggler verdict" in err for _c, _o, err in results)


def test_midstream_reset_heals_on_the_hierarchical_schedule():
    """The session layer sits below the collective schedule: the
    two-level hierarchical plan's intra/inter-group streams heal the
    same way the flat ring's do."""
    results = spawn_tcp_ranks(4, SESSION_WORKER, extra_env={
        **_SESSION_ENV,
        "HVD_TPU_SCHEDULE": "hierarchical",
        "HVD_HIER_LOCAL_SIZE": "2",
        "HVD_TPU_RECONNECT_BUDGET": "30",
        "HVD_TPU_FAULT_SPEC": "rank2:link:*:reset:0.3",
    }, timeout=180)
    digests = _session_digests(results)
    assert len(set(digests)) == 1, digests
    assert _healed_count(results) >= 1
