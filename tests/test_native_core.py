"""Native C++ core tests: controller selection, wire codec round-trip,
response-cache behavior, and python-controller fallback parity."""

import os
import subprocess
import sys

import numpy as np
import pytest


def test_native_controller_selected(hvd):
    from horovod_tpu.common import basics

    assert type(basics._get_state().controller).__name__ == \
        "NativeController"


def test_cache_hits_on_steady_state(hvd):
    """Re-submitting the same named tensor with the same signature is a
    cache hit (reference: response_cache.cc states MISS -> HIT)."""
    import jax.numpy as jnp
    from horovod_tpu.common import basics

    controller = basics._get_state().controller
    before = controller.cache_stats()

    def fn(r):
        for _ in range(3):
            hvd.allreduce(jnp.ones((4,)), op=hvd.Sum, name="cache.probe")

    basics.run_parallel(fn)
    after = controller.cache_stats()
    assert after["size"] >= 1
    # first negotiation misses, the next two hit
    assert after["misses"] - before["misses"] == 1
    assert after["hits"] - before["hits"] == 2


def test_wire_roundtrip_request_fields():
    """The Python encoder must match the C++ decoder field-for-field; this
    exercises the same layout through the live core by driving an op with
    every optional field set."""
    from horovod_tpu.common import wire

    payload = wire.encode_request(
        req_id=7, rank=3, req_type=0, op=1, dtype=np.float32, root_rank=-1,
        prescale=0.5, postscale=2.0, name="x", shape=[2, 3], splits=[])
    assert isinstance(payload, bytes) and len(payload) > 30


SCRIPT = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common import basics

hvd.init()
controller = type(basics._get_state().controller).__name__
def fn(r):
    s = np.asarray(hvd.allreduce(jnp.full((3,), float(r)), op=hvd.Sum,
                                 name="t"))
    g = np.asarray(hvd.allgather(jnp.full((r + 1, 1), float(r)), name="g"))
    b = np.asarray(hvd.broadcast(jnp.full((2,), float(r)), 2, name="b"))
    assert np.allclose(s, 28.0), s
    assert g.shape == (36, 1), g.shape
    assert np.allclose(b, 2.0), b
basics.run_parallel(fn)
hvd.shutdown()
print("OK", controller)
"""


@pytest.mark.parametrize("controller", ["native", "python"])
def test_controller_parity(controller):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HVD_CONTROLLER": controller,
    })
    result = subprocess.run([sys.executable, "-c", SCRIPT], env=env,
                            capture_output=True, text=True, timeout=300,
                            cwd=os.path.dirname(os.path.dirname(__file__)))
    assert result.returncode == 0, result.stderr
    expected = ("NativeController" if controller == "native"
                else "PythonController")
    assert f"OK {expected}" in result.stdout


PY_CACHE_SCRIPT = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.common.handles import HvdError

hvd.init()
controller = basics._get_state().controller

# steady state: same name + signature 3x -> first cycle validates (MISS),
# the next two take the cache fast path (HIT)
def fn(r):
    for i in range(3):
        out = np.asarray(hvd.allreduce(jnp.full((4,), float(r)),
                                       op=hvd.Sum, name="steady"))
        assert np.allclose(out, 28.0), out
basics.run_parallel(fn)
assert controller.cache_hits == 2, controller.cache_hits

# signature change (shape) invalidates: next call re-validates, no new hit
def fn2(r):
    out = np.asarray(hvd.allreduce(jnp.full((8,), float(r)),
                                   op=hvd.Sum, name="steady"))
    assert np.allclose(out, 28.0), out
basics.run_parallel(fn2)
assert controller.cache_hits == 2, controller.cache_hits

# a cached name must still error on cross-rank mismatch (slow path
# re-engages because signatures differ between ranks)
def fn3(r):
    shape = (2,) if r == 0 else (3,)
    try:
        hvd.allreduce(jnp.ones(shape), op=hvd.Sum, name="steady")
    except HvdError:
        return "raised"
    return "no-error"
results = basics.run_parallel(fn3)
assert all(x == "raised" for x in results), results
assert controller.cache_hits == 2, controller.cache_hits

hvd.shutdown()
print("PY-CACHE OK")
"""


def test_python_controller_response_cache():
    """The eager device-rank python controller has the reference's
    steady-state fast path (response_cache.cc): repeat submissions with an
    unchanged signature skip validation; signature changes or cross-rank
    mismatches re-engage it."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HVD_CONTROLLER": "python",
    })
    result = subprocess.run([sys.executable, "-c", PY_CACHE_SCRIPT], env=env,
                            capture_output=True, text=True, timeout=300,
                            cwd=os.path.dirname(os.path.dirname(__file__)))
    assert result.returncode == 0, result.stderr
    assert "PY-CACHE OK" in result.stdout
